"""Public ``Dataset`` and ``Booster`` classes.

TPU-native re-implementation of the reference Python API surface
(python-package/lightgbm/basic.py: Dataset:1747, Booster:3567) — same
signatures and semantics, but backed directly by the JAX engine instead of a
ctypes C API.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Union

import jax.numpy as jnp
import numpy as np

from .config import Config
from .dataset import BinnedDataset, _TextFileSequenceImpl
from .models.boosting import GBDT, create_boosting
from .models.objective import create_objective
from .models.tree import Tree
from .utils import log
from .utils.log import LightGBMError

__all__ = ["Dataset", "Booster", "LightGBMError", "Sequence",
           "TextFileSequence"]


class Sequence:
    """Generic data access interface for streaming Dataset construction
    (reference: basic.py Sequence ABC :896).

    Subclass and implement ``__getitem__`` (int -> (F,) row, slice ->
    (k, F) rows) and ``__len__``; pass one or a list of instances as
    ``Dataset(data=...)``.  Binning samples individual rows; the binned
    matrix is then filled chunk-by-chunk of ``batch_size`` rows, so the
    full raw matrix is never materialized in memory."""

    batch_size = 4096

    def __getitem__(self, idx):
        raise NotImplementedError("Sequence subclasses must implement "
                                  "__getitem__")

    def __len__(self):
        raise NotImplementedError("Sequence subclasses must implement "
                                  "__len__")


class TextFileSequence(_TextFileSequenceImpl, Sequence):
    """Text/CSV file-backed :class:`Sequence`: rows are read from disk
    in ``batch_size`` blocks during streaming construction, so the raw
    matrix never materializes in host memory.  See
    :class:`~lightgbm_tpu.dataset._TextFileSequenceImpl` for parsing
    semantics (float64 fields, NA-ish -> NaN, auto header skip,
    ``usecols`` column selection, ``read_column`` for labels)."""


def _is_cat_dtype(dt: str) -> bool:
    return (dt == "category" or dt in ("object", "bool", "boolean")
            or dt.startswith("str"))


def _dataframe_to_matrix(df, pandas_categorical=None):
    """pandas DataFrame -> (matrix, auto categorical column indices,
    pandas_categorical).

    category/object/str/bool dtype columns are encoded as integer codes;
    missing/unseen values become NaN.  The per-column category lists are
    persisted in the model (reference: basic.py _data_from_pandas +
    the `pandas_categorical` model-file line written by the Python
    wrapper) so predict-time frames are mapped with the TRAINING codes."""
    cols = []
    auto_cats = []
    maps_out = []
    cat_i = 0
    for j, name in enumerate(df.columns):
        col = df[name]
        dt = str(col.dtype)
        if not _is_cat_dtype(dt):
            cols.append(np.asarray(col, dtype=np.float64))
            continue
        if pandas_categorical is not None:   # predict: reuse training maps
            if cat_i >= len(pandas_categorical):
                raise ValueError(
                    "DataFrame has more categorical columns than the model "
                    "was trained with")
            lookup = {v: i for i, v in enumerate(pandas_categorical[cat_i])}
            codes = np.array([float(lookup.get(v, -1))
                              for v in col.tolist()], dtype=np.float64)
        elif dt == "category":
            maps_out.append(list(col.cat.categories))
            codes = np.asarray(col.cat.codes, dtype=np.float64)
        else:
            seen: Dict[Any, int] = {}
            vals = col.tolist()
            codes = np.empty(len(vals), dtype=np.float64)
            for i, v in enumerate(vals):
                if v is None or (isinstance(v, float) and np.isnan(v)):
                    codes[i] = -1
                    continue
                if v not in seen:
                    seen[v] = len(seen)
                codes[i] = seen[v]
            maps_out.append(list(seen.keys()))
        cols.append(np.where(codes < 0, np.nan, codes))
        auto_cats.append(j)
        cat_i += 1
    mat = np.column_stack(cols) if cols else np.zeros((len(df), 0))
    if pandas_categorical is None:
        pandas_categorical = maps_out
    return mat, auto_cats, pandas_categorical


def _is_arrow(data) -> bool:
    """True for pyarrow Table / RecordBatch / ChunkedArray / Array without
    importing pyarrow (detected by module, so the dependency stays
    optional — reference: basic.py _data_from_arrow / arrow ingestion in
    LGBM_DatasetCreateFromArrow, c_api.cpp)."""
    mod = type(data).__module__ or ""
    return mod.split(".")[0] == "pyarrow"


def _arrow_column_to_numpy(col) -> np.ndarray:
    """pyarrow (Chunked)Array -> float64 numpy with nulls as NaN."""
    try:
        import pyarrow as pa
        col = col.cast(pa.float64())
        return col.to_numpy(zero_copy_only=False)
    except Exception:
        return np.asarray(col.to_pandas(), dtype=np.float64)


def _arrow_table_to_matrix(table):
    """pyarrow Table/RecordBatch -> (float64 matrix, column names)."""
    names = [str(c) for c in table.column_names]
    cols = [_arrow_column_to_numpy(table.column(i))
            for i in range(len(names))]
    return np.column_stack(cols) if cols else np.zeros((0, 0)), names


def _arrow_1d_to_numpy(arr) -> np.ndarray:
    if hasattr(arr, "column_names"):         # single-column table
        return _arrow_column_to_numpy(arr.column(0))
    return _arrow_column_to_numpy(arr)


def _to_matrix(data, pandas_categorical=None) -> np.ndarray:
    if isinstance(data, np.ndarray):
        return data
    if hasattr(data, "columns") and hasattr(data, "dtypes"):  # DataFrame
        return _dataframe_to_matrix(data, pandas_categorical)[0]
    if hasattr(data, "toarray"):  # scipy sparse
        return np.asarray(data.toarray(), dtype=np.float64)
    if _is_arrow(data):
        return _arrow_table_to_matrix(data)[0]
    return np.asarray(data, dtype=np.float64)


def _resolve_categoricals(categorical_feature, names, cfg) -> List[int]:
    """Resolve the categorical_feature spec (ints, names, or the config
    string) to column indices (reference: _LGBMCheckClassificationTargets /
    categorical handling in basic.py Dataset)."""
    cats: List[int] = []
    if isinstance(categorical_feature, (list, tuple)):
        for c in categorical_feature:
            if isinstance(c, str) and names and c in names:
                cats.append(names.index(c))
            elif isinstance(c, int):
                cats.append(c)
    elif cfg.categorical_feature:
        cats = [int(x) for x in str(cfg.categorical_feature).split(",")
                if x.strip().lstrip("-").isdigit()]
    return cats


class Dataset:
    """Training data wrapper (reference: basic.py Dataset:1747).

    Construction is lazy like the reference: binning happens on first use
    (``construct``), so parameters from ``train()`` can still apply.
    """

    def __init__(self, data, label=None, reference: Optional["Dataset"] = None,
                 weight=None, group=None, init_score=None,
                 feature_name: Union[str, List[str]] = "auto",
                 categorical_feature: Union[str, List] = "auto",
                 params: Optional[Dict[str, Any]] = None,
                 free_raw_data: bool = True, position=None):
        self.data = data
        self.label = label
        self.reference = reference
        self.weight = weight
        self.group = group
        self.init_score = init_score
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.params = dict(params) if params else {}
        self.free_raw_data = free_raw_data
        self.position = position
        self._inner: Optional[BinnedDataset] = None
        self.used_indices: Optional[np.ndarray] = None
        self.pandas_categorical: Optional[List[List[Any]]] = None

    # ------------------------------------------------------------------
    def construct(self, extra_params: Optional[Dict[str, Any]] = None) -> "Dataset":
        if self._inner is not None:
            return self
        params = dict(self.params)
        if extra_params:
            merged = dict(extra_params)
            merged.update(params)
            params = merged
        cfg = Config(params)
        # Arrow ingestion (reference: tests/python_package_test/test_arrow.py
        # surface): tables become the feature matrix, arrow arrays become
        # metadata vectors.  Conversion is lazy/duck-typed so pyarrow stays
        # an optional dependency.
        for attr in ("label", "weight", "group", "init_score", "position"):
            v = getattr(self, attr)
            if v is not None and _is_arrow(v):
                setattr(self, attr, _arrow_1d_to_numpy(v))
        if _is_arrow(self.data):
            mat, names = _arrow_table_to_matrix(self.data)
            self.data = mat
            if not isinstance(self.feature_name, list) and names:
                self.feature_name = names
        if isinstance(self.data, str):
            # file path: binary fast path (reference: LoadFromBinFile,
            # dataset_loader.cpp:417) or text load
            from .dataset import BinnedDataset as _BD
            if _BD.is_binary_file(self.data):
                self._inner = _BD.load_binary(self.data, cfg)
                md = self._inner.metadata
                if self.label is not None:
                    md.set_label(self.label)
                if self.weight is not None:
                    md.set_weight(self.weight)
                if self.group is not None:
                    md.set_group(self.group)
                if self.init_score is not None:
                    md.set_init_score(self.init_score)
                if self.position is not None:
                    md.set_position(self.position)
                return self
            from .utils.textio import load_text_file
            loaded = load_text_file(
                self.data, has_header=bool(cfg.header),
                label_column=cfg.label_column,
                weight_column=cfg.weight_column,
                group_column=cfg.group_column,
                ignore_column=cfg.ignore_column)
            if self.label is None:
                self.label = loaded.label
            if self.weight is None:
                self.weight = loaded.weight
            if self.group is None:
                self.group = loaded.group
            self.data = loaded.X
            if loaded.feature_names and not isinstance(self.feature_name,
                                                       list):
                self.feature_name = loaded.feature_names
        if isinstance(self.data, Sequence) or (
                isinstance(self.data, (list, tuple)) and self.data
                and all(isinstance(s, Sequence) for s in self.data)):
            names = (self.feature_name
                     if isinstance(self.feature_name, list) else None)
            cats = _resolve_categoricals(self.categorical_feature, names, cfg)
            ref_inner = None
            if self.reference is not None:
                self.reference.construct(extra_params)
                ref_inner = self.reference._inner
            self._inner = BinnedDataset.from_sequences(
                self.data, cfg, label=self.label, weight=self.weight,
                group=self.group, init_score=self.init_score,
                feature_names=names, categorical_features=cats,
                position=self.position, reference=ref_inner)
            return self
        ref_inner_early = None
        if self.reference is not None:
            self.reference.construct(extra_params)
            ref_inner_early = self.reference._inner
        auto_cats: List[int] = []
        self.pandas_categorical = None
        if hasattr(self.data, "columns") and hasattr(self.data, "dtypes"):
            # validation frames must be encoded with the TRAINING category
            # codes (reference: _data_from_pandas with pandas_categorical)
            ref_maps = (self.reference.pandas_categorical
                        if self.reference is not None else None)
            mat, auto_cats, self.pandas_categorical = \
                _dataframe_to_matrix(self.data, ref_maps)
        else:
            mat = _to_matrix(self.data)
        feature_names = None
        if isinstance(self.feature_name, list):
            feature_names = list(self.feature_name)
        elif hasattr(self.data, "columns"):
            feature_names = [str(c) for c in self.data.columns]
        cats = _resolve_categoricals(self.categorical_feature,
                                     feature_names, cfg)
        if not cats and not isinstance(self.categorical_feature,
                                       (list, tuple)) \
                and not cfg.categorical_feature:
            cats = auto_cats   # pandas category dtypes ("auto" mode)
        ref_inner = ref_inner_early
        self._inner = BinnedDataset.from_matrix(
            mat, cfg, label=self.label, weight=self.weight, group=self.group,
            init_score=self.init_score, feature_names=feature_names,
            categorical_features=cats, reference=ref_inner,
            position=self.position)
        self._raw_mat = None if self.free_raw_data else mat
        return self

    # ------------------------------------------------------------------
    def set_label(self, label) -> "Dataset":
        self.label = label
        if self._inner is not None and label is not None:
            self._inner.metadata.set_label(label)
        return self

    def set_weight(self, weight) -> "Dataset":
        self.weight = weight
        if self._inner is not None:
            self._inner.metadata.set_weight(weight)
        return self

    def set_group(self, group) -> "Dataset":
        self.group = group
        if self._inner is not None:
            self._inner.metadata.set_group(group)
        return self

    def set_init_score(self, init_score) -> "Dataset":
        self.init_score = init_score
        if self._inner is not None:
            self._inner.metadata.set_init_score(init_score)
        return self

    def get_label(self):
        if self._inner is not None and self._inner.metadata.label is not None:
            return np.asarray(self._inner.metadata.label)
        return np.asarray(self.label) if self.label is not None else None

    def get_weight(self):
        return self.weight

    def get_group(self):
        return self.group

    def num_data(self) -> int:
        if self._inner is not None:
            return self._inner.num_data
        return _to_matrix(self.data).shape[0]

    def num_feature(self) -> int:
        if self._inner is not None:
            return self._inner.num_total_features
        return _to_matrix(self.data).shape[1]

    def get_feature_name(self) -> List[str]:
        self.construct()
        return list(self._inner.feature_names)

    def subset(self, used_indices, params=None) -> "Dataset":
        idx = np.asarray(used_indices)
        mat = _to_matrix(self.data)[idx]
        group = None
        if self.group is not None:
            # expand query sizes to per-row qids, slice, re-run-length encode
            # (valid when the subset keeps whole queries, as cv() does)
            sizes = np.asarray(self.group, dtype=np.int64)
            qid = np.repeat(np.arange(len(sizes)), sizes)[idx]
            _, group = np.unique(qid, return_counts=True)
        init_score = None
        if self.init_score is not None:
            init_score = np.asarray(self.init_score)[idx]
        sub = Dataset(
            mat,
            label=None if self.label is None else np.asarray(self.label)[idx],
            weight=None if self.weight is None else np.asarray(self.weight)[idx],
            group=group, init_score=init_score,
            feature_name=self.feature_name,
            categorical_feature=self.categorical_feature,
            params=params or self.params)
        sub.used_indices = idx
        return sub

    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, params=None) -> "Dataset":
        return Dataset(data, label=label, reference=self, weight=weight,
                       group=group, init_score=init_score,
                       params=params or self.params)

    def save_binary(self, filename: str) -> "Dataset":
        """Write the constructed dataset in the binary fast-load format
        (reference: Dataset::SaveBinaryFile, dataset.h:691)."""
        self.construct()
        self._inner.save_binary(filename)
        return self


class Booster:
    """Booster (reference: basic.py Booster:3567)."""

    def __init__(self, params: Optional[Dict[str, Any]] = None,
                 train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None):
        params = params or {}
        self.params = dict(params)
        self.config = Config(params)
        from .obs import health as _obs_health
        from .obs import telemetry as _obs
        _obs.configure_from_config(self.config)
        _obs_health.configure_from_config(self.config)
        self._gbdt: Optional[GBDT] = None
        self.train_set = train_set
        self.best_iteration = -1
        self.best_score: Dict[str, Dict[str, float]] = {}
        self._valid_names: List[str] = []
        self._valid_sets: List[Dataset] = []
        # the train set's eval-row name; engine.train overrides it with
        # the valid_names entry when the train set is evaluated
        # (reference: Booster train_data_name / _EarlyStoppingCallback)
        self._train_data_name = "training"

        self.pandas_categorical: Optional[List[List[Any]]] = None
        if train_set is not None:
            train_set.construct(self.params)
            objective = create_objective(self.config)
            self._gbdt = create_boosting(self.config, train_set._inner, objective)
            self._objective = objective
            self.pandas_categorical = train_set.pandas_categorical
        elif model_file is not None:
            with open(model_file) as fh:
                self._load_model_string(fh.read())
        elif model_str is not None:
            self._load_model_string(model_str)
        else:
            log.fatal("Booster requires train_set, model_file or model_str")

    # ------------------------------------------------------------------
    @classmethod
    def _shell_for_gbdt(cls, gbdt) -> "Booster":
        """A fully-attribute-initialized Booster wrapping an EXISTING
        GBDT without training or loading anything — the serializer
        entry point for code that holds a bare GBDT (a standalone
        ``ServingEngine.__getstate__`` snapshotting its forest as a
        model string).  Keeps the attribute surface in ONE place: any
        instance attribute ``model_to_string`` (or what it calls) may
        read must be set here, matching ``__init__``."""
        shell = cls.__new__(cls)
        shell.params = {}
        shell.config = gbdt.config
        shell._gbdt = gbdt
        shell.train_set = None
        shell.best_iteration = -1
        shell.best_score = {}
        shell._valid_names = []
        shell._valid_sets = []
        shell._train_data_name = "training"
        shell.pandas_categorical = None
        return shell

    # ------------------------------------------------------------------
    # pickle / deepcopy: the GBDT holds jitted closures (fused step,
    # traversal, the serving engine's compiled predictors) that cannot
    # pickle, so — like the reference python-package Booster, which
    # pickles its C handle as a model string — the state is the model
    # text plus the picklable python attributes.  The restored booster
    # re-warms its serving engine lazily on the FIRST predict
    # (models/serving.py mark_rewarm): one re-pack + one trace per
    # (kind, bucket), never a crash or a per-call cold trace.
    def __getstate__(self):
        state = self.__dict__.copy()
        state.pop("train_set", None)
        state.pop("_valid_sets", None)
        state.pop("_init_booster", None)
        state.pop("_objective", None)
        g = state.pop("_gbdt", None)
        if g is not None:
            g._flush_pending()
            state["_model_str"] = self.model_to_string()
            state["_serving_was_warm"] = bool(
                g.serving._packs or g.serving._rewarm)
        return state

    def __setstate__(self, state):
        model_str = state.pop("_model_str", None)
        was_warm = state.pop("_serving_was_warm", False)
        self.__dict__.update(state)
        self.train_set = None
        self._valid_sets = []
        self._gbdt = None
        if model_str is not None:
            self._load_model_string(model_str)
            if was_warm:
                self._gbdt.serving.mark_rewarm()

    # ------------------------------------------------------------------
    def _continue_from(self, init_model) -> "Booster":
        """Continued training: seed this (fresh, train-set-backed) booster
        with the trees and scores of ``init_model`` (a Booster, model file
        path, or model string).  Reference: Application::LoadData builds a
        Predictor over the input model to initialize scores
        (application.cpp:94-97); engine.py train(init_model=)."""
        if isinstance(init_model, Booster):
            init_bst = init_model
        elif isinstance(init_model, str) and "\n" in init_model:
            init_bst = Booster(model_str=init_model)
        else:
            init_bst = Booster(model_file=init_model)
        init_bst._gbdt._flush_pending()
        g = self._gbdt
        ig = init_bst._gbdt
        if not ig.models:
            return self
        if ig.num_tree_per_iteration != g.num_tree_per_iteration:
            raise ValueError(
                f"init_model has num_tree_per_iteration="
                f"{ig.num_tree_per_iteration}, training config needs "
                f"{g.num_tree_per_iteration}")
        if type(g).__name__ == "RF":
            # the reference RF rebuilds fixed-score gradients that a loaded
            # model cannot reproduce; failing loudly beats silently training
            # a different model than the pipeline requested
            raise ValueError(
                "init_model continuation is not supported for boosting=rf")
        raw = self._raw_matrix(self.train_set, init_bst)
        if raw is None:
            raise ValueError(
                "continued training needs the raw train rows to score the "
                "init model (reference: application.cpp:94-97); the train "
                "Dataset no longer holds them")
        g.continue_from(ig.models, ig.predict_raw(raw))
        self._init_booster = init_bst
        return self

    def _raw_matrix(self, dataset: Optional[Dataset], init_bst: "Booster"):
        if dataset is None:
            return None
        data = dataset.data
        if data is None or isinstance(data, str):
            inner = getattr(dataset, "_inner", None)
            return getattr(inner, "raw_data", None)
        # encode categoricals with the INIT model's own category maps —
        # the new frame's observed categories can map codes differently
        # (reference python package predicts with the init booster, whose
        # predict applies its own pandas_categorical)
        cats = (init_bst.pandas_categorical
                if init_bst.pandas_categorical is not None
                else self.pandas_categorical)
        return _to_matrix(data, cats)

    def add_valid(self, data: Dataset, name: str) -> "Booster":
        data.construct(self.params)
        extra = None
        if getattr(self, "_init_booster", None) is not None:
            raw = self._raw_matrix(data, self._init_booster)
            if raw is None:
                raise ValueError("continued training needs the raw rows of "
                                 "validation sets to score the init model")
            extra = self._init_booster._gbdt.predict_raw(raw)
        self._gbdt.add_valid_data(data._inner, extra_score=extra)
        self._valid_names.append(name)
        self._valid_sets.append(data)
        return self

    def update(self, train_set: Optional[Dataset] = None, fobj=None) -> bool:
        """One boosting iteration; returns True if no further splits were possible
        (reference: basic.py Booster.update:4073)."""
        from .obs import telemetry as _obs
        with _obs.span("train.iteration", i=self._gbdt.iter):
            if fobj is not None:
                score = self._gbdt.scores
                grad, hess = fobj(np.asarray(score), self.train_set)
                return self.__boost(grad, hess)
            return self._gbdt.train_one_iter()

    def __boost(self, grad, hess) -> bool:
        return self._gbdt.train_one_iter(np.asarray(grad, dtype=np.float32),
                                         np.asarray(hess, dtype=np.float32))

    def boost(self, grad, hess) -> bool:
        return self.__boost(grad, hess)

    def rollback_one_iter(self) -> "Booster":
        self._gbdt.rollback_one_iter()
        return self

    @property
    def current_iteration(self) -> int:
        return self._gbdt.current_iteration

    def num_trees(self) -> int:
        return self._gbdt.num_trees()

    def num_model_per_iteration(self) -> int:
        return self._gbdt.num_tree_per_iteration

    def num_feature(self) -> int:
        return self._gbdt.max_feature_idx + 1

    def telemetry_report(self, include_memory: bool = True) -> Dict[str, Any]:
        """Aggregate runtime telemetry (lightgbm_tpu/obs/): span
        latency histograms, counters, compile events attributed to
        spans, and (``include_memory``) device-memory attribution by
        owner.  The session is process-wide — training, serving and
        the continual runtime all write to it — plus this booster's
        own serving-engine trace/call counters, whose per-(kind,
        bucket) compile counts the session's ``serving.*`` compile
        events reproduce exactly when ``telemetry != off``."""
        from . import obs
        rep = obs.get().report()
        if self._gbdt is not None:
            eng = self._gbdt.serving
            rep["serving"] = {
                "traces": {f"{k[0]}@{k[1]}": v
                           for k, v in eng.trace_counts.items()},
                "calls": {f"{k[0]}@{k[1]}": v
                          for k, v in eng.call_counts.items()},
                "packs": sorted(eng._packs),
            }
        if include_memory:
            rep["memory"] = obs.memory_snapshot()
        return rep

    def health_report(self) -> Dict[str, Any]:
        """Model & data health (lightgbm_tpu/obs/health.py, gated by
        ``health=off|counters|trace``): the training flight recorder
        (per-iteration split decisions, gain trajectory, leaf/gradient
        digests, effective sample counts), the reference data profile
        captured at Dataset construction, and the serving-side
        training↔serving skew digest (per-bucket rows, top-PSI feature
        ranking, prediction-margin log2 histogram, alert count)."""
        from .obs import health as _health
        g = self._gbdt
        # lagged fused-iteration records land in the recorder at
        # materialization; a report is a materialization point
        g._flush_pending()
        rep: Dict[str, Any] = {"mode": _health.get().mode}
        rep["flight_recorder"] = (g.flight.report()
                                  if g.flight is not None else None)
        prof = getattr(g, "health_profile", None)
        if prof is None:
            rep["reference_profile"] = None
        else:
            rep["reference_profile"] = {
                "num_data": prof["num_data"],
                "num_features": len(prof["features"]),
                "features": [
                    {k: fe[k] for k in ("index", "name", "num_bin",
                                        "missing_rate", "zero_rate",
                                        "cardinality")}
                    for fe in prof["features"]],
            }
        mon = g.serving._skew
        rep["serving_skew"] = (mon.report()
                               if mon not in (None, False) else None)
        return rep

    # ------------------------------------------------------------------
    def eval_train(self, feval=None):
        results = []
        for name, val, is_max in self._gbdt.eval_train():
            results.append((self._train_data_name, name, val, is_max))
        if feval is not None:
            results.extend(self._custom_eval(feval, self._train_data_name,
                                             train=True))
        return results

    def eval_valid(self, feval=None):
        results = []
        for vi, vname in enumerate(self._valid_names):
            for name, val, is_max in self._gbdt.eval_valid(vi):
                results.append((vname, name, val, is_max))
            if feval is not None:
                results.extend(self._custom_eval(feval, vname, valid_index=vi))
        return results

    def _custom_eval(self, feval, dataset_name, train=False, valid_index=0):
        fevals = feval if isinstance(feval, list) else [feval]
        out = []
        if train:
            score = np.asarray(self._gbdt.scores)
            dataset = self.train_set
        else:
            score = np.asarray(self._gbdt.valid_scores[valid_index])
            dataset = self._valid_sets[valid_index]
        for f in fevals:
            res = f(score, dataset)
            if isinstance(res, tuple):
                res = [res]
            for name, val, is_max in res:
                out.append((dataset_name, name, val, is_max))
        return out

    # ------------------------------------------------------------------
    def predict(self, data, start_iteration: int = 0,
                num_iteration: Optional[int] = None,
                raw_score: bool = False, pred_leaf: bool = False,
                pred_contrib: bool = False,
                validate_features: bool = False, **kwargs) -> np.ndarray:
        if validate_features and hasattr(data, "columns"):
            # reference: Predictor's data_names vs model feature-name
            # check (c_api.cpp LGBM_BoosterPredictForMats
            # validate_features path)
            got = [str(c) for c in data.columns]
            want = self.feature_name()
            if got != want:
                raise LightGBMError(
                    "Data names mismatch with model feature names: "
                    f"expected {want}, got {got}")
        if num_iteration is None:
            # after early stopping, default to the best iteration
            # (reference: basic.py Booster.predict)
            num_iteration = self.best_iteration if self.best_iteration > 0 else -1
        elif num_iteration == 0:
            num_iteration = -1
        mat = _to_matrix(data, self.pandas_categorical)
        if getattr(mat, "ndim", 2) == 1:
            # a single row vector predicts as one sample (reference
            # wrapper promotes 1-D input before the shape check)
            mat = np.asarray(mat).reshape(1, -1)
        # feature-count validation (reference: c_api Predictor checks
        # ncol against the model's max_feature_idx; bypassed by
        # predict_disable_shape_check, config.h predict section)
        nf = self.num_feature()
        if mat.ndim == 2 and mat.shape[1] != nf:
            if not kwargs.get("predict_disable_shape_check",
                              bool(getattr(self.config,
                                           "predict_disable_shape_check",
                                           False))):
                raise LightGBMError(
                    f"The number of features in data ({mat.shape[1]}) is "
                    f"not the same as it was in training data ({nf}).\n"
                    "You can set ``predict_disable_shape_check=true`` to "
                    "discard this error, but please be aware what you are "
                    "doing.")
            if mat.shape[1] > nf:
                mat = mat[:, :nf]
            else:
                # absent features stay 0.0: the reference C API predicts
                # from a zero-initialized row buffer, so trees routing
                # NaN via missing_type=NaN must not see the padding as
                # missing (ADVICE round 5)
                pad = np.zeros((mat.shape[0], nf - mat.shape[1]),
                               dtype=mat.dtype if np.issubdtype(
                                   mat.dtype, np.floating) else np.float64)
                mat = np.concatenate([np.asarray(mat, pad.dtype), pad],
                                     axis=1)
        if pred_leaf:
            return self._gbdt.predict_leaf_index(mat, start_iteration,
                                                 num_iteration)
        if pred_contrib:
            return self.predict_contrib(mat, start_iteration, num_iteration)
        es_kw = {k: kwargs[k] for k in
                 ("pred_early_stop", "pred_early_stop_freq",
                  "pred_early_stop_margin") if k in kwargs}
        return self._gbdt.predict(mat, raw_score=raw_score,
                                  start_iteration=start_iteration,
                                  num_iteration=num_iteration, **es_kw)

    def predict_contrib(self, data, start_iteration=0, num_iteration=-1):
        """SHAP feature contributions via per-tree path attribution
        (reference: tree.h PredictContrib / TreeSHAP).  Served by the
        device TreeSHAP kernel when eligible (models/serving.py), with
        the exact host recursion as oracle and fallback."""
        return self._gbdt.predict_contrib(
            np.asarray(data, dtype=np.float64), start_iteration,
            num_iteration)

    # ------------------------------------------------------------------
    def model_to_string(self, num_iteration: int = -1,
                        start_iteration: int = 0,
                        importance_type: str = "split") -> str:
        """reference: GBDT::SaveModelToString (gbdt_model_text.cpp:280-430)."""
        g = self._gbdt
        g._flush_pending()
        cfg = self.config
        K = g.num_tree_per_iteration
        lines = ["tree"]
        lines.append("version=v4")
        lines.append(f"num_class={g.num_class}")
        lines.append(f"num_tree_per_iteration={K}")
        lines.append(f"label_index={g.label_idx}")
        lines.append(f"max_feature_idx={g.max_feature_idx}")
        obj = g.objective
        if obj is not None:
            lines.append(f"objective={obj.to_string()}")
        if g.average_output:
            lines.append("average_output")
        lines.append("feature_names=" + " ".join(g.feature_names))
        infos = []
        if g.train_data is not None:
            for bm in g.train_data.bin_mappers:
                infos.append(bm.feature_info())
        lines.append("feature_infos=" + " ".join(infos))
        total = len(g.models)
        end = total if num_iteration < 0 else min(total, (start_iteration + num_iteration) * K)
        tree_strs = [g.models[i].to_string(i - start_iteration * K)
                     for i in range(start_iteration * K, end)]
        tree_sizes = [len(s) + 1 for s in tree_strs]
        lines.append("tree_sizes=" + " ".join(str(s) for s in tree_sizes))
        lines.append("")
        body = "\n".join(lines) + "\n"
        body += "\n".join(tree_strs)
        body += "end of trees\n"
        # saved_feature_importance_type: 0 = split counts, 1 = total gain
        # (reference: GBDT::FeatureImportance via config.h
        # saved_feature_importance_type, tree.cpp DumpModel)
        imp_type = ("gain" if int(getattr(self.config,
                                          "saved_feature_importance_type",
                                          0) or 0) == 1 else "split")
        imp = self.feature_importance(importance_type=imp_type)
        pairs = [(imp[i], g.feature_names[i]) for i in range(len(imp)) if imp[i] > 0]
        pairs.sort(key=lambda x: -x[0])
        body += "\nfeature_importances:\n"
        for v, n in pairs:
            body += (f"{n}={int(v)}\n" if imp_type == "split"
                     else f"{n}={float(v):g}\n")
        body += "\nparameters:\n" + self.config.save_to_string() + "\nend of parameters\n"
        if getattr(g, "health_profile", None) is not None:
            # the data-health reference profile rides the model file
            # (one JSON line, like pandas_categorical below; loaders
            # that predate it skip unknown header-less lines) as the
            # offline-audit / scoring reference — live serving digests
            # additionally need the in-session bin-space path, which a
            # loaded model (threshold-index packs, no mappers) lacks
            import json as _json
            body += ("health_profile:"
                     + _json.dumps(g.health_profile,
                                   separators=(",", ":")) + "\n")
        if self.pandas_categorical is not None:
            # final line, like the reference Python wrapper (basic.py
            # _dump_pandas_categorical)
            import json as _json
            body += ("pandas_categorical:"
                     + _json.dumps(self.pandas_categorical, default=str)
                     + "\n")
        return body

    def save_model(self, filename: str, num_iteration: int = -1,
                   start_iteration: int = 0,
                   importance_type: str = "split") -> "Booster":
        with open(filename, "w") as fh:
            fh.write(self.model_to_string(num_iteration, start_iteration,
                                          importance_type))
        return self

    def _load_model_string(self, text: str) -> None:
        """reference: GBDT::LoadModelFromString (gbdt_model_text.cpp:430-560)."""
        for line in reversed(text.rstrip().split("\n")[-5:]):
            if line.startswith("pandas_categorical:"):
                import json as _json
                try:
                    self.pandas_categorical = _json.loads(
                        line[len("pandas_categorical:"):])
                except ValueError:
                    pass
                break
        header: Dict[str, str] = {}
        lines = text.split("\n")
        i = 0
        while i < len(lines):
            line = lines[i].strip()
            if line.startswith("Tree="):
                break
            if "=" in line:
                k, v = line.split("=", 1)
                header[k.strip()] = v.strip()
            elif line == "average_output":
                header["average_output"] = "1"
            i += 1
        # restore the training parameters embedded in the model file
        # (reference: GBDT::LoadModelFromString reads the `parameters:`
        # section saved by SaveModelToString; Config::GetLoadedParam)
        saved_params: Dict[str, Any] = {}
        if "\nparameters:" in text:
            psec = text.split("\nparameters:", 1)[1]
            psec = psec.split("end of parameters", 1)[0]
            for pline in psec.split("\n"):
                pline = pline.strip()
                if pline.startswith("[") and pline.endswith("]") \
                        and ":" in pline:
                    k, v = pline[1:-1].split(":", 1)
                    saved_params[k.strip()] = v.strip()
        saved_params.pop("task", None)
        saved_params["objective"] = header.get(
            "objective", saved_params.get("objective", "regression")).split(" ")[0]
        saved_params["num_class"] = int(header.get("num_class", 1))
        # the re-arm opt-in belongs to the LOADING call, not the saved
        # model: capture it from the pre-load config (and env) before
        # the saved params replace it, and make sure a saved
        # obs_rearm_on_load can never re-enable itself on later loads
        from .obs import telemetry as _obs_tel
        allow_rearm = _obs_tel.rearm_on_load_allowed(self.config)
        saved_params.pop("obs_rearm_on_load", None)
        self.config = Config(saved_params)
        # a model trained with telemetry on does NOT silently re-arm the
        # process-wide session on restore: re-arming is opt-in
        # (obs_rearm_on_load=True / LIGHTGBM_TPU_OBS_REARM_ON_LOAD=1)
        # and skipping it warns once — a loaded model file is data, not
        # a process configuration change.  (In an already-armed process
        # — e.g. the pickle round-trip of a booster trained here —
        # nothing changes: sessions are upgrade-only.)
        _obs_tel.configure_from_config(self.config, from_model_load=True,
                                       allow_rearm=allow_rearm)
        self.params = dict(saved_params)
        objective = create_objective(self.config)
        self._gbdt = GBDT(self.config, None, objective)
        self._objective = objective
        g = self._gbdt
        g.num_tree_per_iteration = int(header.get("num_tree_per_iteration", 1))
        g.num_class = int(header.get("num_class", 1))
        g.label_idx = int(header.get("label_index", 0))
        g.max_feature_idx = int(header.get("max_feature_idx", 0))
        g.feature_names = header.get("feature_names", "").split()
        g.average_output = "average_output" in header
        # parse trees
        blocks = text.split("Tree=")[1:]
        for blk in blocks:
            body = blk.split("end of trees")[0]
            g.models.append(Tree.from_string("Tree=" + body))
        # data-health reference profile (written after the parameters
        # section; absent in models saved before it existed)
        if "\nhealth_profile:" in text:
            import json as _json
            line = text.split("\nhealth_profile:", 1)[1].split("\n", 1)[0]
            try:
                g.health_profile = _json.loads(line)
            except ValueError:
                pass
        from .obs import health as _obs_health
        _obs_health.configure_from_config(self.config, from_model_load=True,
                                          allow_rearm=allow_rearm)

    def dump_model(self, num_iteration: int = -1, start_iteration: int = 0) -> dict:
        """reference: GBDT::DumpModel (gbdt_model_text.cpp:23-120)."""
        g = self._gbdt
        g._flush_pending()
        K = g.num_tree_per_iteration
        total = len(g.models)
        end = total if num_iteration < 0 else min(total, (start_iteration + num_iteration) * K)
        return {
            "name": "tree",
            "version": "v4",
            "num_class": g.num_class,
            "num_tree_per_iteration": K,
            "label_index": g.label_idx,
            "max_feature_idx": g.max_feature_idx,
            "objective": g.objective.to_string() if g.objective else "none",
            "average_output": g.average_output,
            "feature_names": list(g.feature_names),
            "tree_info": [dict(tree_index=i, **g.models[i].to_json())
                          for i in range(start_iteration * K, end)],
        }

    # ------------------------------------------------------------------
    def feature_importance(self, importance_type: str = "split",
                           iteration: Optional[int] = None) -> np.ndarray:
        """reference: GBDT::FeatureImportance (gbdt.cpp)."""
        self._gbdt._flush_pending()
        n = self._gbdt.max_feature_idx + 1
        imp = np.zeros(n, dtype=np.float64)
        for tree in self._gbdt.models:
            for node in range(tree.num_nodes()):
                f = int(tree.split_feature[node])
                if f < n:
                    if importance_type == "split":
                        imp[f] += 1
                    else:
                        imp[f] += max(tree.split_gain[node], 0.0)
        return imp

    def feature_name(self) -> List[str]:
        return list(self._gbdt.feature_names)

    def refit(self, data, label, weight=None, group=None,
              decay_rate: Optional[float] = None,
              inplace: bool = False, **kwargs) -> "Booster":
        """Refit the existing tree structures on new data: keep every split,
        recompute leaf outputs from the new gradients
        (reference: GBDT::RefitTree gbdt.cpp:252-290 and
        SerialTreeLearner::FitByExistingTree; basic.py Booster.refit).

        ``inplace=True`` commits the new leaf values into THIS booster
        (the continual-training runtime's per-tick path) instead of
        returning a fresh one: device trees and the serving engine's
        warm packs update eagerly through
        ``GBDT.apply_refit_leaf_values`` — the mutation counter bumps
        at commit, like update/rollback, never "at the next update".
        In-place refit makes the booster serving-only (its training
        scores no longer match the model); continue training from a
        fresh booster instead.

        ``nonfinite_policy`` (robustness/guard.py) guards the refit
        gradients exactly like full training iterations: ``raise``
        aborts naming the refit iteration, ``skip_iteration`` keeps
        that iteration's old leaf values, ``clamp`` zeroes the poisoned
        entries so those rows drop out of the leaf sums."""
        from .dataset import Metadata
        from .ops.split import leaf_output as _leaf_output
        from .robustness import faultinject as _faultinject
        from .robustness.guard import NonFiniteGuard

        g = self._gbdt
        g._flush_pending()
        if not g.models:
            raise LightGBMError("Cannot refit an empty model")
        merged = dict(self.params)
        merged.update(kwargs)
        cfg = self.config.update(merged) if merged else self.config
        decay = cfg.refit_decay_rate if decay_rate is None else decay_rate
        mat = _to_matrix(data)
        n = mat.shape[0]
        K = g.num_tree_per_iteration

        if inplace:
            new_booster = self
            ng = g
        else:
            new_booster = Booster(model_str=self.model_to_string())
            new_booster.config = cfg
            ng = new_booster._gbdt
        objective = create_objective(cfg)
        nf_guard = NonFiniteGuard.from_config(cfg)
        # observable by callers (the continual runtime reports whether a
        # tick's refit was guard-skipped); None when no policy is active
        new_booster._refit_guard = nf_guard

        meta = Metadata(n)
        meta.set_label(label)
        meta.set_weight(weight)
        meta.set_group(group)
        objective.init(meta)

        leaf_preds = ng.predict_leaf_index(mat)  # (n, num_trees)
        num_iters = len(ng.models) // K
        scores = np.zeros((n, K) if K > 1 else n, dtype=np.float64)
        l1, l2 = float(cfg.lambda_l1), float(cfg.lambda_l2)
        mds = float(cfg.max_delta_step)
        eps = 1e-15  # kEpsilon hessian floor (serial_tree_learner.cpp:251)
        # new leaf values accumulate OUT OF PLACE and commit at the end:
        # the serving engine must never observe a half-refit forest
        new_values = [np.asarray(t.leaf_value, np.float64).copy()
                      for t in ng.models]
        for it in range(num_iters):
            grad, hess = objective.get_gradients(
                np.asarray(scores, dtype=np.float64))
            grad = np.asarray(grad, dtype=np.float64)
            hess = np.asarray(hess, dtype=np.float64)
            if _faultinject.is_active():
                grad, hess = (np.asarray(a, dtype=np.float64) for a in
                              _faultinject.maybe_corrupt_gradients(
                                  it, grad, hess))
            if K > 1 and grad.ndim == 1:
                grad = grad.reshape(K, n).T
                hess = hess.reshape(K, n).T
            skip = False
            if nf_guard is not None:
                # same guard rails as a full training iteration
                # (robustness/guard.py): one finiteness verdict over the
                # refit gradients before any leaf sum reads them
                grad, hess, skip = nf_guard.filter(it, grad, hess)
                grad = np.asarray(grad, dtype=np.float64)
                hess = np.asarray(hess, dtype=np.float64)
            for k in range(K):
                ti = it * K + k
                tree = ng.models[ti]
                gk = grad[:, k] if K > 1 else grad
                hk = hess[:, k] if K > 1 else hess
                leaves = leaf_preds[:, ti]
                nl = tree.num_leaves
                if not skip:
                    gsum = np.bincount(leaves, weights=gk, minlength=nl)
                    hsum = np.bincount(leaves, weights=hk,
                                       minlength=nl) + eps
                    out = np.asarray(
                        _leaf_output(jnp.asarray(gsum), jnp.asarray(hsum),
                                     l1, l2, mds),
                        dtype=np.float64) * tree.shrinkage
                    new_values[ti] = decay * new_values[ti] + \
                        (1.0 - decay) * out
                # skipped iterations keep their old leaf values but
                # still contribute them to the running scores, so later
                # iterations' gradients stay consistent
                pred = new_values[ti][leaves]
                if K > 1:
                    scores[:, k] += pred
                else:
                    scores += pred
        # committing the leaf rewrites is a model mutation: the version
        # bumps (and packs refresh/drop) EAGERLY so a serving pack warmed
        # by the predict_leaf_index call above — or, inplace, any pack
        # this booster was already serving — can never serve pre-refit
        # values
        ng.apply_refit_leaf_values(new_values)
        return new_booster

    def free_dataset(self) -> "Booster":
        return self

    def reset_parameter(self, params: Dict[str, Any]) -> "Booster":
        self.params.update(params)
        self.config = Config(self.params)
        if self._gbdt is not None:
            self._gbdt.config = self.config
            self._gbdt.shrinkage_rate = float(self.config.learning_rate)
        return self
