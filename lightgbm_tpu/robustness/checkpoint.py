"""Iteration-level checkpointing and bit-exact crash resume.

A crash at iteration 900/1000 of a multi-hour preemptible-TPU run must
not lose everything (ROADMAP north star; the Gemma-on-TPU ops practice
in PAPERS.md treats periodic checkpointing as table stakes).  The
reference's ``snapshot_freq`` (gbdt.cpp:244-248) dumps only the model
text — enough to warm-start via ``init_model``, but NOT bit-exact: the
continued booster re-seeds its scores from float64 host predictions and
its RNG streams restart.  A checkpoint here snapshots the full training
state:

  * the model text (trees + feature infos, self-contained);
  * the float32 train/validation score arrays exactly as the device
    holds them;
  * every python-side RNG stream (bagging, feature-fraction, quantized
    rounding keys; the objective's iteration counter for objectives
    with host-side noise) plus the current bagging mask;
  * the eval history and the booster's best-iteration bookkeeping.

so ``train(..., resume=True)`` continues the run bit-exact with an
uninterrupted one.  (Exception: the ``early_stopping`` CALLBACK's
internal patience counters live in closures and are rebuilt at the
first post-resume iteration — with early stopping enabled a resumed run
restarts its patience window from the resume point, so it may stop
later than the uninterrupted run.  The boosting trajectory itself stays
bit-exact.)  Why that works with the fused physical path: reading
``GBDT.scores`` materializes the physically-permuted payload back to
original row order and drops the physical state, which the next fused
iteration rebuilds from scratch — capture does exactly that read, and it
happens at the SAME iterations in the uninterrupted run (its checkpoint
callback fires there too), so both runs see identical state-reset points
and identical histogram accumulation orders thereafter.

Write protocol: everything lands in a temp directory first, fsynced,
then ``os.rename``d into place (atomic on POSIX) — a reader never
observes a half-written checkpoint.  Retention keeps the newest K.
Under multi-process SPMD every rank CAPTURES (the capture itself is a
collective-ordering-relevant scores read) but only rank 0 WRITES.
"""

from __future__ import annotations

import json
import os
import shutil
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from ..utils import log
from ..utils.log import LightGBMError

_PREFIX = "ckpt_"
_TMP_PREFIX = ".tmp-"

MODEL_FILE = "model.txt"
STATE_FILE = "state.json"
ARRAYS_FILE = "arrays.npz"
HISTORY_FILE = "history.jsonl"


@dataclass
class CheckpointState:
    """One checkpoint's payload (see module docstring for the why of
    each field)."""

    iteration: int
    model_text: str
    scores: np.ndarray
    valid_scores: List[np.ndarray] = field(default_factory=list)
    rng: Dict[str, np.ndarray] = field(default_factory=dict)
    bag_mask: Optional[np.ndarray] = None
    bag_cnt: Optional[int] = None
    empty_run: int = 0
    objective_state: Dict[str, Any] = field(default_factory=dict)
    eval_history: List[Any] = field(default_factory=list)
    best_iteration: int = -1
    best_score: Dict[str, Dict[str, float]] = field(default_factory=dict)


# ---------------------------------------------------------------------------
# capture / restore
# ---------------------------------------------------------------------------
def capture_training_state(booster, iteration: int,
                           eval_history: Optional[List[Any]] = None
                           ) -> CheckpointState:
    """Snapshot a Booster mid-training.  The ``model_to_string`` call
    flushes any lagged fused records and the ``scores`` read
    materializes the physical payload — both intentional: they pin the
    device state to a canonical form at this iteration boundary (and
    the uninterrupted run's checkpoint callback pins it at the same
    boundaries, which is what makes resume bit-exact)."""
    g = booster._gbdt
    model_text = booster.model_to_string()
    scores = np.asarray(g.scores)
    valid_scores = [np.asarray(v) for v in g.valid_scores]
    rng: Dict[str, np.ndarray] = {}
    for name in ("bag_rng", "feat_rng", "quant_rng"):
        key = getattr(g, name, None)
        if key is not None:
            rng[name] = np.asarray(key)
    bag_mask = bag_cnt = None
    cached = getattr(g, "_cached_bag", None)
    if cached is not None:
        bag_mask = np.asarray(cached[0])
        bag_cnt = int(cached[1])
    objective_state = {}
    if g.objective is not None:
        objective_state = g.objective.state_dict()
    return CheckpointState(
        iteration=int(iteration),
        model_text=model_text,
        scores=scores,
        valid_scores=valid_scores,
        rng=rng,
        bag_mask=bag_mask,
        bag_cnt=bag_cnt,
        empty_run=int(getattr(g, "_empty_run", 0)),
        objective_state=objective_state,
        eval_history=list(eval_history or []),
        best_iteration=int(getattr(booster, "best_iteration", -1)),
        best_score=dict(getattr(booster, "best_score", {}) or {}),
    )


def restore_training_state(booster, state: CheckpointState) -> int:
    """Load ``state`` into a freshly constructed, train-set-backed
    Booster (validation sets already attached) and return the iteration
    to continue from.  The head trees come back as host trees (real
    thresholds, no device arrays) exactly like ``init_model``
    continuation — but scores and RNG streams restore from the saved
    arrays, NOT from re-prediction, which is what keeps the continued
    run bit-exact."""
    import jax.numpy as jnp

    from ..parallel import network

    g = booster._gbdt
    if network.num_machines() > 1:
        raise LightGBMError(
            "checkpoint resume is not supported under multi-process "
            "training yet: the snapshot holds rank-0 local scores only. "
            "Restart the whole job from the saved model via init_model "
            "instead (warm start, not bit-exact).")
    if type(g).__name__ in ("DART", "RF"):
        raise LightGBMError(
            f"checkpoint resume is not supported for boosting="
            f"{type(g).__name__.lower()}: its per-tree bookkeeping "
            "(drop weights / fixed-score gradients) needs device trees "
            "that a restored model does not carry")
    if g.models:
        raise LightGBMError("checkpoint resume needs a fresh booster "
                            "(models already present)")
    K = g.num_tree_per_iteration
    # parse the saved trees through the normal model loader
    from ..basic import Booster as _Booster
    loaded = _Booster(model_str=state.model_text)
    g.models = loaded._gbdt.models
    g.device_trees = [None] * len(g.models)
    g._model_version += 1
    g.iter = int(state.iteration)
    g._empty_run = int(state.empty_run)
    # the saved head trees already contain the boost-from-average fold
    # (same reason as GBDT.continue_from)
    g.init_scores = [0.0] * K
    g.scores = jnp.asarray(np.asarray(state.scores, np.float32))
    if len(state.valid_scores) != len(g.valid_scores):
        raise LightGBMError(
            f"checkpoint has {len(state.valid_scores)} validation score "
            f"arrays but the resumed training set up "
            f"{len(g.valid_scores)} validation sets; pass the same "
            "valid_sets as the original run")
    for vi, vs in enumerate(state.valid_scores):
        g.valid_scores[vi] = jnp.asarray(np.asarray(vs, np.float32))
    for name, arr in state.rng.items():
        if getattr(g, name, None) is not None:
            setattr(g, name, jnp.asarray(np.asarray(arr)))
    if state.bag_mask is not None:
        g._cached_bag = (jnp.asarray(np.asarray(state.bag_mask, bool)),
                         int(state.bag_cnt))
    if g.objective is not None and state.objective_state:
        g.objective.load_state_dict(state.objective_state)
    booster.best_iteration = int(state.best_iteration)
    booster.best_score = dict(state.best_score or {})
    return int(state.iteration)


# ---------------------------------------------------------------------------
# on-disk manager
# ---------------------------------------------------------------------------
class CheckpointManager:
    """Atomic, keep-last-K checkpoint directory layout:

        <checkpoint_dir>/ckpt_00000010/{model.txt,state.json,arrays.npz}

    Writers stage under ``.tmp-*`` and rename; readers only ever see
    complete directories.  ``latest()`` walks newest-to-oldest and skips
    unreadable entries, so a torn write (crash mid-stage) degrades to
    the previous checkpoint instead of failing the resume."""

    def __init__(self, checkpoint_dir: str, keep: int = 2):
        if not checkpoint_dir:
            raise LightGBMError("checkpoint_dir must be a non-empty path")
        self.dir = str(checkpoint_dir)
        self.keep = max(int(keep), 1)
        os.makedirs(self.dir, exist_ok=True)
        # entries of history.jsonl this manager knows are on disk; None
        # until the first save, which REWRITES the log (truncating any
        # stale tail from a killed run) before switching to appends
        self._hist_logged: Optional[int] = None

    # -- eval-history append log --------------------------------------
    # state.json used to re-serialize the FULL eval history at every
    # checkpoint, so the per-checkpoint cost grew linearly with
    # iterations trained (PERF.md).  The history now lives in one
    # append-only <checkpoint_dir>/history.jsonl shared by all
    # checkpoints (one JSON line per evaluated iteration); each
    # state.json records only ITS history LENGTH, and restore caps the
    # log at that length to reconstruct the full history.  The log
    # grows O(total iterations) on disk, but a checkpoint append is
    # O(delta) instead of O(history).
    #
    # ONE TRAINING RUN PER checkpoint_dir: like the ckpt_NNNN
    # directories themselves (which same-iteration writers replace
    # wholesale), the shared log assumes a single live writer — two
    # INDEPENDENT runs pointed at one directory interleave/truncate
    # each other's history exactly as they already clobber each other's
    # checkpoints.  (Multi-process SPMD is fine: only rank 0 writes.)
    @property
    def history_path(self) -> str:
        return os.path.join(self.dir, HISTORY_FILE)

    def _sync_history(self, history: List[Any]) -> None:
        rows = _history_to_json(history)
        if (self._hist_logged is None and not rows
                and not os.path.exists(self.history_path)):
            # no evals recorded and no stale log: don't create an empty
            # file (runs without valid sets keep a clean directory)
            self._hist_logged = 0
            return
        if self._hist_logged is None or self._hist_logged > len(rows):
            # first save of this run (or a rewound history): rewrite the
            # log atomically so stale tails from a killed run vanish
            tmp = self.history_path + f".tmp-{os.getpid()}"
            with open(tmp, "w") as fh:
                for row in rows:
                    fh.write(json.dumps(row) + "\n")
                fh.flush()
                os.fsync(fh.fileno())
            os.rename(tmp, self.history_path)
        elif self._hist_logged < len(rows):
            with open(self.history_path, "a") as fh:
                for row in rows[self._hist_logged:]:
                    fh.write(json.dumps(row) + "\n")
                fh.flush()
                os.fsync(fh.fileno())
        self._hist_logged = len(rows)

    def _read_history(self, upto: int) -> List[Any]:
        if upto <= 0 or not os.path.exists(self.history_path):
            if upto > 0:
                log.warning("checkpoint expects %d eval-history entries "
                            "but %s is missing; resuming with an empty "
                            "history", upto, self.history_path)
            return []
        rows: List[Any] = []
        with open(self.history_path) as fh:
            for line in fh:
                if len(rows) >= upto:
                    break
                try:
                    rows.append(json.loads(line))
                except ValueError:
                    break       # torn trailing line from a crash
        if len(rows) < upto:
            log.warning("eval-history log holds %d of the %d entries "
                        "this checkpoint recorded; the tail is lost",
                        len(rows), upto)
        return _history_from_json(rows)

    # -- listing -------------------------------------------------------
    def iterations(self) -> List[int]:
        out = []
        for name in os.listdir(self.dir):
            if name.startswith(_PREFIX):
                try:
                    out.append(int(name[len(_PREFIX):]))
                except ValueError:
                    continue
        return sorted(out)

    def _path(self, iteration: int) -> str:
        return os.path.join(self.dir, f"{_PREFIX}{iteration:08d}")

    # -- write ---------------------------------------------------------
    def save(self, state: CheckpointState) -> str:
        final = self._path(state.iteration)
        tmp = os.path.join(
            self.dir,
            f"{_TMP_PREFIX}{_PREFIX}{state.iteration:08d}-{os.getpid()}")
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        try:
            self._sync_history(state.eval_history)
            self._write_payload(tmp, state)
            if os.path.exists(final):
                shutil.rmtree(final)
            os.rename(tmp, final)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._prune()
        log.debug("checkpoint saved at iteration %d -> %s",
                  state.iteration, final)
        return final

    @staticmethod
    def _write_payload(path: str, state: CheckpointState) -> None:
        def _fsync_write(fname: str, mode: str, writer) -> None:
            with open(os.path.join(path, fname), mode) as fh:
                writer(fh)
                fh.flush()
                os.fsync(fh.fileno())

        _fsync_write(MODEL_FILE, "w", lambda fh: fh.write(state.model_text))
        arrays: Dict[str, np.ndarray] = {"scores": state.scores}
        for vi, vs in enumerate(state.valid_scores):
            arrays[f"valid_scores_{vi}"] = vs
        for name, arr in state.rng.items():
            arrays[f"rng_{name}"] = arr
        if state.bag_mask is not None:
            arrays["bag_mask"] = state.bag_mask
        _fsync_write(ARRAYS_FILE, "wb",
                     lambda fh: np.savez(fh, **arrays))
        meta = {
            "format_version": 2,
            "iteration": state.iteration,
            "num_valid_scores": len(state.valid_scores),
            "rng_names": sorted(state.rng),
            "bag_cnt": state.bag_cnt,
            "empty_run": state.empty_run,
            "objective_state": state.objective_state,
            # the history itself lives in the shared append-only
            # history.jsonl; each checkpoint stores only its LENGTH
            "eval_history_len": len(state.eval_history),
            "best_iteration": state.best_iteration,
            "best_score": state.best_score,
        }
        _fsync_write(STATE_FILE, "w", lambda fh: json.dump(meta, fh))

    def _prune(self) -> None:
        iters = self.iterations()
        for it in iters[:-self.keep]:
            shutil.rmtree(self._path(it), ignore_errors=True)
        # stale temp dirs from THIS process's earlier (crashed-and-
        # restarted-in-place) saves only: tmp names are pid-suffixed, and
        # another live writer sharing this dir must not lose its in-
        # flight staging directory
        suffix = f"-{os.getpid()}"
        for name in os.listdir(self.dir):
            if name.startswith(_TMP_PREFIX) and name.endswith(suffix):
                shutil.rmtree(os.path.join(self.dir, name),
                              ignore_errors=True)
            elif name == f"{HISTORY_FILE}.tmp{suffix}":
                # staging file from a crashed history rewrite
                try:
                    os.remove(os.path.join(self.dir, name))
                except OSError:
                    pass

    # -- read ----------------------------------------------------------
    def load(self, iteration: int) -> CheckpointState:
        path = self._path(iteration)
        with open(os.path.join(path, STATE_FILE)) as fh:
            meta = json.load(fh)
        with open(os.path.join(path, MODEL_FILE)) as fh:
            model_text = fh.read()
        with np.load(os.path.join(path, ARRAYS_FILE)) as npz:
            scores = np.asarray(npz["scores"])
            valid_scores = [np.asarray(npz[f"valid_scores_{vi}"])
                            for vi in range(int(meta["num_valid_scores"]))]
            rng = {name: np.asarray(npz[f"rng_{name}"])
                   for name in meta.get("rng_names", [])}
            bag_mask = (np.asarray(npz["bag_mask"])
                        if "bag_mask" in npz.files else None)
        if "eval_history" in meta:     # format_version 1 compatibility
            history = _history_from_json(meta.get("eval_history") or [])
        else:
            history = self._read_history(int(meta.get("eval_history_len",
                                                      0)))
        return CheckpointState(
            iteration=int(meta["iteration"]),
            model_text=model_text,
            scores=scores,
            valid_scores=valid_scores,
            rng=rng,
            bag_mask=bag_mask,
            bag_cnt=meta.get("bag_cnt"),
            empty_run=int(meta.get("empty_run", 0)),
            objective_state=meta.get("objective_state") or {},
            eval_history=history,
            best_iteration=int(meta.get("best_iteration", -1)),
            best_score=meta.get("best_score") or {},
        )

    def latest(self) -> Optional[CheckpointState]:
        for it in reversed(self.iterations()):
            try:
                return self.load(it)
            except Exception as exc:  # torn write: fall back to older
                log.warning("checkpoint at iteration %d unreadable (%s); "
                            "trying the previous one", it, exc)
        return None


def _history_to_json(history: List[Any]) -> List[Any]:
    # eval rows are (data_name, metric, value, is_max[, stdv]) tuples per
    # iteration; tuples/np scalars flatten to plain JSON lists
    out = []
    for rows in history:
        out.append([[row[0], row[1], float(row[2]), bool(row[3])]
                    + ([float(row[4])] if len(row) > 4 else [])
                    for row in (rows or [])])
    return out


def _history_from_json(history: List[Any]) -> List[Any]:
    return [[tuple(row) for row in rows] for rows in history]


# ---------------------------------------------------------------------------
# training callback
# ---------------------------------------------------------------------------
class CheckpointCallback:
    """After-iteration callback that records the eval history and writes
    a checkpoint every ``interval`` iterations (rank 0 only; every rank
    still captures, keeping SPMD ranks' device state in lockstep).

    Appended automatically by ``train()`` when ``checkpoint_dir`` and
    ``checkpoint_interval`` are configured, or pass an instance in
    ``callbacks`` for custom retention."""

    order = 40                     # after record_evaluation/early_stopping

    def __init__(self, checkpoint_dir: str, interval: int, keep: int = 2):
        if int(interval) <= 0:
            raise LightGBMError("checkpoint_interval must be > 0")
        self.manager = CheckpointManager(checkpoint_dir, keep=keep)
        self.interval = int(interval)
        self.eval_history: List[Any] = []

    def seed_history(self, history: List[Any]) -> None:
        """Pre-load the eval history restored from a checkpoint so the
        post-resume checkpoints carry the full run's history."""
        self.eval_history = list(history or [])

    def __call__(self, env) -> None:
        booster = env.model
        from ..basic import Booster as _Booster
        if not isinstance(booster, _Booster):
            # CVBooster's __getattr__ fans any method out per fold, so a
            # duck check would silently "succeed"; require the real type
            raise LightGBMError(
                "CheckpointCallback supports train() boosters only "
                "(cv() fold ensembles are not checkpointable)")
        if env.evaluation_result_list:
            self.eval_history.append(list(env.evaluation_result_list))
        it = env.iteration + 1
        if it % self.interval != 0:
            return
        state = capture_training_state(booster, it, self.eval_history)
        from ..parallel import network
        if network.rank() == 0:
            self.manager.save(state)
