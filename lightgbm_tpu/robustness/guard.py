"""Non-finite guard rails on the boosting iteration.

A single NaN/Inf gradient batch silently poisons every subsequent tree
(leaf values, scores, then the whole model); GPU boosting practice
(XGBoost GPU, PAPERS.md) shows per-iteration statistics checks are
cheap relative to histogram work.  ``nonfinite_policy`` selects what
happens when gradients/hessians/scores stop being finite:

  * ``raise`` — abort with an actionable error naming the iteration;
  * ``skip_iteration`` — log one warning, drop the iteration (no tree
    is built from the poisoned batch), continue training;
  * ``clamp`` — zero the non-finite gradient/hessian entries (the
    poisoned rows drop out of the tree's sufficient statistics, like an
    out-of-bag row) and continue.

The check is ONE device-side scalar reduction (`sum(g)+sum(h)+sum(s)`
is finite iff every element is, modulo sum overflow — which is itself a
diagnosis) and one host sync per iteration.  Activating a policy keeps
training on the eager per-stage path: the fused single-program
iteration cannot surface a mid-program verdict to the host without
breaking its one-dispatch contract (models/boosting.py gates on this).
"""

from __future__ import annotations

from typing import Optional

from ..utils import log
from ..utils.log import LightGBMError

POLICIES = ("raise", "skip_iteration", "clamp")
_OFF = ("", "none", "off")


class NonFiniteGuard:
    """Per-iteration finiteness check over (grad, hess, scores)."""

    def __init__(self, policy: str):
        if policy not in POLICIES:
            log.fatal("Unknown nonfinite_policy %s (expected one of %s)",
                      policy, "|".join(POLICIES))
        self.policy = policy
        self.skipped_iterations = []
        self.clamped_iterations = []

    @classmethod
    def from_config(cls, config) -> Optional["NonFiniteGuard"]:
        policy = str(getattr(config, "nonfinite_policy", "none")).lower()
        if policy in _OFF:
            return None
        return cls(policy)

    def filter(self, iteration: int, grad, hess, scores=None):
        """Returns (grad, hess, skip).  ``skip`` True means the caller
        must drop this boosting iteration entirely."""
        import jax.numpy as jnp
        total = jnp.sum(grad) + jnp.sum(hess)
        if scores is not None:
            total = total + jnp.sum(scores)
        if bool(jnp.isfinite(total)):
            return grad, hess, False
        if self.policy == "raise":
            raise LightGBMError(
                f"non-finite gradients/hessians/scores at iteration "
                f"{iteration}: the input batch, a custom objective, or an "
                f"exploding learning_rate produced NaN/Inf.  Set "
                f"nonfinite_policy=skip_iteration or clamp to degrade "
                f"gracefully instead of aborting.")
        if self.policy == "skip_iteration":
            log.warning("nonfinite_policy=skip_iteration: non-finite "
                        "gradients/hessians/scores at iteration %d; "
                        "skipping this boosting iteration", iteration)
            self.skipped_iterations.append(int(iteration))
            return grad, hess, True
        # clamp: zero the poisoned entries so the affected rows drop out
        # of the tree's sufficient statistics (like out-of-bag rows)
        log.warning("nonfinite_policy=clamp: non-finite gradient/hessian "
                    "entries at iteration %d zeroed", iteration)
        self.clamped_iterations.append(int(iteration))
        grad = jnp.where(jnp.isfinite(grad), grad, 0.0)
        hess = jnp.where(jnp.isfinite(hess), hess, 0.0)
        return grad, hess, False
