"""Deterministic fault injection for testing the robustness runtime.

Test-only: nothing here is imported on the hot path unless an injection
is armed (`is_active()` is a plain module-bool check).  Six fault
classes cover the runtime's failure surface:

  * ``kill_at_iteration=k`` — raise ``TrainingKilled`` at the top of
    boosting iteration k (simulated process death / preemption; the
    engine never catches it);
  * ``corrupt_gradients_at=k`` — overwrite the head of the gradient
    batch with NaN at iteration k (a poisoned input batch), exercising
    every ``nonfinite_policy``;
  * ``fail_bootstrap_attempts=n`` — fail the first n distributed
    bootstrap attempts with a retriable connection error, exercising
    the backoff path in ``parallel/network.py``;
  * ``fail_predict_model=name, fail_predict_times=n`` — the next n
    serve-plane dispatches of model ``name`` (any model when name is
    None) raise ``InjectedPredictError``: drives circuit-breaker trip
    / half-open probe / recovery drills;
  * ``slow_predict_model=name, slow_predict_seconds=s,
    slow_predict_times=n`` — the next n dispatches of ``name`` stall
    ``s`` seconds ON THE INJECTED CLOCK (drills pair a ManualClock so
    the stall is virtual — deadline-shed drills never sleep);
  * ``flood_tenant=t, flood_requests=n`` — a one-shot queue-flood spec
    the serve drill harness consumes (``take_flood``) to submit a
    deterministic burst that overruns the tenant's bounded queue.

Injections are process-local and explicit (no env vars): tests call
``inject(...)`` / ``clear()``, or use the ``injected(...)`` context
manager which always clears.

Concurrency contract (conlint tier C): module state is deliberately
lock-free.  Arming/clearing happens on the test thread BEFORE the
threads under test run (the drills are single-threaded on a manual
clock; the schedule explorer serializes its threads cooperatively),
and each hot-path check is a single GIL-atomic module-global read —
a lock here would put a blocking point inside every dispatch for state
that is never mutated concurrently with it.
"""

from __future__ import annotations

import contextlib
from typing import Optional, Tuple

_active = False
_kill_at: Optional[int] = None
_corrupt_at: Optional[int] = None
_corrupt_rows = 16
_fail_bootstrap_remaining = 0
bootstrap_attempts_seen = 0
_fail_predict_model: Optional[str] = None
_fail_predict_remaining = 0
_slow_predict_model: Optional[str] = None
_slow_predict_seconds = 0.0
_slow_predict_remaining = 0
_flood: Optional[Tuple[str, int]] = None


class TrainingKilled(RuntimeError):
    """Simulated process death mid-training (fault injection only)."""


class InjectedBootstrapError(ConnectionError):
    """Retriable injected failure of a distributed bootstrap attempt."""


class InjectedPredictError(RuntimeError):
    """Injected failure of a serve-plane model dispatch (fault
    injection only; drives the circuit-breaker drills)."""


def inject(kill_at_iteration: Optional[int] = None,
           corrupt_gradients_at: Optional[int] = None,
           corrupt_rows: int = 16,
           fail_bootstrap_attempts: int = 0,
           fail_predict_model: Optional[str] = None,
           fail_predict_times: int = 0,
           slow_predict_model: Optional[str] = None,
           slow_predict_seconds: float = 0.0,
           slow_predict_times: int = 0,
           flood_tenant: Optional[str] = None,
           flood_requests: int = 0) -> None:
    """Arm one or more fault injections (iteration indices are 0-based,
    matching ``GBDT.iter`` at the top of the iteration)."""
    global _active, _kill_at, _corrupt_at, _corrupt_rows
    global _fail_bootstrap_remaining, bootstrap_attempts_seen
    global _fail_predict_model, _fail_predict_remaining
    global _slow_predict_model, _slow_predict_seconds
    global _slow_predict_remaining, _flood
    _kill_at = kill_at_iteration
    _corrupt_at = corrupt_gradients_at
    _corrupt_rows = int(corrupt_rows)
    _fail_bootstrap_remaining = int(fail_bootstrap_attempts)
    bootstrap_attempts_seen = 0
    _fail_predict_model = fail_predict_model
    _fail_predict_remaining = int(fail_predict_times)
    _slow_predict_model = slow_predict_model
    _slow_predict_seconds = float(slow_predict_seconds)
    _slow_predict_remaining = int(slow_predict_times)
    _flood = ((str(flood_tenant), int(flood_requests))
              if flood_requests > 0 else None)
    _active = (_kill_at is not None or _corrupt_at is not None
               or _fail_bootstrap_remaining > 0
               or _fail_predict_remaining > 0
               or _slow_predict_remaining > 0
               or _flood is not None)


def clear() -> None:
    global _active, _kill_at, _corrupt_at, _fail_bootstrap_remaining
    global _fail_predict_model, _fail_predict_remaining
    global _slow_predict_model, _slow_predict_remaining, _flood
    _active = False
    _kill_at = None
    _corrupt_at = None
    _fail_bootstrap_remaining = 0
    _fail_predict_model = None
    _fail_predict_remaining = 0
    _slow_predict_model = None
    _slow_predict_remaining = 0
    _flood = None


def is_active() -> bool:
    return _active


@contextlib.contextmanager
def injected(**kwargs):
    inject(**kwargs)
    try:
        yield
    finally:
        clear()


def maybe_kill(iteration: int) -> None:
    if _active and _kill_at is not None and iteration == _kill_at:
        raise TrainingKilled(
            f"fault injection: training killed at iteration {iteration}")


def maybe_corrupt_gradients(iteration: int, grad, hess):
    """Return (grad, hess) with the head of the batch NaN-poisoned when
    this iteration is the armed corruption target."""
    if not (_active and _corrupt_at is not None and iteration == _corrupt_at):
        return grad, hess
    import jax.numpy as jnp
    n = min(_corrupt_rows, int(grad.shape[0]))
    grad = jnp.asarray(grad).at[:n].set(jnp.nan)
    hess = jnp.asarray(hess).at[:n].set(jnp.nan)
    return grad, hess


def maybe_fail_bootstrap() -> None:
    global _fail_bootstrap_remaining, bootstrap_attempts_seen
    if not _active:
        return
    bootstrap_attempts_seen += 1
    if _fail_bootstrap_remaining > 0:
        _fail_bootstrap_remaining -= 1
        raise InjectedBootstrapError(
            "fault injection: bootstrap attempt failed "
            f"({_fail_bootstrap_remaining} injected failures remaining)")


def maybe_fail_predict(model: str) -> None:
    """Raise ``InjectedPredictError`` when a failing-model injection is
    armed for ``model`` (or for any model)."""
    global _fail_predict_remaining
    if not (_active and _fail_predict_remaining > 0):
        return
    if _fail_predict_model is not None and _fail_predict_model != model:
        return
    _fail_predict_remaining -= 1
    raise InjectedPredictError(
        f"fault injection: predict failed for model {model!r} "
        f"({_fail_predict_remaining} injected failures remaining)")


def predict_fault_armed(model: str) -> bool:
    """True when a fail- or slow-predict injection would fire for
    ``model``, WITHOUT consuming the injection budget.  The serving
    cohort fast path uses this to degrade a wave to the per-model
    dispatch path — where the counted injection then fires exactly
    once and breaker policy owns it — so arming N failures produces N
    recorded failures whether or not cohort lanes are on."""
    if not _active:
        return False
    if _fail_predict_remaining > 0 and (
            _fail_predict_model is None or _fail_predict_model == model):
        return True
    return _slow_predict_remaining > 0 and (
        _slow_predict_model is None or _slow_predict_model == model)


def maybe_slow_predict(model: str) -> float:
    """Seconds of injected stall for this dispatch of ``model`` (0.0
    when no slow-predict injection matches).  The CALLER advances its
    clock — with a ManualClock the stall is virtual, never a sleep."""
    global _slow_predict_remaining
    if not (_active and _slow_predict_remaining > 0):
        return 0.0
    if _slow_predict_model is not None and _slow_predict_model != model:
        return 0.0
    _slow_predict_remaining -= 1
    return _slow_predict_seconds


def take_flood() -> Optional[Tuple[str, int]]:
    """One-shot (tenant, request_count) queue-flood spec, or None.
    Consumed by the serve drill harness, which submits the burst —
    keeping the injector host-only and the service path clean."""
    global _flood
    if not _active or _flood is None:
        return None
    spec, _flood = _flood, None
    return spec
