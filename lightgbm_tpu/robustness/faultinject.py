"""Deterministic fault injection for testing the robustness runtime.

Test-only: nothing here is imported on the hot path unless an injection
is armed (`is_active()` is a plain module-bool check).  Three fault
classes cover the runtime's failure surface:

  * ``kill_at_iteration=k`` — raise ``TrainingKilled`` at the top of
    boosting iteration k (simulated process death / preemption; the
    engine never catches it);
  * ``corrupt_gradients_at=k`` — overwrite the head of the gradient
    batch with NaN at iteration k (a poisoned input batch), exercising
    every ``nonfinite_policy``;
  * ``fail_bootstrap_attempts=n`` — fail the first n distributed
    bootstrap attempts with a retriable connection error, exercising
    the backoff path in ``parallel/network.py``.

Injections are process-local and explicit (no env vars): tests call
``inject(...)`` / ``clear()``, or use the ``injected(...)`` context
manager which always clears.
"""

from __future__ import annotations

import contextlib
from typing import Optional

_active = False
_kill_at: Optional[int] = None
_corrupt_at: Optional[int] = None
_corrupt_rows = 16
_fail_bootstrap_remaining = 0
bootstrap_attempts_seen = 0


class TrainingKilled(RuntimeError):
    """Simulated process death mid-training (fault injection only)."""


class InjectedBootstrapError(ConnectionError):
    """Retriable injected failure of a distributed bootstrap attempt."""


def inject(kill_at_iteration: Optional[int] = None,
           corrupt_gradients_at: Optional[int] = None,
           corrupt_rows: int = 16,
           fail_bootstrap_attempts: int = 0) -> None:
    """Arm one or more fault injections (iteration indices are 0-based,
    matching ``GBDT.iter`` at the top of the iteration)."""
    global _active, _kill_at, _corrupt_at, _corrupt_rows
    global _fail_bootstrap_remaining, bootstrap_attempts_seen
    _kill_at = kill_at_iteration
    _corrupt_at = corrupt_gradients_at
    _corrupt_rows = int(corrupt_rows)
    _fail_bootstrap_remaining = int(fail_bootstrap_attempts)
    bootstrap_attempts_seen = 0
    _active = (_kill_at is not None or _corrupt_at is not None
               or _fail_bootstrap_remaining > 0)


def clear() -> None:
    global _active, _kill_at, _corrupt_at, _fail_bootstrap_remaining
    _active = False
    _kill_at = None
    _corrupt_at = None
    _fail_bootstrap_remaining = 0


def is_active() -> bool:
    return _active


@contextlib.contextmanager
def injected(**kwargs):
    inject(**kwargs)
    try:
        yield
    finally:
        clear()


def maybe_kill(iteration: int) -> None:
    if _active and _kill_at is not None and iteration == _kill_at:
        raise TrainingKilled(
            f"fault injection: training killed at iteration {iteration}")


def maybe_corrupt_gradients(iteration: int, grad, hess):
    """Return (grad, hess) with the head of the batch NaN-poisoned when
    this iteration is the armed corruption target."""
    if not (_active and _corrupt_at is not None and iteration == _corrupt_at):
        return grad, hess
    import jax.numpy as jnp
    n = min(_corrupt_rows, int(grad.shape[0]))
    grad = jnp.asarray(grad).at[:n].set(jnp.nan)
    hess = jnp.asarray(hess).at[:n].set(jnp.nan)
    return grad, hess


def maybe_fail_bootstrap() -> None:
    global _fail_bootstrap_remaining, bootstrap_attempts_seen
    if not _active:
        return
    bootstrap_attempts_seen += 1
    if _fail_bootstrap_remaining > 0:
        _fail_bootstrap_remaining -= 1
        raise InjectedBootstrapError(
            "fault injection: bootstrap attempt failed "
            f"({_fail_bootstrap_remaining} injected failures remaining)")
