"""Fault-tolerant training runtime.

Production posture for multi-hour boosting runs on preemptible TPU pods
(ROADMAP north star): the reference C++ stack assumes a reliable process
and reliable socket/MPI peers, which large-TPU practice does not grant.
This package supplies the pieces the training path is wired through:

  * ``checkpoint`` — iteration-level snapshots (model text + RNG/score
    state + eval history) with atomic write-to-temp-then-rename,
    keep-last-K retention and a bit-exact ``train(..., resume=True)``
    path (the TPU analog of the reference's ``snapshot_freq`` model
    dumps, gbdt.cpp:244-248, which save only the model and cannot
    resume bit-exact);
  * ``guard`` — per-iteration non-finite guard rails over
    gradients/hessians/scores (``nonfinite_policy=raise|skip_iteration|
    clamp``), one cheap device-side reduction per iteration;
  * ``retry`` — exponential-backoff-with-deadline used to harden the
    ``jax.distributed`` bootstrap in ``parallel/network.py``;
  * ``faultinject`` — a test-only deterministic fault injector (kill at
    iteration k, corrupt a gradient batch, fail the first N bootstrap
    attempts) so every behavior above is exercised in tier-1 tests.
"""

from .checkpoint import (CheckpointCallback, CheckpointManager,
                         CheckpointState, capture_training_state,
                         restore_training_state)
from .guard import NonFiniteGuard
from .retry import retry_with_backoff

__all__ = [
    "CheckpointCallback", "CheckpointManager", "CheckpointState",
    "capture_training_state", "restore_training_state",
    "NonFiniteGuard", "retry_with_backoff",
]
