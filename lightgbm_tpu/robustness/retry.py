"""Exponential backoff with a deadline — deterministically replayable.

The distributed bootstrap (``jax.distributed.initialize``), the
continual-training runtime's background retrains, and anything else
that talks to a flaky dependency retries through here; the policy is
the standard large-TPU one (cf. PAPERS.md, Gemma-on-TPU ops practice):
capped exponential backoff with optional jitter, a deadline, and a
clear terminal error instead of a hang.

Every source of nondeterminism is threaded explicitly so fault-
injection replays (kill + resume drills) are bit-reproducible:

* the delay sequence is a PURE function of the policy arguments —
  :func:`backoff_schedule` — with jitter drawn from a SEEDED stream,
  never from process-global randomness;
* elapsed time for the deadline check comes from an injectable
  ``clock`` (default ``time.monotonic``), so tests that stub ``sleep``
  pair it with a :class:`ManualClock` and the out-of-budget decision
  depends only on the scheduled delays, not on how long the attempt
  bodies really took on the wall clock.
"""

from __future__ import annotations

import random
import time
from typing import Callable, List, Optional, Tuple, Type

from ..utils import log
from ..utils.log import LightGBMError


class ManualClock:
    """A virtual clock for deterministic retry replays: ``clock()``
    returns the accumulated virtual time and ``sleep(d)`` advances it —
    pass both to :func:`retry_with_backoff` and the whole retry
    schedule (including the deadline cut-off) replays identically on
    every run, however long the attempts themselves take."""

    def __init__(self, start: float = 0.0):
        self.now = float(start)

    def __call__(self) -> float:
        return self.now

    def sleep(self, seconds: float) -> None:
        self.now += float(seconds)


def backoff_schedule(attempts: int, base_delay: float = 1.0,
                     max_delay: float = 30.0, jitter: float = 0.0,
                     seed: int = 0,
                     deadline: Optional[float] = None) -> List[float]:
    """The exact delay sequence a :func:`retry_with_backoff` call will
    use: capped exponential, times ``1 + jitter * u_i`` with ``u_i``
    drawn from ``random.Random(seed)``.  A pure function of its
    arguments — two calls with the same arguments return the same
    floats, which is what makes kill+resume fault drills replayable.

    ``deadline`` is an overall retry budget in seconds: the schedule
    truncates at the first delay whose CUMULATIVE sleep time would
    cross it, so ``len(schedule)`` reports how many retry sleeps the
    budget affords (the consumer makes ``len(schedule) + 1`` attempts
    at most).  Jitter draws stay positionally identical with or
    without a deadline — truncation never re-rolls the stream, so
    tightening a budget cannot silently change the surviving delays."""
    rnd = random.Random(int(seed))
    out: List[float] = []
    total = 0.0
    for attempt in range(1, max(int(attempts), 1) + 1):
        d = min(base_delay * (2.0 ** (attempt - 1)), max_delay)
        if jitter > 0.0:
            d *= 1.0 + float(jitter) * rnd.random()
        if deadline is not None and total + d > float(deadline):
            break
        total += d
        out.append(d)
    return out


def retry_with_backoff(fn: Callable,
                       attempts: int = 5,
                       base_delay: float = 1.0,
                       max_delay: float = 30.0,
                       deadline: Optional[float] = None,
                       retriable: Tuple[Type[BaseException], ...] = (
                           RuntimeError, OSError, ConnectionError,
                           TimeoutError),
                       fatal_if: Optional[Callable[[BaseException], bool]]
                       = None,
                       describe: str = "operation",
                       sleep: Callable[[float], None] = time.sleep,
                       jitter: float = 0.0,
                       seed: int = 0,
                       clock: Callable[[], float] = time.monotonic):
    """Call ``fn`` until it succeeds, a non-retriable error escapes, the
    attempt budget runs out, or the next delay would cross ``deadline``
    seconds of total elapsed time (as measured by ``clock``).
    ``fatal_if(exc)`` short-circuits retrying for errors that can never
    heal (e.g. "already initialized").  Delays come from
    :func:`backoff_schedule` — jitter is seeded, never wall-clock, so a
    replay with the same (attempts, base_delay, max_delay, jitter,
    seed) sleeps the identical sequence.  Returns ``fn()``'s result;
    raises ``LightGBMError`` on exhaustion with the last underlying
    error chained."""
    # the deadline prunes the schedule STATICALLY (how many sleeps the
    # budget affords at all) and is re-checked DYNAMICALLY below
    # (attempt bodies consume budget the schedule cannot know about)
    delays = backoff_schedule(attempts, base_delay, max_delay,
                              jitter=jitter, seed=seed,
                              deadline=deadline)
    start = clock()
    last: Optional[BaseException] = None
    attempt = 0
    for attempt in range(1, max(int(attempts), 1) + 1):
        try:
            return fn()
        except retriable as exc:
            if fatal_if is not None and fatal_if(exc):
                raise
            last = exc
            elapsed = clock() - start
            out_of_budget = attempt >= attempts or attempt > len(delays)
            if not out_of_budget:
                delay = delays[attempt - 1]
                out_of_budget = (deadline is not None
                                 and elapsed + delay > deadline)
            if out_of_budget:
                break
            log.warning("%s failed (attempt %d/%d, %.1fs elapsed): %s; "
                        "retrying in %.1fs", describe, attempt, attempts,
                        elapsed, exc, delay)
            sleep(delay)
    elapsed = clock() - start
    raise LightGBMError(
        f"{describe} failed after {attempt} attempt(s) over "
        f"{elapsed:.1f}s (deadline "
        f"{'none' if deadline is None else f'{deadline:.1f}s'}): "
        f"{last}") from last
