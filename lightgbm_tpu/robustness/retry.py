"""Exponential backoff with a deadline.

The distributed bootstrap (``jax.distributed.initialize``) and anything
else that talks to a flaky coordinator retries through here; the policy
is the standard large-TPU one (cf. PAPERS.md, Gemma-on-TPU ops
practice): capped exponential backoff, a wall-clock deadline, and a
clear terminal error instead of a hang.
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Tuple, Type

from ..utils import log
from ..utils.log import LightGBMError


def retry_with_backoff(fn: Callable,
                       attempts: int = 5,
                       base_delay: float = 1.0,
                       max_delay: float = 30.0,
                       deadline: Optional[float] = None,
                       retriable: Tuple[Type[BaseException], ...] = (
                           RuntimeError, OSError, ConnectionError,
                           TimeoutError),
                       fatal_if: Optional[Callable[[BaseException], bool]]
                       = None,
                       describe: str = "operation",
                       sleep: Callable[[float], None] = time.sleep):
    """Call ``fn`` until it succeeds, a non-retriable error escapes, the
    attempt budget runs out, or the next delay would cross ``deadline``
    seconds of total elapsed time.  ``fatal_if(exc)`` short-circuits
    retrying for errors that can never heal (e.g. "already initialized").
    Returns ``fn()``'s result; raises ``LightGBMError`` on exhaustion
    with the last underlying error chained."""
    start = time.monotonic()
    last: Optional[BaseException] = None
    attempt = 0
    for attempt in range(1, max(int(attempts), 1) + 1):
        try:
            return fn()
        except retriable as exc:
            if fatal_if is not None and fatal_if(exc):
                raise
            last = exc
            elapsed = time.monotonic() - start
            delay = min(base_delay * (2.0 ** (attempt - 1)), max_delay)
            out_of_budget = attempt >= attempts or (
                deadline is not None and elapsed + delay > deadline)
            if out_of_budget:
                break
            log.warning("%s failed (attempt %d/%d, %.1fs elapsed): %s; "
                        "retrying in %.1fs", describe, attempt, attempts,
                        elapsed, exc, delay)
            sleep(delay)
    elapsed = time.monotonic() - start
    raise LightGBMError(
        f"{describe} failed after {attempt} attempt(s) over "
        f"{elapsed:.1f}s: {last}") from last
