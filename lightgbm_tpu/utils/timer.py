"""Per-phase wall-clock accounting (reference: Common::Timer /
FunctionTimer + the -DUSE_TIMETAG global_timer, include/LightGBM/utils/
common.h:973-1060): every hot phase is annotated and an aggregate table is
printed at shutdown.

Enabled by LIGHTGBM_TPU_TIMETAG=1 (the runtime analog of the reference's
compile-time flag).  When enabled, device work is synchronized at section
ends so phases are attributed correctly despite XLA's async dispatch; a
`jax.profiler` trace can additionally be captured with
LIGHTGBM_TPU_PROFILE_DIR=<dir> for TensorBoard.
"""

from __future__ import annotations

import atexit
import os
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict

__all__ = ["global_timer", "timed"]


class GlobalTimer:
    def __init__(self):
        self.enabled = os.environ.get("LIGHTGBM_TPU_TIMETAG", "") == "1"
        self.profile_dir = os.environ.get("LIGHTGBM_TPU_PROFILE_DIR", "")
        self._acc: Dict[str, float] = defaultdict(float)
        self._cnt: Dict[str, int] = defaultdict(int)
        self._started_profile = False
        if self.enabled:
            atexit.register(self.print_table)
        if self.profile_dir:
            self._start_profiler()

    def _start_profiler(self):
        try:
            import jax
            jax.profiler.start_trace(self.profile_dir)
            self._started_profile = True
            atexit.register(self._stop_profiler)
        except Exception:
            pass

    def _stop_profiler(self):
        if self._started_profile:
            import jax
            jax.profiler.stop_trace()
            self._started_profile = False

    @contextmanager
    def section(self, name: str, sync=None):
        """Accumulate wall time under `name`; `sync` is an optional value
        whose device computation is waited on before stopping the clock."""
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if sync is not None:
                try:
                    import jax
                    jax.block_until_ready(sync() if callable(sync) else sync)
                except Exception:
                    pass
            self._acc[name] += time.perf_counter() - t0
            self._cnt[name] += 1

    def print_table(self):
        if not self._acc:
            return
        from . import log
        width = max(len(k) for k in self._acc)
        log.info("%-*s %12s %8s", width, "phase", "seconds", "calls")
        for name, sec in sorted(self._acc.items(), key=lambda kv: -kv[1]):
            log.info("%-*s %12.3f %8d", width, name, sec, self._cnt[name])

    def reset(self):
        self._acc.clear()
        self._cnt.clear()


global_timer = GlobalTimer()


def timed(name: str):
    """Decorator form (reference: FunctionTimer RAII)."""
    def wrap(fn):
        if not global_timer.enabled:
            return fn

        def inner(*a, **kw):
            with global_timer.section(name):
                return fn(*a, **kw)
        return inner
    return wrap
