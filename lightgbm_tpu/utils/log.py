"""Logging for lightgbm_tpu.

TPU-native re-design of the reference logger (include/LightGBM/utils/log.h:88-178):
levels Debug/Info/Warning/Fatal, a pluggable callback, and ``Fatal`` raising an
exception instead of aborting the process.
"""

from __future__ import annotations

import sys
from typing import Callable, Optional


class LightGBMError(RuntimeError):
    """Error raised by the framework (reference: Log::Fatal -> std::runtime_error)."""


# Verbosity levels mirror the reference config `verbosity`:
#   <0 = Fatal only, 0 = Error/Warning, 1 = Info, >1 = Debug
_LEVEL_FATAL = -1
_LEVEL_WARNING = 0
_LEVEL_INFO = 1
_LEVEL_DEBUG = 2

_verbosity: int = 1
_callback: Optional[Callable[[str], None]] = None


def set_verbosity(level: int) -> None:
    global _verbosity
    _verbosity = int(level)


def get_verbosity() -> int:
    return _verbosity


def register_callback(cb: Optional[Callable[[str], None]]) -> None:
    """Reference: LGBM_RegisterLogCallback / Log::ResetCallBack."""
    global _callback
    _callback = cb


def register_logger(logger, info_method_name: str = "info",
                    warning_method_name: str = "warning") -> None:
    """Route all framework output through a `logging.Logger`-like object
    (reference: lightgbm.register_logger, basic.py:134-180)."""
    if not callable(getattr(logger, info_method_name, None)) or \
            not callable(getattr(logger, warning_method_name, None)):
        raise TypeError(
            f"logger must provide callable {info_method_name}() and "
            f"{warning_method_name}() methods")
    info_fn = getattr(logger, info_method_name)
    warn_fn = getattr(logger, warning_method_name)

    def _cb(msg: str) -> None:
        text = msg.rstrip("\n")
        if "[Warning]" in text or "[Fatal]" in text:
            warn_fn(text)
        else:
            info_fn(text)

    register_callback(_cb)


def _emit(msg: str) -> None:
    if _callback is not None:
        _callback(msg + "\n")
    else:
        print(msg, file=sys.stderr, flush=True)


def debug(msg: str, *args) -> None:
    if _verbosity >= _LEVEL_DEBUG:
        _emit("[LightGBM] [Debug] " + (msg % args if args else msg))


def info(msg: str, *args) -> None:
    if _verbosity >= _LEVEL_INFO:
        _emit("[LightGBM] [Info] " + (msg % args if args else msg))


def warning(msg: str, *args) -> None:
    if _verbosity >= _LEVEL_WARNING:
        _emit("[LightGBM] [Warning] " + (msg % args if args else msg))


def fatal(msg: str, *args) -> None:
    text = msg % args if args else msg
    _emit("[LightGBM] [Fatal] " + text)
    raise LightGBMError(text)
