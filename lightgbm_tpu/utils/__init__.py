"""Subpackage init."""
