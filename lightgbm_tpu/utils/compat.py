"""JAX version compatibility shims.

The distributed plane is written against the current `jax.shard_map`
with its vma ("varying-mesh-axes") type system; older runtimes (< 0.5)
only ship `jax.experimental.shard_map.shard_map` with the `check_rep`
static check and no vma marking.  Production fleets pin old runtimes
for months, so the training path degrades instead of crashing with
``AttributeError: module 'jax' has no attribute 'shard_map'``:

  * `shard_map(...)` resolves the best available implementation and
    translates `check_vma` (new) to `check_rep=False` (old — the vma
    annotations the programs rely on don't exist there, so the static
    replication check must be off to avoid spurious rejections);
  * `mark_device_varying(x, axis)` is the vma marking when the runtime
    has it (`jax.lax.pcast`) and the identity otherwise.
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    """Version-portable `jax.shard_map` (keyword-compatible with the
    `functools.partial(..., mesh=..., check_vma=...)` call sites)."""
    if hasattr(jax, "shard_map"):
        kw = {} if check_vma is None else {"check_vma": check_vma}
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)


def mark_device_varying(x, axis_name: str):
    """vma marking for loop carries initialized from constants; identity
    on runtimes without the vma type system (their shard_map runs with
    the static check disabled, see `shard_map` above)."""
    if not hasattr(jax, "typeof") or not hasattr(jax.lax, "pcast"):
        return x

    def mark(a):
        vma = getattr(jax.typeof(a), "vma", frozenset())
        if axis_name in vma:
            return a
        return jax.lax.pcast(a, (axis_name,), to="varying")

    return jax.tree.map(mark, x)
