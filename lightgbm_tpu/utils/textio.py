"""Text data file loading: CSV / TSV / LibSVM with auto-detection.

TPU-native re-implementation of the reference parser + loader semantics
(src/io/parser.cpp CreateParser auto-detect, src/io/dataset_loader.cpp
LoadFromFile / SetHeader label/weight/group/ignore column handling).
"""

from __future__ import annotations

import io
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["load_text_file", "parse_column_spec", "LoadedFile"]


class LoadedFile:
    def __init__(self, X, label, weight, group, feature_names):
        self.X = X
        self.label = label
        self.weight = weight
        self.group = group
        self.feature_names = feature_names


def parse_column_spec(spec: str, header_names: Optional[List[str]]) -> int:
    """Resolve a column spec: an index, or ``name:<column_name>``
    (reference: dataset_loader.cpp SetHeader:70-180). Returns -1 if unset."""
    if spec is None or spec == "":
        return -1
    spec = str(spec)
    if spec.startswith("name:"):
        name = spec[5:]
        if not header_names:
            raise ValueError(
                f"Cannot resolve column 'name:{name}' without a header")
        if name not in header_names:
            raise ValueError(f"Column '{name}' not found in header")
        return header_names.index(name)
    return int(spec)


def _parse_ignore_spec(spec: str, header_names) -> List[int]:
    if not spec:
        return []
    spec = str(spec)
    if spec.startswith("name:"):
        names = spec[5:].split(",")
        if not header_names:
            raise ValueError("ignore_column by name requires a header")
        return [header_names.index(n) for n in names if n in header_names]
    return [int(x) for x in spec.split(",") if x.strip() != ""]


def _detect_format(sample_lines: List[str]) -> Tuple[str, str]:
    """Returns (kind, sep) with kind in {'libsvm','delim'}.
    reference: parser.cpp GetDelimiter/DetermineDataType."""
    for line in sample_lines:
        toks = line.split()
        if any(":" in t for t in toks[1:]):
            # index:value pairs after the label → LibSVM
            if all(":" in t for t in toks[1:] if t):
                return "libsvm", " "
    line = sample_lines[0]
    for sep in ("\t", ",", " ", ";"):
        if sep in line:
            return "delim", sep
    return "delim", ","


def _is_number(tok: str) -> bool:
    try:
        float(tok)
        return True
    except ValueError:
        return False


def load_text_file(path: str, *, has_header: bool = False,
                   label_column: str = "", weight_column: str = "",
                   group_column: str = "", ignore_column: str = "",
                   max_rows: Optional[int] = None) -> LoadedFile:
    """Load a CSV/TSV/LibSVM file into a dense matrix + metadata columns.

    The file is read once as bytes; the native parser consumes the raw
    buffer directly (no per-line or re-encoded copies on the hot path)."""
    with open(path, "rb") as fh:
        raw = fh.read()
    # decode only a small probe for header/format detection
    probe_text = raw[:65536].decode("utf-8", errors="replace")
    probe_lines = [ln for ln in probe_text.split("\n") if ln.strip() != ""]
    if not probe_lines:
        raise ValueError(f"Empty data file: {path}")

    header_names: Optional[List[str]] = None
    data_start = 0
    first_line = probe_lines[0]
    probe = first_line.replace(",", " ").replace("\t", " ").split()
    header_detected = has_header or not all(
        _is_number(t) or ":" in t for t in probe)
    if header_detected:
        sep0 = "\t" if "\t" in first_line else \
            ("," if "," in first_line else " ")
        header_names = [c.strip() for c in first_line.split(sep0)]
        nl = raw.find(b"\n")
        data_start = nl + 1 if nl >= 0 else len(raw)
    kind, sep = _detect_format(
        probe_lines[1:101] if header_detected else probe_lines[:100])

    label_idx = parse_column_spec(label_column, header_names)
    if label_idx < 0:
        label_idx = 0  # reference default: first column is the label
    weight_idx = parse_column_spec(weight_column, header_names)
    group_idx = parse_column_spec(group_column, header_names)
    ignore = set(_parse_ignore_spec(ignore_column, header_names))

    data = raw[data_start:]
    if max_rows is not None:
        # keep only the first max_rows non-empty lines
        kept, cnt, pos = [], 0, 0
        while cnt < max_rows and pos < len(data):
            nl = data.find(b"\n", pos)
            end = nl if nl >= 0 else len(data)
            if data[pos:end].strip():
                cnt += 1
            pos = end + 1 if nl >= 0 else len(data)
        data = data[:pos]

    if kind == "libsvm":
        return _load_libsvm(data, weight_idx, group_idx)

    # hot path: the native C++ parser (multi-threaded, ctypes; reference
    # analog: src/io/parser.cpp CSVParser::ParseOneLine), with the Python
    # loop as fallback
    from ..native import parse_delim
    mat = parse_delim(data, sep)
    if mat is None:
        data_lines = [ln for ln in data.decode("utf-8", errors="replace")
                      .split("\n") if ln.strip() != ""]
        rows = [ln.split(sep) for ln in data_lines]
        ncol = max(len(r) for r in rows)
        mat = np.full((len(rows), ncol), np.nan, dtype=np.float64)
        for i, r in enumerate(rows):
            for j, tok in enumerate(r):
                tok = tok.strip()
                if tok == "" or tok.lower() in ("na", "nan", "null", "none"):
                    continue
                try:
                    mat[i, j] = float(tok)
                except ValueError:
                    mat[i, j] = np.nan
    ncol = mat.shape[1]

    label = mat[:, label_idx].copy()
    weight = mat[:, weight_idx].copy() if weight_idx >= 0 else None
    group_col = mat[:, group_idx].copy() if group_idx >= 0 else None

    meta_cols = {label_idx} | ignore
    if weight_idx >= 0:
        meta_cols.add(weight_idx)
    if group_idx >= 0:
        meta_cols.add(group_idx)
    feat_cols = [j for j in range(ncol) if j not in meta_cols]
    X = mat[:, feat_cols]
    feature_names = None
    if header_names:
        feature_names = [header_names[j] for j in feat_cols]

    group = None
    if group_col is not None:
        # group column holds a query id per row → convert to group sizes
        # (reference: metadata.cpp SetQueryId)
        ids = group_col
        boundaries = [0]
        for i in range(1, len(ids)):
            if ids[i] != ids[i - 1]:
                boundaries.append(i)
        boundaries.append(len(ids))
        group = np.diff(boundaries).astype(np.int32)

    return LoadedFile(X, label, weight, group, feature_names)


def _qids_to_group(qids: np.ndarray) -> Optional[np.ndarray]:
    """Consecutive qid runs -> group sizes (reference: Metadata::SetQueryId)."""
    if qids is None or np.isnan(qids).all():
        return None
    boundaries = [0]
    for i in range(1, len(qids)):
        if qids[i] != qids[i - 1]:
            boundaries.append(i)
    boundaries.append(len(qids))
    return np.diff(boundaries).astype(np.int32)


def _load_libsvm(data, weight_idx: int, group_idx: int) -> LoadedFile:
    from ..native import parse_libsvm
    native = parse_libsvm(data)
    if native is not None:
        X, labels, qids = native
        return LoadedFile(X, labels, None, _qids_to_group(qids), None)
    data_lines = [ln for ln in data.decode("utf-8", errors="replace")
                  .split("\n") if ln.strip() != ""]
    labels = np.empty(len(data_lines), dtype=np.float64)
    qids = np.full(len(data_lines), np.nan)
    entries: List[List[Tuple[int, float]]] = []
    max_feat = -1
    for i, ln in enumerate(data_lines):
        toks = ln.split()
        labels[i] = float(toks[0])
        row = []
        for t in toks[1:]:
            if ":" not in t:
                continue
            k, v = t.split(":", 1)
            if k == "qid":
                qids[i] = float(v)
                continue
            try:
                j = int(k)
            except ValueError:   # malformed key: skip, like the native path
                continue
            row.append((j, float(v)))
            max_feat = max(max_feat, j)
        entries.append(row)
    X = np.zeros((len(data_lines), max_feat + 1), dtype=np.float64)
    for i, row in enumerate(entries):
        for j, v in row:
            X[i, j] = v
    return LoadedFile(X, labels, None, _qids_to_group(qids), None)
