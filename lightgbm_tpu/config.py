"""Parameter system.

TPU-native re-implementation of the reference parameter schema
(include/LightGBM/config.h, src/io/config.cpp, src/io/config_auto.cpp):
the same parameter names, aliases, defaults and validation rules, but held in a
single table-driven Python ``Config`` instead of a generated C++ struct.

The alias table and defaults follow `config_auto.cpp` (GetMembersFromString
/ parameter2aliases); the derived-flag logic follows `Config::Set`
(src/io/config.cpp).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple, Union

from .utils import log

_NO_DEFAULT = object()


@dataclass
class _Param:
    name: str
    default: Any
    typ: type
    aliases: Tuple[str, ...] = ()
    check: Optional[str] = None  # e.g. ">=0.0", ">0", "0.0<=x<=1.0"


def _p(name, default, typ, aliases=(), check=None):
    return _Param(name, default, typ, tuple(aliases), check)


# ---------------------------------------------------------------------------
# Parameter table — mirrors config.h sections: Core / Learning control / IO /
# Objective / Metric / Network / Device.  (reference: include/LightGBM/config.h)
# ---------------------------------------------------------------------------
_PARAMS: List[_Param] = [
    # --- Core ---
    _p("config", "", str, ("config_file",)),
    _p("task", "train", str, ("task_type",)),
    _p("objective", "regression", str,
       ("objective_type", "app", "application", "loss")),
    _p("boosting", "gbdt", str, ("boosting_type", "boost")),
    _p("data_sample_strategy", "bagging", str),
    _p("data", "", str, ("train", "train_data", "train_data_file", "data_filename")),
    _p("valid", "", str, ("test", "valid_data", "valid_data_file", "test_data",
                          "test_data_file", "valid_filenames")),
    _p("num_iterations", 100, int,
       ("num_iteration", "n_iter", "num_tree", "num_trees", "num_round",
        "num_rounds", "nrounds", "num_boost_round", "n_estimators", "max_iter"),
       ">=0"),
    _p("learning_rate", 0.1, float, ("shrinkage_rate", "eta"), ">0.0"),
    _p("num_leaves", 31, int,
       ("num_leaf", "max_leaves", "max_leaf", "max_leaf_nodes"), ">1"),
    _p("tree_learner", "serial", str,
       ("tree", "tree_type", "tree_learner_type")),
    _p("num_threads", 0, int,
       ("num_thread", "nthread", "nthreads", "n_jobs")),
    _p("device_type", "tpu", str, ("device",)),
    _p("seed", None, int, ("random_seed", "random_state")),
    _p("deterministic", False, bool),
    # --- Learning control ---
    _p("force_col_wise", False, bool),
    _p("force_row_wise", False, bool),
    _p("histogram_pool_size", -1.0, float, ("hist_pool_size",)),
    _p("max_depth", -1, int),
    _p("min_data_in_leaf", 20, int,
       ("min_data_per_leaf", "min_data", "min_child_samples", "min_samples_leaf"),
       ">=0"),
    _p("min_sum_hessian_in_leaf", 1e-3, float,
       ("min_sum_hessian_per_leaf", "min_sum_hessian", "min_hessian",
        "min_child_weight"), ">=0.0"),
    _p("bagging_fraction", 1.0, float,
       ("sub_row", "subsample", "bagging"), "0.0<x<=1.0"),
    _p("pos_bagging_fraction", 1.0, float,
       ("pos_sub_row", "pos_subsample", "pos_bagging"), "0.0<x<=1.0"),
    _p("neg_bagging_fraction", 1.0, float,
       ("neg_sub_row", "neg_subsample", "neg_bagging"), "0.0<x<=1.0"),
    _p("bagging_freq", 0, int, ("subsample_freq",)),
    _p("bagging_seed", 3, int, ("bagging_fraction_seed",)),
    _p("bagging_by_query", False, bool),
    _p("feature_fraction", 1.0, float,
       ("sub_feature", "colsample_bytree"), "0.0<x<=1.0"),
    _p("feature_fraction_bynode", 1.0, float,
       ("sub_feature_bynode", "colsample_bynode"), "0.0<x<=1.0"),
    _p("feature_fraction_seed", 2, int),
    _p("extra_trees", False, bool, ("extra_tree",)),
    _p("extra_seed", 6, int),
    _p("early_stopping_round", 0, int,
       ("early_stopping_rounds", "early_stopping", "n_iter_no_change")),
    _p("early_stopping_min_delta", 0.0, float, (), ">=0.0"),
    _p("first_metric_only", False, bool),
    _p("max_delta_step", 0.0, float, ("max_tree_output", "max_leaf_output")),
    _p("lambda_l1", 0.0, float, ("reg_alpha", "l1_regularization"), ">=0.0"),
    _p("lambda_l2", 0.0, float,
       ("reg_lambda", "lambda", "l2_regularization"), ">=0.0"),
    _p("linear_lambda", 0.0, float, (), ">=0.0"),
    _p("min_gain_to_split", 0.0, float, ("min_split_gain",), ">=0.0"),
    _p("drop_rate", 0.1, float, ("rate_drop",), "0.0<=x<=1.0"),
    _p("max_drop", 50, int),
    _p("skip_drop", 0.5, float, (), "0.0<=x<=1.0"),
    _p("xgboost_dart_mode", False, bool),
    _p("uniform_drop", False, bool),
    _p("drop_seed", 4, int),
    _p("top_rate", 0.2, float, (), "0.0<=x<=1.0"),
    _p("other_rate", 0.1, float, (), "0.0<=x<=1.0"),
    _p("min_data_per_group", 100, int, (), ">0"),
    _p("max_cat_threshold", 32, int, (), ">0"),
    _p("cat_l2", 10.0, float, (), ">=0.0"),
    _p("cat_smooth", 10.0, float, (), ">=0.0"),
    _p("max_cat_to_onehot", 4, int, (), ">0"),
    _p("top_k", 20, int, ("topk",), ">0"),
    _p("monotone_constraints", "", str, ("mc", "monotone_constraint")),
    _p("monotone_constraints_method", "basic", str, ("monotone_constraining_method", "mc_method")),
    _p("monotone_penalty", 0.0, float, ("monotone_splits_penalty", "ms_penalty", "mc_penalty"), ">=0.0"),
    _p("feature_contri", "", str, ("feature_contrib", "fc", "fp", "feature_penalty")),
    _p("forcedsplits_filename", "", str, ("fs", "forced_splits_filename", "forced_splits_file", "forced_splits")),
    _p("refit_decay_rate", 0.9, float, (), "0.0<=x<=1.0"),
    _p("cegb_tradeoff", 1.0, float, (), ">=0.0"),
    _p("cegb_penalty_split", 0.0, float, (), ">=0.0"),
    _p("cegb_penalty_feature_lazy", "", str),
    _p("cegb_penalty_feature_coupled", "", str),
    _p("path_smooth", 0.0, float, (), ">=0.0"),
    _p("interaction_constraints", "", str),
    _p("verbosity", 1, int, ("verbose",)),
    _p("input_model", "", str, ("model_input", "model_in")),
    _p("output_model", "LightGBM_model.txt", str,
       ("model_output", "model_out")),
    _p("saved_feature_importance_type", 0, int),
    _p("snapshot_freq", -1, int, ("save_period",)),
    # --- Robustness (new in this framework; lightgbm_tpu/robustness/) ---
    # iteration-level checkpointing: every checkpoint_interval iterations
    # the full training state (model text + scores + RNG streams + eval
    # history) is written atomically under checkpoint_dir, keeping the
    # newest checkpoint_keep snapshots; train(resume=True) (or
    # checkpoint_resume=true) continues bit-exact from the latest one
    _p("checkpoint_dir", "", str, ("checkpoint_directory",)),
    _p("checkpoint_interval", 0, int, ("checkpoint_freq",), ">=0"),
    _p("checkpoint_keep", 2, int, ("checkpoint_keep_last",), ">0"),
    _p("checkpoint_resume", False, bool, ("resume_from_checkpoint",)),
    # what to do when gradients/hessians/scores stop being finite:
    # none (no checks) | raise | skip_iteration | clamp
    _p("nonfinite_policy", "none", str, ("non_finite_policy",)),
    # distributed bootstrap hardening (parallel/network.py): retry
    # attempts around jax.distributed.initialize with exponential
    # backoff (deadline = time_out)
    _p("bootstrap_retries", 5, int, (), ">0"),
    _p("bootstrap_retry_delay", 1.0, float, (), ">0.0"),
    # --- Observability (lightgbm_tpu/obs/) ---
    # runtime telemetry: "off" (default; zero host bookkeeping and —
    # pinned by the jaxlint telemetry.off budget — zero ops in any
    # lowered program), "counters" (host-side spans/counters/compile
    # detectors + per-(kind,bucket) serving latency histograms),
    # "trace" (counters plus a bounded event log exportable as Chrome
    # trace / JSONL / Prometheus, with jax.profiler span bridging).
    # Session-wide and upgrade-only; see Booster.telemetry_report()
    _p("telemetry", "off", str, ("telemetry_mode",)),
    # directory where the CLI writes telemetry.jsonl / trace.json /
    # metrics.prom when the task finishes ("" = no export)
    _p("telemetry_out", "", str, ("telemetry_dir",)),
    # loading a model whose saved params carry telemetry=counters|trace
    # (or health=...) does NOT re-arm the process-wide session by
    # default (a one-time warning names what was skipped); set this (or
    # LIGHTGBM_TPU_OBS_REARM_ON_LOAD=1) to opt back into re-arming —
    # see README "Observability"
    _p("obs_rearm_on_load", False, bool),
    # model & data health (lightgbm_tpu/obs/health.py + digest.py),
    # riding the telemetry modes: "off" (default; zero host bookkeeping
    # and — pinned by the jaxlint health.off budget — zero ops in any
    # lowered program), "counters" (training flight recorder + reference
    # profile + serving-side skew digests, all host-side), "trace"
    # (counters plus flight-recorder / skew-alert marks on the telemetry
    # ring — upgrades the telemetry session to trace so the PR-7
    # exporters carry them).  See Booster.health_report()
    _p("health", "off", str, ("health_mode",)),
    # top-k features reported by skew rankings / the flight recorder
    _p("health_topk", 5, int, (), ">0"),
    # PSI above this fires a health.skew alert event (0.25 = the classic
    # "distribution has shifted" rule of thumb)
    _p("health_psi_threshold", 0.25, float, (), ">=0.0"),
    # --- Continual training (lightgbm_tpu/continual/) ---
    # windowed regression detection: mean tick metric over the last
    # continual_window ticks vs the window before; a relative
    # degradation beyond continual_metric_threshold triggers a
    # background retrain, and the same threshold drives the post-swap
    # rollback watchdog for continual_rollback_window ticks
    _p("continual_window", 3, int, (), ">0"),
    _p("continual_metric_threshold", 0.15, float, (), ">=0.0"),
    _p("continual_rollback_window", 3, int, (), ">0"),
    # how many recent tick mini-batches feed a retrain
    _p("continual_buffer_ticks", 8, int, (), ">0"),
    # 0 = inherit num_iterations
    _p("continual_retrain_rounds", 0, int, (), ">=0"),
    # retry/backoff policy around retrains (robustness/retry.py;
    # jitter is SEEDED so fault drills replay bit-exact)
    _p("continual_retrain_attempts", 3, int, (), ">0"),
    _p("continual_backoff_base", 0.05, float, (), ">0.0"),
    _p("continual_backoff_jitter", 0.1, float, (), ">=0.0"),
    # swap gate: a candidate worse than the served model by more than
    # this relative margin on the gate batch is rejected
    _p("continual_swap_margin", 0.0, float, (), ">=0.0"),
    # detection quiet period (ticks) after a swap/rollback/failure
    _p("continual_cooldown", 3, int, (), ">=0"),
    # tick metric: auto (from the objective) | l2 | binary_logloss |
    # multi_logloss — lower is better, computed on the host
    _p("continual_metric", "auto", str),
    # overall retry deadline (seconds of backoff_schedule budget) for a
    # retrain cycle; 0 = attempts alone bound it.  Consumed by
    # robustness/retry.py backoff_schedule(deadline=) — the schedule
    # truncates where the budget runs out, so a retrain degrades to
    # last-good ON TIME instead of sleeping past its usefulness
    _p("continual_retrain_deadline", 0.0, float, (), ">=0.0"),
    # --- Serving service (lightgbm_tpu/serving/) ---
    # `lightgbm_tpu serve`: coalescing micro-batcher + multi-model
    # registry + per-tenant admission control over the ServingEngine.
    # See README "Serving service".
    _p("serve_host", "127.0.0.1", str),
    _p("serve_port", 8080, int, (), ">=0"),
    # resident models at startup: "name=path[,name=path...]"; falls
    # back to input_model= published as "default"
    _p("serve_models", "", str),
    # micro-batcher: flush a coalescing lane at this many pending rows
    # (pick one of the engine's power-of-two buckets) ...
    _p("serve_flush_rows", 256, int, (), ">0"),
    # ... or once its oldest request has waited this long (ms)
    _p("serve_flush_ms", 2.0, float, (), ">=0.0"),
    # bounded per-tenant queue depth (backpressure + ladder shedding)
    _p("serve_queue_depth", 256, int, (), ">0"),
    # per-tenant token bucket: sustained requests/s (0 = unlimited)
    # and burst capacity
    _p("serve_rate_limit", 0.0, float, (), ">=0.0"),
    _p("serve_burst", 64.0, float, (), ">0.0"),
    # default per-request deadline budget (ms; 0 = none): expired work
    # is shed before dispatch, never after
    _p("serve_default_deadline_ms", 0.0, float, (), ">=0.0"),
    # hard per-request row cap (the rate limiter meters REQUESTS, so
    # without a cap one huge-row request would buy unbounded device
    # work for one token); default = the engine's MAX_BUCKET
    _p("serve_max_request_rows", 65536, int, (), ">0"),
    # per-model circuit breaker: consecutive dispatch failures that
    # trip it, and the seeded backoff probe policy (jitter uses `seed`)
    _p("serve_breaker_threshold", 5, int, (), ">0"),
    _p("serve_breaker_base", 0.05, float, (), ">0.0"),
    _p("serve_breaker_jitter", 0.0, float, (), ">=0.0"),
    # registry pack-memory budget (MB; 0 = unlimited): LRU models'
    # engine packs are evicted (lazily re-packed, never re-compiled)
    _p("serve_pack_budget_mb", 0.0, float, (), ">=0.0"),
    # operator endpoints (publish/rollback) auth: when set, requests
    # must carry it as the X-Admin-Token header; when unset, the ops
    # endpoints only answer loopback clients (hot-swapping a serving
    # model from an arbitrary server-side file path is an OPERATOR
    # action, never an open API)
    _p("serve_admin_token", "", str),
    # multi-forest batched execution: when >= 2 tenant models' raw
    # full-range lanes are due in the same pump wave, stack their
    # forests into one padded (forest, tree, node) tensor and serve the
    # whole cohort in ONE compiled dispatch (serving/registry.py cohort
    # packs over ops/forest_tensor.py; compile counts stay pinned per
    # (kind, bucket, cohort-signature)).  Ineligible models (categorical
    # splits, loaded-only, breaker not closed) fall back to per-model
    # dispatch
    _p("serve_cohort", False, bool),
    # minimum due models that form a cohort dispatch (below it the
    # per-model path is already one dispatch each)
    _p("serve_cohort_min", 2, int, (), ">=2"),
    _p("use_quantized_grad", False, bool),
    _p("num_grad_quant_bins", 4, int),
    _p("quant_train_renew_leaf", False, bool),
    _p("stochastic_rounding", True, bool),
    # --- IO / dataset ---
    _p("linear_tree", False, bool, ("linear_trees",)),
    # piece-wise linear trees: "refit" keeps the historical behaviour
    # (tree structure chosen by constant-leaf gain, leaf-local linear
    # models fit post-hoc on the host); "leafwise_gain" computes split
    # gain over leaf-local linear models inside the device search
    # (ops/split.py:find_best_split_linear) so the STRUCTURE itself is
    # PL-aware, and the per-leaf models come out of the winning split
    # candidates — no extra data pass.  Ineligible configs (see
    # learner._linear_gain_eligible) fall back to refit with a warning
    _p("linear_tree_mode", "refit", str),
    _p("max_bin", 255, int, ("max_bins",), ">1"),
    _p("max_bin_by_feature", "", str),
    _p("min_data_in_bin", 3, int, (), ">0"),
    _p("bin_construct_sample_cnt", 200000, int,
       ("subsample_for_bin",), ">0"),
    _p("data_random_seed", 1, int, ("data_seed",)),
    _p("is_enable_sparse", True, bool,
       ("is_sparse", "enable_sparse", "sparse")),
    _p("enable_bundle", True, bool, ("is_enable_bundle", "bundle")),
    _p("use_missing", True, bool),
    _p("zero_as_missing", False, bool),
    _p("feature_pre_filter", True, bool),
    _p("pre_partition", False, bool, ("is_pre_partition",)),
    _p("two_round", False, bool,
       ("two_round_loading", "use_two_round_loading")),
    _p("header", False, bool, ("has_header",)),
    _p("label_column", "", str, ("label",)),
    _p("weight_column", "", str, ("weight",)),
    _p("group_column", "", str,
       ("group", "group_id", "query_column", "query", "query_id")),
    _p("ignore_column", "", str,
       ("ignore_feature", "blacklist")),
    _p("categorical_feature", "", str,
       ("cat_feature", "categorical_column", "cat_column", "categorical_features")),
    _p("forcedbins_filename", "", str),
    _p("save_binary", False, bool, ("is_save_binary", "is_save_binary_file")),
    # dataset construction path (ops/construct.py): "off" = the original
    # per-feature host loops (the oracle); "auto" = vectorized host
    # construction (one batched searchsorted over all features, matmul
    # EFB conflict counts) + direct-to-device (G, N_pad) ingest for
    # training datasets; "on" = auto, plus the host binned matrix is
    # not materialized (recoverable from the device buffer on demand)
    _p("construct_device", "auto", str),
    # free the host binned matrix once the device ingest buffer holds
    # the data — the free_raw_data analog for the packed bin matrix (a
    # raw float copy is only retained under linear_tree, which keeps it)
    _p("free_host_binned", False, bool),
    # out-of-core bin finding (ops/sketch.py): "exact" = the full
    # column sort of the row sample (the oracle); "sketch" =
    # deterministic mergeable per-feature quantile sketches accumulated
    # chunk by chunk — the dense raw matrix never materializes, and
    # rank-sharded construction merges fixed-size sketch states instead
    # of row samples; "auto" = sketch above sketch_row_threshold rows
    _p("bin_construct_mode", "auto", str),
    # sketch capacity per feature: below k distinct values the sketch
    # is exact (mappers bit-identical to the oracle); past it, cells
    # coarsen in power-of-two steps and the CDF error is bounded by the
    # heaviest cell (FeatureSketch.rank_error_bound)
    _p("sketch_k", 8192, int, (), ">=16"),
    _p("sketch_row_threshold", 1000000, int, (), ">0"),
    _p("precise_float_parser", False, bool),
    _p("parser_config_file", "", str),
    # --- Predict ---
    _p("start_iteration_predict", 0, int),
    _p("num_iteration_predict", -1, int),
    _p("predict_raw_score", False, bool,
       ("is_predict_raw_score", "predict_rawscore", "raw_score")),
    _p("predict_leaf_index", False, bool,
       ("is_predict_leaf_index", "leaf_index")),
    _p("predict_contrib", False, bool,
       ("is_predict_contrib", "contrib")),
    _p("predict_disable_shape_check", False, bool),
    # serving traversal kernel (models/serving.py / ops/forest_tensor.py):
    # "layered" reformulates packed-forest traversal as per-depth dense
    # gather+compare ops with a FIXED trip count (= max tree depth, a
    # pack-time host constant) and quantized u8/u16 node planes — no
    # data-dependent while_loop in the lowered program; "loop" is the
    # stacked while-loop oracle (ops/predict.py); "auto" serves layered
    # whenever the forest fits the quantized planes and unroll ceiling,
    # falling back to the loop oracle otherwise.  The f32 layered path
    # is bit-identical to the loop oracle (tests/test_forest_tensor.py)
    _p("predict_kernel", "auto", str),
    # store packed leaf-value planes in bf16 (accumulation stays f32):
    # halves the leaf gather traffic at a ~3-decimal-digit leaf
    # precision cost — opt-in, OFF keeps bit-parity with the oracle
    _p("predict_bf16_leaves", False, bool),
    _p("pred_early_stop", False, bool),
    _p("pred_early_stop_freq", 10, int),
    _p("pred_early_stop_margin", 10.0, float),
    _p("output_result", "LightGBM_predict_result.txt", str,
       ("predict_result", "prediction_result", "predict_name",
        "prediction_name", "pred_name", "name_pred")),
    # --- Convert ---
    _p("convert_model_language", "", str),
    _p("convert_model", "gbdt_prediction.cpp", str,
       ("convert_model_file",)),
    # --- Objective ---
    _p("objective_seed", 5, int),
    _p("num_class", 1, int, ("num_classes",), ">0"),
    _p("is_unbalance", False, bool,
       ("unbalance", "unbalanced_sets")),
    _p("scale_pos_weight", 1.0, float, (), ">0.0"),
    _p("sigmoid", 1.0, float, (), ">0.0"),
    _p("boost_from_average", True, bool),
    _p("reg_sqrt", False, bool),
    _p("alpha", 0.9, float, (), ">0.0"),
    _p("fair_c", 1.0, float, (), ">0.0"),
    _p("poisson_max_delta_step", 0.7, float, (), ">0.0"),
    _p("tweedie_variance_power", 1.5, float, (), "1.0<=x<2.0"),
    _p("lambdarank_truncation_level", 30, int, (), ">0"),
    _p("lambdarank_norm", True, bool),
    _p("label_gain", "", str),
    _p("lambdarank_position_bias_regularization", 0.0, float, (), ">=0.0"),
    # --- Metric ---
    _p("metric", "", str, ("metrics", "metric_types")),
    _p("metric_freq", 1, int, ("output_freq",), ">0"),
    _p("is_provide_training_metric", False, bool,
       ("training_metric", "is_training_metric", "train_metric")),
    _p("eval_at", "1,2,3,4,5", str,
       ("ndcg_eval_at", "ndcg_at", "map_eval_at", "map_at")),
    _p("multi_error_top_k", 1, int, (), ">0"),
    _p("auc_mu_weights", "", str),
    # TPU extension: gather score/label pairs across ranks for an EXACT
    # global AUC under data-parallel row sharding (default stays the
    # reference-shaped per-rank weighted mean, which warns once)
    _p("distributed_exact_auc", False, bool),
    # --- Network ---
    _p("num_machines", 1, int, ("num_machine",), ">0"),
    _p("local_listen_port", 12400, int, ("local_port", "port"), ">0"),
    _p("time_out", 120, int, (), ">0"),
    _p("machine_list_filename", "", str,
       ("machine_list_file", "machine_list", "mlist")),
    _p("machines", "", str, ("workers", "nodes")),
    # --- Device ---
    _p("gpu_platform_id", -1, int),
    _p("gpu_device_id", -1, int),
    _p("gpu_use_dp", False, bool),
    _p("num_gpu", 1, int, (), ">0"),
    # --- TPU-specific (new in this framework) ---
    _p("tpu_hist_dtype", "float32", str),       # float32 | bfloat16_pair
    _p("tpu_hist_kernel", "xla", str),          # xla | pallas
    # per-leaf histogram state: "auto" = lane-flattened state updated in
    # place by the Pallas RMW kernel (ops/hist_state_pallas.py) when the
    # fast serial path is active; "xla" = (L+1, G, B, 2) dynamic-slice
    # state (the fallback and the A/B baseline)
    _p("tpu_hist_state", "auto", str),
    # measurement-only: duplicate one component inside the compiled tree
    # loop with a runtime-opaque select so tools/ab_bench.py can read its
    # IN-CONTEXT cost as the paired e2e delta ("" | "hist" | "search")
    _p("tpu_ab_double", "", str),
    _p("tpu_partition_kernel", "pallas", str),  # pallas | xla
    # split mega-kernel: partition + BOTH children's histograms in one
    # Pallas program per split (ops/split_megakernel_pallas.py) — no
    # parent-histogram read, no subtraction trick, no (L+1)-slot
    # histogram state in the while-loop carry.  "auto" probes the kernel
    # on TPU and falls back to the current split path; "pallas" forces
    # the attempt; "xla" runs the same math as plain XLA ops (the
    # correctness oracle, any backend); "off" disables
    _p("tpu_megakernel", "auto", str),
    # frontier-batched tree growth: grow the top-K gain leaves of the
    # current frontier per while-loop step instead of 1, amortizing the
    # per-split fixed bookkeeping cost ~K-fold (models/learner.py; the
    # oracle-order replay keeps trained trees BIT-identical to the K=1
    # learner, including at the num_leaves budget boundary).  "auto"
    # engages K=4 on TPU backends when the plain serial path is active
    # and stays at 1 elsewhere; an explicit integer K forces batching on
    # any backend (falls back to 1 with a warning when forced splits,
    # monotone constraints, CEGB, extra_trees, feature_fraction_bynode,
    # interaction constraints or a parallel tree learner are active)
    _p("tpu_frontier_k", "auto", str),
    # radix-4 compaction network in the partition/mega kernels: half the
    # roll-network steps of the binary network (bit-identical layouts;
    # an instruction-budget lever — see PERF.md round 6)
    _p("tpu_compact_radix", False, bool),
    # run the Pallas kernels through the interpreter on any backend
    # (testing/debug: enables the kernel paths off-TPU; SLOW)
    _p("tpu_kernel_interpret", False, bool),
    # rows per partition/histogram chunk; 4096 measured best end-to-end
    # on v5e (round 3: fixed cost 15.9 -> 12.1 ms/iter vs 8192 at equal
    # slope — smaller per-split padding waste).  "auto" consults the
    # BENCH_history.jsonl trajectory for a same-fingerprint chunk-sweep
    # winner before falling back to 4096 (ops/chunkpolicy.py); also the
    # SEED of the leaf-size-adaptive menu below
    _p("tpu_row_chunk", "4096", str),
    # leaf-size-adaptive chunk policy (ops/chunkpolicy.py): per-leaf
    # histogram/partition passes pick their chunk width from a bounded
    # static menu seeded by tpu_row_chunk, so small leaves stop paying
    # the worst-case padded chunk (68% of the CPU iteration, PERF.md
    # round 12) while trees stay BIT-identical to the fixed grid.
    # "auto" = adaptive in the small-leaf regime (or per a measured
    # same-fingerprint chunk-sweep verdict) on the plain XLA serial
    # path; "fixed" = the base grid everywhere; "adaptive" = force on
    _p("tpu_chunk_policy", "auto", str),
    # ride the rowid row inside the spare packed-bin bytes when G <= G32-4
    # (one fewer payload sublane through the partition roll networks)
    _p("tpu_pack_rowid", False, bool),
    # disable the fused single-program iteration (A/B + debugging; the
    # eager per-stage dispatch path is the fallback)
    _p("tpu_fused_iteration", True, bool),
    # data-parallel histogram sync: "scatter" = ReduceScatter ownership
    # (psum_scatter + per-device feature ownership + winner election),
    # preserving the reference's placement decision
    # (data_parallel_tree_learner.cpp:282-296) — each histogram element
    # crosses the wire once instead of ndev times; "psum" = full-hist
    # allreduce (the round-4 behavior)
    _p("tpu_data_hist_sync", "scatter", str),
    _p("tpu_feature_block", 64, int, (), ">0"),  # feature groups per histogram block
    _p("tpu_min_bucket_log2", 10, int, (), ">=0"),  # smallest partition bucket
    _p("tpu_donate_state", True, bool),
]

_PARAM_BY_NAME: Dict[str, _Param] = {p.name: p for p in _PARAMS}
_ALIAS2NAME: Dict[str, str] = {}
for _param in _PARAMS:
    _ALIAS2NAME[_param.name] = _param.name
    for _a in _param.aliases:
        _ALIAS2NAME.setdefault(_a, _param.name)

_OBJECTIVE_ALIASES = {
    # objective-string aliases (reference: config.cpp ParseObjectiveAlias)
    "regression": "regression", "regression_l2": "regression", "l2": "regression",
    "mean_squared_error": "regression", "mse": "regression", "l2_root": "regression",
    "root_mean_squared_error": "regression", "rmse": "regression",
    "regression_l1": "regression_l1", "l1": "regression_l1",
    "mean_absolute_error": "regression_l1", "mae": "regression_l1",
    "multiclass": "multiclass", "softmax": "multiclass",
    "multiclassova": "multiclassova", "multiclass_ova": "multiclassova",
    "ova": "multiclassova", "ovr": "multiclassova",
    "xentropy": "cross_entropy", "cross_entropy": "cross_entropy",
    "xentlambda": "cross_entropy_lambda", "cross_entropy_lambda": "cross_entropy_lambda",
    "mean_absolute_percentage_error": "mape", "mape": "mape",
    "none": "none", "null": "none", "custom": "none", "na": "none",
    "binary": "binary", "huber": "huber", "fair": "fair", "poisson": "poisson",
    "quantile": "quantile", "gamma": "gamma", "tweedie": "tweedie",
    "lambdarank": "lambdarank", "rank_xendcg": "rank_xendcg",
    "xendcg": "rank_xendcg", "xe_ndcg": "rank_xendcg",
    "xe_ndcg_mart": "rank_xendcg", "xendcg_mart": "rank_xendcg",
}

_METRIC_ALIASES = {
    # reference: config.cpp ParseMetricAlias
    "null": "", "none": "", "na": "custom",
    "mean_squared_error": "l2", "mse": "l2", "regression_l2": "l2", "regression": "l2",
    "l2_root": "rmse", "root_mean_squared_error": "rmse",
    "mean_absolute_error": "l1", "mae": "l1", "regression_l1": "l1",
    "binary_logloss": "binary_logloss", "binary": "binary_logloss",
    "ndcg": "ndcg", "lambdarank": "ndcg", "rank_xendcg": "ndcg",
    "xendcg": "ndcg", "xe_ndcg": "ndcg", "xe_ndcg_mart": "ndcg", "xendcg_mart": "ndcg",
    "mean_average_precision": "map",
    "multiclass": "multi_logloss", "softmax": "multi_logloss",
    "multiclassova": "multi_logloss", "multiclass_ova": "multi_logloss",
    "ova": "multi_logloss", "ovr": "multi_logloss",
    "xentropy": "xentropy", "cross_entropy": "xentropy",
    "xentlambda": "xentlambda", "cross_entropy_lambda": "xentlambda",
    "kldiv": "kullback_leibler", "kullback_leibler": "kullback_leibler",
    "mean_absolute_percentage_error": "mape", "mape": "mape",
}


def _coerce(param: _Param, value: Any) -> Any:
    if param.typ is bool:
        if isinstance(value, bool):
            return value
        if isinstance(value, (int, float)):
            return bool(value)
        s = str(value).strip().lower()
        if s in ("true", "1", "+", "yes", "on"):
            return True
        if s in ("false", "0", "-", "no", "off"):
            return False
        log.fatal("Invalid boolean value %s for parameter %s", value, param.name)
    if param.typ is int:
        if value is None:
            return None
        return int(float(value))
    if param.typ is float:
        return float(value)
    return str(value)


def _check_value(param: _Param, v: Any) -> None:
    if param.check is None or v is None or param.typ is str:
        return
    c = param.check
    ok = True
    if "<=x<" in c or "<x<=" in c or "<=x<=" in c or "<x<" in c:
        import re
        m = re.match(r"([-\d.eE+]+)(<=|<)x(<=|<)([-\d.eE+]+)", c)
        lo, lop, hip, hi = float(m.group(1)), m.group(2), m.group(3), float(m.group(4))
        ok = (lo <= v if lop == "<=" else lo < v) and (v <= hi if hip == "<=" else v < hi)
    elif c.startswith(">="):
        ok = v >= float(c[2:])
    elif c.startswith(">"):
        ok = v > float(c[1:])
    elif c.startswith("<="):
        ok = v <= float(c[2:])
    elif c.startswith("<"):
        ok = v < float(c[1:])
    if not ok:
        log.fatal("Parameter %s should satisfy %s, got %s", param.name, c, v)


_WARNED_UNKNOWN: set = set()


def reset_unknown_param_warnings() -> None:
    """Open a fresh unknown-parameter warning scope.

    Called at every top-level ``train()``/``cv()`` entry: within one call
    Config is legitimately rebuilt several times from the same raw params
    (Dataset, Booster, engine) and the warning must fire once — but a
    typo'd key in a LATER, unrelated training session in the same process
    must warn again, not be swallowed by a process-lifetime set."""
    _WARNED_UNKNOWN.clear()


class Config:
    """Resolved training configuration (reference: include/LightGBM/config.h)."""

    def __init__(self, params: Optional[Dict[str, Any]] = None, **kwargs):
        merged: Dict[str, Any] = {}
        if params:
            merged.update(params)
        merged.update(kwargs)
        self._raw = dict(merged)
        # canonicalize aliases; earlier (canonical) names win on conflict, like
        # the reference KeyAliasTransform keeping the first-priority alias.
        resolved: Dict[str, Any] = {}
        self._unknown: Dict[str, Any] = {}
        for key, value in merged.items():
            k = str(key).strip().lower().replace("-", "_")
            # list/tuple values join to comma-separated strings, like the
            # reference python package's _param_dict_to_str (basic.py:303)
            if isinstance(value, (list, tuple)):
                value = ",".join(str(v) for v in value)
            name = _ALIAS2NAME.get(k)
            if name is None:
                self._unknown[k] = value
                continue
            if name in resolved and k != name:
                continue  # canonical key already set; alias loses
            resolved[name] = value
        for p in _PARAMS:
            if p.name in resolved and resolved[p.name] is not None:
                v = _coerce(p, resolved[p.name])
                _check_value(p, v)
                setattr(self, p.name, v)
            else:
                setattr(self, p.name, p.default)
        self._post_process()
        # reference: Config surfaces unrecognized keys instead of
        # silently dropping them (include/LightGBM/config.h:1242
        # "Unknown parameter: %s"); a typo'd key (num_leafs) must not
        # train silently with defaults.  Deduped per warning scope (one
        # top-level train()/cv() call, see reset_unknown_param_warnings):
        # one train call legitimately rebuilds Config several times
        # (Dataset, Booster, engine) from the same raw params.
        for k in self._unknown:
            if k not in _WARNED_UNKNOWN:
                _WARNED_UNKNOWN.add(k)
                log.warning("Unknown parameter: %s", k)

    # -- derived state (reference: Config::Set, src/io/config.cpp) --
    def _post_process(self) -> None:
        # str-typed numeric-or-auto knobs keep config-time validation
        # (a typo must fail HERE with a clear message, not surface as a
        # swallowed exception in dataset/learner construction)
        from .ops.chunkpolicy import parse_row_chunk
        try:
            parse_row_chunk(self.tpu_row_chunk)
        except ValueError as exc:
            log.fatal("%s", exc)
        if str(self.tpu_chunk_policy).strip().lower() not in (
                "auto", "fixed", "adaptive", ""):
            log.warning("unknown tpu_chunk_policy=%r; treating as auto",
                        self.tpu_chunk_policy)
        ltm = str(self.linear_tree_mode).strip().lower() or "refit"
        if ltm not in ("refit", "leafwise_gain"):
            log.warning("unknown linear_tree_mode=%r; treating as refit",
                        self.linear_tree_mode)
            ltm = "refit"
        self.linear_tree_mode = ltm
        self.objective = _OBJECTIVE_ALIASES.get(
            str(self.objective).lower(), str(self.objective).lower())
        # boosting aliases; "goss" boosting folds into gbdt + goss strategy
        b = str(self.boosting).lower()
        b = {"gbrt": "gbdt", "gbm": "gbdt", "random_forest": "rf"}.get(b, b)
        if b == "goss":
            b = "gbdt"
            self.data_sample_strategy = "goss"
        self.boosting = b
        if self.seed is not None:
            # reference: config.cpp uses seed to derive the other seeds
            base = int(self.seed)
            self.data_random_seed = base + 1
            self.bagging_seed = base + 3
            self.drop_seed = base + 4
            self.feature_fraction_seed = base + 2
            self.extra_seed = base + 6
            self.objective_seed = base + 5
        else:
            self.seed = 0
        # metric list
        raw_metrics = [m.strip().lower() for m in str(self.metric).split(",") if m.strip()]
        self.metric_list: List[str] = []
        for m in raw_metrics:
            m = _METRIC_ALIASES.get(m, m)
            if m and m not in self.metric_list:
                self.metric_list.append(m)
        self.eval_at_list = [int(x) for x in str(self.eval_at).split(",") if x.strip()]
        # parallel flags (reference: config.cpp Config::Set)
        tl = str(self.tree_learner).lower()
        tl = {"serial": "serial", "feature": "feature", "feature_parallel": "feature",
              "data": "data", "data_parallel": "data", "voting": "voting",
              "voting_parallel": "voting"}.get(tl, tl)
        self.tree_learner = tl
        self.is_parallel = tl != "serial" and self.num_machines > 1
        self.is_data_based_parallel = tl in ("data", "voting") and self.num_machines > 1
        self.bagging_by_ = None
        if self.verbosity is not None:
            log.set_verbosity(self.verbosity)

    # ------------------------------------------------------------------
    def update(self, params: Dict[str, Any]) -> "Config":
        raw = dict(self._raw)
        raw.update(params)
        return Config(raw)

    def to_dict(self) -> Dict[str, Any]:
        return {p.name: getattr(self, p.name) for p in _PARAMS}

    def non_default_items(self) -> Dict[str, Any]:
        out = {}
        for p in _PARAMS:
            v = getattr(self, p.name)
            if v != p.default:
                out[p.name] = v
        return out

    def save_to_string(self) -> str:
        """Model-file `parameters:` section (reference: SaveMembersToString)."""
        lines = []
        for p in _PARAMS:
            v = getattr(self, p.name)
            if isinstance(v, bool):
                v = int(v)
            lines.append(f"[{p.name}: {v}]")
        return "\n".join(lines)

    @staticmethod
    def canonical_name(key: str) -> Optional[str]:
        return _ALIAS2NAME.get(str(key).strip().lower().replace("-", "_"))


def parse_config_file(path: str) -> Dict[str, str]:
    """Parse a `key=value` config file (reference: Application ctor KV2Map)."""
    out: Dict[str, str] = {}
    with open(path, "r") as fh:
        for line in fh:
            line = line.split("#", 1)[0].strip()
            if not line or "=" not in line:
                continue
            k, v = line.split("=", 1)
            out[k.strip()] = v.strip()
    return out


def param_alias_map() -> Dict[str, str]:
    return dict(_ALIAS2NAME)
