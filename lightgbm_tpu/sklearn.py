"""scikit-learn estimator API.

TPU-native re-implementation of python-package/lightgbm/sklearn.py
(LGBMModel:482, LGBMRegressor:1169, LGBMClassifier:1215, LGBMRanker:1402)
with the same constructor surface and fit/predict semantics, built on the
jax engine instead of the C API.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from .basic import Booster, Dataset, LightGBMError
from .callback import record_evaluation
from .engine import train as _train

try:  # sklearn is available in-image; keep a soft fallback anyway
    from sklearn.base import BaseEstimator as _SKBaseEstimator
    from sklearn.base import ClassifierMixin as _SKClassifierMixin
    from sklearn.base import RegressorMixin as _SKRegressorMixin
    _SKLEARN_INSTALLED = True
except ImportError:  # pragma: no cover
    _SKLEARN_INSTALLED = False

    class _SKBaseEstimator:  # type: ignore
        pass

    class _SKClassifierMixin:  # type: ignore
        pass

    class _SKRegressorMixin:  # type: ignore
        pass

__all__ = ["LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker"]


class _ObjectiveFunctionWrapper:
    """Adapt a sklearn-style objective ``f(y_true, y_pred[, weight[, group]])
    -> (grad, hess)`` to the engine's ``f(preds, dataset)`` signature.

    reference: sklearn.py _ObjectiveFunctionWrapper:147.
    """

    def __init__(self, func: Callable):
        self.func = func

    def __call__(self, preds, dataset):
        labels = dataset.get_label()
        argc = self.func.__code__.co_argcount
        if argc == 2:
            grad, hess = self.func(labels, preds)
        elif argc == 3:
            grad, hess = self.func(labels, preds, dataset.get_weight())
        elif argc == 4:
            grad, hess = self.func(labels, preds, dataset.get_weight(),
                                   dataset.get_group())
        else:
            raise TypeError(f"Self-defined objective should have 2-4 "
                            f"arguments, got {argc}")
        return grad, hess


class _EvalFunctionWrapper:
    """Adapt a sklearn-style metric ``f(y_true, y_pred, ...) -> (name, value,
    is_higher_better)`` to the engine feval signature.

    reference: sklearn.py _EvalFunctionWrapper:234.
    """

    def __init__(self, func: Callable):
        self.func = func

    def __call__(self, preds, dataset):
        labels = dataset.get_label()
        argc = self.func.__code__.co_argcount
        if argc == 2:
            return self.func(labels, preds)
        if argc == 3:
            return self.func(labels, preds, dataset.get_weight())
        if argc == 4:
            return self.func(labels, preds, dataset.get_weight(),
                             dataset.get_group())
        raise TypeError(f"Self-defined eval function should have 2-4 "
                        f"arguments, got {argc}")


def _to_2d(X) -> np.ndarray:
    if hasattr(X, "toarray"):
        X = X.toarray()
    arr = np.asarray(X, dtype=np.float64)
    if arr.ndim == 1:
        arr = arr.reshape(1, -1)
    return arr


class LGBMModel(_SKBaseEstimator):
    """Base estimator (reference: sklearn.py LGBMModel:482)."""

    def __init__(self, boosting_type: str = "gbdt", num_leaves: int = 31,
                 max_depth: int = -1, learning_rate: float = 0.1,
                 n_estimators: int = 100, subsample_for_bin: int = 200000,
                 objective: Optional[Union[str, Callable]] = None,
                 class_weight: Optional[Union[Dict, str]] = None,
                 min_split_gain: float = 0.0, min_child_weight: float = 1e-3,
                 min_child_samples: int = 20, subsample: float = 1.0,
                 subsample_freq: int = 0, colsample_bytree: float = 1.0,
                 reg_alpha: float = 0.0, reg_lambda: float = 0.0,
                 random_state=None, n_jobs: Optional[int] = None,
                 importance_type: str = "split", **kwargs):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.importance_type = importance_type
        self._Booster: Optional[Booster] = None
        self._evals_result: Dict = {}
        self._best_score: Dict = {}
        self._best_iteration: int = -1
        self._objective = objective
        self._class_weight = class_weight
        self._other_params: Dict[str, Any] = {}
        self._n_features: int = -1
        self._n_classes: int = -1
        self.set_params(**kwargs)

    # -- param handling (mirrors reference get_params/set_params behavior) --
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        params = super().get_params(deep=deep) if _SKLEARN_INSTALLED else {
            k: getattr(self, k) for k in self._param_names()}
        params.update(self._other_params)
        return params

    def _param_names(self):
        return ["boosting_type", "num_leaves", "max_depth", "learning_rate",
                "n_estimators", "subsample_for_bin", "objective",
                "class_weight", "min_split_gain", "min_child_weight",
                "min_child_samples", "subsample", "subsample_freq",
                "colsample_bytree", "reg_alpha", "reg_lambda", "random_state",
                "n_jobs", "importance_type"]

    def set_params(self, **params) -> "LGBMModel":
        for key, value in params.items():
            setattr(self, key, value)
            if hasattr(self, f"_{key}"):
                setattr(self, f"_{key}", value)
            if key not in self._param_names():
                self._other_params[key] = value
        return self

    # ------------------------------------------------------------------
    def _process_params(self, stage: str) -> Dict[str, Any]:
        assert stage in ("fit", "predict")
        params = self.get_params()
        params.pop("objective", None)
        for alias in ("n_estimators", "class_weight", "importance_type",
                      "n_jobs"):
            params.pop(alias, None)
        if isinstance(self.random_state, np.random.RandomState):
            params["random_state"] = self.random_state.randint(
                np.iinfo(np.int32).max)
        elif isinstance(self.random_state, np.random.Generator):
            params["random_state"] = int(self.random_state.integers(
                np.iinfo(np.int32).max))
        elif self.random_state is not None:
            params["random_state"] = self.random_state
        else:
            params.pop("random_state", None)
        if callable(self._objective):
            if stage == "fit":
                params["objective"] = _ObjectiveFunctionWrapper(
                    self._objective)
            else:
                params["objective"] = "none"
        elif self._objective is not None:
            params["objective"] = self._objective
        # rename sklearn names to lightgbm names
        params["num_leaves"] = self.num_leaves
        params["max_depth"] = self.max_depth
        params["learning_rate"] = self.learning_rate
        params["bagging_fraction"] = params.pop("subsample", self.subsample)
        params["bagging_freq"] = params.pop("subsample_freq",
                                            self.subsample_freq)
        params["feature_fraction"] = params.pop("colsample_bytree",
                                                self.colsample_bytree)
        params["lambda_l1"] = params.pop("reg_alpha", self.reg_alpha)
        params["lambda_l2"] = params.pop("reg_lambda", self.reg_lambda)
        params["min_gain_to_split"] = params.pop("min_split_gain",
                                                 self.min_split_gain)
        params["min_sum_hessian_in_leaf"] = params.pop("min_child_weight",
                                                       self.min_child_weight)
        params["min_data_in_leaf"] = params.pop("min_child_samples",
                                                self.min_child_samples)
        params["bin_construct_sample_cnt"] = params.pop(
            "subsample_for_bin", self.subsample_for_bin)
        params["boosting"] = params.pop("boosting_type", self.boosting_type)
        params.setdefault("verbosity", -1)
        return params

    def _compute_sample_weight(self, y, sample_weight, class_weight):
        if class_weight is None:
            return sample_weight
        classes, y_idx = np.unique(y, return_inverse=True)
        if class_weight == "balanced":
            counts = np.bincount(y_idx)
            w_per_class = len(y) / (len(classes) * counts)
        else:
            w_per_class = np.array([class_weight.get(c, 1.0) for c in classes],
                                   dtype=np.float64)
        cw = w_per_class[y_idx]
        if sample_weight is not None:
            cw = cw * np.asarray(sample_weight, dtype=np.float64)
        return cw

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_class_weight=None, eval_init_score=None, eval_group=None,
            eval_metric=None, feature_name: Union[str, List[str]] = "auto",
            categorical_feature: Union[str, List] = "auto",
            callbacks=None, init_model=None) -> "LGBMModel":
        """Fit the model (reference: sklearn.py LGBMModel.fit:745)."""
        params = self._process_params(stage="fit")

        y = np.asarray(np.ravel(y), dtype=np.float64)
        cw = self._class_weight if self._class_weight is not None \
            else self.class_weight
        sample_weight = self._compute_sample_weight(y, sample_weight, cw)

        feval_list: List[Callable] = []
        if eval_metric is not None:
            metrics = eval_metric if isinstance(eval_metric, list) \
                else [eval_metric]
            str_metrics = [m for m in metrics if isinstance(m, str)]
            fn_metrics = [m for m in metrics if callable(m)]
            if str_metrics:
                existing = params.get("metric")
                merged = list(str_metrics)
                if existing:
                    if isinstance(existing, str):
                        existing = [existing]
                    merged = list(existing) + [m for m in str_metrics
                                               if m not in existing]
                params["metric"] = ",".join(merged)
            feval_list = [_EvalFunctionWrapper(f) for f in fn_metrics]

        train_set = Dataset(X, label=y, weight=sample_weight, group=group,
                            init_score=init_score, feature_name=feature_name,
                            categorical_feature=categorical_feature,
                            params=params)
        self._n_features = int(np.shape(X)[1])

        valid_sets: List[Dataset] = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                vy = np.asarray(np.ravel(vy), dtype=np.float64)
                if vx is X and vy.shape == y.shape and np.array_equal(vy, y):
                    valid_sets.append(train_set)
                    continue

                def _item(collection, idx):
                    if collection is None:
                        return None
                    if isinstance(collection, dict):
                        return collection.get(idx)
                    return collection[idx]

                vw = _item(eval_sample_weight, i)
                vcw = _item(eval_class_weight, i)
                if vcw is not None:
                    vw = self._compute_sample_weight(vy, vw, vcw)
                vs = Dataset(vx, label=vy, weight=vw,
                             group=_item(eval_group, i),
                             init_score=_item(eval_init_score, i),
                             reference=train_set, params=params)
                valid_sets.append(vs)

        evals_result: Dict = {}
        callbacks = list(callbacks) if callbacks else []
        callbacks.append(record_evaluation(evals_result))

        self._Booster = _train(
            params, train_set,
            num_boost_round=self.n_estimators,
            valid_sets=valid_sets or None,
            valid_names=eval_names,
            feval=feval_list or None,
            init_model=init_model,
            callbacks=callbacks,
        )
        self._evals_result = evals_result
        self._best_iteration = self._Booster.best_iteration
        self._best_score = self._Booster.best_score
        self.fitted_ = True
        return self

    def predict(self, X, raw_score: bool = False, start_iteration: int = 0,
                num_iteration: Optional[int] = None, pred_leaf: bool = False,
                pred_contrib: bool = False, validate_features: bool = False,
                **kwargs):
        """Predict (reference: sklearn.py LGBMModel.predict:930)."""
        if self._Booster is None:
            raise LightGBMError("Estimator not fitted, call fit first")
        # frames pass through AS FRAMES so Booster.predict applies the
        # training pandas_categorical code mapping (and, with
        # validate_features, the column-name check) — the reference
        # sklearn wrapper does the same; converting here would feed raw
        # category values (or crash on string categories) for a model
        # trained on codes
        if hasattr(X, "columns"):
            arg, ncol = X, X.shape[1]
        else:
            arg = _to_2d(X)
            ncol = arg.shape[1]
        if ncol != self._n_features:
            raise ValueError(
                f"Number of features of the model must match the input. "
                f"Model n_features_ is {self._n_features} and input "
                f"n_features is {ncol}")
        return self._Booster.predict(
            arg, raw_score=raw_score, start_iteration=start_iteration,
            num_iteration=num_iteration, pred_leaf=pred_leaf,
            pred_contrib=pred_contrib, validate_features=validate_features,
            **kwargs)

    # -- fitted attributes ------------------------------------------------
    @property
    def n_features_(self) -> int:
        if self._n_features < 0:
            raise LightGBMError("No n_features found. Need to call fit first.")
        return self._n_features

    @property
    def n_features_in_(self) -> int:
        return self.n_features_

    @property
    def best_score_(self) -> Dict:
        return self._best_score

    @property
    def best_iteration_(self) -> int:
        if self._Booster is None:
            raise LightGBMError("No best_iteration found. "
                                "Need to call fit with early stopping first.")
        return self._best_iteration

    @property
    def objective_(self):
        if self._Booster is None:
            raise LightGBMError("No objective found. Need to call fit first.")
        return self._objective if self._objective is not None \
            else self._Booster.params.get("objective")

    @property
    def booster_(self) -> Booster:
        if self._Booster is None:
            raise LightGBMError("No booster found. Need to call fit first.")
        return self._Booster

    @property
    def evals_result_(self) -> Dict:
        return self._evals_result

    @property
    def feature_importances_(self) -> np.ndarray:
        if self._Booster is None:
            raise LightGBMError("No feature_importances found. "
                                "Need to call fit first.")
        return self._Booster.feature_importance(
            importance_type=self.importance_type)

    @property
    def feature_name_(self) -> List[str]:
        if self._Booster is None:
            raise LightGBMError("No feature_name found. "
                                "Need to call fit first.")
        return self._Booster.feature_name()

    @property
    def feature_names_in_(self) -> np.ndarray:
        return np.asarray(self.feature_name_)

    def __sklearn_is_fitted__(self) -> bool:
        return getattr(self, "fitted_", False)


class LGBMRegressor(_SKRegressorMixin, LGBMModel):
    """reference: sklearn.py LGBMRegressor:1169."""

    def fit(self, X, y, sample_weight=None, init_score=None, eval_set=None,
            eval_names=None, eval_sample_weight=None, eval_init_score=None,
            eval_metric=None, feature_name="auto",
            categorical_feature="auto", callbacks=None,
            init_model=None) -> "LGBMRegressor":
        if self.objective is None:
            self._objective = "regression"
        super().fit(X, y, sample_weight=sample_weight, init_score=init_score,
                    eval_set=eval_set, eval_names=eval_names,
                    eval_sample_weight=eval_sample_weight,
                    eval_init_score=eval_init_score, eval_metric=eval_metric,
                    feature_name=feature_name,
                    categorical_feature=categorical_feature,
                    callbacks=callbacks, init_model=init_model)
        return self


class LGBMClassifier(_SKClassifierMixin, LGBMModel):
    """reference: sklearn.py LGBMClassifier:1215."""

    @property
    def classes_(self) -> np.ndarray:
        if self._Booster is None:
            raise LightGBMError("No classes found. Need to call fit first.")
        return self._classes

    @property
    def n_classes_(self) -> int:
        if self._Booster is None:
            raise LightGBMError("No classes found. Need to call fit first.")
        return self._n_classes

    def fit(self, X, y, sample_weight=None, init_score=None, eval_set=None,
            eval_names=None, eval_sample_weight=None, eval_class_weight=None,
            eval_init_score=None, eval_metric=None, feature_name="auto",
            categorical_feature="auto", callbacks=None,
            init_model=None) -> "LGBMClassifier":
        y_arr = np.ravel(np.asarray(y))
        self._classes, y_enc = np.unique(y_arr, return_inverse=True)
        self._n_classes = len(self._classes)
        # translate a class_weight dict keyed by ORIGINAL labels into one
        # keyed by encoded class ids, so _compute_sample_weight (which sees
        # encoded y) applies the intended weights
        cw = self.class_weight
        if isinstance(cw, dict):
            self._class_weight = {i: cw[c] for i, c in
                                  enumerate(self._classes) if c in cw}
        else:
            self._class_weight = cw
        if self._n_classes > 2:
            if self.objective is None or (isinstance(self.objective, str) and
                                          self.objective == "multiclass"):
                self._objective = "multiclass"
            self._other_params["num_class"] = self._n_classes
        else:
            if self.objective is None:
                self._objective = "binary"
        ev_metric = eval_metric
        if ev_metric is None and eval_set is not None:
            ev_metric = ("multi_logloss" if self._n_classes > 2
                         else "binary_logloss")
        eval_set_enc = None
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            eval_set_enc = []
            lut = {c: i for i, c in enumerate(self._classes)}
            for vx, vy in eval_set:
                vy_enc = np.array([lut[v] for v in np.ravel(np.asarray(vy))],
                                  dtype=np.float64)
                eval_set_enc.append((vx, vy_enc))
        super().fit(X, y_enc.astype(np.float64), sample_weight=sample_weight,
                    init_score=init_score, eval_set=eval_set_enc,
                    eval_names=eval_names,
                    eval_sample_weight=eval_sample_weight,
                    eval_class_weight=eval_class_weight,
                    eval_init_score=eval_init_score, eval_metric=ev_metric,
                    feature_name=feature_name,
                    categorical_feature=categorical_feature,
                    callbacks=callbacks, init_model=init_model)
        return self

    def predict_proba(self, X, raw_score: bool = False,
                      start_iteration: int = 0,
                      num_iteration: Optional[int] = None,
                      pred_leaf: bool = False, pred_contrib: bool = False,
                      validate_features: bool = False, **kwargs):
        result = super().predict(X, raw_score=raw_score,
                                 start_iteration=start_iteration,
                                 num_iteration=num_iteration,
                                 pred_leaf=pred_leaf,
                                 pred_contrib=pred_contrib,
                                 validate_features=validate_features,
                                 **kwargs)
        if raw_score or pred_leaf or pred_contrib:
            return result
        if callable(self._objective):
            # raw scores: the booster has no link function for a custom
            # objective (reference: sklearn.py LGBMClassifier.predict_proba)
            from .utils import log
            log.warning("Cannot compute class probabilities or labels due to "
                        "the usage of customized objective function; "
                        "returning raw scores instead.")
            return result
        if self._n_classes > 2:
            return result
        result = np.asarray(result).reshape(-1)
        return np.vstack((1.0 - result, result)).transpose()

    def predict(self, X, raw_score: bool = False, start_iteration: int = 0,
                num_iteration: Optional[int] = None, pred_leaf: bool = False,
                pred_contrib: bool = False, validate_features: bool = False,
                **kwargs):
        result = self.predict_proba(X, raw_score=raw_score,
                                    start_iteration=start_iteration,
                                    num_iteration=num_iteration,
                                    pred_leaf=pred_leaf,
                                    pred_contrib=pred_contrib,
                                    validate_features=validate_features,
                                    **kwargs)
        if raw_score or pred_leaf or pred_contrib or \
                callable(self._objective):
            return result
        class_index = np.argmax(np.asarray(result), axis=1)
        return self._classes[class_index]


class LGBMRanker(LGBMModel):
    """reference: sklearn.py LGBMRanker:1402."""

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_init_score=None, eval_group=None, eval_metric=None,
            eval_at=(1, 2, 3, 4, 5), feature_name="auto",
            categorical_feature="auto", callbacks=None,
            init_model=None) -> "LGBMRanker":
        if group is None:
            raise ValueError("Should set group for ranking task")
        if eval_set is not None and eval_group is None:
            raise ValueError("Eval_group cannot be None when eval_set "
                             "is not None")
        if self.objective is None:
            self._objective = "lambdarank"
        self._other_params["eval_at"] = ",".join(str(a) for a in eval_at)
        super().fit(X, y, sample_weight=sample_weight, init_score=init_score,
                    group=group, eval_set=eval_set, eval_names=eval_names,
                    eval_sample_weight=eval_sample_weight,
                    eval_init_score=eval_init_score, eval_group=eval_group,
                    eval_metric=eval_metric, feature_name=feature_name,
                    categorical_feature=categorical_feature,
                    callbacks=callbacks, init_model=init_model)
        return self
