"""Plotting utilities.

API-parity module for the reference's python-package/lightgbm/plotting.py
(plot_importance:37, plot_split_value_histogram:171, plot_metric:287,
create_tree_digraph:614, plot_tree:740).  Signatures and rendered content
match the reference; the implementations are matplotlib-native:

  * ``plot_tree`` draws the tree directly with matplotlib (no graphviz
    binary required — unlike the reference, which shells out to dot);
  * ``create_tree_digraph`` returns a ``graphviz.Digraph`` when the optional
    ``graphviz`` package is importable, else raises ImportError.
"""

from __future__ import annotations

from copy import deepcopy
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from .basic import Booster, LightGBMError


def _window(pair, name: str):
    """Validate an (lo, hi) axis-window argument."""
    if not (isinstance(pair, tuple) and len(pair) == 2):
        raise TypeError(f"{name} must be a tuple of 2 elements.")
    return pair


def _to_booster(model) -> Booster:
    if isinstance(model, Booster):
        return model
    if hasattr(model, "booster_"):
        return model.booster_
    raise TypeError("model must be a Booster or a fitted LGBMModel")


def _import_matplotlib():
    try:
        import matplotlib.pyplot as plt
        return plt
    except ImportError as e:  # pragma: no cover
        raise ImportError("You must install matplotlib to use plotting") from e


def _new_axes(plt, figsize, dpi):
    if figsize is not None:
        _window(figsize, "figsize")
    _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    return ax


def plot_importance(booster, ax=None, height: float = 0.2,
                    xlim: Optional[Tuple[float, float]] = None,
                    ylim: Optional[Tuple[float, float]] = None,
                    title: Optional[str] = "Feature importance",
                    xlabel: Optional[str] = "Feature importance",
                    ylabel: Optional[str] = "Features",
                    importance_type: str = "auto",
                    max_num_features: Optional[int] = None,
                    ignore_zero: bool = True, figsize=None, dpi=None,
                    grid: bool = True, precision: Optional[int] = 3,
                    **kwargs):
    """Horizontal bar chart of feature importances
    (reference: plotting.py:37-168)."""
    plt = _import_matplotlib()
    booster = _to_booster(booster)
    if importance_type == "auto":
        importance_type = "split"
    imp = np.asarray(
        booster.feature_importance(importance_type=importance_type),
        dtype=np.float64)
    if imp.size == 0:
        raise ValueError("Booster's feature_importance is empty.")
    names = np.asarray(booster.feature_name(), dtype=object)

    # ascending by importance so the biggest bar lands on top
    order = np.argsort(imp, kind="stable")
    if ignore_zero:
        order = order[imp[order] > 0]
    if max_num_features is not None and max_num_features > 0:
        order = order[max(len(order) - max_num_features, 0):]
    shown = imp[order]
    rows = np.arange(shown.size)

    if ax is None:
        ax = _new_axes(plt, figsize, dpi)
    ax.barh(rows, shown, align="center", height=height, **kwargs)
    counts_only = importance_type == "split"
    for r, v in enumerate(shown):
        if counts_only or v.is_integer():
            ax.text(v + 1, r, f"{int(v)}", va="center")
        elif precision is None:
            ax.text(v, r, f"{v}", va="center")
        else:
            ax.text(v, r, f"{v:.{precision}f}", va="center")
    ax.set_yticks(rows)
    ax.set_yticklabels(names[order])
    ax.set_xlim(_window(xlim, "xlim") if xlim is not None
                else (0, 1.1 * shown.max() if shown.size else 1))
    ax.set_ylim(_window(ylim, "ylim") if ylim is not None
                else (-1, shown.size))
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_split_value_histogram(booster, feature, bins=None, ax=None,
                               width_coef: float = 0.8,
                               xlim=None, ylim=None,
                               title="Split value histogram for "
                                     "feature with @index/name@ @feature@",
                               xlabel="Feature split value",
                               ylabel="Count", figsize=None, dpi=None,
                               grid: bool = True, **kwargs):
    """Histogram of a feature's split threshold values
    (reference: plotting.py:171-284)."""
    plt = _import_matplotlib()
    booster = _to_booster(booster)
    model = booster.dump_model()
    feature_names = model.get("feature_names", [])
    if isinstance(feature, str):
        if feature not in feature_names:
            raise ValueError(f"feature {feature} not found")
        fidx = feature_names.index(feature)
        ftype = "name"
    else:
        fidx = int(feature)
        ftype = "index"

    values: List[float] = []

    def walk(node):
        if "split_feature" in node:
            if node["split_feature"] == fidx and \
                    node.get("decision_type") == "<=":
                values.append(node["threshold"])
            walk(node["left_child"])
            walk(node["right_child"])

    for tree in model["tree_info"]:
        walk(tree["tree_structure"])
    if not values:
        raise ValueError(
            "Cannot plot split value histogram, "
            f"because feature {feature} was not used in splitting")
    hist, bin_edges = np.histogram(values, bins=bins or "auto")
    if ax is None:
        ax = _new_axes(plt, figsize, dpi)
    centers = (bin_edges[:-1] + bin_edges[1:]) / 2.0
    width = width_coef * (bin_edges[1] - bin_edges[0]) \
        if len(bin_edges) > 1 else width_coef
    ax.bar(centers, hist, width=width, **kwargs)
    if xlim is not None:
        ax.set_xlim(_window(xlim, "xlim"))
    ax.set_ylim(_window(ylim, "ylim") if ylim is not None
                else (0, hist.max() * 1.1))
    if title is not None:
        title = title.replace("@feature@", str(feature)) \
                     .replace("@index/name@", ftype)
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(booster, metric: Optional[str] = None,
                dataset_names: Optional[List[str]] = None,
                ax=None, xlim=None, ylim=None,
                title: Optional[str] = "Metric during training",
                xlabel: Optional[str] = "Iterations",
                ylabel: Optional[str] = "@metric@",
                figsize=None, dpi=None, grid: bool = True):
    """Plot a metric recorded by ``record_evaluation``
    (reference: plotting.py:287-425)."""
    plt = _import_matplotlib()
    if isinstance(booster, dict):
        eval_results = deepcopy(booster)
    elif hasattr(booster, "evals_result_"):
        eval_results = deepcopy(booster.evals_result_)
    else:
        raise TypeError(
            "booster must be a dict from record_evaluation or a fitted "
            "LGBMModel with evals_result_")
    if not eval_results:
        raise ValueError("eval results cannot be empty.")

    names = (list(eval_results.keys()) if dataset_names is None
             else list(dataset_names))
    if metric is None:
        first = eval_results[names[0]]
        if len(first) > 1:
            raise ValueError("more than one metric available, pick one")
        metric = next(iter(first))
    curves = []
    for name in names:
        per_metric = eval_results[name]
        if metric not in per_metric:
            raise ValueError("No given metric in eval results.")
        curves.append((name, per_metric[metric]))

    if ax is None:
        ax = _new_axes(plt, figsize, dpi)
    for name, series in curves:
        ax.plot(range(len(series)), series, label=name)
    ax.legend(loc="best")

    if xlim is None:
        xlim = (0, max(len(s) for _, s in curves))
    if ylim is None:
        lo = min(min(s) for _, s in curves)
        hi = max(max(s) for _, s in curves)
        pad = (hi - lo) * 0.2
        ylim = (lo - pad, hi + pad)
    ax.set_xlim(_window(xlim, "xlim"))
    ax.set_ylim(_window(ylim, "ylim"))
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel.replace("@metric@", metric))
    ax.grid(grid)
    return ax


# ----------------------------------------------------------------------
# tree rendering
# ----------------------------------------------------------------------

def _tree_nodes(tree_structure: dict):
    """Flatten a dumped tree into (node_dict, depth, is_leaf) rows plus
    parent-child edges; assigns x positions by leaf order."""
    nodes = []
    edges = []
    next_x = [0.0]

    def walk(node, depth):
        my_id = len(nodes)
        nodes.append([node, depth, "left_child" not in node, 0.0])
        if "left_child" in node:
            lid = walk(node["left_child"], depth + 1)
            rid = walk(node["right_child"], depth + 1)
            edges.append((my_id, lid, True))
            edges.append((my_id, rid, False))
            nodes[my_id][3] = (nodes[lid][3] + nodes[rid][3]) / 2.0
        else:
            nodes[my_id][3] = next_x[0]
            next_x[0] += 1.0
        return my_id

    walk(tree_structure, 0)
    return nodes, edges


def _node_label(node: dict, feature_names, precision: int,
                show_info: List[str]) -> str:
    if "split_feature" in node:
        f = node["split_feature"]
        name = feature_names[f] if feature_names and f < len(feature_names) \
            else f"f{f}"
        op = node.get("decision_type", "<=")
        thr = node["threshold"]
        thr_s = thr if isinstance(thr, str) else f"{thr:.{precision}g}"
        label = f"{name} {op} {thr_s}"
        extra = []
        if "split_gain" in show_info:
            extra.append(f"gain: {node['split_gain']:.{precision}g}")
        if "internal_value" in show_info:
            extra.append(f"value: {node['internal_value']:.{precision}g}")
        if "internal_count" in show_info:
            extra.append(f"count: {node['internal_count']}")
        return "\n".join([label] + extra)
    extra = []
    if "leaf_count" in show_info and "leaf_count" in node:
        extra.append(f"count: {node['leaf_count']}")
    if "leaf_weight" in show_info and "leaf_weight" in node:
        extra.append(f"weight: {node['leaf_weight']:.{precision}g}")
    # single-leaf (constant) trees dump as {'leaf_value': v} with no index
    leaf_idx = node.get("leaf_index", 0)
    return "\n".join(
        [f"leaf {leaf_idx}: {node['leaf_value']:.{precision}g}"]
        + extra)


def plot_tree(booster, ax=None, tree_index: int = 0, figsize=None, dpi=None,
              show_info: Optional[List[str]] = None, precision: int = 3,
              orientation: str = "horizontal", **kwargs):
    """Draw one tree natively with matplotlib (reference plot_tree:740
    renders through graphviz; this implementation has no external binary
    dependency)."""
    plt = _import_matplotlib()
    booster = _to_booster(booster)
    model = booster.dump_model()
    if tree_index >= len(model["tree_info"]):
        raise IndexError(f"tree_index {tree_index} out of range")
    tree = model["tree_info"][tree_index]
    feature_names = model.get("feature_names")
    show_info = show_info or []

    nodes, edges = _tree_nodes(tree["tree_structure"])
    max_depth = max(d for _, d, _, _ in nodes) if nodes else 0
    n_leaves = sum(1 for _, _, is_leaf, _ in nodes if is_leaf)

    if ax is None:
        if figsize is None:
            figsize = (max(6, n_leaves * 1.8), max(4, (max_depth + 1) * 1.6))
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)

    horizontal = orientation == "horizontal"

    def xy(node_row):
        _, depth, _, x = node_row
        return (depth, -x) if horizontal else (x, -depth)

    for pid, cid, is_left in edges:
        x0, y0 = xy(nodes[pid])
        x1, y1 = xy(nodes[cid])
        ax.plot([x0, x1], [y0, y1], "-", color="0.6", zorder=1)
        ax.annotate("yes" if is_left else "no",
                    ((x0 + x1) / 2, (y0 + y1) / 2),
                    fontsize=7, color="0.4", ha="center", zorder=2)

    for row in nodes:
        node, depth, is_leaf, _ = row
        x, y = xy(row)
        label = _node_label(node, feature_names, precision, show_info)
        ax.annotate(
            label, (x, y), ha="center", va="center", fontsize=8, zorder=3,
            bbox=dict(boxstyle="round,pad=0.4",
                      fc="#e8f4e8" if is_leaf else "#e8eef8",
                      ec="0.5"))
    ax.axis("off")
    ax.set_title(f"Tree {tree_index}")
    return ax


def create_tree_digraph(booster, tree_index: int = 0,
                        show_info: Optional[List[str]] = None,
                        precision: int = 3,
                        orientation: str = "horizontal",
                        name: Optional[str] = None, comment: Optional[str] = None,
                        filename: Optional[str] = None,
                        directory: Optional[str] = None,
                        format: Optional[str] = None,  # noqa: A002
                        engine: Optional[str] = None,
                        encoding: Optional[str] = None,
                        graph_attr: Optional[Dict[str, str]] = None,
                        node_attr: Optional[Dict[str, str]] = None,
                        edge_attr: Optional[Dict[str, str]] = None):
    """Build a graphviz Digraph of one tree (reference: plotting.py:614).

    Requires the optional ``graphviz`` python package.
    """
    try:
        import graphviz
    except ImportError as e:
        raise ImportError(
            "You must install graphviz to use create_tree_digraph; "
            "plot_tree renders natively with matplotlib instead") from e
    booster = _to_booster(booster)
    model = booster.dump_model()
    if tree_index >= len(model["tree_info"]):
        raise IndexError(f"tree_index {tree_index} out of range")
    tree = model["tree_info"][tree_index]
    feature_names = model.get("feature_names")
    show_info = show_info or []

    graph = graphviz.Digraph(
        name=name, comment=comment, filename=filename, directory=directory,
        format=format, engine=engine, encoding=encoding,
        graph_attr=graph_attr, node_attr=node_attr, edge_attr=edge_attr)
    if orientation == "horizontal":
        graph.attr(rankdir="LR")

    counter = [0]

    def walk(node) -> str:
        nid = f"n{counter[0]}"
        counter[0] += 1
        label = _node_label(node, feature_names, precision, show_info) \
            .replace("\n", "\\n")
        is_leaf = "split_feature" not in node
        graph.node(nid, label=label, shape="box" if not is_leaf else "ellipse")
        if not is_leaf:
            lid = walk(node["left_child"])
            rid = walk(node["right_child"])
            graph.edge(nid, lid, label="yes")
            graph.edge(nid, rid, label="no")
        return nid

    walk(tree["tree_structure"])
    return graph
