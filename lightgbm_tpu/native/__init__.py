"""Native (C++) runtime components, loaded via ctypes.

The reference implements its data loader in C++ (src/io/parser.cpp,
src/io/dataset_loader.cpp); this package provides the TPU framework's
native equivalents.  The shared library is compiled on demand with g++
(cached beside the source) and every entry point has a pure-Python
fallback, so the framework works even without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

from ..utils import log

_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False

_SRC = os.path.join(os.path.dirname(__file__), "text_parser.cpp")
_SO = os.path.join(os.path.dirname(__file__), "_text_parser.so")


def _configure(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.lgbm_parse_delim.restype = ctypes.POINTER(ctypes.c_double)
    lib.lgbm_parse_delim.argtypes = [
        ctypes.c_char_p, ctypes.c_long, ctypes.c_char, ctypes.c_int,
        ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_int)]
    lib.lgbm_parse_libsvm.restype = ctypes.POINTER(ctypes.c_double)
    lib.lgbm_parse_libsvm.argtypes = [
        ctypes.c_char_p, ctypes.c_long, ctypes.c_int,
        ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_double))]
    lib.lgbm_native_free.restype = None
    lib.lgbm_native_free.argtypes = [ctypes.c_void_p]
    return lib


def get_native() -> Optional[ctypes.CDLL]:
    """Return the native library, building it on first use (or None)."""
    global _LIB, _TRIED
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        try:
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                tmp = _SO + ".tmp"
                # invariant: _LOCK exists precisely so concurrent
                # importers BLOCK on the one-time compile instead of
                # racing g++ over the same .so; blocking under it is
                # the contract, not a bug (runs at most once per
                # source change, _TRIED gates every later call)
                subprocess.run(               # conlint: ok=CL003
                    ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
                     "-pthread", "-o", tmp, _SRC],
                    check=True, capture_output=True)
                os.replace(tmp, _SO)          # conlint: ok=CL003
            _LIB = _configure(ctypes.CDLL(_SO))
        except Exception as exc:  # missing g++, sandboxed fs, ...
            log.info("native text parser unavailable (%s); "
                     "using the Python fallback", exc)
            _LIB = None
        return _LIB


def parse_delim(text, sep: str,
                num_threads: int = 0) -> Optional[np.ndarray]:
    """Parse delimited text (str or bytes) into a dense (R, C) float64
    matrix, or None if the native library is unavailable."""
    lib = get_native()
    if lib is None:
        return None
    buf = text if isinstance(text, bytes) else text.encode()
    rows = ctypes.c_long()
    cols = ctypes.c_int()
    ptr = lib.lgbm_parse_delim(buf, len(buf), sep.encode(), num_threads,
                               ctypes.byref(rows), ctypes.byref(cols))
    if not ptr or rows.value == 0 or cols.value == 0:
        if ptr:
            lib.lgbm_native_free(ptr)
        return np.zeros((rows.value, cols.value), dtype=np.float64)
    arr = np.ctypeslib.as_array(ptr, shape=(rows.value, cols.value)).copy()
    lib.lgbm_native_free(ptr)
    return arr


def parse_libsvm(text, num_threads: int = 0
                 ) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Parse LibSVM text (str or bytes) into (X dense (R, C), labels (R,),
    qids (R,) with NaN where absent), or None."""
    lib = get_native()
    if lib is None:
        return None
    buf = text if isinstance(text, bytes) else text.encode()
    rows = ctypes.c_long()
    cols = ctypes.c_int()
    labels_ptr = ctypes.POINTER(ctypes.c_double)()
    qids_ptr = ctypes.POINTER(ctypes.c_double)()
    ptr = lib.lgbm_parse_libsvm(buf, len(buf), num_threads,
                                ctypes.byref(rows), ctypes.byref(cols),
                                ctypes.byref(labels_ptr),
                                ctypes.byref(qids_ptr))
    def _take(p, shape, default):
        if p:
            arr = np.ctypeslib.as_array(p, shape=shape).copy()
            lib.lgbm_native_free(p)
            return arr
        return default
    R = rows.value
    labels = _take(labels_ptr, (R,), np.zeros(R, dtype=np.float64)) \
        if R else np.zeros(0, dtype=np.float64)
    qids = _take(qids_ptr, (R,), np.full(R, np.nan)) \
        if R else np.zeros(0, dtype=np.float64)
    if ptr and cols.value > 0 and R:
        X = _take(ptr, (R, cols.value), None)
    else:
        if ptr:
            lib.lgbm_native_free(ptr)
        X = np.zeros((R, 0), dtype=np.float64)
    return X, labels, qids
