"""Native (C++) runtime components, loaded via ctypes.

The reference implements its data loader in C++ (src/io/parser.cpp,
src/io/dataset_loader.cpp); this package provides the TPU framework's
native equivalents.  The shared library is compiled on demand with g++
(cached beside the source) and every entry point has a pure-Python
fallback, so the framework works even without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

from ..utils import log

_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False

_SRC = os.path.join(os.path.dirname(__file__), "text_parser.cpp")
_SO = os.path.join(os.path.dirname(__file__), "_text_parser.so")


def _configure(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.lgbm_parse_delim.restype = ctypes.POINTER(ctypes.c_double)
    lib.lgbm_parse_delim.argtypes = [
        ctypes.c_char_p, ctypes.c_long, ctypes.c_char, ctypes.c_int,
        ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_int)]
    lib.lgbm_parse_libsvm.restype = ctypes.POINTER(ctypes.c_double)
    lib.lgbm_parse_libsvm.argtypes = [
        ctypes.c_char_p, ctypes.c_long, ctypes.c_int,
        ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_double))]
    lib.lgbm_native_free.restype = None
    lib.lgbm_native_free.argtypes = [ctypes.c_void_p]
    return lib


def get_native() -> Optional[ctypes.CDLL]:
    """Return the native library, building it on first use (or None)."""
    global _LIB, _TRIED
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        try:
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                tmp = _SO + ".tmp"
                subprocess.run(
                    ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
                     "-pthread", "-o", tmp, _SRC],
                    check=True, capture_output=True)
                os.replace(tmp, _SO)
            _LIB = _configure(ctypes.CDLL(_SO))
        except Exception as exc:  # missing g++, sandboxed fs, ...
            log.info("native text parser unavailable (%s); "
                     "using the Python fallback", exc)
            _LIB = None
        return _LIB


def parse_delim(text: str, sep: str,
                num_threads: int = 0) -> Optional[np.ndarray]:
    """Parse delimited text into a dense (R, C) float64 matrix, or None if
    the native library is unavailable."""
    lib = get_native()
    if lib is None:
        return None
    buf = text.encode()
    rows = ctypes.c_long()
    cols = ctypes.c_int()
    ptr = lib.lgbm_parse_delim(buf, len(buf), sep.encode(), num_threads,
                               ctypes.byref(rows), ctypes.byref(cols))
    if not ptr or rows.value == 0 or cols.value == 0:
        if ptr:
            lib.lgbm_native_free(ptr)
        return np.zeros((rows.value, cols.value), dtype=np.float64)
    arr = np.ctypeslib.as_array(ptr, shape=(rows.value, cols.value)).copy()
    lib.lgbm_native_free(ptr)
    return arr


def parse_libsvm(text: str, num_threads: int = 0
                 ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Parse LibSVM text into (X dense (R, C), labels (R,)), or None."""
    lib = get_native()
    if lib is None:
        return None
    buf = text.encode()
    rows = ctypes.c_long()
    cols = ctypes.c_int()
    labels_ptr = ctypes.POINTER(ctypes.c_double)()
    ptr = lib.lgbm_parse_libsvm(buf, len(buf), num_threads,
                                ctypes.byref(rows), ctypes.byref(cols),
                                ctypes.byref(labels_ptr))
    if rows.value == 0:
        if ptr:
            lib.lgbm_native_free(ptr)
        if labels_ptr:
            lib.lgbm_native_free(labels_ptr)
        return (np.zeros((0, 0), dtype=np.float64),
                np.zeros(0, dtype=np.float64))
    labels = np.ctypeslib.as_array(labels_ptr, shape=(rows.value,)).copy() \
        if labels_ptr else np.zeros(rows.value, dtype=np.float64)
    if ptr and cols.value > 0:
        X = np.ctypeslib.as_array(ptr, shape=(rows.value, cols.value)).copy()
    else:
        X = np.zeros((rows.value, 0), dtype=np.float64)
    if ptr:
        lib.lgbm_native_free(ptr)
    if labels_ptr:
        lib.lgbm_native_free(labels_ptr)
    return X, labels
