// Multi-threaded text -> dense matrix parser.
//
// Native runtime component of lightgbm_tpu, standing in for the reference's
// C++ Parser / DatasetLoader text path (reference: src/io/parser.cpp
// CSVParser/TSVParser/LibSVMParser, src/io/dataset_loader.cpp
// LoadFromFile): line indexing, per-thread chunked parsing, missing-value
// tokens ("", na, nan, null, none) -> NaN, ragged rows padded with NaN.
//
// Exposed through a minimal C ABI consumed via ctypes
// (lightgbm_tpu/native/__init__.py); compiled on demand with g++.

#include <algorithm>
#include <cctype>
#include <cmath>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

namespace {

struct LineIndex {
  std::vector<const char*> starts;
  std::vector<long> lens;
};

LineIndex IndexLines(const char* buf, long len) {
  LineIndex out;
  long i = 0;
  while (i < len) {
    long start = i;
    while (i < len && buf[i] != '\n') ++i;
    long end = i;
    if (end > start && buf[end - 1] == '\r') --end;
    bool nonempty = false;
    for (long j = start; j < end; ++j) {
      if (!std::isspace(static_cast<unsigned char>(buf[j]))) {
        nonempty = true;
        break;
      }
    }
    if (nonempty) {
      out.starts.push_back(buf + start);
      out.lens.push_back(end - start);
    }
    ++i;
  }
  return out;
}

bool IsMissingToken(const char* s, long n) {
  while (n > 0 && std::isspace(static_cast<unsigned char>(*s))) { ++s; --n; }
  while (n > 0 && std::isspace(static_cast<unsigned char>(s[n - 1]))) --n;
  if (n == 0) return true;
  static const char* kWords[] = {"na", "nan", "null", "none"};
  for (const char* w : kWords) {
    const long wl = static_cast<long>(std::strlen(w));
    if (n == wl) {
      bool eq = true;
      for (long k = 0; k < wl; ++k) {
        if (std::tolower(static_cast<unsigned char>(s[k])) != w[k]) {
          eq = false;
          break;
        }
      }
      if (eq) return true;
    }
  }
  return false;
}

// Parse one token [s, s+n) like Python float(): full consumption required,
// no hex floats, single underscores allowed between digits.
double ParseToken(const char* s, long n) {
  while (n > 0 && std::isspace(static_cast<unsigned char>(*s))) { ++s; --n; }
  while (n > 0 && std::isspace(static_cast<unsigned char>(s[n - 1]))) --n;
  if (n == 0) return NAN;
  // Python float() rejects hex literals that strtod accepts
  {
    long k = 0;
    if (k < n && (s[k] == '+' || s[k] == '-')) ++k;
    if (k + 1 < n && s[k] == '0' && (s[k + 1] == 'x' || s[k + 1] == 'X')) {
      return NAN;
    }
  }
  char buf[64];
  if (std::memchr(s, '_', n) != nullptr) {
    // Python float() allows single underscores BETWEEN digits
    if (n >= static_cast<long>(sizeof(buf))) return NAN;
    long m = 0;
    for (long k = 0; k < n; ++k) {
      if (s[k] == '_') {
        const bool ok = k > 0 && k + 1 < n &&
            std::isdigit(static_cast<unsigned char>(s[k - 1])) &&
            std::isdigit(static_cast<unsigned char>(s[k + 1]));
        if (!ok) return NAN;
        continue;
      }
      buf[m++] = s[k];
    }
    buf[m] = '\0';
    char* end = nullptr;
    const double v = std::strtod(buf, &end);
    if (end != buf + m) return NAN;
    return v;
  }
  char* end = nullptr;
  const double v = std::strtod(s, &end);
  if (end != s + n) return NAN;
  return v;
}

int ResolveThreads(int num_threads) {
  if (num_threads <= 0) {
    num_threads = static_cast<int>(std::thread::hardware_concurrency());
  }
  return std::max(1, std::min(num_threads, 64));
}

template <typename Fn>
void ParallelFor(int num_threads, Fn&& fn) {
  if (num_threads == 1) {
    fn(0);
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(num_threads);
  for (int t = 0; t < num_threads; ++t) pool.emplace_back(fn, t);
  for (auto& th : pool) th.join();
}

}  // namespace

extern "C" {

// Delimited (CSV/TSV/...) parse: returns a malloc'd row-major (R x C) double
// matrix; rows shorter than C are NaN-padded.  Caller frees with
// lgbm_native_free.
double* lgbm_parse_delim(const char* buf, long len, char sep, int num_threads,
                         long* n_rows_out, int* n_cols_out) {
  const LineIndex lines = IndexLines(buf, len);
  const long R = static_cast<long>(lines.starts.size());
  *n_rows_out = R;
  *n_cols_out = 0;
  if (R == 0) return nullptr;
  const int T = ResolveThreads(num_threads);

  std::vector<int> tmax(T, 1);
  ParallelFor(T, [&](int t) {
    int mx = 1;
    for (long i = t; i < R; i += T) {
      int c = 1;
      const char* s = lines.starts[i];
      const long n = lines.lens[i];
      for (long j = 0; j < n; ++j) c += (s[j] == sep);
      mx = std::max(mx, c);
    }
    tmax[t] = mx;
  });
  const int C = *std::max_element(tmax.begin(), tmax.end());

  double* mat = static_cast<double*>(std::malloc(sizeof(double) * R * C));
  if (mat == nullptr) return nullptr;
  ParallelFor(T, [&](int t) {
    for (long i = t; i < R; i += T) {
      const char* s = lines.starts[i];
      const long n = lines.lens[i];
      double* row = mat + i * C;
      int col = 0;
      long tok_start = 0;
      for (long j = 0; j <= n && col < C; ++j) {
        if (j == n || s[j] == sep) {
          const char* tok = s + tok_start;
          const long tlen = j - tok_start;
          row[col++] = IsMissingToken(tok, tlen) ? NAN : ParseToken(tok, tlen);
          tok_start = j + 1;
        }
      }
      for (; col < C; ++col) row[col] = NAN;
    }
  });
  *n_cols_out = C;
  return mat;
}

namespace {

// Parse "key:value"; returns feature index, or -1 for qid (value stored in
// *qid), or -2 for any other non-integer key (ignored, like the reference
// parser skipping malformed pairs).
long ParseSvmKey(const char* p, const char* colon, double* qid,
                 const char* colon_end) {
  char* iend = nullptr;
  const long idx = std::strtol(p, &iend, 10);
  if (iend == colon && iend != p) return idx;
  if (colon - p == 3 && p[0] == 'q' && p[1] == 'i' && p[2] == 'd') {
    *qid = std::strtod(colon + 1, nullptr);
    (void)colon_end;
    return -1;
  }
  return -2;
}

}  // namespace

// LibSVM parse ("label [qid:q] idx:val idx:val ..."): returns a malloc'd
// dense (R x C) feature matrix (zeros for absent entries); labels and
// per-row qids (NaN when absent) written to malloc'd (R,) arrays.
double* lgbm_parse_libsvm(const char* buf, long len, int num_threads,
                          long* n_rows_out, int* n_cols_out,
                          double** labels_out, double** qids_out) {
  const LineIndex lines = IndexLines(buf, len);
  const long R = static_cast<long>(lines.starts.size());
  *n_rows_out = R;
  *n_cols_out = 0;
  *labels_out = nullptr;
  *qids_out = nullptr;
  if (R == 0) return nullptr;
  const int T = ResolveThreads(num_threads);

  double* labels = static_cast<double*>(std::malloc(sizeof(double) * R));
  double* qids = static_cast<double*>(std::malloc(sizeof(double) * R));
  if (labels == nullptr || qids == nullptr) {
    std::free(labels);
    std::free(qids);
    return nullptr;
  }
  std::vector<long> tmaxf(T, -1);
  ParallelFor(T, [&](int t) {
    long mx = -1;
    for (long i = t; i < R; i += T) {
      const char* s = lines.starts[i];
      const char* endl = s + lines.lens[i];
      char* end = nullptr;
      labels[i] = std::strtod(s, &end);
      qids[i] = NAN;
      const char* p = end;
      while (p < endl) {
        while (p < endl && std::isspace(static_cast<unsigned char>(*p))) ++p;
        const char* colon = p;
        while (colon < endl && *colon != ':' &&
               !std::isspace(static_cast<unsigned char>(*colon))) ++colon;
        if (colon >= endl || *colon != ':') { p = colon; continue; }
        const long idx = ParseSvmKey(p, colon, &qids[i], endl);
        if (idx >= 0) mx = std::max(mx, idx);
        p = colon + 1;
        while (p < endl && !std::isspace(static_cast<unsigned char>(*p))) ++p;
      }
    }
    tmaxf[t] = mx;
  });
  const long maxf = *std::max_element(tmaxf.begin(), tmaxf.end());
  const int C = static_cast<int>(maxf + 1);
  if (C <= 0) {
    *labels_out = labels;
    *qids_out = qids;
    return nullptr;
  }
  double* mat = static_cast<double*>(std::calloc(R * C, sizeof(double)));
  if (mat == nullptr) {
    std::free(labels);
    std::free(qids);
    return nullptr;
  }
  ParallelFor(T, [&](int t) {
    for (long i = t; i < R; i += T) {
      const char* s = lines.starts[i];
      const char* endl = s + lines.lens[i];
      char* end = nullptr;
      std::strtod(s, &end);  // skip label
      const char* p = end;
      double* row = mat + i * C;
      double qid_dummy;
      while (p < endl) {
        while (p < endl && std::isspace(static_cast<unsigned char>(*p))) ++p;
        const char* colon = p;
        while (colon < endl && *colon != ':' &&
               !std::isspace(static_cast<unsigned char>(*colon))) ++colon;
        if (colon >= endl || *colon != ':') { p = colon; continue; }
        const long idx = ParseSvmKey(p, colon, &qid_dummy, endl);
        char* vend = nullptr;
        const double v = std::strtod(colon + 1, &vend);
        if (idx >= 0 && idx < C) row[idx] = v;
        p = vend;
      }
    }
  });
  *labels_out = labels;
  *qids_out = qids;
  *n_cols_out = C;
  return mat;
}

void lgbm_native_free(void* p) { std::free(p); }

}  // extern "C"
