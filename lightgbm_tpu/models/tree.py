"""Host-side tree model: structure, prediction on raw features, text serde.

TPU-native counterpart of the reference Tree (include/LightGBM/tree.h:25-729,
src/io/tree.cpp): training happens on device (models/learner.py); the finished
tree is pulled to the host as flat arrays in the reference's layout so that
model files are interchangeable with the reference's text format
(src/boosting/gbdt_model_text.cpp, src/io/tree.cpp Tree::ToString:340-408).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

import numpy as np

K_ZERO_THRESHOLD = 1e-35

# decision_type bit layout (reference: include/LightGBM/tree.h:19-20,260-278)
K_CATEGORICAL_MASK = 1
K_DEFAULT_LEFT_MASK = 2

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2


class Tree:
    """Flat-array binary tree (reference: include/LightGBM/tree.h:25)."""

    def __init__(self, num_leaves: int):
        n = max(num_leaves - 1, 0)
        self.num_leaves = num_leaves
        self.split_feature: np.ndarray = np.zeros(n, dtype=np.int32)
        self.threshold_bin: np.ndarray = np.zeros(n, dtype=np.int32)
        self.threshold: np.ndarray = np.zeros(n, dtype=np.float64)
        self.decision_type: np.ndarray = np.zeros(n, dtype=np.int8)
        self.left_child: np.ndarray = np.zeros(n, dtype=np.int32)
        self.right_child: np.ndarray = np.zeros(n, dtype=np.int32)
        self.split_gain: np.ndarray = np.zeros(n, dtype=np.float64)
        self.internal_value: np.ndarray = np.zeros(n, dtype=np.float64)
        self.internal_weight: np.ndarray = np.zeros(n, dtype=np.float64)
        self.internal_count: np.ndarray = np.zeros(n, dtype=np.int64)
        self.leaf_value: np.ndarray = np.zeros(num_leaves, dtype=np.float64)
        self.leaf_weight: np.ndarray = np.zeros(num_leaves, dtype=np.float64)
        self.leaf_count: np.ndarray = np.zeros(num_leaves, dtype=np.int64)
        self.shrinkage: float = 1.0
        self.num_cat: int = 0
        self.cat_boundaries: List[int] = [0]
        self.cat_threshold: List[int] = []
        self.is_linear: bool = False
        # linear-tree leaf models (reference: tree.h leaf_coeff_/leaf_const_/
        # leaf_features_, populated by LinearTreeLearner::CalculateLinear)
        self.leaf_const: np.ndarray = np.zeros(num_leaves, dtype=np.float64)
        self.leaf_features: List[List[int]] = [[] for _ in range(num_leaves)]
        self.leaf_coeff: List[List[float]] = [[] for _ in range(num_leaves)]

    # -- decision bits --------------------------------------------------
    @staticmethod
    def pack_decision_type(categorical: bool, default_left: bool,
                           missing_type: int) -> int:
        d = 0
        if categorical:
            d |= K_CATEGORICAL_MASK
        if default_left:
            d |= K_DEFAULT_LEFT_MASK
        d |= (missing_type & 3) << 2
        return d

    @staticmethod
    def unpack_decision_type(d: int):
        return bool(d & K_CATEGORICAL_MASK), bool(d & K_DEFAULT_LEFT_MASK), (d >> 2) & 3

    # -- prediction on raw feature values -------------------------------
    def predict(self, data: np.ndarray) -> np.ndarray:
        """Vectorized traversal (reference: tree.h Predict/NumericalDecision:335)."""
        n = data.shape[0]
        if self.num_leaves <= 1:
            if self.is_linear:
                return self._linear_output(data,
                                           np.zeros(n, dtype=np.int32))
            return np.full(n, self.leaf_value[0] if len(self.leaf_value) else 0.0)
        out_leaf = self.predict_leaf(data)
        if self.is_linear:
            return self._linear_output(data, out_leaf)
        return self.leaf_value[out_leaf]

    def _linear_output(self, data: np.ndarray, leaf: np.ndarray) -> np.ndarray:
        """Linear-leaf prediction with per-row NaN fallback to the constant
        leaf value (reference: tree.cpp PredictLinear macro, tree.cpp:133)."""
        out = np.empty(len(leaf), dtype=np.float64)
        for lf in np.unique(leaf):
            sel = leaf == lf
            feats = self.leaf_features[lf]
            if not feats:
                out[sel] = self.leaf_const[lf]
                continue
            sub = data[np.ix_(sel, np.asarray(feats, dtype=np.intp))] \
                .astype(np.float64)
            vals = self.leaf_const[lf] + sub.dot(
                np.asarray(self.leaf_coeff[lf], dtype=np.float64))
            nan_rows = np.isnan(sub).any(axis=1)
            out[sel] = np.where(nan_rows, self.leaf_value[lf], vals)
        return out

    def predict_leaf(self, data: np.ndarray) -> np.ndarray:
        n = data.shape[0]
        if self.num_leaves <= 1:
            return np.zeros(n, dtype=np.int32)
        node = np.zeros(n, dtype=np.int32)
        active = np.ones(n, dtype=bool)
        result = np.zeros(n, dtype=np.int32)
        for _ in range(self.num_leaves * 2):
            if not active.any():
                break
            nid = node[active]
            f = self.split_feature[nid]
            fval = data[active, f].astype(np.float64)
            dtp = self.decision_type[nid]
            is_cat = (dtp & K_CATEGORICAL_MASK) != 0
            dleft = (dtp & K_DEFAULT_LEFT_MASK) != 0
            mtype = (dtp.astype(np.int32) >> 2) & 3
            nan_mask = np.isnan(fval)
            fv = np.where(nan_mask & (mtype != MISSING_NAN), 0.0, fval)
            is_missing = ((mtype == MISSING_ZERO) & (np.abs(fv) <= K_ZERO_THRESHOLD)) | \
                         ((mtype == MISSING_NAN) & nan_mask)
            goes_left = np.where(is_missing, dleft, fv <= self.threshold[nid])
            if is_cat.any():
                goes_left = np.where(
                    is_cat, self._categorical_decision(nid, fval), goes_left)
            nxt = np.where(goes_left, self.left_child[nid], self.right_child[nid])
            leaf_hit = nxt < 0
            act_idx = np.nonzero(active)[0]
            result[act_idx[leaf_hit]] = ~nxt[leaf_hit]
            node[act_idx] = np.where(leaf_hit, node[act_idx], nxt)
            still = np.zeros(n, dtype=bool)
            still[act_idx[~leaf_hit]] = True
            active = still
        return result

    def _cat_np(self):
        """Cached ndarray views of the category bitsets (rebuilt only when
        the underlying lists grow)."""
        cached = getattr(self, "_cat_cache", None)
        if cached is None or cached[2] != len(self.cat_threshold):
            bounds = np.asarray(self.cat_boundaries, dtype=np.int64)
            words = np.asarray(self.cat_threshold, dtype=np.uint32) \
                if self.cat_threshold else np.zeros(1, dtype=np.uint32)
            cached = (bounds, words, len(self.cat_threshold))
            self._cat_cache = cached
        return cached[0], cached[1]

    def _categorical_decision(self, nid, fval):
        """reference: tree.h CategoricalDecision:400 (bitset membership).

        Vectorized: the vector is evaluated for every active row and
        non-categorical nodes are masked out by the caller.
        """
        nid = np.asarray(nid)
        is_cat = (self.decision_type[nid] & K_CATEGORICAL_MASK) != 0
        # the reference truncates toward zero (static_cast<int>) and sends
        # negative ints right; values beyond int32 cannot be categories
        tv = np.trunc(fval)
        ok = is_cat & np.isfinite(fval) & (tv >= 0) & (tv < 2.0 ** 31)
        iv = np.where(ok, tv, 0).astype(np.int64)
        cat_idx = np.where(is_cat, self.threshold[nid], 0).astype(np.int64)
        bounds, words = self._cat_np()
        lo = bounds[cat_idx]
        hi = bounds[np.minimum(cat_idx + 1, len(bounds) - 1)]
        word = iv // 32
        in_set = word < (hi - lo)
        widx = np.minimum(lo + word, len(words) - 1)
        bit = (words[widx] >> (iv % 32).astype(np.uint32)) & 1
        return ok & in_set & (bit != 0)

    # -- serialization ---------------------------------------------------
    def to_string(self, tree_index: int) -> str:
        """reference: Tree::ToString (src/io/tree.cpp:340)."""
        def join(arr, fmt="{:g}"):
            return " ".join(fmt.format(x) for x in arr)

        lines = [f"Tree={tree_index}",
                 f"num_leaves={self.num_leaves}",
                 f"num_cat={self.num_cat}"]
        if self.num_leaves > 1:
            lines.append("split_feature=" + join(self.split_feature, "{:d}"))
            lines.append("split_gain=" + join(self.split_gain))
            lines.append("threshold=" + " ".join(
                repr(float(t)) for t in self.threshold))
            lines.append("decision_type=" + join(self.decision_type, "{:d}"))
            lines.append("left_child=" + join(self.left_child, "{:d}"))
            lines.append("right_child=" + join(self.right_child, "{:d}"))
            lines.append("leaf_value=" + " ".join(
                repr(float(v)) for v in self.leaf_value[:self.num_leaves]))
            lines.append("leaf_weight=" + join(self.leaf_weight[:self.num_leaves]))
            lines.append("leaf_count=" + join(self.leaf_count[:self.num_leaves], "{:d}"))
            lines.append("internal_value=" + join(self.internal_value))
            lines.append("internal_weight=" + join(self.internal_weight))
            lines.append("internal_count=" + join(self.internal_count, "{:d}"))
            if self.num_cat > 0:
                lines.append("cat_boundaries=" + join(self.cat_boundaries, "{:d}"))
                lines.append("cat_threshold=" + join(self.cat_threshold, "{:d}"))
        else:
            lines.append("leaf_value=" + repr(float(
                self.leaf_value[0] if len(self.leaf_value) else 0.0)))
        lines.append(f"is_linear={int(self.is_linear)}")
        if self.is_linear:
            # reference: Tree::ToString linear block (tree.cpp:377-399)
            lines.append("leaf_const=" + " ".join(
                repr(float(v)) for v in self.leaf_const[:self.num_leaves]))
            lines.append("num_features=" + join(
                [len(c) for c in self.leaf_coeff[:self.num_leaves]], "{:d}"))
            lines.append("leaf_features=" + " ".join(
                " ".join(str(f) for f in feats)
                for feats in self.leaf_features[:self.num_leaves]
                if feats is not None))
            lines.append("leaf_coeff=" + " ".join(
                " ".join(repr(float(c)) for c in coefs)
                for coefs in self.leaf_coeff[:self.num_leaves]))
        lines.append(f"shrinkage={self.shrinkage:g}")
        lines.append("")
        lines.append("")
        return "\n".join(lines)

    @classmethod
    def from_string(cls, text: str) -> "Tree":
        kv: Dict[str, str] = {}
        for line in text.splitlines():
            line = line.strip()
            if "=" in line:
                k, v = line.split("=", 1)
                kv[k] = v

        num_leaves = int(kv.get("num_leaves", 1))
        t = cls(num_leaves)
        t.num_cat = int(kv.get("num_cat", 0))

        def parse(key, dtype, n):
            if key not in kv or not kv[key].strip():
                return np.zeros(n, dtype=dtype)
            return np.asarray([dtype(x) for x in kv[key].split()], dtype=dtype)

        if num_leaves > 1:
            n = num_leaves - 1
            t.split_feature = parse("split_feature", np.int32, n)
            t.split_gain = parse("split_gain", np.float64, n)
            t.threshold = parse("threshold", np.float64, n)
            t.decision_type = parse("decision_type", np.int8, n)
            t.left_child = parse("left_child", np.int32, n)
            t.right_child = parse("right_child", np.int32, n)
            t.leaf_value = parse("leaf_value", np.float64, num_leaves)
            t.leaf_weight = parse("leaf_weight", np.float64, num_leaves)
            t.leaf_count = parse("leaf_count", np.int64, num_leaves)
            t.internal_value = parse("internal_value", np.float64, n)
            t.internal_weight = parse("internal_weight", np.float64, n)
            t.internal_count = parse("internal_count", np.int64, n)
            if t.num_cat > 0:
                t.cat_boundaries = [int(x) for x in kv["cat_boundaries"].split()]
                t.cat_threshold = [int(x) for x in kv["cat_threshold"].split()]
        else:
            t.leaf_value = np.asarray([float(kv.get("leaf_value", 0.0))])
        t.shrinkage = float(kv.get("shrinkage", 1.0))
        t.is_linear = bool(int(kv.get("is_linear", 0)))
        if t.is_linear:
            t.leaf_const = parse("leaf_const", np.float64, num_leaves)
            nfeat = parse("num_features", np.int64, num_leaves)
            flat_f = [int(x) for x in kv.get("leaf_features", "").split()]
            flat_c = [float(x) for x in kv.get("leaf_coeff", "").split()]
            pos = 0
            for i in range(num_leaves):
                k = int(nfeat[i])
                t.leaf_features[i] = flat_f[pos:pos + k]
                t.leaf_coeff[i] = flat_c[pos:pos + k]
                pos += k
        return t

    def to_json(self) -> dict:
        """reference: Tree::ToJSON (src/io/tree.cpp:411)."""
        out = {"num_leaves": int(self.num_leaves), "num_cat": int(self.num_cat),
               "shrinkage": self.shrinkage}
        if self.num_leaves == 1:
            out["tree_structure"] = {"leaf_value": float(
                self.leaf_value[0] if len(self.leaf_value) else 0.0)}
        else:
            out["tree_structure"] = self._node_to_json(0)
        return out

    def _cats_of_node(self, node: int) -> List[int]:
        """Decode a categorical node's bitset into category values."""
        cat_idx = int(self.threshold[node])
        lo = self.cat_boundaries[cat_idx]
        hi = self.cat_boundaries[cat_idx + 1]
        cats = []
        for w in range(lo, hi):
            word = self.cat_threshold[w]
            base = (w - lo) * 32
            for b in range(32):
                if (word >> b) & 1:
                    cats.append(base + b)
        return cats

    def _node_to_json(self, node: int) -> dict:
        if node >= 0:
            cat, dleft, mtype = self.unpack_decision_type(int(self.decision_type[node]))
            # categorical nodes dump the category list 'a||b||c'
            # (reference: Tree::NodeToJSON categorical arm)
            thr = "||".join(str(c) for c in self._cats_of_node(node)) \
                if cat else float(self.threshold[node])
            return {
                "split_index": int(node),
                "split_feature": int(self.split_feature[node]),
                "split_gain": float(self.split_gain[node]),
                "threshold": thr,
                "decision_type": "==" if cat else "<=",
                "default_left": bool(dleft),
                "missing_type": ["None", "Zero", "NaN"][mtype],
                "internal_value": float(self.internal_value[node]),
                "internal_weight": float(self.internal_weight[node]),
                "internal_count": int(self.internal_count[node]),
                "left_child": self._node_to_json(int(self.left_child[node])),
                "right_child": self._node_to_json(int(self.right_child[node])),
            }
        leaf = ~node
        out = {
            "leaf_index": int(leaf),
            "leaf_value": float(self.leaf_value[leaf]),
            "leaf_weight": float(self.leaf_weight[leaf]),
            "leaf_count": int(self.leaf_count[leaf]),
        }
        if self.is_linear:
            out["leaf_const"] = float(self.leaf_const[leaf])
            out["leaf_features"] = list(self.leaf_features[leaf])
            out["leaf_coeff"] = [float(c) for c in self.leaf_coeff[leaf]]
        return out

    def apply_shrinkage(self, rate: float) -> None:
        """reference: Tree::Shrinkage (tree.h)."""
        self.leaf_value = self.leaf_value * rate
        self.internal_value = self.internal_value * rate
        if self.is_linear:
            self.leaf_const = self.leaf_const * rate
            self.leaf_coeff = [[c * rate for c in cs]
                               for cs in self.leaf_coeff]
        self.shrinkage *= rate

    def num_nodes(self) -> int:
        return max(self.num_leaves - 1, 0)


def tree_from_device_record(record: Dict[str, np.ndarray], num_nodes: int,
                            bin_mappers, learner_meta,
                            shrinkage: float = 1.0) -> Tree:
    """Convert the device learner's state record into a host Tree.

    Maps bin thresholds back to real-valued thresholds via the feature's
    BinMapper upper bounds (reference: BinMapper::BinToValue used by
    Tree::RealThreshold).
    """
    num_leaves = num_nodes + 1
    t = Tree(num_leaves)
    if num_nodes == 0:
        t.leaf_value = np.asarray([0.0])
        return t
    nslice = slice(0, num_nodes)
    t.split_feature = np.asarray(record["node_feature"][nslice], dtype=np.int32)
    t.threshold_bin = np.asarray(record["node_threshold"][nslice], dtype=np.int32)
    t.left_child = np.asarray(record["node_left"][nslice], dtype=np.int32)
    t.right_child = np.asarray(record["node_right"][nslice], dtype=np.int32)
    t.split_gain = np.asarray(record["node_gain"][nslice], dtype=np.float64)
    t.internal_value = np.asarray(record["node_internal_value"][nslice], dtype=np.float64)
    t.internal_weight = np.asarray(record["node_internal_weight"][nslice], dtype=np.float64)
    t.internal_count = np.asarray(record["node_internal_count"][nslice], dtype=np.int64)
    default_left = np.asarray(record["node_default_left"][nslice])
    missing = np.asarray(record["node_missing_type"][nslice], dtype=np.int32)
    node_is_cat = np.asarray(
        record.get("node_is_cat", np.zeros(num_nodes, bool))[nslice])
    node_cat_set = np.asarray(
        record["node_cat_set"][nslice]) if "node_cat_set" in record else None
    t.decision_type = np.asarray(
        [Tree.pack_decision_type(bool(ic), bool(dl) and not ic, int(mt))
         for ic, dl, mt in zip(node_is_cat, default_left, missing)],
        dtype=np.int8)
    # real-valued thresholds from bin upper bounds; categorical nodes store an
    # index into cat_boundaries/cat_threshold bitsets of CATEGORY VALUES
    # (reference: Tree::SplitCategorical, src/io/tree.cpp; bitset layout
    # Common::ConstructBitset)
    thresholds = np.zeros(num_nodes, dtype=np.float64)
    for i in range(num_nodes):
        f = int(t.split_feature[i])
        bm = bin_mappers[f]
        if node_is_cat[i]:
            cats = [bm.bin_2_categorical[b]
                    for b in np.nonzero(node_cat_set[i])[0]
                    if b < len(bm.bin_2_categorical)
                    and bm.bin_2_categorical[b] >= 0]
            n_words = (max(cats) // 32 + 1) if cats else 1
            words = [0] * n_words
            for c in cats:
                words[c // 32] |= (1 << (c % 32))
            thresholds[i] = t.num_cat
            t.num_cat += 1
            t.cat_threshold.extend(words)
            t.cat_boundaries.append(len(t.cat_threshold))
            continue
        b = int(t.threshold_bin[i])
        ub = bm.bin_upper_bound
        b = min(b, len(ub) - 1)
        v = ub[b]
        if math.isinf(v) or math.isnan(v):
            v = bm.bin_upper_bound[max(b - 1, 0)] if len(ub) > 1 else 0.0
            v = max(v, bm.max_val) + 1.0 if math.isinf(v) or math.isnan(v) else v
        thresholds[i] = v
    t.threshold = thresholds
    t.leaf_value = np.asarray(record["leaf_value"][:num_leaves], dtype=np.float64)
    t.leaf_weight = np.asarray(record["leaf_sum_h"][:num_leaves], dtype=np.float64)
    cnt_key = "leaf_cnt_g" if "leaf_cnt_g" in record else "leaf_cnt"
    t.leaf_count = np.asarray(record[cnt_key][:num_leaves], dtype=np.int64)
    if shrinkage != 1.0:
        t.apply_shrinkage(shrinkage)
    return t
