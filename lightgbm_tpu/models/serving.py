"""Device-resident serving engine: packed forests, bucketed batches,
and a compiled-predictor cache.

The training path dispatches one fused program per iteration; before
this module the PREDICT path re-stacked tree arrays per
(start_iteration, end_iteration) range and re-traced its jitted
traversal for every distinct batch size — serving-shaped traffic
(many small, oddly-sized batches) paid a host re-stack plus an XLA
compile on almost every call.  The engine removes both costs:

* **Packed forests** — per model version, the whole forest's node
  arrays (and, lazily, TreeSHAP path matrices) are stacked ONCE on the
  host and shipped in one transfer.  ``start_iteration``/
  ``num_iteration`` slicing is a (T,) 0/1 tree mask argument, never a
  re-stack or a re-trace.
* **Bucketed batches** — rows are padded to power-of-two buckets
  (``MIN_BUCKET``..``MAX_BUCKET``; larger batches stream in
  ``MAX_BUCKET`` chunks), so the jit cache is keyed by (pred kind,
  bucket, forest signature) and N same-bucket calls cost exactly one
  trace.  Compare the reference's OpenMP batch predictor
  (predictor.hpp:30) and the batched-traversal design point of the
  GPU-GBDT literature (Mitchell & Frank, arXiv:1806.11248).
* **Compiled-predictor cache** — packs are keyed on the model mutation
  counter (``gbdt._model_version``); ``update``/``rollback``/model
  load bump the counter, so a stale pack can never serve a mutated
  model.  ``invalidate()`` additionally drops the device arrays
  eagerly.  Trace/call counters are exported for the compile-count
  guard tests and ``tools/profile_predict.py``.

Prediction kinds served: ``raw_score`` (in-session bin-space and
loaded threshold-index forests — including piece-wise LINEAR forests,
whose per-leaf models ride (T, L, J) coefficient planes applied by one
FMA over the caller's raw rows after the ordinary traversal; see
``_insession_pack``), ``pred_leaf``, ``pred_contrib`` (ops/shap.py
vectorized TreeSHAP, f64 under an x64 context), and
``pred_early_stop`` (block-masked device accumulation).  Anything the
device cannot serve exactly (EFB-bundled categoricals without an OOV
sentinel, loaded models for SHAP, loaded or SHAP'd/early-stopped
linear models) falls back to the host paths, which remain the oracles.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..obs import health as obs_health
from ..obs import memory as obs_memory
from ..obs import telemetry as obs
from ..ops import forest_tensor
from ..ops.predict import predict_leaf_binned, predict_leaf_thridx
from ..ops.shap import leggauss_01, tree_shap_stacked
from ..utils import log
from ..utils.log import LightGBMError
from .shap import _expected_value, tree_path_arrays
from .tree import K_CATEGORICAL_MASK

K_EPSILON = 1e-15


def _pack_memory_arrays(eng):
    """Telemetry memory provider: every pack payload (full forests and
    range sub-packs) this engine keeps resident."""
    out = [payload for _, payload in eng._packs.values()]
    out.extend(eng._range_packs.values())
    return out


def bucket_rows(n: int, min_bucket: int = 128,
                max_bucket: int = 1 << 16) -> int:
    """Smallest power-of-two bucket >= n (clamped to the bucket range)."""
    b = min_bucket
    while b < n and b < max_bucket:
        b <<= 1
    return b


class ServingEngine:
    MIN_BUCKET = 128
    MAX_BUCKET = 1 << 16
    # TreeSHAP streams ~L doubles per (row, element); chunks above ~8k
    # rows push the (leaves, rows) working set out of L2/L3 and the
    # unroll-fused kernel becomes DRAM-bound (measured ~2x on the CPU
    # host).  Traversal kinds keep the big bucket.
    CONTRIB_MAX_BUCKET = 1 << 13
    # a COLD pack stack costs a host gather + device round trip that only
    # pays for itself on big batches; once warm, any size is served
    COLD_MIN_ROWS = 4096
    # bounded LRU of per-range sub-packs: a start/num_iteration slice
    # traverses ONLY its trees instead of the whole forest under a mask
    # (the PERF.md round-7 trade-off), at one extra trace per distinct
    # slice LENGTH (jit keys on the stacked shapes) and ~4 live slices
    RANGE_CACHE = 4

    def __init__(self, gbdt):
        self.gbdt = gbdt
        self.trace_counts: Dict[Any, int] = {}   # (kind, bucket) -> traces
        self.call_counts: Dict[Any, int] = {}    # (kind, bucket) -> calls
        self._packs: Dict[str, Any] = {}         # name -> (key, payload)
        self._range_packs: "OrderedDict[Any, Any]" = OrderedDict()
        self._fns: Dict[str, Any] = {}           # kind -> jitted callable
        # pack names to re-warm LAZILY on the first predict after a
        # pickle/deepcopy restore: the restored copy bypasses the
        # COLD_MIN_ROWS gate for these names (the original was serving
        # them, so the copy is serving-shaped traffic too) instead of
        # silently answering small batches from the host paths
        self._rewarm: set = set()
        # training<->serving skew monitor (obs/health.py): None = not
        # built yet, False = this model can't host one (no reference
        # profile / no mappers)
        self._skew = None
        # telemetry HBM attribution: whatever packs this engine holds
        obs_memory.register("serving.packs", self, _pack_memory_arrays)

    # jitted callables and device packs are neither picklable nor worth
    # copying (sklearn deepcopy / dask shipping): a copy re-packs and
    # re-traces ONCE on its first predict (see _rewarm above).  The
    # GBDT itself holds jitted closures too, so a STANDALONE engine
    # pickle (a registry snapshot, a worker shipping one engine) snaps
    # the forest to its model string — the same model-text state
    # Booster uses — and the restored copy rebuilds a loaded-model
    # GBDT whose first predict re-packs + traces once per
    # (kind, bucket), exactly like a pickled Booster's engine.
    def __getstate__(self):
        from ..basic import Booster
        g = self.gbdt
        g._flush_pending()
        # a boolean, not the name list: the restored forest is a
        # LOADED model serving from a different pack family, so only
        # was-warm-at-all survives (same contract as Booster's
        # _serving_was_warm flag)
        return {"model_str":
                Booster._shell_for_gbdt(g).model_to_string(),
                "warm": bool(self._packs or self._rewarm)}

    def __setstate__(self, state):
        from ..basic import Booster
        self.__init__(Booster(model_str=state["model_str"])._gbdt)
        if state.get("warm"):
            # the restored forest is a LOADED model (threshold-index
            # space, no training mappers), so warmth must cover the
            # pack family it will actually serve from — the same
            # translation Booster.__setstate__ applies
            self.mark_rewarm()

    def mark_rewarm(self, names=("insession", "contrib", "loaded")) -> None:
        """Treat ``names`` as warm for cold-row gating until their packs
        are actually rebuilt (Booster.__setstate__ calls this when the
        pickled booster's engine was warm)."""
        self._rewarm |= set(names)

    # -- cache plumbing -------------------------------------------------
    def _sig(self):
        """Forest signature: any mutation (update/rollback/load) bumps
        ``_model_version``, so packs keyed on it can never serve stale
        trees."""
        return (len(self.gbdt.models), self.gbdt._model_version)

    def invalidate(self) -> None:
        """Drop every pack (device arrays included).  Correctness never
        depends on this — pack keys embed the model version — but
        mutation paths call it so dead forests free their HBM."""
        self._packs.clear()
        self._range_packs.clear()

    def _pack(self, name: str, build):
        key = self._sig()
        hit = self._packs.get(name)
        if hit is not None and hit[0] == key:
            return hit[1]
        payload = build()
        if payload is not None:
            self._packs[name] = (key, payload)
        # settle the re-warm debt either way: one failed build means
        # this model can't serve the pack (e.g. a restored categorical
        # model), and re-attempting the O(trees) eligibility scan on
        # every small-batch predict would be worse than the cold gate
        self._rewarm.discard(name)
        return payload

    def _warm(self, name: str) -> bool:
        if name in self._rewarm:
            return True
        hit = self._packs.get(name)
        return hit is not None and hit[0] == self._sig()

    def _count_trace(self, kind: str, bucket: int) -> None:
        k = (kind, bucket)
        self.trace_counts[k] = self.trace_counts.get(k, 0) + 1
        # runtime retrace detector (obs/): the same per-(kind, bucket)
        # compile counts the tests pin, now visible while serving —
        # attributed to whichever span (tick, swap, predict) traced it
        obs.compile_event(f"serving.{kind}@{bucket}")

    def _count_call(self, kind: str, bucket: int) -> None:
        k = (kind, bucket)
        self.call_counts[k] = self.call_counts.get(k, 0) + 1

    def stats(self) -> Dict[str, Any]:
        return {"traces": dict(self.trace_counts),
                "calls": dict(self.call_counts),
                "packs": sorted(self._packs)}

    def trace_snapshot(self) -> Dict[Any, int]:
        """Copy of the (kind, bucket) -> trace-count map, for callers
        (the continual runtime's drift drill, the jaxlint tier-B tick
        budget) that assert how many NEW compiles an operation cost."""
        return dict(self.trace_counts)

    def new_traces_since(self, snapshot: Dict[Any, int]) -> Dict[Any, int]:
        """Traces added since ``snapshot`` (positive deltas only)."""
        out = {}
        for k, v in self.trace_counts.items():
            d = v - snapshot.get(k, 0)
            if d > 0:
                out[k] = d
        return out

    def refit_leaf_values(self, new_values) -> None:
        """Leaf-only mutation fast path.  ``GBDT.apply_refit_leaf_values``
        commits through here AFTER bumping the model version: a refit
        changes every tree's leaf values but NO structure, so the warm
        in-session raw pack keeps its stacked node arrays and only the
        small per-class delta matrices re-transfer — a refit tick in
        the continual runtime costs one (T_k, L) device put instead of
        a full forest re-pack, and zero re-traces (shapes unchanged).
        The refreshed packs are re-keyed to the CURRENT signature, so
        the mutation counter still gates staleness exactly as for a
        full re-pack.  The same refresh applies to the loaded
        (threshold-index) pack — its per-tree leaf-value matrix is the
        only thing a refit changes.  Everything else (contrib path
        matrices carry leaf values; range sub-packs hold stale slices)
        drops and rebuilds lazily."""
        self._range_packs.clear()
        self._packs.pop("contrib", None)
        g = self.gbdt
        # the pack must be EXACTLY one version behind (the caller just
        # bumped it): a length-only check would resurrect a pack some
        # earlier mutation left version-stale under a fresh signature
        prev_sig = (len(g.models), g._model_version - 1)

        def stack(vals, W):
            mat = np.zeros((len(vals), W), np.float32)
            for i, v in enumerate(vals):
                n = min(len(v), W)
                mat[i, :n] = np.asarray(v)[:n]
            return jnp.asarray(mat)

        for name in ("insession", "loaded"):
            hit = self._packs.get(name)
            if hit is None:
                continue
            key, pack = hit
            if key != prev_sig or len(new_values) != len(g.models):
                # stale or structurally changed: no fast path
                self._packs.pop(name, None)
                continue
            if name == "insession" and pack.get("is_linear"):
                # a refit rewrites linear leaves as constants (the host
                # trees drop their models) — the coefficient planes are
                # wholesale stale, so rebuild lazily instead of
                # refreshing deltas nothing reads
                self._packs.pop(name, None)
                continue
            # refresh OUT OF PLACE and install with one reference
            # assignment: a concurrent predict grabs the pack once per
            # call, so it sees all-old or all-new leaf values — never
            # class 0 post-refit paired with class 1 pre-refit
            K = pack["K"]
            fresh = dict(pack)
            fresh["per_k"] = list(pack["per_k"])
            for k in range(K):
                vals = new_values[k::K]
                if name == "insession":
                    pk = dict(pack["per_k"][k])
                    # keep the pack's leaf dtype (a bf16 quantized
                    # plane refreshed as f32 would change shapes/
                    # dtypes and re-trace)
                    pk["deltas"] = stack(
                        vals, int(pk["deltas"].shape[1])).astype(
                            pk["deltas"].dtype)
                    fresh["per_k"][k] = pk
                else:
                    node, lv = pack["per_k"][k]
                    fresh["per_k"][k] = (node, stack(vals,
                                                     int(lv.shape[1])))
            self._packs[name] = (self._sig(), fresh)

    # -- kernel selection (predict_kernel = auto | layered | loop) ------
    def _kernel_for(self, pack) -> str:
        """Which traversal kernel serves this pack: the layered dense
        path (ops/forest_tensor.py — fixed trip count, quantized
        planes) or the stacked while-loop oracle (ops/predict.py).
        ``auto`` prefers layered whenever the pack could build planes
        (it falls back for over-deep or overflowing forests); ``loop``
        forces the oracle; ``layered`` forces the dense path and warns
        once when the pack cannot take it."""
        choice = str(getattr(self.gbdt.config, "predict_kernel",
                             "auto") or "auto")
        if choice not in ("auto", "layered", "loop"):
            raise LightGBMError(
                f"predict_kernel={choice!r} must be one of "
                "auto | layered | loop")
        if choice == "loop":
            return "loop"
        if pack.get("layers_depth") is not None:
            return "layered"
        if choice == "layered" and not getattr(self, "_warned_layered",
                                               False):
            self._warned_layered = True
            log.warning(
                "predict_kernel=layered: this forest cannot take the "
                "layered path (depth > %d or bin values overflow the "
                "quantized planes); serving from the loop oracle",
                forest_tensor.MAX_UNROLL_DEPTH)
        return "loop"

    # -- jitted predictors (one per kind; jit caches per shape) ---------
    def _fn(self, kind: str):
        if kind in self._fns:
            return self._fns[kind]
        eng = self
        static = ()

        if kind == "raw":
            def f(nodes, deltas, mask, binned):
                eng._count_trace("raw", binned.shape[0])
                leaves = jax.vmap(
                    lambda nd: predict_leaf_binned(binned, nd))(nodes)
                vals = jax.vmap(jnp.take)(deltas, leaves)      # (T, n)
                return jnp.sum(vals * mask[:, None], axis=0)
        elif kind == "raw_layered":
            # same (kind, bucket) trace label as the loop path: the
            # compile-count pins are kernel-agnostic
            def f(layers, deltas, mask, binned, max_depth):
                eng._count_trace("raw", binned.shape[0])
                leaves = forest_tensor.predict_leaf_layered(
                    binned, layers, max_depth)
                return forest_tensor.raw_from_leaves(deltas, leaves,
                                                     mask)
            static = ("max_depth",)
        elif kind == "raw_linear":
            # piece-wise linear forests: same traversal, then the
            # coefficient-plane FMA over the caller's raw rows.  Trace
            # label stays "raw" — the per-(kind, bucket) compile-count
            # pins are representation-agnostic, like the layered path.
            def f(nodes, linear, mask, binned, raw_aug):
                eng._count_trace("raw", binned.shape[0])
                leaves = jax.vmap(
                    lambda nd: predict_leaf_binned(binned, nd))(nodes)
                return forest_tensor.linear_from_leaves(
                    raw_aug, leaves, linear["const"], linear["coeff"],
                    linear["fid"], linear["fallback"], mask)
        elif kind == "raw_linear_layered":
            def f(layers, linear, mask, binned, raw_aug, max_depth):
                eng._count_trace("raw", binned.shape[0])
                leaves = forest_tensor.predict_leaf_layered(
                    binned, layers, max_depth)
                return forest_tensor.linear_from_leaves(
                    raw_aug, leaves, linear["const"], linear["coeff"],
                    linear["fid"], linear["fallback"], mask)
            static = ("max_depth",)
        elif kind == "leaf":
            def f(nodes, binned):
                eng._count_trace("leaf", binned.shape[0])
                return jax.vmap(
                    lambda nd: predict_leaf_binned(binned, nd))(nodes)
        elif kind == "leaf_layered":
            def f(layers, binned, max_depth):
                eng._count_trace("leaf", binned.shape[0])
                return forest_tensor.predict_leaf_layered(
                    binned, layers, max_depth)
            static = ("max_depth",)
        elif kind.startswith("contrib"):
            def f(nodes, paths, mask, tq, om, col_iota, binned,
                  _kind=kind):
                eng._count_trace(_kind, binned.shape[0])
                return tree_shap_stacked(binned, nodes, paths, mask,
                                         tq, om, col_iota.shape[0])
        elif kind == "raw_loaded":
            def f(node, lv, mask, packed_vals):
                eng._count_trace("raw_loaded", packed_vals.shape[1])
                leaves = jax.vmap(
                    lambda nd: predict_leaf_thridx(packed_vals, nd))(node)
                vals = jax.vmap(jnp.take)(lv, leaves)
                return jnp.sum(vals * mask[:, None], axis=0)
        elif kind == "leaf_loaded":
            def f(node, packed_vals):
                eng._count_trace("leaf_loaded", packed_vals.shape[1])
                return jax.vmap(
                    lambda nd: predict_leaf_thridx(packed_vals, nd))(node)
        else:
            raise ValueError(kind)
        self._fns[kind] = jax.jit(f, static_argnames=static) \
            if static else jax.jit(f)
        return self._fns[kind]

    def _run_raw(self, sub, mask, b, raw=None) -> np.ndarray:
        """One bucketed raw-score dispatch per class forest, through
        whichever kernel ``predict_kernel`` selects (``sub`` is a full
        pack or a per-range sub-pack; both carry ``layers_depth``).
        ``raw`` is the (bucket, F+1) sentinel-augmented raw chunk that
        linear packs apply their coefficient planes to."""
        bd = jnp.asarray(b)
        layered = self._kernel_for(sub) == "layered"
        if sub.get("is_linear"):
            rd = jnp.asarray(raw)
            if layered:
                fn = self._fn("raw_linear_layered")
                d = sub["layers_depth"]
                return np.stack(
                    [np.asarray(fn(pk["layers"], pk["linear"], mask,
                                   bd, rd, max_depth=d))
                     for pk in sub["per_k"]], axis=1)
            fn = self._fn("raw_linear")
            return np.stack(
                [np.asarray(fn(pk["nodes"], pk["linear"], mask, bd, rd))
                 for pk in sub["per_k"]], axis=1)
        if layered:
            fn = self._fn("raw_layered")
            d = sub["layers_depth"]
            return np.stack(
                [np.asarray(fn(pk["layers"], pk["deltas"], mask, bd,
                               max_depth=d))
                 for pk in sub["per_k"]], axis=1)
        fn = self._fn("raw")
        return np.stack(
            [np.asarray(fn(pk["nodes"], pk["deltas"], mask, bd))
             for pk in sub["per_k"]], axis=1)

    # -- bucketed execution over row chunks -----------------------------
    def _chunks(self, n: int, max_bucket: Optional[int] = None):
        """(start, stop, bucket) spans covering [0, n)."""
        mb = max_bucket or self.MAX_BUCKET
        out = []
        pos = 0
        while pos < n:
            take = min(n - pos, mb)
            out.append((pos, pos + take, bucket_rows(
                take, self.MIN_BUCKET, mb)))
            pos += take
        return out

    def _skew_monitor(self):
        """The skew monitor for this model, built lazily the first time
        health is enabled AND the model carries a reference profile +
        training mappers; False caches "can't" so the eligibility scan
        never repeats on the hot path."""
        if self._skew is None:
            g = self.gbdt
            prof = getattr(g, "health_profile", None)
            ds = g.train_data
            if (prof is None or ds is None
                    or getattr(ds, "groups", None) is None):
                self._skew = False
            else:
                self._skew = obs_health.SkewMonitor.from_dataset(
                    prof, ds, g.config)
        return self._skew or None

    def _run_bucketed(self, kind: str, rows: np.ndarray, run, out_cols,
                      dtype=np.float64, max_bucket: Optional[int] = None,
                      observe: bool = True, aux: Optional[np.ndarray] = None):
        """Pad ``rows`` (n, G) to buckets and collect ``run(padded)``
        slices into an (n, out_cols) host array.  ``aux`` is an optional
        second row-aligned matrix (the raw rows a linear pack's FMA
        reads) chunked and zero-padded in lockstep; when given, ``run``
        is called as ``run(chunk, aux_chunk)``."""
        n = rows.shape[0]
        # training<->serving skew digests: for bin-space kinds the rows
        # ARE the packed bin matrix, already host-resident — one
        # vectorized bincount per chunk folds them into the rolling
        # per-bucket digest (obs/health.py).  health=off costs one
        # attribute load + compare.  ``observe=False`` opts a caller
        # out (the early-stop loop re-runs the same rows per block with
        # PARTIAL sums — double-counted digests and part-sum margins
        # would poison the distributions).
        mon = None
        if observe and obs_health.enabled() \
                and kind in ("raw", "leaf", "contrib"):
            mon = self._skew_monitor()
        out = np.zeros((n, out_cols), dtype=dtype)
        for start, stop, bucket in self._chunks(n, max_bucket):
            chunk = rows[start:stop]
            if mon is not None:
                mon.observe_binned(chunk, bucket=bucket)
            if bucket > chunk.shape[0]:
                pad = np.zeros((bucket - chunk.shape[0],) + chunk.shape[1:],
                               dtype=chunk.dtype)
                chunk = np.concatenate([chunk, pad], axis=0)
            args = (chunk,)
            if aux is not None:
                a = aux[start:stop]
                if bucket > a.shape[0]:
                    # zero padding (never NaN): padded rows pass the
                    # FMA's NaN test cheaply and are sliced away below
                    a = np.concatenate(
                        [a, np.zeros((bucket - a.shape[0],)
                                     + a.shape[1:], dtype=a.dtype)],
                        axis=0)
                args = (chunk, a)
            self._count_call(kind, bucket)
            # per-(kind, bucket) latency histogram: run() materializes
            # its result to the host, so the span measures the real
            # round trip — no extra sync is added (off mode skips even
            # the name formatting)
            with (obs.span(f"serve.{kind}@{bucket}")
                  if obs.enabled() else obs.NULL):
                out[start:stop] = run(*args)[:stop - start]
        if mon is not None and kind == "raw":
            mon.observe_margins(out)
        return out

    # ------------------------------------------------------------------
    # In-session forests (bin-space traversal over the training mappers)
    # ------------------------------------------------------------------
    def _insession_eligible(self) -> bool:
        # linear-leaf forests are served too: traversal is unchanged and
        # the per-leaf models ride coefficient planes applied by one FMA
        # over the caller's raw rows (see _insession_pack), so the old
        # linear_tree exclusion is gone.  SHAP and early-stop for linear
        # models still answer from the host paths (their guards below).
        g = self.gbdt
        return not (g.train_data is None
                    or getattr(g.train_data, "bin_mappers", None) is None
                    or not g.models
                    or any(d is None for d in g.device_trees))

    def _insession_pack(self):
        """Stack the WHOLE forest's node arrays per class: one host
        gather, one device transfer, any (start, end) range afterwards
        is a mask."""
        g = self.gbdt
        if not self._insession_eligible():
            return None
        K = g.num_tree_per_iteration
        has_cat = any(d.get("has_cat_split", "is_cat" in d["nodes"])
                      for d in g.device_trees)
        if has_cat and not g._cat_sentinel_ok():
            return None
        # stack the per-tree node arrays on the HOST with ONE device_get
        # (per-tree jnp.stack dispatches hundreds of tiny tunnel ops)
        host = jax.device_get([(d["nodes"], d["leaf_value"])
                               for d in g.device_trees])
        bf16 = bool(getattr(g.config, "predict_bf16_leaves", False))
        # predict_kernel=loop forces the oracle: skip building (and
        # uploading) layered planes the selected kernel can never read
        # — they cost ~45% extra resident pack bytes per model.  A
        # later knob flip to layered/auto takes effect at the next
        # pack build (invalidate/update), matching how the pack
        # already binds other config at build time.
        want_layers = str(getattr(g.config, "predict_kernel", "auto")
                          or "auto") != "loop"
        # piece-wise linear forests (linear_tree, both refit and
        # leafwise_gain): the device traversal is identical, the leaf
        # VALUES become per-leaf FMAs over the caller's raw rows.  The
        # coefficient planes come from the HOST trees (leaf_const /
        # leaf_coeff / leaf_features — host and device leaf ids match,
        # the same contract refit_leaf_values relies on): const (T, L),
        # coeff/fid (T, L, J) with unused slots pointing fid at the
        # appended all-zero sentinel column of the raw matrix, and
        # fallback (T, L) = leaf_value for NaN rows.  ONE global J
        # across classes keeps uniform shapes (one trace per bucket).
        is_linear = any(t.is_linear for t in g.models)
        J = 1
        if is_linear:
            J = max([1] + [len(f) for t in g.models
                           for f in (t.leaf_features or [])])
        fid_sentinel = g.max_feature_idx + 1
        per_k = []
        depth = 0
        for k in range(K):
            hk = host[k::K]
            host_stacked = {name: np.stack([h[0][name] for h in hk])
                            for name in hk[0][0]}
            nodes = jax.tree.map(jnp.asarray, dict(host_stacked))
            deltas_np = np.stack([h[1] for h in hk])
            deltas = jnp.asarray(deltas_np)
            if bf16:
                # quantized leaf plane: half the gather traffic;
                # accumulation stays f32 (ops/forest_tensor.py
                # raw_from_leaves) so only the leaf representation
                # loses precision.  Opt-in — the f32 default keeps
                # bit-parity with the loop oracle.
                deltas = deltas.astype(jnp.bfloat16)
            layers = (forest_tensor.pack_layered(host_stacked)
                      if want_layers else None)
            if layers is not None:
                depth = max(depth, layers.pop("max_depth"))
            linear = None
            if is_linear:
                trees = g.models[k::K]
                W = deltas_np.shape[1]
                const = np.zeros((len(trees), W), np.float32)
                coeffp = np.zeros((len(trees), W, J), np.float32)
                fidp = np.full((len(trees), W, J), fid_sentinel,
                               np.int32)
                fall = np.zeros((len(trees), W), np.float32)
                for i, t in enumerate(trees):
                    lv = np.asarray(t.leaf_value, np.float64)
                    m = min(len(lv), W)
                    fall[i, :m] = lv[:m]
                    if not t.is_linear:
                        const[i, :m] = lv[:m]
                        continue
                    lc = np.asarray(t.leaf_const, np.float64)
                    const[i, :min(len(lc), W)] = lc[:W]
                    for lf in range(min(len(t.leaf_features), W)):
                        fs = t.leaf_features[lf]
                        if fs:
                            d = len(fs)
                            coeffp[i, lf, :d] = t.leaf_coeff[lf]
                            fidp[i, lf, :d] = fs
                linear = {"const": jnp.asarray(const),
                          "coeff": jnp.asarray(coeffp),
                          "fid": jnp.asarray(fidp),
                          "fallback": jnp.asarray(fall)}
            per_k.append({"nodes": nodes, "deltas": deltas,
                          "layers": layers, "linear": linear})
        layered_ok = all(pk["layers"] is not None for pk in per_k)
        return {"per_k": per_k, "has_cat": has_cat, "K": K,
                "T_k": len(g.models) // K,
                "is_linear": is_linear,
                "num_raw_cols": fid_sentinel + 1,
                # ONE forest-wide unroll depth (max over classes):
                # per-class depths would compile one program per
                # distinct depth and break the pinned one-trace-per-
                # (kind, bucket) counts; extra levels are settled-row
                # no-ops
                "layers_depth": depth if layered_ok else None}

    def _bin(self, data: np.ndarray, has_cat: bool):
        try:
            return self.gbdt.train_data.bin_matrix(
                np.asarray(data), cat_oov_sentinel=has_cat)
        except Exception:
            return None

    def _tree_mask(self, T_k: int, start: int, end: int) -> jnp.ndarray:
        m = np.zeros(T_k, dtype=np.float32)
        m[start:end] = 1.0
        return jnp.asarray(m)

    # -- per-range sub-packs --------------------------------------------
    def _range_sub(self, name: str, pack, start: int, end: int, slice_k):
        """A sub-pack holding ONLY trees [start, end) of ``pack`` so a
        ``start/num_iteration`` slice traverses its own trees instead of
        the whole forest under a mask (a 100-of-1000-trees slice used to
        pay the full 1000-tree traversal — the PERF.md round-7 known
        trade-off).  Sub-packs live in a bounded LRU (``RANGE_CACHE``
        entries, stale model versions age out); the device slices cost
        one gather each and one extra trace per distinct slice LENGTH
        (the jit cache keys on the stacked tree-array shapes, so two
        different same-length ranges share a trace)."""
        T_k = pack["T_k"]
        start, end = max(start, 0), min(end, T_k)
        if start == 0 and end == T_k:
            return pack
        key = (name, self._sig(), start, end)
        hit = self._range_packs.get(key)
        if hit is None:
            hit = dict(pack)
            hit["per_k"] = [slice_k(pk, start, end)
                            for pk in pack["per_k"]]
            hit["T_k"] = end - start
            self._range_packs[key] = hit
            while len(self._range_packs) > self.RANGE_CACHE:
                self._range_packs.popitem(last=False)
        else:
            self._range_packs.move_to_end(key)
        return hit

    @staticmethod
    def _slice_insession(pk, start: int, end: int):
        return {"nodes": jax.tree.map(lambda a: a[start:end],
                                      pk["nodes"]),
                "deltas": pk["deltas"][start:end],
                "layers": (forest_tensor.slice_layered(
                    pk["layers"], start, end)
                    if pk.get("layers") is not None else None),
                "linear": ({n: a[start:end]
                            for n, a in pk["linear"].items()}
                           if pk.get("linear") is not None else None)}

    @staticmethod
    def _slice_loaded(pk, start: int, end: int):
        node, lv = pk
        return (jax.tree.map(lambda a: a[start:end], node),
                lv[start:end])

    def _ready_insession(self, data, start_iteration: int, end_iter: int,
                         min_rows: int, warm_name: str = "insession"):
        """Shared in-session prologue: range guard, eligibility,
        cold-row gating, pack fetch, row binning.  Returns
        (n, pack, binned) or None.

        Note a deliberate scope decision (vs the pre-engine code):
        eligibility is whole-model, so continued-training boosters
        whose loaded head has no device arrays always use the host
        paths.  Sliced ranges are served from per-range sub-packs (see
        ``_range_sub``) so traversal cost scales with the slice; only
        early-stop keeps full-forest masks (its per-block ranges would
        churn the bounded cache)."""
        if end_iter <= start_iteration or not self._insession_eligible():
            return None
        n = np.asarray(data).shape[0]
        if n < min_rows and not self._warm(warm_name):
            return None
        pack = self._pack("insession", self._insession_pack)
        if pack is None:
            return None
        binned = self._bin(data, pack["has_cat"])
        if binned is None:
            return None
        return n, pack, binned

    def raw_insession(self, data: np.ndarray, start_iteration: int,
                      end_iter: int) -> Optional[np.ndarray]:
        """(n, K) raw-score sums over iterations [start, end), or None
        when the device can't serve this model."""
        g = self.gbdt
        ready = self._ready_insession(data, start_iteration, end_iter,
                                      self.COLD_MIN_ROWS)
        if ready is None:
            return None
        n, pack, binned = ready
        K = pack["K"]
        sub = self._range_sub("insession", pack, start_iteration,
                              end_iter, self._slice_insession)
        mask = self._tree_mask(sub["T_k"], 0, sub["T_k"])
        aux = None
        if pack.get("is_linear"):
            # sentinel-augmented raw rows for the coefficient-plane FMA
            # (ops/predict.py linear_leaf_values): unused fid slots
            # gather the appended zero column
            F = pack["num_raw_cols"] - 1
            raw = np.asarray(data, dtype=np.float32)
            aux = np.concatenate(
                [raw[:, :F], np.zeros((n, 1), np.float32)], axis=1)

        def run(b, r=None):
            # one device put per chunk; the K class forests share it
            return self._run_raw(sub, mask, b, raw=r)

        out = self._run_bucketed("raw", binned, run, K, aux=aux)
        # boost-from-average is folded into the first HOST tree only;
        # the device deltas exclude it — EXCEPT linear packs, whose
        # planes come from the host trees and so already carry it
        if not pack.get("is_linear"):
            for k in range(K):
                if (start_iteration == 0
                        and abs(g.init_scores[k]) > K_EPSILON):
                    out[:, k] += g.init_scores[k]
        return out

    def leaves_insession(self, data: np.ndarray, start_iteration: int,
                         end_iter: int) -> Optional[np.ndarray]:
        """(n, num_sliced_trees) leaf indices, model order, or None."""
        ready = self._ready_insession(data, start_iteration, end_iter,
                                      self.COLD_MIN_ROWS)
        if ready is None:
            return None
        n, pack, binned = ready
        K = pack["K"]
        sub = self._range_sub("insession", pack, start_iteration,
                              end_iter, self._slice_insession)
        lo = start_iteration if sub is pack else 0
        layered = self._kernel_for(sub) == "layered"
        fn = self._fn("leaf_layered" if layered else "leaf")
        width = (end_iter - start_iteration) * K

        def run(b):
            bd = jnp.asarray(b)
            cols = np.zeros((b.shape[0], width), dtype=np.int32)
            for k, pk in enumerate(sub["per_k"]):
                allk = np.asarray(
                    fn(pk["layers"], bd, max_depth=sub["layers_depth"])
                    if layered else fn(pk["nodes"], bd)
                ).T                                   # (bucket, T_sub)
                cols[:, k::K] = allk[:, lo:lo + width // K]
            return cols

        return self._run_bucketed("leaf", binned, run, width,
                                  dtype=np.int32)

    # -- device TreeSHAP ------------------------------------------------
    def _contrib_pack(self):
        g = self.gbdt
        if any(t.is_linear for t in g.models):
            # TreeSHAP over linear leaves needs the reference's
            # path-dependent linear redistribution — the host oracle
            # keeps serving those models
            return None
        base = self._pack("insession", self._insession_pack)
        if base is None:
            return None
        K = base["K"]
        num_cols = g.max_feature_idx + 2
        per_k = []
        for k in range(K):
            trees = g.models[k::K]
            mats = [tree_path_arrays(t) for t in trees]
            L = max(m["zf"].shape[0] for m in mats)
            # group trees by PADDED unique-path depth (next even value):
            # one worst-case tree must not inflate every tree's padded D
            # and quadrature count — with a 100-tree forest where late
            # trees split on noise features, global-max padding measured
            # ~8x slower than depth-grouped stacks
            groups: Dict[int, List[int]] = {}
            for i, m in enumerate(mats):
                dg = max(2, (m["zf"].shape[1] + 1) // 2 * 2)
                groups.setdefault(dg, []).append(i)
            built = []
            for dg in sorted(groups):
                idxs = groups[dg]
                M = max(mats[i]["node"].shape[2] for i in idxs)
                T = len(idxs)
                zf = np.ones((T, L, dg))
                feat = np.zeros((T, L, dg), np.int32)
                nodec = np.zeros((T, L, dg, M), np.int32)
                dirc = np.full((T, L, dg, M), 2, np.int8)
                lv = np.zeros((T, L))
                for j, i in enumerate(idxs):
                    m = mats[i]
                    l, d = m["zf"].shape
                    mm = m["node"].shape[2]
                    zf[j, :l, :d] = m["zf"]
                    feat[j, :l, :d] = m["feat"]
                    nodec[j, :l, :d, :mm] = m["node"]
                    dirc[j, :l, :d, :mm] = m["dir"]
                    lv[j, :l] = m["leaf_value"]
                tq, om = leggauss_01(dg)
                # node arrays are all-integer, so the raw pack's device
                # stacks serve SHAP unchanged; only the f64 path
                # matrices need an x64-context conversion
                with jax.experimental.enable_x64():
                    paths = {"zf": jnp.asarray(zf),
                             "feat": jnp.asarray(feat),
                             "node": jnp.asarray(nodec),
                             "dir": jnp.asarray(dirc),
                             "leaf_value": jnp.asarray(lv)}
                    nodes = jax.tree.map(
                        lambda a, sel=np.asarray(idxs): jnp.asarray(
                            np.asarray(a)[sel]),
                        base["per_k"][k]["nodes"])
                built.append({"dg": dg, "iters": np.asarray(idxs),
                              "paths": paths, "nodes": nodes,
                              "tq": tq, "om": om})
            # row-independent bias terms (host oracle: expected value per
            # multi-leaf tree, leaf_value for stumps)
            expected = np.asarray(
                [(float(t.leaf_value[0]) if len(t.leaf_value) else 0.0)
                 if t.num_leaves <= 1 else _expected_value(t)
                 for t in trees])
            per_k.append({"groups": built, "expected": expected})
        return {"per_k": per_k, "K": K, "T_k": len(g.models) // K,
                "num_cols": num_cols, "has_cat": base["has_cat"]}

    def contrib(self, data: np.ndarray, start_iteration: int,
                end_iter: int) -> Optional[np.ndarray]:
        """(n, K, num_features + 1) SHAP contributions with the
        expected-value bias in the last column, or None (host oracle
        serves loaded/linear/ineligible models)."""
        ready = self._ready_insession(data, start_iteration, end_iter,
                                      self.MIN_BUCKET, warm_name="contrib")
        if ready is None:
            return None
        n, _, binned = ready
        pack = self._pack("contrib", self._contrib_pack)
        if pack is None:
            return None
        K, num_cols = pack["K"], pack["num_cols"]
        col_iota = np.zeros(num_cols, np.int32)
        with jax.experimental.enable_x64():

            def run(b):
                bd = jnp.asarray(b)      # one device put per chunk
                blocks = []
                for pk in pack["per_k"]:
                    acc = None
                    for grp in pk["groups"]:
                        m = ((grp["iters"] >= start_iteration)
                             & (grp["iters"] < end_iter)).astype(
                                 np.float32)
                        fn = self._fn("contrib_d%d" % grp["dg"])
                        r = fn(grp["nodes"], grp["paths"],
                               jnp.asarray(m), grp["tq"], grp["om"],
                               col_iota, bd)
                        acc = r if acc is None else acc + r
                    blocks.append(np.asarray(acc))
                return np.concatenate(blocks, axis=1)  # (bucket, K*cols)

            flat = self._run_bucketed(
                "contrib", binned, run, K * num_cols,
                max_bucket=self.CONTRIB_MAX_BUCKET)
        out = flat.reshape(n, K, num_cols)
        for k, pk in enumerate(pack["per_k"]):
            out[:, k, -1] += float(
                pk["expected"][start_iteration:end_iter].sum())
        return out

    # -- device early stopping ------------------------------------------
    def raw_early_stop(self, data: np.ndarray, start_iteration: int,
                       end_iter: int, freq: int,
                       margin: float) -> Optional[np.ndarray]:
        """Block-masked device accumulation replicating the host
        early-stop loop (reference: prediction_early_stop.cpp): margins
        are re-evaluated every ``freq`` iterations and settled rows stop
        traversing — on device, by shrinking the active-row bucket."""
        g = self.gbdt
        if freq <= 0:
            return None
        ready = self._ready_insession(data, start_iteration, end_iter,
                                      self.COLD_MIN_ROWS)
        if ready is None:
            return None
        n, pack, binned = ready
        if pack.get("is_linear"):
            # the block loop re-dispatches shrinking row subsets with
            # full-forest masks; threading aligned raw-row subsets
            # through it buys nothing (early stop is a margin check,
            # not a hot serving path) — host loop serves linear models
            return None
        K = pack["K"]
        out = np.zeros((n, K), dtype=np.float64)
        # boost-from-average is folded into the first HOST tree, so the
        # host loop's margins include it from iteration 0 — seed it
        # BEFORE the blocks or rows settle at different margins
        if start_iteration == 0:
            for k in range(K):
                if abs(g.init_scores[k]) > K_EPSILON:
                    out[:, k] += g.init_scores[k]
        active = np.arange(n)
        for block in range(start_iteration, end_iter, freq):
            if block > start_iteration:
                if K == 1:
                    m = np.abs(out[active, 0])
                else:
                    part = np.partition(out[active], K - 2, axis=1)
                    m = part[:, K - 1] - part[:, K - 2]
                active = active[m < margin]
                if not len(active):
                    break
            mask = self._tree_mask(pack["T_k"], block,
                                   min(block + freq, end_iter))
            sub = binned[active]

            def run(b, mask=mask):
                return self._run_raw(pack, mask, b)

            out[active] += self._run_bucketed("raw", sub, run, K,
                                              observe=False)
        return out

    # ------------------------------------------------------------------
    # Loaded forests (real thresholds -> exact threshold-index space)
    # ------------------------------------------------------------------
    def _loaded_pack(self):
        """Pack a LOADED model (no bin mappers): per-feature threshold
        tables + per-tree node arrays in threshold-index space (see
        ops/predict.py predict_leaf_thridx)."""
        g = self.gbdt
        if not g.models:
            return None
        trees = g.models
        # loaded linear models stay host-served: in-session linear packs
        # get their raw-row alignment from the training mappers, which a
        # loaded model doesn't carry (threshold-index space only)
        if any(t.is_linear or
               (len(t.decision_type) and
                (np.asarray(t.decision_type) & K_CATEGORICAL_MASK).any())
               for t in trees):
            return None
        K = g.num_tree_per_iteration
        feat_thr: Dict[int, set] = {}
        for t in trees:
            for f, thr in zip(np.asarray(t.split_feature),
                              np.asarray(t.threshold)):
                feat_thr.setdefault(int(f), set()).add(float(thr))
        feats = sorted(feat_thr)
        enum = {f: i for i, f in enumerate(feats)}
        thr_list = [np.asarray(sorted(feat_thr[f]), np.float64)
                    for f in feats]
        b0 = np.asarray([int(np.searchsorted(tl, 0.0, side="left"))
                         for tl in thr_list], np.int32)
        nmax = max(max((len(t.split_feature) for t in trees),
                       default=1), 1)
        per_k = []
        for k in range(K):
            ts = trees[k::K]
            T = len(ts)
            arrs = {name: np.zeros((T, nmax), np.int32)
                    for name in ("col", "kidx", "default_left",
                                 "mtype", "left", "right")}
            arrs["left"][:] = -1
            arrs["right"][:] = -1
            nn = np.zeros((T,), np.int32)
            lv = np.zeros((T, nmax + 1), np.float32)
            for ti, t in enumerate(ts):
                m = len(t.split_feature)
                nn[ti] = m
                lv[ti, :len(t.leaf_value)] = t.leaf_value
                if m == 0:
                    if len(t.leaf_value):
                        lv[ti, 0] = t.leaf_value[0]
                    continue
                dt = np.asarray(t.decision_type).astype(np.int32)
                arrs["col"][ti, :m] = [enum[int(f)]
                                       for f in t.split_feature]
                arrs["kidx"][ti, :m] = [
                    int(np.searchsorted(thr_list[enum[int(f)]],
                                        float(v), side="left"))
                    for f, v in zip(t.split_feature, t.threshold)]
                arrs["default_left"][ti, :m] = (dt >> 1) & 1
                arrs["mtype"][ti, :m] = (dt >> 2) & 3
                arrs["left"][ti, :m] = t.left_child
                arrs["right"][ti, :m] = t.right_child
            node = {n_: jnp.asarray(a) for n_, a in arrs.items()}
            node["num_nodes"] = jnp.asarray(nn)
            node["b0"] = jnp.broadcast_to(jnp.asarray(b0),
                                          (T, len(feats)))
            per_k.append((node, jnp.asarray(lv)))
        return {"feats": feats, "thr_list": thr_list, "per_k": per_k,
                "K": K, "T_k": len(trees) // K}

    def _pack_thridx_rows(self, data: np.ndarray, pack) -> np.ndarray:
        """(n, Fu) packed threshold-index rows: b*4 + nan*2 + zeroish."""
        data = np.asarray(data, dtype=np.float64)
        feats, thr_list = pack["feats"], pack["thr_list"]
        packed = np.zeros((data.shape[0], max(len(feats), 1)), np.int32)
        for i, f in enumerate(feats):
            v = data[:, f]
            nan = np.isnan(v)
            fv = np.where(nan, 0.0, v)
            b = np.searchsorted(thr_list[i], v, side="left")
            packed[:, i] = (b.astype(np.int64) * 4 + nan * 2 +
                            (np.abs(fv) <= 1e-35)).astype(np.int32)
        return packed

    def raw_loaded(self, data: np.ndarray, start_iteration: int,
                   end_iter: int) -> Optional[np.ndarray]:
        if end_iter <= start_iteration:
            return None
        n = np.asarray(data).shape[0]
        if n < self.COLD_MIN_ROWS and not self._warm("loaded"):
            return None
        pack = self._pack("loaded", self._loaded_pack)
        if pack is None:
            return None
        K = pack["K"]
        sub = self._range_sub("loaded", pack, start_iteration, end_iter,
                              self._slice_loaded)
        mask = self._tree_mask(sub["T_k"], 0, sub["T_k"])
        rows = self._pack_thridx_rows(data, pack)
        fn = self._fn("raw_loaded")

        def run(b):
            pv = jnp.asarray(b).T        # one device put per chunk
            return np.stack([np.asarray(fn(node, lv, mask, pv))
                             for node, lv in sub["per_k"]], axis=1)

        return self._run_bucketed("raw_loaded", rows, run, K)

    def leaves_loaded(self, data: np.ndarray, start_iteration: int,
                      end_iter: int) -> Optional[np.ndarray]:
        n = np.asarray(data).shape[0]
        if end_iter <= start_iteration:
            return None
        if n < self.COLD_MIN_ROWS and not self._warm("loaded"):
            return None
        pack = self._pack("loaded", self._loaded_pack)
        if pack is None:
            return None
        K = pack["K"]
        sub = self._range_sub("loaded", pack, start_iteration, end_iter,
                              self._slice_loaded)
        lo = start_iteration if sub is pack else 0
        rows = self._pack_thridx_rows(data, pack)
        fn = self._fn("leaf_loaded")
        width = (end_iter - start_iteration) * K

        def run(b):
            pv = jnp.asarray(b).T
            cols = np.zeros((b.shape[0], width), dtype=np.int32)
            for k, (node, _) in enumerate(sub["per_k"]):
                allk = np.asarray(fn(node, pv)).T     # (bucket, T_sub)
                cols[:, k::K] = allk[:, lo:lo + width // K]
            return cols

        return self._run_bucketed("leaf_loaded", rows, run, width,
                                  dtype=np.int32)
