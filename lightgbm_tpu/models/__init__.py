"""Subpackage init."""
