"""Evaluation metrics (vectorized JAX).

TPU-native re-implementation of the reference metric matrix
(src/metric/metric.cpp:19-120 factory; regression_metric.hpp,
binary_metric.hpp, multiclass_metric.hpp, rank_metric.hpp,
xentropy_metric.hpp): each metric is a jit-friendly reduction over device
arrays; ranking metrics reuse the padded query buckets of the rank objectives.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..dataset import Metadata
from ..utils import log

K_EPSILON = 1e-15

_RANK_MEAN_WARNED = False


class Metric:
    name = "metric"
    is_max_better = False

    def __init__(self, config: Config):
        self.config = config

    def init(self, metadata: Metadata) -> None:
        self.num_data = metadata.num_data
        self.label = jnp.asarray(metadata.label, dtype=jnp.float32)
        self.weight = (jnp.asarray(metadata.weight, dtype=jnp.float32)
                       if metadata.weight is not None else None)
        self.sum_weight = (float(np.sum(metadata.weight))
                           if metadata.weight is not None else float(self.num_data))
        self.metadata = metadata

    def eval(self, score, objective) -> List[Tuple[str, float]]:
        """score: raw (unconverted) model output."""
        raise NotImplementedError

    def _wmean(self, values):
        """Weighted mean of a per-row loss; under multi-process training
        the numerator/denominator sums are reduced ACROSS ranks so every
        process reports the metric over the full rank-sharded dataset.
        (The reference evaluates on each machine's local shard only — no
        Network calls exist in src/metric/; the global reduction here is
        deliberate so distributed logs agree with single-process runs.)"""
        if self.weight is not None:
            vs = float(jnp.sum(values * self.weight))
            ws = self.sum_weight
        else:
            vs = float(jnp.sum(values))
            ws = float(int(np.prod(values.shape)))
        vs, ws = _global_pair(vs, ws)
        return vs / max(ws, K_EPSILON)

    def _rank_mean(self, value: float) -> float:
        """Cross-rank aggregation for non-decomposable metrics (AUC, NDCG
        family): the sum_weight-weighted mean of per-rank values.  Exact
        only when every rank sees the full data (feature-parallel); an
        explicit approximation for rank-sharded rows."""
        from ..parallel import network
        global _RANK_MEAN_WARNED
        if network.num_machines() > 1 and not _RANK_MEAN_WARNED:
            # surface the approximation once so early-stopping users know
            # (cross-rank score pairs are never compared; the reference
            # reports per-machine metrics instead — src/metric/ has no
            # Network calls)
            _RANK_MEAN_WARNED = True
            log.warning(
                "non-decomposable metric aggregated as a weighted mean "
                "of per-rank values under data-parallel row sharding — "
                "an approximation of the true global metric")
        vs, ws = _global_pair(value * self.sum_weight, self.sum_weight)
        return vs / max(ws, K_EPSILON)


def _global_pair(vsum: float, wsum: float) -> Tuple[float, float]:
    from ..parallel import network
    if network.num_machines() <= 1:
        return vsum, wsum
    out = network.global_sum([vsum, wsum])
    return float(out[0]), float(out[1])


def _global_queries(totals: "np.ndarray", num_queries: int) -> float:
    """Sum per-rank DCG/AP totals (in place) and query counts across the
    process group so ranking metrics cover the full sharded dataset."""
    from ..parallel import network
    if network.num_machines() <= 1:
        return float(num_queries)
    out = network.global_sum(list(totals) + [float(num_queries)])
    totals[:] = out[:-1]
    return float(out[-1])


def _convert(score, objective):
    if objective is not None:
        return objective.convert_output(score)
    return score


# ---------------------------------------------------------------------------
# Regression metrics (reference: src/metric/regression_metric.hpp)
# ---------------------------------------------------------------------------
class _PointwiseMetric(Metric):
    def point_loss(self, pred, label):
        raise NotImplementedError

    def transform(self, value: float) -> float:
        return value

    def eval(self, score, objective):
        pred = _convert(score, objective)
        loss = self.point_loss(pred, self.label)
        return [(self.name, self.transform(float(self._wmean(loss))))]


class L2Metric(_PointwiseMetric):
    name = "l2"

    def point_loss(self, pred, label):
        return (pred - label) ** 2


class RMSEMetric(L2Metric):
    name = "rmse"

    def transform(self, value):
        return math.sqrt(value)


class L1Metric(_PointwiseMetric):
    name = "l1"

    def point_loss(self, pred, label):
        return jnp.abs(pred - label)


class QuantileMetric(_PointwiseMetric):
    name = "quantile"

    def point_loss(self, pred, label):
        alpha = float(self.config.alpha)
        delta = label - pred
        return jnp.where(delta >= 0, alpha * delta, (alpha - 1.0) * delta)


class HuberMetric(_PointwiseMetric):
    name = "huber"

    def point_loss(self, pred, label):
        alpha = float(self.config.alpha)
        diff = pred - label
        return jnp.where(jnp.abs(diff) <= alpha, 0.5 * diff * diff,
                         alpha * (jnp.abs(diff) - 0.5 * alpha))


class FairMetric(_PointwiseMetric):
    name = "fair"

    def point_loss(self, pred, label):
        c = float(self.config.fair_c)
        x = jnp.abs(pred - label)
        return c * x - c * c * jnp.log1p(x / c)


class PoissonMetric(_PointwiseMetric):
    name = "poisson"

    def point_loss(self, pred, label):
        eps = 1e-10
        return pred - label * jnp.log(jnp.maximum(pred, eps))


class MAPEMetric(_PointwiseMetric):
    name = "mape"

    def point_loss(self, pred, label):
        return jnp.abs((label - pred) / jnp.maximum(1.0, jnp.abs(label)))


class GammaMetric(_PointwiseMetric):
    name = "gamma"

    def point_loss(self, pred, label):
        psi = 1.0
        theta = -1.0 / jnp.maximum(pred, 1e-10)
        a = psi
        b = -jnp.log(-theta)
        c = 1.0 / psi * jnp.log(label / psi) - jnp.log(label) - 0  # lgamma(1/psi)=0
        return -((label * theta - b) / a + c)


class GammaDevianceMetric(_PointwiseMetric):
    name = "gamma_deviance"

    def point_loss(self, pred, label):
        epsilon = 1e-9
        tmp = label / jnp.maximum(pred, epsilon)
        return tmp - jnp.log(tmp) - 1.0

    def transform(self, value):
        return value * 2.0


class TweedieMetric(_PointwiseMetric):
    name = "tweedie"

    def point_loss(self, pred, label):
        rho = float(self.config.tweedie_variance_power)
        eps = 1e-10
        p = jnp.maximum(pred, eps)
        a = label * jnp.exp((1.0 - rho) * jnp.log(p)) / (1.0 - rho)
        b = jnp.exp((2.0 - rho) * jnp.log(p)) / (2.0 - rho)
        return -a + b


# ---------------------------------------------------------------------------
# Binary metrics (reference: src/metric/binary_metric.hpp)
# ---------------------------------------------------------------------------
class BinaryLoglossMetric(_PointwiseMetric):
    name = "binary_logloss"

    def point_loss(self, pred, label):
        p = jnp.clip(pred, K_EPSILON, 1.0 - K_EPSILON)
        return -(label * jnp.log(p) + (1.0 - label) * jnp.log(1.0 - p))


class BinaryErrorMetric(_PointwiseMetric):
    name = "binary_error"

    def point_loss(self, pred, label):
        pred_label = (pred > 0.5).astype(jnp.float32)
        return (pred_label != label).astype(jnp.float32)


def _weighted_auc(score, label, weight):
    """Tie-aware weighted AUC via sorted cumulative sums
    (reference: src/metric/binary_metric.hpp AUCMetric::Eval)."""
    order = jnp.argsort(-score, stable=True)
    s = score[order]
    y = label[order]
    w = weight[order] if weight is not None else jnp.ones_like(s)
    wp = w * (y > 0)
    wn = w * (y <= 0)
    tp = jnp.cumsum(wp)
    fp = jnp.cumsum(wn)
    n = s.shape[0]
    is_end = jnp.concatenate([s[1:] != s[:-1], jnp.array([True])])
    # previous boundary's (tp, fp) per position: "last seen" exclusive scan
    def combine(a, b):
        av, af, avalid = a
        bv, bf, bvalid = b
        return (jnp.where(bvalid, bv, av), jnp.where(bvalid, bf, af),
                avalid | bvalid)
    tagged = (jnp.where(is_end, tp, 0.0), jnp.where(is_end, fp, 0.0), is_end)
    inc = jax.lax.associative_scan(combine, tagged)
    prev_tp = jnp.concatenate([jnp.zeros(1), inc[0][:-1]])
    prev_fp = jnp.concatenate([jnp.zeros(1), inc[1][:-1]])
    area = jnp.sum(jnp.where(is_end, (fp - prev_fp) * (tp + prev_tp) * 0.5, 0.0))
    total_p = tp[-1]
    total_n = fp[-1]
    return jnp.where((total_p > 0) & (total_n > 0),
                     area / (total_p * total_n), 1.0)


class AUCMetric(Metric):
    name = "auc"
    is_max_better = True

    def eval(self, score, objective):
        from ..parallel import network
        if network.num_machines() > 1 and bool(
                getattr(self.config, "distributed_exact_auc", False)):
            # EXACT global AUC under data-parallel row sharding: gather
            # every rank's (score, label, weight) rows once and run the
            # tie-aware sorted-cumsum evaluation over the full dataset.
            # The sort makes rank concatenation order irrelevant, so
            # this equals the single-process value to fp roundoff.
            # (The warned per-rank weighted mean stays the default:
            # the gather is O(total rows) host traffic per eval.)
            # gather the ORIGINAL f64 metadata arrays, not the f32
            # device copies init() keeps — and keep the whole gather +
            # evaluation under x64, else the allgather and the sorted
            # cumsums silently truncate to f32 (collapsing distinct
            # scores into ties) and the exactness claim is void
            meta = self.metadata
            with jax.experimental.enable_x64():
                s = network.global_concat(
                    np.asarray(score, dtype=np.float64))
                y = network.global_concat(np.asarray(meta.label,
                                                     dtype=np.float64))
                w_local = (np.asarray(meta.weight, dtype=np.float64)
                           if meta.weight is not None
                           else np.ones(len(np.asarray(meta.label)),
                                        dtype=np.float64))
                w = network.global_concat(w_local)
                return [(self.name, float(_weighted_auc(
                    jnp.asarray(s), jnp.asarray(y), jnp.asarray(w))))]
        return [(self.name, self._rank_mean(float(_weighted_auc(
            jnp.asarray(score), self.label, self.weight))))]


class AveragePrecisionMetric(Metric):
    name = "average_precision"
    is_max_better = True

    def eval(self, score, objective):
        order = jnp.argsort(-jnp.asarray(score), stable=True)
        y = self.label[order]
        w = self.weight[order] if self.weight is not None else jnp.ones_like(y)
        tp = jnp.cumsum(w * (y > 0))
        total = jnp.cumsum(w)
        precision = tp / jnp.maximum(total, K_EPSILON)
        pos_w = w * (y > 0)
        ap = jnp.sum(precision * pos_w) / jnp.maximum(jnp.sum(pos_w), K_EPSILON)
        return [(self.name, self._rank_mean(float(ap)))]


# ---------------------------------------------------------------------------
# Multiclass metrics (reference: src/metric/multiclass_metric.hpp)
# ---------------------------------------------------------------------------
class MultiLoglossMetric(Metric):
    name = "multi_logloss"

    def eval(self, score, objective):
        p = _convert(score, objective)  # (N, K) softmax
        lbl = self.label.astype(jnp.int32)
        p_true = jnp.take_along_axis(p, lbl[:, None], axis=1)[:, 0]
        loss = -jnp.log(jnp.maximum(p_true, K_EPSILON))
        return [(self.name, float(self._wmean(loss)))]


class MultiErrorMetric(Metric):
    name = "multi_error"

    def eval(self, score, objective):
        k = int(self.config.multi_error_top_k)
        lbl = self.label.astype(jnp.int32)
        true_score = jnp.take_along_axis(score, lbl[:, None], axis=1)[:, 0]
        # error if the true class' score is not within the top k
        num_better = jnp.sum(score > true_score[:, None], axis=1)
        err = (num_better >= k).astype(jnp.float32)
        return [(self.name, float(self._wmean(err)))]


class AucMuMetric(Metric):
    """AUC-mu for multiclass (reference: src/metric/multiclass_metric.hpp
    AucMuMetric:183, following Kleiman & Page 2019): average over class
    pairs (i, j) of the AUC of the projection onto the partition-weight
    difference vector, with optional `auc_mu_weights` (K*K, row-major,
    zero diagonal)."""
    name = "auc_mu"
    is_max_better = True

    def init(self, metadata):
        super().init(metadata)
        K = int(self.config.num_class)
        self.K = K
        spec = str(self.config.auc_mu_weights or "").strip()
        if spec:
            vals = [float(v) for v in spec.replace(" ", "").split(",") if v]
            if len(vals) != K * K:
                from ..utils import log as _log
                _log.fatal("auc_mu_weights must have %d elements, found %d",
                           K * K, len(vals))
            W = np.asarray(vals, dtype=np.float64).reshape(K, K)
            np.fill_diagonal(W, 0.0)
        else:
            W = 1.0 - np.eye(K)
        self.W = W

    def eval(self, score, objective):
        score = np.asarray(score, dtype=np.float64)   # (N, K) raw
        lbl = np.asarray(self.label).astype(np.int64)
        w = np.asarray(self.weight) if self.weight is not None else None
        K = self.K
        total = 0.0
        for i in range(K):
            ii = np.nonzero(lbl == i)[0]
            if len(ii) == 0:
                continue
            for j in range(i + 1, K):
                jj = np.nonzero(lbl == j)[0]
                if len(jj) == 0:
                    continue
                v = self.W[i] - self.W[j]                   # (K,)
                t1 = v[i] - v[j]
                idx = np.concatenate([ii, jj])
                dist = t1 * (score[idx] @ v)
                is_i = lbl[idx] == i
                wi = w[idx] if w is not None else np.ones(len(idx))
                # rank with ties counted half (reference: the sequential
                # num_j/num_current_j scan, multiclass_metric.hpp:282-323)
                order = np.lexsort((~is_i, dist))   # ties: class j first
                d_s = dist[order]
                i_s = is_i[order]
                w_s = wi[order]
                wj = np.where(~i_s, w_s, 0.0)
                cum_j = np.concatenate([[0.0], np.cumsum(wj)])[:-1]
                # per tied-group j-weight for the 0.5 correction
                grp = np.concatenate([[True], np.abs(np.diff(d_s)) > 1e-15])
                gid = np.cumsum(grp) - 1
                grp_j = np.zeros(gid[-1] + 1)
                np.add.at(grp_j, gid, wj)
                grp_start_cum = cum_j[np.nonzero(grp)[0]]
                s_ij = np.sum(np.where(
                    i_s, w_s * (grp_start_cum[gid] + 0.5 * grp_j[gid]), 0.0))
                den_i = np.sum(wi[:len(ii)]) if w is not None else len(ii)
                den_j = np.sum(w[jj]) if w is not None else len(jj)
                total += (s_ij / den_i) / den_j
        ans = (2.0 * total / K) / (K - 1)
        return [(self.name, self._rank_mean(float(ans)))]


# ---------------------------------------------------------------------------
# Ranking metrics (reference: src/metric/rank_metric.hpp, dcg_calculator.cpp)
# ---------------------------------------------------------------------------
class NDCGMetric(Metric):
    name = "ndcg"
    is_max_better = True

    def init(self, metadata: Metadata) -> None:
        super().init(metadata)
        if metadata.query_boundaries is None:
            log.fatal("The NDCG metric requires query information")
        self.eval_at = list(self.config.eval_at_list) or [1, 2, 3, 4, 5]
        if self.config.label_gain:
            gains = np.asarray([float(x) for x in str(self.config.label_gain).split(",")])
        else:
            gains = (2.0 ** np.arange(32)) - 1.0
        qb = np.asarray(metadata.query_boundaries)
        sizes = np.diff(qb)
        lbl = np.asarray(metadata.label).astype(np.int32)
        self.query_weights = None
        # bucket queries by padded size (shared pattern with LambdarankNDCG)
        buckets: Dict[int, List[int]] = {}
        for q, sz in enumerate(sizes):
            p = 1
            while p < sz:
                p <<= 1
            buckets.setdefault(max(p, 2), []).append(q)
        self.buckets = []
        gain_of = gains[lbl]
        for p, qs in sorted(buckets.items()):
            doc_idx = np.full((len(qs), p), -1, dtype=np.int32)
            idcg = np.zeros((len(qs), len(self.eval_at)), dtype=np.float64)
            for row, q in enumerate(qs):
                n = sizes[q]
                doc_idx[row, :n] = np.arange(qb[q], qb[q + 1])
                g_sorted = np.sort(gain_of[qb[q]:qb[q + 1]])[::-1]
                disc = 1.0 / np.log2(np.arange(2, n + 2))
                for ki, k in enumerate(self.eval_at):
                    kk = min(k, n)
                    idcg[row, ki] = np.sum(g_sorted[:kk] * disc[:kk])
            self.buckets.append({
                "P": p,
                "doc_idx": jnp.asarray(doc_idx),
                "idcg": jnp.asarray(idcg.astype(np.float32)),
            })
        self.gains_dev = jnp.asarray(gain_of.astype(np.float32))
        self.num_queries = len(sizes)

    def eval(self, score, objective):
        score = jnp.asarray(score)
        # per-bucket sums stay ON DEVICE inside the loop and sync once
        # at the end: a float() per (bucket, k) serializes one blocking
        # device round-trip per size bucket per eval round (jaxlint
        # JL001); cross-bucket accumulation runs in f64 on host exactly
        # as before
        bucket_sums = []
        for b in self.buckets:
            P = b["P"]
            doc_idx = b["doc_idx"]
            valid = doc_idx >= 0
            idx = jnp.maximum(doc_idx, 0)
            s = jnp.where(valid, score[idx], -jnp.inf)
            g = jnp.where(valid, self.gains_dev[idx], 0.0)
            order = jnp.argsort(-s, axis=1, stable=True)
            g_sorted = jnp.take_along_axis(g, order, axis=1)
            disc = 1.0 / jnp.log2(2.0 + jnp.arange(P, dtype=jnp.float32))
            per_k = []
            for ki, k in enumerate(self.eval_at):
                kk = min(k, P)
                dcg = jnp.sum(g_sorted[:, :kk] * disc[:kk], axis=1)
                idcg = b["idcg"][:, ki]
                ndcg = jnp.where(idcg > 0, dcg / jnp.maximum(idcg, K_EPSILON), 1.0)
                per_k.append(jnp.sum(ndcg))
            bucket_sums.append(jnp.stack(per_k))
        totals = np.sum(np.asarray(jax.device_get(bucket_sums),
                                   dtype=np.float64), axis=0) \
            if bucket_sums else np.zeros(len(self.eval_at))
        nq = _global_queries(totals, self.num_queries)
        return [(f"ndcg@{k}", totals[ki] / nq)
                for ki, k in enumerate(self.eval_at)]


class MapMetric(Metric):
    name = "map"
    is_max_better = True

    def init(self, metadata: Metadata) -> None:
        super().init(metadata)
        if metadata.query_boundaries is None:
            log.fatal("The MAP metric requires query information")
        self.eval_at = list(self.config.eval_at_list) or [1, 2, 3, 4, 5]
        qb = np.asarray(metadata.query_boundaries)
        sizes = np.diff(qb)
        buckets: Dict[int, List[int]] = {}
        for q, sz in enumerate(sizes):
            p = 1
            while p < sz:
                p <<= 1
            buckets.setdefault(max(p, 2), []).append(q)
        self.buckets = []
        for p, qs in sorted(buckets.items()):
            doc_idx = np.full((len(qs), p), -1, dtype=np.int32)
            for row, q in enumerate(qs):
                n = sizes[q]
                doc_idx[row, :n] = np.arange(qb[q], qb[q + 1])
            self.buckets.append({"P": p, "doc_idx": jnp.asarray(doc_idx)})
        self.num_queries = len(sizes)

    def eval(self, score, objective):
        score = jnp.asarray(score)
        # same one-sync-per-eval batching as NDCGMetric.eval (jaxlint
        # JL001): device sums per bucket, host f64 cross-bucket total
        bucket_sums = []
        for b in self.buckets:
            P = b["P"]
            doc_idx = b["doc_idx"]
            valid = doc_idx >= 0
            idx = jnp.maximum(doc_idx, 0)
            s = jnp.where(valid, score[idx], -jnp.inf)
            y = jnp.where(valid, self.label[idx] > 0, False)
            order = jnp.argsort(-s, axis=1, stable=True)
            y_sorted = jnp.take_along_axis(y, order, axis=1).astype(jnp.float32)
            cum_rel = jnp.cumsum(y_sorted, axis=1)
            pos = jnp.arange(1, P + 1, dtype=jnp.float32)
            prec = cum_rel / pos
            per_k = []
            for ki, k in enumerate(self.eval_at):
                kk = min(k, P)
                ap_num = jnp.sum(prec[:, :kk] * y_sorted[:, :kk], axis=1)
                denom = jnp.maximum(jnp.minimum(cum_rel[:, -1], float(kk)), 1.0)
                ap = ap_num / denom
                per_k.append(jnp.sum(ap))
            bucket_sums.append(jnp.stack(per_k))
        totals = np.sum(np.asarray(jax.device_get(bucket_sums),
                                   dtype=np.float64), axis=0) \
            if bucket_sums else np.zeros(len(self.eval_at))
        nq = _global_queries(totals, self.num_queries)
        return [(f"map@{k}", totals[ki] / nq)
                for ki, k in enumerate(self.eval_at)]


# ---------------------------------------------------------------------------
# Cross-entropy metrics (reference: src/metric/xentropy_metric.hpp)
# ---------------------------------------------------------------------------
class CrossEntropyMetric(_PointwiseMetric):
    name = "xentropy"

    def point_loss(self, pred, label):
        p = jnp.clip(pred, K_EPSILON, 1.0 - K_EPSILON)
        return -(label * jnp.log(p) + (1.0 - label) * jnp.log(1.0 - p))


class CrossEntropyLambdaMetric(Metric):
    name = "xentlambda"

    def eval(self, score, objective):
        # hhat = log1p(exp(score)); loss vs label under lambda parameterization
        hhat = jnp.log1p(jnp.exp(jnp.asarray(score)))
        y = self.label
        loss = hhat - y * jnp.log(jnp.maximum(1.0 - jnp.exp(-hhat), K_EPSILON)) - hhat
        # xentlambda loss: yl*log(z) terms; use KL-style formulation
        z = 1.0 - jnp.exp(-hhat)
        loss = -(y * jnp.log(jnp.maximum(z, K_EPSILON)) +
                 (1.0 - y) * jnp.log(jnp.maximum(1.0 - z, K_EPSILON)))
        return [(self.name, float(self._wmean(loss)))]


class KLDivMetric(Metric):
    name = "kullback_leibler"

    def eval(self, score, objective):
        p = jnp.clip(_convert(score, objective), K_EPSILON, 1.0 - K_EPSILON)
        y = jnp.clip(self.label, 0.0, 1.0)
        ce = -(y * jnp.log(p) + (1.0 - y) * jnp.log(1.0 - p))
        ent = jnp.where((y > 0) & (y < 1),
                        -(y * jnp.log(y) + (1.0 - y) * jnp.log(1.0 - y)), 0.0)
        return [(self.name, float(self._wmean(ce - ent)))]


_METRICS = {
    "l2": L2Metric, "mse": L2Metric, "rmse": RMSEMetric, "l1": L1Metric,
    "mae": L1Metric, "quantile": QuantileMetric, "huber": HuberMetric,
    "fair": FairMetric, "poisson": PoissonMetric, "mape": MAPEMetric,
    "gamma": GammaMetric, "gamma_deviance": GammaDevianceMetric,
    "tweedie": TweedieMetric,
    "binary_logloss": BinaryLoglossMetric, "binary_error": BinaryErrorMetric,
    "auc": AUCMetric, "average_precision": AveragePrecisionMetric,
    "multi_logloss": MultiLoglossMetric, "multi_error": MultiErrorMetric,
    "auc_mu": AucMuMetric,
    "ndcg": NDCGMetric, "map": MapMetric,
    "xentropy": CrossEntropyMetric, "xentlambda": CrossEntropyLambdaMetric,
    "kullback_leibler": KLDivMetric,
}

_DEFAULT_METRIC_FOR_OBJECTIVE = {
    "regression": "l2", "regression_l1": "l1", "huber": "huber", "fair": "fair",
    "poisson": "poisson", "quantile": "quantile", "mape": "mape",
    "gamma": "gamma", "tweedie": "tweedie",
    "binary": "binary_logloss",
    "multiclass": "multi_logloss", "multiclassova": "multi_logloss",
    "cross_entropy": "xentropy", "cross_entropy_lambda": "xentlambda",
    "lambdarank": "ndcg", "rank_xendcg": "ndcg",
}


def create_metrics(config: Config, for_objective: Optional[str] = None) -> List[Metric]:
    """reference: Metric::CreateMetric (src/metric/metric.cpp:19)."""
    names = list(config.metric_list)
    if not names and for_objective:
        default = _DEFAULT_METRIC_FOR_OBJECTIVE.get(for_objective)
        if default:
            names = [default]
    out = []
    for name in names:
        if name in ("", "custom", "none"):
            continue
        cls = _METRICS.get(name)
        if cls is None:
            log.warning("Unknown metric %s, ignoring", name)
            continue
        out.append(cls(config))
    return out
