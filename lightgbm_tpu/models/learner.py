"""Leaf-wise histogram tree learner, fully on device.

TPU-native re-design of the reference's serial learner
(src/treelearner/serial_tree_learner.cpp:179-239) following the structure of
the CUDA single-GPU learner (src/treelearner/cuda/cuda_single_gpu_tree_learner.cpp:155-293):
the whole per-tree loop — histogram build, histogram subtraction, best-split
search, leaf partition, tree-structure update — runs inside one jitted
``lax.while_loop``; no per-split host round-trips.

Key TPU adaptations vs. the CUDA design:
  * Rows are **physically partitioned by leaf**: the binned matrix, the
    grad/hess pair and the original row ids are reordered together on every
    split, so each leaf occupies one contiguous row range.  Histograms then
    read straight HBM slices — the random-index gathers that a literal port
    of the CUDA learner (leaf index lists + gather) would need are absent,
    because TPU gathers are latency-bound while contiguous DMA runs at full
    HBM bandwidth.  This mirrors the effect of CUDADataPartition's
    SplitInnerKernel (cuda_data_partition.cu:907) which also moves payload.
  * Histograms are MXU one-hot matmuls over the leaf slice (ops/histogram.py:
    Pallas kernel on TPU, chunked einsum elsewhere), not shared-memory
    atomics.
  * The leaf partition is a single sequential pass over fixed-size chunks
    with a running (left, right) offset carry: lefts are packed forward from
    the range start, rights backward from the range end (stability across
    chunks is not required — histogram sums and future partitions are
    order-invariant), then the scratch range is copied back.
  * Variable leaf sizes inside the static-shape jit are handled by fixed-size
    row chunks with a *dynamic* trip count (``lax.fori_loop``).
  * The smaller child's histogram is computed, the larger one obtained by
    subtraction from the parent (reference: serial_tree_learner.cpp:334-374,
    FeatureHistogram::Subtract), with per-leaf histogram slots in HBM
    replacing the reference's LRU HistogramPool.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..dataset import BinnedDataset
from ..ops import split as split_ops
from ..ops.histogram import leaf_hist_pallas, leaf_hist_slice
from ..ops.partition import split_decision
from ..utils import log

NEG_INF = float("-inf")

# ---------------------------------------------------------------------------
# Packed while-loop state: per-leaf scalars live as rows of one (NLF, L) f32
# matrix and per-node scalars as rows of one (NND, nodes) f32 matrix (ints
# bitcast into the f32 container).  A split then updates TWO columns of each
# matrix instead of ~45 separate arrays — on TPU the per-op overhead of the
# many tiny dynamic-updates dominated the whole tree build.
# ---------------------------------------------------------------------------
(LM_START, LM_CNT, LM_CNT_G, LM_SUM_G, LM_SUM_H, LM_DEPTH, LM_CMIN, LM_CMAX,
 LM_VALUE, LM_PARENT, LM_PSIDE, LM_BGAIN, LM_BFEAT, LM_BTHR, LM_BDL,
 LM_BLCNT, LM_BRCNT, LM_BLSG, LM_BLSH, LM_BRSG, LM_BRSH, LM_BLOUT,
 LM_BROUT, LM_BISCAT, LM_FORCED) = range(25)
NLF = 25

# Piece-wise-linear leafwise-gain rows (linear_tree_mode=leafwise_gain
# only): the leaf's OWN fitted linear model — const + coeff over the
# raw value of LM_LIN_FEAT (an ORIGINAL feature id), the best
# whole-leaf single-feature fit read off the leaf's own split search
# (ops/split.py:find_best_split_linear self_* fields).  Constant mode
# keeps the (NLF, L+1) leafmat — self._nlf gates the packing at Python
# level so constant-gain bodies lower bit-identically to the
# pre-linear build (jaxlint tier-B `linear.gain` pins this).
(LM_LIN_CONST, LM_LIN_COEF, LM_LIN_FEAT) = range(NLF, NLF + 3)
NLF_LINEAR = NLF + 3

(ND_FEATURE, ND_FEATURE_ENUM, ND_THRESHOLD, ND_DL, ND_GAIN, ND_LEFT,
 ND_RIGHT, ND_IVALUE, ND_IWEIGHT, ND_ICOUNT, ND_COL, ND_BIN_START,
 ND_IS_BUNDLED, ND_NUM_BIN, ND_DEFAULT_BIN, ND_MISSING, ND_IS_CAT) = range(17)
NND = 17

# The frontier-batched mode (tpu_frontier_k > 1) appends parent-leaf
# SNAPSHOT rows to its node matrix — the start/count/sum_g/depth of the
# leaf each split consumed — so the oracle-order renumber pass can
# reconstruct the leaf record of a PRUNED speculative split without a
# host round-trip (see _renumber_frontier).
(ND_START, ND_CNTP, ND_SUM_G, ND_DEPTH) = range(NND, NND + 4)
NND_FR = NND + 4


def _i2f(x):
    return jax.lax.bitcast_convert_type(
        jnp.asarray(x, jnp.int32), jnp.float32)


def _f2i(x):
    return jax.lax.bitcast_convert_type(x, jnp.int32)


def parse_monotone_constraints(spec, num_total_features: int) -> np.ndarray:
    """Parse the `monotone_constraints` param ("1,-1,0" / list) into a
    per-original-feature int8 array (reference: config parsing of
    monotone_constraints, config_auto.cpp)."""
    out = np.zeros(num_total_features, dtype=np.int32)
    if spec is None:
        return out
    if isinstance(spec, str):
        spec = spec.strip().strip("()[]")
        if not spec:
            return out
        items = [s for s in spec.replace(" ", "").split(",") if s]
    else:
        items = list(spec)
    vals = [int(v) for v in items]
    if len(vals) > num_total_features:
        raise ValueError(
            f"monotone_constraints has {len(vals)} entries but the dataset "
            f"has {num_total_features} features")
    out[:len(vals)] = vals
    if np.any((out < -1) | (out > 1)):
        raise ValueError("monotone_constraints entries must be -1, 0 or 1")
    return out


def parse_interaction_constraints(spec, num_total_features: int):
    """Parse interaction_constraints ("[0,1,2],[2,3]" or list of lists) into
    a (C, F_total) bool matrix of allowed-feature sets (reference:
    col_sampler.hpp SetInteractionConstraints)."""
    if spec is None:
        return None
    if isinstance(spec, str):
        s = spec.strip()
        if not s:
            return None
        import json as _json
        groups = _json.loads(f"[{s}]" if not s.startswith("[[") else s)
    else:
        groups = [list(g) for g in spec]
    if not groups:
        return None
    out = np.zeros((len(groups), num_total_features), dtype=bool)
    for i, g in enumerate(groups):
        for f in g:
            f = int(f)
            if not 0 <= f < num_total_features:
                raise ValueError(
                    f"interaction_constraints feature {f} out of range")
            out[i, f] = True
    return out


def parse_per_feature_penalty(spec, num_total_features: int):
    """Parse cegb_penalty_feature_{lazy,coupled} ("0.1,0.2,...")."""
    if spec is None:
        return None
    if isinstance(spec, str):
        s = spec.strip().strip("()[]")
        if not s:
            return None
        vals = [float(v) for v in s.replace(" ", "").split(",") if v]
    else:
        vals = [float(v) for v in spec]
    if len(vals) != num_total_features:
        raise ValueError(
            f"per-feature penalty has {len(vals)} entries, expected "
            f"{num_total_features}")
    return np.asarray(vals, dtype=np.float32)


def _pow2ceil(x: int) -> int:
    p = 1
    while p < x:
        p *= 2
    return p


class SerialTreeLearner:
    """Builds one tree per call, entirely on device.

    With ``axis_name`` set, the same program runs SPMD inside ``shard_map``:
      * ``parallel_mode='data'``  — rows sharded; per-leaf histograms are
        ``psum``ed over ICI so every device sees global statistics and makes
        identical split decisions (TPU analog of the reference
        DataParallelTreeLearner's ReduceScatter+Allreduce,
        src/treelearner/data_parallel_tree_learner.cpp:282-441).
      * ``parallel_mode='feature'`` — rows replicated, the split *search* is
        sharded via a per-device feature mask and the winning split is agreed
        with an arg-max reduction (TPU analog of FeatureParallelTreeLearner's
        SyncUpGlobalBestSplit, src/treelearner/parallel_tree_learner.h:209).
    """

    def __init__(self, dataset: BinnedDataset, config: Config,
                 axis_name: Optional[str] = None,
                 parallel_mode: str = "serial",
                 num_shards: int = 1,
                 local_num_data: Optional[int] = None):
        self.ds = dataset
        self.cfg = config
        self.axis_name = axis_name
        self.parallel_mode = parallel_mode
        self.num_shards = num_shards
        meta = dataset.feature_meta_arrays()
        self.G = max(dataset.num_groups, 1)
        self.B = max(dataset.max_group_bins, 2)
        self.F = len(meta["feature"])
        self.BF = int(meta["num_bin"].max()) if self.F else 2
        self.L = config.num_leaves
        self.max_splits = self.L - 1

        # ---- per-feature device metadata ----
        grp = meta["group"]
        is_bundled = np.zeros(self.F, dtype=np.int32)
        for g, ginfo in enumerate(dataset.groups):
            if len(ginfo.feature_indices) > 1:
                is_bundled[grp == g] = 1
        self.ctx = split_ops.SplitContext(
            num_bin=jnp.asarray(meta["num_bin"]),
            missing_type=jnp.asarray(meta["missing_type"]),
            default_bin=jnp.asarray(meta["default_bin"]),
            is_categorical=jnp.asarray(meta["is_categorical"]),
            feature_index=jnp.asarray(meta["feature"]),
        )
        self.f_group = jnp.asarray(grp)
        self.f_bin_start = jnp.asarray(meta["bin_start"])
        self.f_is_bundled = jnp.asarray(is_bundled)
        self.has_categorical = bool(np.any(meta["is_categorical"]))
        # per-feature metadata packed as COLUMNS of one matrix so the hot
        # loop reads all of a feature's scalars with one lane-dynamic slice
        # (rows: feature_index, group, bin_start, is_bundled, num_bin,
        # default_bin, missing_type, monotone — see body unpack)
        self._fmeta_np = np.stack([
            np.asarray(meta["feature"], np.int32),
            np.asarray(grp, np.int32),
            np.asarray(meta["bin_start"], np.int32),
            is_bundled.astype(np.int32),
            np.asarray(meta["num_bin"], np.int32),
            np.asarray(meta["default_bin"], np.int32),
            np.asarray(meta["missing_type"], np.int32),
            np.zeros(self.F, np.int32),   # monotone filled below
        ]) if self.F else np.zeros((8, 1), np.int32)

        # ---- monotone constraints ----
        mono_all = parse_monotone_constraints(
            config.monotone_constraints, dataset.num_total_features)
        mono_used = mono_all[meta["feature"]].astype(np.int32)
        mono_used[meta["is_categorical"] != 0] = 0  # numerical only
        self.use_mc = bool(np.any(mono_used != 0))
        self.monotone = jnp.asarray(mono_used) if self.use_mc else None
        self.monotone_penalty = float(config.monotone_penalty)
        # `intermediate`/`advanced` select the REGION-EXACT refresh (see
        # _mc_refresh): per-leaf bin ranges + pairwise comparability replace
        # the reference's recursive constraint propagation + per-leaf split
        # recomputation (IntermediateLeafConstraints::Update /
        # GoUpToFindLeavesToUpdate, monotone_constraints.hpp:516-740).
        self.mc_mode = "basic"
        if self.use_mc and config.monotone_constraints_method in (
                "intermediate", "advanced"):
            # `advanced` additionally evaluates candidate children against
            # PER-THRESHOLD bound arrays (the vectorized analog of
            # AdvancedLeafConstraints' constraint segments,
            # monotone_constraints.hpp:858) in the per-split children
            # searches; leaf OUTPUT bounds (the refresh) stay the
            # whole-box scalars in both modes, which is what the
            # reference enforces for leaf values too.
            self.mc_mode = config.monotone_constraints_method
            self.mono_enums = [int(i) for i in np.where(mono_used != 0)[0]]
            self.mono_signs = [int(mono_used[i]) for i in self.mono_enums]
        if self.F:
            self._fmeta_np[7] = mono_used
        self._fmeta = jnp.asarray(self._fmeta_np)
        # ---- interaction constraints ----
        ic = parse_interaction_constraints(
            config.interaction_constraints, dataset.num_total_features)
        self.ic_masks = None
        if ic is not None:
            # map original-feature sets onto the used-feature enumeration
            self.ic_masks = jnp.asarray(ic[:, meta["feature"]])  # (C, F)

        # ---- CEGB ----
        self.cegb_count_coeff = 0.0
        self.cegb_coupled = None
        tradeoff = float(config.cegb_tradeoff)
        if float(config.cegb_penalty_split) > 0:
            self.cegb_count_coeff = tradeoff * float(config.cegb_penalty_split)
        coupled = parse_per_feature_penalty(
            config.cegb_penalty_feature_coupled, dataset.num_total_features)
        if coupled is not None:
            self.cegb_coupled = jnp.asarray(tradeoff * coupled[meta["feature"]])
        # lazy per-(row, feature) penalties (reference:
        # CostEfficientGradientBoosting::DetectSplits 'delta' term +
        # UpdateUsedFeatures, cost_effective_gradient_boosting.hpp): a
        # packed per-row used-feature BITSET (ceil(F/32) int32 rows) rides
        # the partition payload; each child split search subtracts
        # penalty[f] * (#rows in the child whose bit f is still 0)
        self.cegb_lazy = None
        self.aux_rows = 0
        lazy = parse_per_feature_penalty(
            config.cegb_penalty_feature_lazy, dataset.num_total_features)
        if lazy is not None and self.F > 0:
            self.cegb_lazy = jnp.asarray(tradeoff * lazy[meta["feature"]])
            self.aux_rows = (self.F + 31) // 32
        self.has_cegb = (self.cegb_count_coeff > 0
                         or self.cegb_coupled is not None
                         or self.cegb_lazy is not None)

        # ---- forced splits ----
        self.forced = None
        if config.forcedsplits_filename:
            if parallel_mode == "voting":
                log.warning("forcedsplits_filename is not supported with "
                            "tree_learner=voting (local histograms); ignored")
            else:
                self.forced = self._load_forced_splits(
                    config.forcedsplits_filename, dataset, meta)

        # ---- per-node column sampling ----
        self.frac_bynode = float(config.feature_fraction_bynode)
        self.has_bynode = 0.0 < self.frac_bynode < 1.0

        # ---- extra_trees (reference: feature_histogram.hpp USE_RAND) ----
        self.extra_trees = bool(config.extra_trees)
        self.extra_seed = int(config.extra_seed)

        # ---- feature_contri per-feature gain scaling ----
        fc_all = parse_per_feature_penalty(
            config.feature_contri or None, dataset.num_total_features)
        self.feature_contri = None
        if fc_all is not None and np.any(fc_all != 1.0):
            self.feature_contri = jnp.asarray(fc_all[meta["feature"]])

        self.cat_params = None
        if self.has_categorical:
            self.cat_params = {
                "max_cat_threshold": int(config.max_cat_threshold),
                "cat_l2": float(config.cat_l2),
                "cat_smooth": float(config.cat_smooth),
                "max_cat_to_onehot": int(config.max_cat_to_onehot),
                "min_data_per_group": int(config.min_data_per_group),
            }

        # feature-view gather: (F, BF) flat indices into (G*B [+1 pad slot])
        gather = np.full((self.F, self.BF), self.G * self.B, dtype=np.int32)
        fix_mask = np.zeros(self.F, dtype=np.float32)
        default_pos = np.zeros(self.F, dtype=np.int32)
        for i in range(self.F):
            g = int(grp[i])
            nb = int(meta["num_bin"][i])
            if is_bundled[i]:
                shift = int(meta["bin_start"][i])
                for b in range(1, nb):
                    gather[i, b] = g * self.B + shift + b
                fix_mask[i] = 1.0
                default_pos[i] = int(meta["default_bin"][i])  # == 0 for bundled
            else:
                for b in range(nb):
                    gather[i, b] = g * self.B + b
                default_pos[i] = int(meta["default_bin"][i])
        self.feat_gather = jnp.asarray(gather)
        self.fix_mask = jnp.asarray(fix_mask)
        self.default_pos = jnp.asarray(default_pos)
        # identity feature->group mapping (no bundling): the (F, BF, 2)
        # view is a plain slice — no gather, no default-bin reconstruction
        # (bins >= num_bin never occur, so those hist cells are zero)
        self._plain_view = (self.F == self.G
                            and not np.any(is_bundled)
                            and np.array_equal(grp, np.arange(self.F)))

        # ---- row geometry ----
        # a dataset built through the direct-to-device construction path
        # (ops/construct.py DeviceIngest) may carry its packed bins ONLY
        # in the transposed (G, N_pad) device buffer; the host matrix is
        # then optional and recoverable on demand
        self._ingest = (getattr(dataset, "device_ingest", None)
                        if local_num_data is None else None)
        if local_num_data is None:
            if dataset.binned is None and self._ingest is None:
                raise ValueError("dataset has no binned data")
            self.N = dataset.num_data
        else:
            self.N = local_num_data
        host_bin_dtype = np.dtype(
            dataset.binned.dtype if dataset.binned is not None
            else (self._ingest.dtype if self._ingest is not None
                  else np.uint8))
        self._host_bin_dtype = host_bin_dtype
        from ..ops import chunkpolicy
        self.row_chunk = min(
            chunkpolicy.resolve_base(config, self.N,
                                     dataset.num_total_features),
            max(_pow2ceil(self.N), 256))
        if self.row_chunk & (self.row_chunk - 1):
            self.row_chunk = _pow2ceil(self.row_chunk)
        # the partition packs (dest << bits) | src into one uint32 sort key
        self.row_chunk = min(self.row_chunk, 1 << 15)
        self._chunk_bits = self.row_chunk.bit_length() - 1
        C = self.row_chunk
        # layout: [C front-pad rows][N data rows][>=2C tail-pad rows]; the
        # front pad keeps the right-aligned partition windows non-negative,
        # the tail pad keeps chunk windows in bounds.  TWO tail chunks: the
        # Pallas partition's pass-2 destination windows start at the
        # 128-aligned floor of an arbitrary leaf offset, so the last
        # (RMW-blended) window can overhang the chunk-aligned cover by up
        # to C-1 rows.  Root range starts at C.
        self.row0 = C
        self.N_pad = C + ((self.N + C - 1) // C + 2) * C
        # tpu_kernel_interpret runs every Pallas kernel through the
        # interpreter, enabling the kernel code paths on any backend
        # (the off-TPU correctness lane for the kernels; SLOW)
        self._interp = bool(getattr(config, "tpu_kernel_interpret", False))
        kernel_backend_ok = jax.default_backend() == "tpu" or self._interp
        self._use_pallas = (jax.default_backend() == "tpu"
                            and config.tpu_hist_kernel == "pallas")
        if self._use_pallas:
            # Mosaic requires lane-aligned tile shapes; probe-compile on the
            # actual geometry and fall back to the XLA kernel on failure
            try:
                tiny = jnp.zeros((self.G, self.row_chunk * 2),
                                 host_bin_dtype)
                ghi0 = jnp.zeros((3, self.row_chunk * 2), jnp.float32)
                jax.block_until_ready(leaf_hist_pallas(
                    tiny, ghi0[0], ghi0[1], jnp.int32(0),
                    jnp.int32(4), num_bins=self.B,
                    row_chunk=self.row_chunk))
            except Exception as exc:
                log.warning("tpu_hist_kernel=pallas unavailable on this "
                            "device geometry (%s); using the XLA kernel",
                            str(exc).split("\n")[0][:120])
                self._use_pallas = False

        # ---- Pallas partition kernel ----
        # The leaf partition dominates the tree build in the XLA
        # formulation (window ops on few-sublane shapes run at 12-16 GB/s
        # on this stack, see PERF.md); the Pallas kernel
        # (ops/partition_pallas.py) streams aligned window DMAs at
        # ~360 GB/s with in-VMEM shift-network compaction (~4 ms per 1M
        # rows vs ~500 ms).  Falls back to the XLA path off-TPU, for
        # categorical splits / cegb-lazy payloads (not yet kernelized),
        # and when the probe-compile fails.  DMA tiling requires
        # sublane-padded row buffers: bins to a multiple of 32 (u8 tile),
        # grad/hess/rowid to 8 f32 rows.
        self._use_pallas_part = (
            kernel_backend_ok
            and config.tpu_partition_kernel == "pallas"
            and not self.has_categorical
            and self.cegb_lazy is None
            and parallel_mode == "serial"
            and self.F > 0
            and (dataset.binned is not None or self._ingest is not None)
            and host_bin_dtype == np.uint8)
        self._compact_radix = bool(getattr(config, "tpu_compact_radix",
                                           False))
        self._pb_rows = self.G
        # (8, N_pad) f32 ghi payload in BOTH partition modes: rows are
        # (grad, hess, rowid-bits, then optional score/objective-payload
        # rows for the physical fused step, zero-padded).  The Pallas DMA
        # tiling needs 8 f32 sublanes anyway; the XLA path's per-row
        # gather cost is width-independent (PERF.md).
        self._ghi_rows = 8
        self._ghi_live = 3     # rows the Pallas kernel must carry
        if self._use_pallas_part:
            try:
                from ..ops.partition_pallas import (partition_leaf_pallas,
                                                    make_scalars,
                                                    sc_rows_for)
                g32 = ((self.G + 31) // 32) * 32
                self._pack_rowid = (bool(getattr(config, "tpu_pack_rowid",
                                                 True))
                                    and g32 - self.G >= 4 and g32 >= 16)
                cpr = self.row_chunk
                tiny = 4 * cpr

                def _part_probe(radix):
                    out = partition_leaf_pallas(
                        jnp.zeros((g32, tiny), jnp.uint8),
                        jnp.zeros((8, tiny), jnp.float32),
                        jnp.zeros((sc_rows_for(g32), tiny), jnp.int32),
                        make_scalars(cpr, cpr, 0, 0, 0, 255, 0, 0, 128, 0),
                        row_chunk=cpr, pack_rowid=self._pack_rowid,
                        compact_radix=radix, interpret=self._interp)
                    jax.block_until_ready(out)

                try:
                    _part_probe(self._compact_radix)
                except Exception as exc:
                    if not self._compact_radix:
                        raise
                    # the radix-4 network is an opt-in lever: fall back
                    # to the proven binary network, not to the XLA path
                    log.warning("tpu_compact_radix unavailable (%s); "
                                "using the binary compaction network",
                                str(exc).split("\n")[0][:120])
                    self._compact_radix = False
                    _part_probe(False)
                self._pb_rows = g32
                self._ghi_rows = 8
            except Exception as exc:
                log.warning("tpu_partition_kernel=pallas unavailable "
                            "(%s); using the XLA partition",
                            str(exc).split("\n")[0][:120])
                self._use_pallas_part = False
        # fused multiclass carries K score rows + label (+ weight) through
        # the partition; the XLA path takes any row count (its per-row
        # gather cost is width-independent), the Pallas kernel is capped
        # at its 8-row f32 tile (partition_pallas.py asserts GH == 8)
        K_cls = max(int(config.num_class), 1)
        if K_cls > 1 and not self._use_pallas_part:
            need = 4 + K_cls + (1 if dataset.metadata.weight is not None
                                else 0)
            if need > self._ghi_rows:
                self._ghi_rows = ((need + 7) // 8) * 8

        # Row layout: the binned matrix TRANSPOSED to (G, N_pad) in its
        # native bin dtype, plus a packed (3, N_pad) grad/hess/rowid matrix.
        # Rows live on the MINOR (lane) axis: in (N, G) orientation XLA's
        # layout heuristic prefers column-major for the multi-MB buffers
        # (G < 128 would waste 4.5x footprint row-major) while the
        # partition's row-gather loops demand row-major, and the
        # disagreement inserted full-buffer transpose copies EVERY split.
        # (G, N) row-major is bit-identical to (N, G) column-major, so all
        # consumers now agree.  The partition still moves rows with
        # vectorized 2-D gathers on chunk-local transposes + contiguous
        # window writes.  Rows are never gathered by bag index:
        # bagging/GOSS zero the out-of-bag gradients instead.
        self._part0 = None
        # True when _part0 is the ingest's master buffer (or its
        # sublane-padded extension): the fused trainer may then ADOPT
        # the buffer and release the ingest's reference (single-copy
        # residency, boosting._adopt_master_buffer)
        self._part0_from_ingest = False
        if local_num_data is None:
            ing = self._ingest
            if (ing is not None and ing.N == self.N
                    and ing.matches(self.row_chunk, self.N_pad,
                                    host_bin_dtype)):
                # construction already streamed the transposed layout to
                # the device: no host transpose, no host pad copy
                self._part0 = ing.part0(self._pb_rows)
                self._part0_from_ingest = True
            else:
                binned = dataset.binned
                if binned is None and ing is not None:
                    # geometry changed between construction and train
                    # (e.g. a different tpu_row_chunk): an out-of-core
                    # dataset re-streams its retained chunk source into
                    # a fresh ingest buffer at THIS geometry (epoch
                    # re-streaming, dataset.py restream_ingest) — the
                    # full host matrix never materializes
                    restream = getattr(dataset, "restream_ingest", None)
                    if restream is not None and getattr(
                            dataset, "_stream_src", None):
                        ing2 = restream(self.row_chunk)
                        if (ing2 is not None and ing2.N == self.N
                                and ing2.matches(self.row_chunk,
                                                 self.N_pad,
                                                 host_bin_dtype)):
                            self._part0 = ing2.part0(self._pb_rows)
                            self._part0_from_ingest = True
                            # drop the stale-geometry buffer: keeping
                            # both would hold 2x the binned footprint
                            # for the whole training run
                            self._ingest = ing = ing2
                    if self._part0 is None:
                        # last resort: recover the host matrix once and
                        # rebuild through the oracle path
                        binned = ing.host_binned()
                if self._part0 is None:
                    binned = np.ascontiguousarray(binned)
                    if binned.shape[1] < self.G:   # zero usable features
                        binned = np.zeros((binned.shape[0], self.G),
                                          binned.dtype)
                    pad = np.zeros((self._pb_rows, self.N_pad),
                                   binned.dtype)
                    pad[:self.G, C:C + self.N] = binned.T
                    self._part0 = jnp.asarray(pad)

        # ---- scalars ----
        self.l1 = float(config.lambda_l1)
        self.l2 = float(config.lambda_l2)
        self.max_delta_step = float(config.max_delta_step)
        self.min_gain_to_split = float(config.min_gain_to_split)
        self.min_data_in_leaf = int(config.min_data_in_leaf)
        self.min_sum_hessian = float(config.min_sum_hessian_in_leaf)
        self.max_depth = int(config.max_depth)
        self.top_k = int(config.top_k)
        self.path_smooth = float(config.path_smooth)

        # lean split search: the per-split fixed cost is op-dispatch-bound
        # (PERF.md); plain configs take the op-packed formulation whose
        # f32 count cumsum is exact below 2^24 rows
        self._fast_search = (not self.has_categorical and not self.use_mc
                             and not self.has_cegb
                             and self.path_smooth <= 0.0
                             and self.N < (1 << 24))

        # ---- piece-wise linear leafwise gain (linear_tree_mode) ----
        # Split gain over leaf-local linear models inside the device
        # search (ops/split.py:find_best_split_linear).  The eligibility
        # set is the fast-search envelope minus the split refinements
        # whose bodies re-derive candidate stats (the linear candidate's
        # child models ride the packed winner read): ineligible configs
        # warn once and fall back to the post-hoc refit mode, which
        # trains exactly like before.
        want_lin = (bool(config.linear_tree) and
                    str(getattr(config, "linear_tree_mode", "refit"))
                    == "leafwise_gain")
        if want_lin:
            lin_block = []
            if not self._fast_search:
                lin_block.append("categorical/monotone/CEGB/path_smooth"
                                 "/huge-N configs")
            if self.forced is not None:
                lin_block.append("forced splits")
            if parallel_mode != "serial" or axis_name is not None:
                lin_block.append("parallel tree learners")
            if self.l1 > 0.0:
                lin_block.append("lambda_l1 > 0")
            if self.max_delta_step > 0.0:
                lin_block.append("max_delta_step > 0")
            if self.feature_contri is not None:
                lin_block.append("feature_contri")
            if self.F == 0:
                lin_block.append("no usable features")
            if lin_block:
                log.warning("linear_tree_mode=leafwise_gain is not "
                            "supported with %s; falling back to the "
                            "post-hoc refit mode", ", ".join(lin_block))
                want_lin = False
        self._linear_gain = want_lin
        self.linear_lambda = float(config.linear_lambda)
        self._nlf = NLF_LINEAR if self._linear_gain else NLF
        self._rep_vals = None
        if self._linear_gain:
            # per-(feature, bin) representative raw values — the linear
            # moment planes are rank-1 scalings of the histogram by this
            # table (ops/histogram.py:linear_moment_planes).  Empirical
            # within-bin means (one host pass over the retained raw
            # matrix) rather than bin bounds: bound-reps overestimate x
            # by up to a bin width, which measurably biases fitted
            # slopes in wide tail bins.
            raw = getattr(dataset, "raw_data", None)
            rep = np.zeros((self.F, self.BF), np.float32)
            for i, orig in enumerate(meta["feature"]):
                col = raw[:, orig] if raw is not None else None
                rep[i] = dataset.bin_mappers[orig].bin_rep_values(
                    self.BF, values=col)
            self._rep_vals = jnp.asarray(rep)

        # ReduceScatter histogram ownership (reference placement:
        # data_parallel_tree_learner.cpp:282-296) — see _psum.  Plain
        # fast-search geometry only; the forced/monotone/categorical
        # paths read whole-histogram state and keep the full psum.
        self._scatter_per = 0
        self._scatter_groups = (
            parallel_mode == "data" and self.axis_name is not None
            and getattr(config, "tpu_data_hist_sync",
                        "scatter") == "scatter"
            and self._fast_search and self._plain_view
            and self.forced is None
            and num_shards > 1 and self.F >= num_shards)
        if self._scatter_groups:
            self._scatter_per = -(-self.G // num_shards)

        # Pallas split-search kernel: one program per split evaluates
        # both children (ops/split_pallas.py).  Plain serial TPU path
        # only; falls back to the XLA fast search elsewhere.
        self._use_pallas_search = (self._use_pallas_part
                                   and self._fast_search
                                   and self._plain_view
                                   and self.forced is None
                                   # the pair kernel's 13-scalar tile
                                   # carries no linear child models
                                   and not self._linear_gain
                                   and not self.extra_trees
                                   and self.feature_contri is None
                                   and parallel_mode == "serial"
                                   and self.F > 0)
        if self._use_pallas_search:
            half = np.zeros((self.F, 8), np.int32)
            half[:, 0] = meta["num_bin"]
            half[:, 1] = meta["missing_type"]
            half[:, 2] = meta["default_bin"]
            self._fmeta_pair = jnp.asarray(np.concatenate([half, half]))
            try:
                from ..ops.split_pallas import best_split_pair_pallas
                t = best_split_pair_pallas(
                    jnp.zeros((2 * self.F, self.BF), jnp.float32),
                    jnp.zeros((2 * self.F, self.BF), jnp.float32),
                    self._fmeta_pair,
                    jnp.zeros((2 * self.F, 8), jnp.float32),
                    l1=self.l1, l2=self.l2,
                    max_delta_step=self.max_delta_step,
                    min_gain_to_split=self.min_gain_to_split,
                    min_data_in_leaf=self.min_data_in_leaf,
                    min_sum_hessian=self.min_sum_hessian,
                    max_depth=self.max_depth, interpret=self._interp)
                jax.block_until_ready(t)
            except Exception as exc:
                log.warning("pallas split-search kernel unavailable (%s); "
                            "using the XLA search",
                            str(exc).split("\n")[0][:120])
                self._use_pallas_search = False

        # ---- flat histogram state + Pallas RMW (fast serial path) ----
        # The (L+1, G, B, 2) state's per-split dynamic-slice read causes
        # XLA to materialize two full-state copies per split (PERF.md
        # "fixed-cost smoking gun"); the flat (L+1, 8, WL) state is
        # updated in place by ops/hist_state_pallas.py with one-row DMAs.
        self._ab_double = str(getattr(config, "tpu_ab_double", "") or "")
        # bfloat16_pair: one-hot/gradient OPERANDS in bf16 with f32
        # accumulation — the TPU analog of the reference GPU's
        # single-precision histograms (gpu_use_dp=false default,
        # docs/GPU-Performance.rst); float32 keeps strict CPU-parity
        self._hist_dtype = (jnp.bfloat16
                            if str(getattr(config, "tpu_hist_dtype",
                                           "float32")) == "bfloat16_pair"
                            else jnp.float32)
        self._init_megakernel(config, dataset, parallel_mode)

        # ---- frontier-batched growth (tpu_frontier_k) ----
        # Grow the top-K gain leaves of the frontier per while-loop step
        # instead of 1: the per-split fixed bookkeeping cost (scalar DUS
        # writes, the parent-hist dynamic-slice read, kernel-launch fixed
        # work) amortizes ~K-fold while an oracle-order replay carried in
        # the loop keeps trained trees BIT-identical to the K=1 learner,
        # including at the num_leaves budget boundary (see
        # _build_tree_frontier).  Order-dependent machinery — forced
        # splits, monotone constraint propagation, CEGB feature
        # accounting, per-step RNG draws (extra_trees / bynode sampling),
        # interaction constraints, parallel learners — falls back to K=1.
        spec = str(getattr(config, "tpu_frontier_k", "auto")
                   or "auto").strip().lower()
        frontier_eligible = (parallel_mode == "serial"
                             and axis_name is None
                             and self.forced is None
                             # leafwise linear gain stays on the K=1
                             # body (residual, see ROADMAP item 3)
                             and not self._linear_gain
                             and not self.use_mc
                             and not self.has_cegb
                             and not self.extra_trees
                             and not self.has_bynode
                             and self.ic_masks is None
                             and not self._ab_double
                             # the Pallas pair-search without the mega
                             # kernel implies the flat-hist RMW state
                             # machinery; the batched body reproduces
                             # the pair search only on the mega path
                             and not (self._use_pallas_search
                                      and self._use_mega is None)
                             and self.F > 0)
        if spec in ("auto", ""):
            # on CPU hosts auto stays at 1: the win is real (see PERF.md
            # round 12) but the bigger traced program taxes every fresh
            # compile, which test-sized trainings pay more than they save
            k_req = 4 if (frontier_eligible
                          and jax.default_backend() == "tpu") else 1
        else:
            try:
                k_req = int(spec)
            except ValueError:
                raise ValueError("tpu_frontier_k must be 'auto' or a "
                                 f"positive integer, got {spec!r}")
            if k_req < 1:
                raise ValueError("tpu_frontier_k must be >= 1")
            if k_req > 1 and not frontier_eligible:
                log.warning(
                    "tpu_frontier_k=%d needs the plain serial tree path "
                    "(no forced splits, monotone constraints, CEGB, "
                    "extra_trees, feature_fraction_bynode, interaction "
                    "constraints or parallel learners); using 1", k_req)
                k_req = 1
        self.frontier_k = max(1, min(k_req, self.L - 1))

        # no histogram state exists on the mega path (the children
        # histograms feed the split search in-register), so the flat
        # state and its probe compile are skipped entirely there; the
        # frontier-batched body replaces the per-split state RMW with
        # one K-row gather + one 2K-row scatter, so it skips it too
        self._use_flat_hist = (self._use_pallas_search
                               and not self._use_pallas
                               and self._use_mega is None
                               and self.frontier_k == 1
                               and getattr(config, "tpu_hist_state",
                                           "auto") != "xla")
        self._flat_geom = None
        if self._use_flat_hist:
            from ..ops.hist_state_pallas import (flat_geometry,
                                                 hist_rmw_pallas)
            self._flat_geom = flat_geometry(self.G, self.B)
            try:
                WL = self._flat_geom[2]
                out = hist_rmw_pallas(
                    jnp.zeros((4, 8, WL), jnp.float32),
                    jnp.zeros((8, WL), jnp.float32),
                    jnp.asarray([0, 1, 2, 1], jnp.int32),
                    interpret=self._interp)
                jax.block_until_ready(out)
            except Exception as exc:
                log.warning("pallas hist-state kernel unavailable (%s); "
                            "using the XLA hist state",
                            str(exc).split("\n")[0][:120])
                self._use_flat_hist = False

        # ---- leaf-size-adaptive chunk policy (ops/chunkpolicy.py) ----
        # Per-leaf hist/partition passes pick their chunk width from a
        # bounded static menu so small leaves stop paying the worst-case
        # padded chunk (68% of the CPU iteration, PERF.md round 12).
        # Band dispatch is zero-trip fori_loops — never lax.switch/cond,
        # whose branch plumbing copies the multi-MB row buffers per
        # split.  Plain XLA serial paths only: the Pallas kernels keep
        # their proven base grid until the on-TPU round (ROADMAP 4b),
        # and the in-context doubling probe must measure the fixed
        # formulation it was calibrated on.  Trees stay BIT-identical
        # to tpu_chunk_policy=fixed (see chunkpolicy module docstring;
        # pinned by tests/test_chunkpolicy.py and ab_bench --chunk).
        chunk_eligible = (parallel_mode == "serial"
                          and axis_name is None
                          and not self._use_pallas
                          and not self._use_pallas_part
                          and self._use_mega != "pallas"
                          and not self._ab_double
                          and self._hist_dtype is jnp.float32
                          and self.F > 0)
        _, self._chunk_policy = chunkpolicy.resolve(
            config, self.N, self.L, chunk_eligible,
            base=self.row_chunk,
            features=dataset.num_total_features)

        axes = (0, 0, 0, 0, 0, 0, 0, 0, 0, 0, None)
        if self.cegb_lazy is not None:
            axes = axes + (0,)
        if self.extra_trees:
            axes = axes + (0,)
        self._best_split_vmapped = jax.vmap(self._leaf_best_split,
                                            in_axes=axes)
        self._build = jax.jit(self._build_impl)

    def _init_megakernel(self, config, dataset, parallel_mode):
        """Split mega-kernel gate + probe (partition + both-children
        histograms in ONE Pallas program per split;
        ops/split_megakernel_pallas.py).  Direct both-children
        accumulation removes the parent-histogram read, the
        smaller/larger selection + subtraction machinery and the
        (L+1)-slot histogram state from the while-loop carry (the
        round-4 "fixed-cost smoking gun": two contextual full-state
        copies per split).  "xla" runs the identical math as plain XLA
        ops — the oracle and the any-backend fallback form.  NOTE the
        mega path's histogram chunk grid is the parent cover, so its
        trees are bit-identical to the mega XLA oracle but only
        numerically equivalent to the subtraction-path trees."""
        mega_mode = str(getattr(config, "tpu_megakernel", "auto")
                        or "off").lower()
        self._use_mega = None
        mega_eligible = (self._fast_search and self._plain_view
                         and self.forced is None
                         # leafwise linear gain: the mega bodies return
                         # the 13-scalar split tiles, not the linear
                         # candidate's child models — residual, see
                         # ROADMAP item 3
                         and not self._linear_gain
                         and not self.extra_trees
                         and self.feature_contri is None
                         and parallel_mode == "serial"
                         and self.F > 0
                         and not self.has_categorical
                         and self.cegb_lazy is None
                         and self.B <= 256
                         and (dataset.binned is not None
                              or self._ingest is not None)
                         and self._host_bin_dtype == np.uint8
                         # the in-context doubling probe hooks the
                         # per-split _hist_leaf calls, which the mega
                         # path does not make — measuring "hist" with
                         # mega active would silently read ~0
                         and self._ab_double != "hist")
        if mega_mode == "xla":
            if mega_eligible:
                self._use_mega = "xla"
            else:
                log.warning("tpu_megakernel=xla needs the plain "
                            "all-numerical serial fast path; using the "
                            "current split path")
        elif mega_mode in ("auto", "pallas"):
            if mega_eligible and self._use_pallas_part:
                try:
                    from ..ops.partition_pallas import (make_scalars,
                                                        sc_rows_for)
                    from ..ops.split_megakernel_pallas import (
                        split_megakernel_pallas)
                    cpr = self.row_chunk
                    tiny = 4 * cpr
                    out = split_megakernel_pallas(
                        jnp.zeros((self._pb_rows, tiny), jnp.uint8),
                        jnp.zeros((8, tiny), jnp.float32),
                        jnp.zeros((sc_rows_for(self._pb_rows), tiny),
                                  jnp.int32),
                        make_scalars(cpr, cpr, 0, 0, 0, 255, 0, 0, 128, 0),
                        row_chunk=cpr, num_bins=self.B,
                        num_groups=self.G,
                        pack_rowid=self._pack_rowid,
                        compact_radix=self._compact_radix,
                        interpret=self._interp)
                    jax.block_until_ready(out)
                    self._use_mega = "pallas"
                except Exception as exc:
                    log.warning("split mega-kernel unavailable (%s); "
                                "using the current split path",
                                str(exc).split("\n")[0][:120])
            elif mega_mode == "pallas":
                log.warning("tpu_megakernel=pallas needs the Pallas "
                            "partition geometry on a kernel-capable "
                            "backend; using the current split path")
        elif mega_mode != "off":
            log.warning("unknown tpu_megakernel=%r; treating as off",
                        mega_mode)
        if self._use_mega is not None:
            log.debug("split mega-kernel active (%s mode)", self._use_mega)

    def _rand_bins(self, key):
        """One random threshold per feature (reference:
        meta_->rand.NextInt(0, num_bin - 2), feature_histogram.hpp:204)."""
        u = jax.random.uniform(key, (self.F,))
        span = jnp.maximum(self.ctx.num_bin - 2, 1).astype(jnp.float32)
        return jnp.floor(u * span).astype(jnp.int32)

    # ------------------------------------------------------------------
    def _hist_leaf(self, part_bins, part_ghi, start, cnt, scale=None):
        if self._use_pallas and scale is None:
            return leaf_hist_pallas(part_bins, part_ghi[0], part_ghi[1],
                                    start, cnt, num_bins=self.B,
                                    row_chunk=self.row_chunk,
                                    num_groups=self.G)
        if self._chunk_policy.adaptive:
            # leaf-size-adaptive bands (eligibility guarantees the
            # plain-XLA path with no in-context doubling); quantized
            # integer carriers are exact at any width by construction
            from ..ops.histogram import leaf_hist_banded
            return leaf_hist_banded(
                part_bins, part_ghi, start, cnt, num_bins=self.B,
                policy=self._chunk_policy,
                dtype=(jnp.bfloat16 if scale is not None
                       else self._hist_dtype),
                vary=self._pvary, num_groups=self.G)
        # quantized training rides INTEGER gradient carriers: the one-hot
        # matmuls run in bfloat16 (exact for the small int grid, double
        # MXU rate — the int16-histogram analog).  The histogram stays
        # in the INTEGER domain here — exact at any summation order and
        # through the whole parent-minus-child subtraction chain; the
        # (grad, hess) scales apply once at the split-search inputs
        # (_scale_hist).  Scaling per-histogram instead was an FMA trap:
        # LLVM contracted `parent - h*scale` into a fused
        # multiply-subtract in some compilation contexts and not others,
        # so "identical" programs drifted by ULPs (the frontier-batched
        # body's bit-identity contract caught it, PERF.md round 12).
        h = leaf_hist_slice(part_bins, part_ghi, start, cnt,
                            num_bins=self.B, row_chunk=self.row_chunk,
                            vary=self._pvary, num_groups=self.G,
                            dtype=(jnp.bfloat16 if scale is not None
                                   else self._hist_dtype))
        if self._ab_double == "hist" and scale is None:
            h = self._double_opaque(
                h, lambda s2: leaf_hist_slice(
                    part_bins, part_ghi, s2, cnt, num_bins=self.B,
                    row_chunk=self.row_chunk, vary=self._pvary,
                    num_groups=self.G, dtype=self._hist_dtype),
                part_ghi, start)
        return h

    @staticmethod
    def _scale_hist(h, scale):
        """Integer-domain quantized histogram -> gain domain at a
        split-search input ((..., 2) trailing (grad, hess) planes times
        (gs, hs)).  Identity when quantized carriers are off."""
        if scale is None:
            return h
        return h * scale[None, None, :]

    def _hist_leaf_flat(self, part_bins, part_ghi, start, cnt):
        """Smaller-child histogram directly in the lane-flattened (8, WL)
        slot layout of the Pallas hist-state RMW kernel."""
        h = leaf_hist_slice(part_bins, part_ghi, start, cnt,
                            num_bins=self.B, row_chunk=self.row_chunk,
                            vary=self._pvary, num_groups=self.G,
                            dtype=self._hist_dtype,
                            flat_geom=self._flat_geom)
        if self._ab_double == "hist":
            h = self._double_opaque(
                h, lambda s2: leaf_hist_slice(
                    part_bins, part_ghi, s2, cnt, num_bins=self.B,
                    row_chunk=self.row_chunk, vary=self._pvary,
                    num_groups=self.G, dtype=self._hist_dtype,
                    flat_geom=self._flat_geom),
                part_ghi, start)
        return h

    def _flatten_hist(self, h):
        """(G, B, 2) histogram -> one (8, WL) flat state slot."""
        Gf, Bf, WL = self._flat_geom
        x = jnp.moveaxis(h, 2, 0)                       # (2, G, B)
        x = jnp.pad(x, ((0, 0), (0, Gf - self.G), (0, Bf - self.B)))
        return x.reshape(8, WL)

    @staticmethod
    def _double_opaque(first, recompute, part_ghi, start):
        """Measurement-only in-context doubling (tpu_ab_double): run the
        component twice with a runtime-opaque perturbation so XLA can
        neither CSE nor hoist the duplicate, and select the second
        (bit-identical) result.  f32 * 0.0 is not folded (NaN rules)."""
        opq = part_ghi[0, :1] * 0.0
        second = recompute(start + opq[0].astype(jnp.int32))
        return jnp.where(opq[0] < 1.0, second, first)

    def _goes_left(self, colv, scalars):
        """Per-row decision from raw group-column values.

        Bundled features decode bin b (≠ default) at offset ``bstart + b``
        (reference: FeatureGroup bin offsets, include/LightGBM/feature_group.h).
        Categorical nodes test bin membership in the split's category set
        (reference: DenseBin::Split categorical arm, src/io/dense_bin.hpp).
        """
        bstart, isb, nb, dbin, mtype, thr, dl, is_cat, cat_set = scalars
        gb = colv.astype(jnp.int32)
        fb_raw = gb - bstart
        in_r = (fb_raw >= 1) & (fb_raw <= nb - 1)
        fb = jnp.where(isb == 1, jnp.where(in_r, fb_raw, dbin), gb)
        num_left = split_decision(fb, thr, dl, mtype, dbin, nb - 1)
        if not self.has_categorical:   # keep the all-numerical hot path lean
            return num_left
        # membership via one-hot AND (C-length 1-D gathers serialize on TPU)
        oh = fb[:, None] == jax.lax.broadcasted_iota(
            jnp.int32, (1, cat_set.shape[0]), 1)
        cat_left = jnp.any(oh & cat_set[None, :], axis=1)
        return jnp.where(is_cat, cat_left, num_left)

    def _partition_leaf(self, st, start, cnt, col, decision_scalars):
        """Two-way partition of the contiguous leaf range [start, start+cnt).

        TPUs scatter into HBM one element at a time (scalar-core DMA), so the
        global scatter a literal CUDA port would use is off the table.
        Each fixed-size chunk is compacted LOCALLY (packed-key sort +
        row-gather on the chunk transpose) and written with contiguous
        window updates.  This replaces the CUDA bitvector +
        AggregateBlockOffset + SplitInner kernels
        (cuda_data_partition.cu:288-907).

        Lefts are forward-packed from the range start and rights backward
        from the range end into the scratch buffers, then the copy-back
        loop composes every destination window from the scratches.  (An
        in-place variant that wrote lefts directly into the row buffers —
        safe because the left frontier never passes the read frontier —
        measured ~1.7x SLOWER end-to-end: the read-modify-write hazard on
        the loop-carried row buffers defeats XLA's in-place scheduling.)
        """
        if self._use_pallas_part:
            return self._partition_leaf_pallas(st, start, cnt, col,
                                               decision_scalars)
        pol = self._chunk_policy
        C = self.row_chunk
        G = self.G
        from ..ops.chunkpolicy import note_variant
        note_variant("partition", C)
        # leaf-size-adaptive banding: the base chunk loops run ZERO
        # trips when a smaller menu width covers the leaf, and each
        # smaller width appends a zero-or-one-trip single-window pass
        # below (bit-identical row moves at any width — see
        # ops/partition.py window_order)
        base_cover = (pol.base_cover(cnt, pol.sizes) if pol.adaptive
                      else None)
        part_bins = st["part_bins"]
        # grad/hess/rowid (+ score/objective payload rows in the fused
        # physical mode) live PERMANENTLY as one (R, N_pad) f32 matrix
        # (ints bitcast to f32) so the per-chunk permute is one 2-D gather
        # on the chunk transpose (1-D gathers serialize on TPU) and no
        # per-split pack/unpack of the full row payload is materialized.
        part_ghi = st["part_ghi"]
        R = part_ghi.shape[0]
        n_chunks = ((cnt + C - 1) // C if base_cover is None
                    else base_cover)

        def blend(dst, val, off, mask):
            # (rows-on-lanes window write at column offset ``off``)
            win = jax.lax.dynamic_slice(dst, (0, off),
                                        (dst.shape[0], val.shape[1]))
            return jax.lax.dynamic_update_slice(
                dst, jnp.where(mask[None, :], val, win), (0, off))

        part_aux = st.get("part_aux")
        sc_aux0 = st.get("sc_aux")
        W = self.aux_rows

        col_onehot = (jax.lax.iota(jnp.int32, self.G) == col)[:, None]

        def scatter_pass(ci, carry):
            nl, nr, sc, sa = carry
            row0 = start + ci * C
            bch = jax.lax.dynamic_slice(part_bins, (0, row0), (G, C))
            gch = jax.lax.dynamic_slice(part_ghi, (0, row0), (R, C))
            # split-column extraction via masked reduction: a dynamic_slice
            # with a runtime SUBLANE offset lowers to a slow per-tile path
            colv = jnp.sum(bch.astype(jnp.int32) * col_onehot, axis=0)
            valid = (ci * C + jax.lax.iota(jnp.int32, C)) < cnt
            gl = self._goes_left(colv, decision_scalars) & valid
            gr = valid & ~gl
            gli = gl.astype(jnp.int32)
            gri = gr.astype(jnp.int32)
            inv = (~valid).astype(jnp.int32)
            nlc = jnp.sum(gli)
            nrc = jnp.sum(gri)
            lrank = jnp.cumsum(gli) - gli
            rrank = jnp.cumsum(gri) - gri
            irank = jnp.cumsum(inv) - inv
            # local destination: [lefts | padding | rights(right-aligned)]
            dloc = jnp.where(gl, lrank,
                             jnp.where(gr, C - nrc + rrank, nlc + irank))
            # inverse permutation via a SINGLE-operand sort of packed
            # (dest << log2C) | src keys: XLA's multi-operand sort (what
            # jnp.argsort lowers to) runs ~50x slower on TPU than the
            # one-array form, and this sort dominated the whole partition
            iot0 = jax.lax.iota(jnp.int32, C)
            packed = ((dloc << self._chunk_bits) | iot0).astype(jnp.uint32)
            order = (jax.lax.sort(packed) & jnp.uint32(C - 1)).astype(
                jnp.int32)
            # permute rows via a row-gather on the chunk TRANSPOSE: the big
            # buffers only ever see contiguous (G, C) window slices/updates,
            # so their row-major (G, N) layout is never contested; the
            # transposes are VMEM-local tile shuffles
            both32 = jnp.concatenate(
                [bch.astype(jnp.int32),
                 jax.lax.bitcast_convert_type(gch, jnp.int32)], axis=0)
            bothc = jnp.take(both32, order, axis=1)      # (G+R, C)
            iot = jax.lax.iota(jnp.int32, C)
            lmask = iot < nlc
            # rights window [start+cnt-nr-C, +C), mask last nrc rows; the
            # front pad rows of the arrays keep this offset non-negative
            rmask = iot >= C - nrc
            roff = start + cnt - nr - C
            # the fused (G+3) i32 block feeds ONE scratch, halving the
            # masked window writes; rows split back only at copy-back
            sc = blend(blend(sc, bothc, start + nl, lmask), bothc, roff,
                       rmask)
            if part_aux is not None:
                ach = jax.lax.dynamic_slice(part_aux, (0, row0), (W, C))
                acomp = jnp.take(ach, order, axis=1)
                sa = blend(blend(sa, acomp, start + nl, lmask), acomp,
                           roff, rmask)
            return nl + nlc, nr + nrc, sc, sa

        sa0 = sc_aux0 if sc_aux0 is not None else jnp.zeros((), jnp.int32)
        carry0 = self._pvary((jnp.int32(0), jnp.int32(0), st["sc32"], sa0))
        nl, nr, sc, sa = jax.lax.fori_loop(
            0, n_chunks, scatter_pass, carry0)

        def copyback(ci, carry):
            pb, pg, pa = carry
            row0 = start + ci * C
            valid = (ci * C + jax.lax.iota(jnp.int32, C)) < cnt
            win = jax.lax.dynamic_slice(sc, (0, row0), (G + R, C))
            pb = blend(pb, win[:G].astype(pb.dtype), row0, valid)
            pg = blend(pg, jax.lax.bitcast_convert_type(win[G:], jnp.float32),
                       row0, valid)
            if part_aux is not None:
                pa = blend(pa, jax.lax.dynamic_slice(sa, (0, row0), (W, C)),
                           row0, valid)
            return pb, pg, pa

        pa0 = part_aux if part_aux is not None else jnp.zeros((), jnp.int32)
        part_bins, part_ghi, part_aux = jax.lax.fori_loop(
            0, n_chunks, copyback, self._pvary((part_bins, part_ghi, pa0)))
        moved = {
            "part_bins": part_bins,
            "part_ghi": part_ghi,
            "sc32": sc,
        }
        if self.aux_rows:
            moved["part_aux"] = part_aux
            moved["sc_aux"] = sa
        if pol.adaptive:
            # exactly one band executes per split; the others cost a
            # zero-trip loop header.  The window pass skips the scratch
            # + copyback entirely (single window: no cross-chunk
            # hazards), writing byte-identical buffers.
            for w, trip in zip(pol.sizes[1:],
                               pol.small_trips(cnt, pol.sizes)):
                moved, nl_w = self._partition_leaf_window(
                    moved, start, cnt, col, decision_scalars, w, trip)
                nl = nl + nl_w
        return moved, nl

    def _partition_leaf_window(self, bufs, start, cnt, col,
                               decision_scalars, width: int, trip):
        """Single-window leaf partition at a smaller menu width: one
        (G+R, W) read, one packed-key sort, one gather, masked window
        writes — wrapped in a ``trip``-gated fori_loop so a non-selected
        band skips at runtime without a conditional (lax.cond/switch
        would copy the multi-MB row buffers every split)."""
        from ..ops.chunkpolicy import note_variant
        from ..ops.partition import window_order
        note_variant("partition", width)
        G = self.G
        W = width
        aw = self.aux_rows
        col_onehot = (jax.lax.iota(jnp.int32, G) == col)[:, None]

        def body(_, carry):
            pb, pg, pa, nl = carry
            PBR = pb.shape[0]
            R = pg.shape[0]
            bch = jax.lax.dynamic_slice(pb, (0, start), (PBR, W))
            gch = jax.lax.dynamic_slice(pg, (0, start), (R, W))
            colv = jnp.sum(bch[:G].astype(jnp.int32) * col_onehot, axis=0)
            valid = jax.lax.iota(jnp.int32, W) < cnt
            gl = self._goes_left(colv, decision_scalars)
            order, nlc = window_order(gl, valid, W)
            both32 = jnp.concatenate(
                [bch.astype(jnp.int32),
                 jax.lax.bitcast_convert_type(gch, jnp.int32)], axis=0)
            perm = jnp.take(both32, order, axis=1)
            vm = valid[None, :]
            pb = jax.lax.dynamic_update_slice(
                pb, jnp.where(vm, perm[:PBR].astype(pb.dtype), bch),
                (0, start))
            pg = jax.lax.dynamic_update_slice(
                pg, jnp.where(vm, jax.lax.bitcast_convert_type(
                    perm[PBR:], jnp.float32), gch), (0, start))
            if aw:
                ach = jax.lax.dynamic_slice(pa, (0, start), (aw, W))
                pa = jax.lax.dynamic_update_slice(
                    pa, jnp.where(vm, jnp.take(ach, order, axis=1), ach),
                    (0, start))
            return pb, pg, pa, nl + nlc

        pa0 = bufs["part_aux"] if aw else jnp.zeros((), jnp.int32)
        carry0 = self._pvary((bufs["part_bins"], bufs["part_ghi"], pa0,
                              jnp.int32(0)))
        pb, pg, pa, nl = jax.lax.fori_loop(0, trip, body, carry0)
        out = {**bufs, "part_bins": pb, "part_ghi": pg}
        if aw:
            out["part_aux"] = pa
        return out, nl

    def _partition_leaf_pallas(self, st, start, cnt, col, decision_scalars):
        """Pallas-kernel leaf partition (see ops/partition_pallas.py):
        bit-identical layout to the XLA path above at ~30x lower cost on
        this stack."""
        from ..ops.partition_pallas import (partition_leaf_pallas,
                                            make_scalars)
        bstart, isb, nb, dbin, mtype, thr, dl, is_cat, cat_set = \
            decision_scalars
        scalars = make_scalars(start, cnt, col, bstart, isb, nb, dbin,
                               mtype, thr, dl)
        pb, pg, sp, nl = partition_leaf_pallas(
            st["part_bins"], st["part_ghi"], st["sc_packed"],
            scalars, row_chunk=self.row_chunk, ghi_live=self._ghi_live,
            pack_rowid=getattr(self, "_pack_rowid", False),
            compact_radix=self._compact_radix, interpret=self._interp)
        moved = {"part_bins": pb, "part_ghi": pg, "sc_packed": sp}
        return moved, nl[0, 0]

    def _split_leaf_mega(self, st, start, cnt, col, decision_scalars,
                         hist_scale=None):
        """Mega-path split: partition the leaf AND produce BOTH
        children's histograms (ops/split_megakernel_pallas.py) — one
        Pallas program in "pallas" mode, the bit-identical XLA oracle
        formulation in "xla" mode.  Returns (moved, left_cnt,
        (hl_g, hl_h, hr_g, hr_h)) with the hist planes (G, Bp)."""
        from ..ops.split_megakernel_pallas import (both_children_hist_xla,
                                                   split_megakernel_pallas,
                                                   unpack_hist4)
        bstart, isb, nb, dbin, mtype, thr, dl, is_cat, cat_set = \
            decision_scalars
        if self._use_mega == "pallas":
            from ..ops.partition_pallas import make_scalars
            scalars = make_scalars(start, cnt, col, bstart, isb, nb, dbin,
                                   mtype, thr, dl)
            pb, pg, sp, nl, acc = split_megakernel_pallas(
                st["part_bins"], st["part_ghi"], st["sc_packed"], scalars,
                row_chunk=self.row_chunk, num_bins=self.B,
                num_groups=self.G, ghi_live=self._ghi_live,
                pack_rowid=getattr(self, "_pack_rowid", False),
                compact_radix=self._compact_radix, interpret=self._interp)
            moved = {"part_bins": pb, "part_ghi": pg, "sc_packed": sp}
            left_cnt = nl[0, 0]
        else:
            # oracle mode: the SAME chunk grid and accumulation math as
            # the kernel, as plain XLA ops, over the pre-partition rows
            if self._chunk_policy.adaptive:
                from ..ops.split_megakernel_pallas import (
                    both_children_hist_banded)
                acc = both_children_hist_banded(
                    st["part_bins"], st["part_ghi"], start, cnt, col,
                    (bstart, isb, nb, dbin, mtype, thr, dl),
                    policy=self._chunk_policy, num_bins=self.B,
                    num_groups=self.G, vary=self._pvary)
            else:
                acc = both_children_hist_xla(
                    st["part_bins"], st["part_ghi"], start, cnt, col,
                    (bstart, isb, nb, dbin, mtype, thr, dl),
                    row_chunk=self.row_chunk, num_bins=self.B,
                    num_groups=self.G, vary=self._pvary)
            moved, left_cnt = self._partition_leaf(st, start, cnt, col,
                                                   decision_scalars)
        hl_g, hl_h, hr_g, hr_h = unpack_hist4(acc, self.B)
        if hist_scale is not None:
            # quantized training: integer carriers accumulated exactly;
            # the (grad, hess) scales apply once per histogram.  The
            # barrier pins the products' rounding across compilation
            # contexts (see _hist_leaf).
            hl_g, hl_h, hr_g, hr_h = jax.lax.optimization_barrier(
                (hl_g * hist_scale[0], hl_h * hist_scale[1],
                 hr_g * hist_scale[0], hr_h * hist_scale[1]))
        return moved, left_cnt, (hl_g, hl_h, hr_g, hr_h)

    # ------------------------------------------------------------------
    def _load_forced_splits(self, filename, dataset, meta):
        """Flatten the forced-splits JSON (reference: forced_split_json_
        BFS in SerialTreeLearner::ForceSplits, serial_tree_learner.cpp:614)
        into parallel arrays: feature enum, bin threshold, child node ids."""
        import json as _json
        with open(filename) as f:
            root = _json.load(f)
        enum_of = {int(orig): i for i, orig in enumerate(meta["feature"])}
        feats, bins_, lefts, rights = [], [], [], []

        def add(node):
            if (not isinstance(node, dict) or "feature" not in node
                    or "threshold" not in node):
                return -1
            orig = int(node["feature"])
            if orig not in enum_of:
                log.warning("forced split on unused feature %d ignored", orig)
                return -1
            fi = enum_of[orig]
            if int(meta["is_categorical"][fi]):
                log.warning("forced split on categorical feature %d ignored",
                            orig)
                return -1
            bm = dataset.bin_mappers[orig]
            thr_bin = bm.value_to_bin(float(node["threshold"]))
            idx = len(feats)
            feats.append(fi)
            bins_.append(int(thr_bin))
            lefts.append(-1)
            rights.append(-1)
            lefts[idx] = add(node.get("left"))
            rights[idx] = add(node.get("right"))
            return idx

        if add(root) < 0:
            return None
        return {
            "feature": jnp.asarray(np.asarray(feats, np.int32)),
            "bin": jnp.asarray(np.asarray(bins_, np.int32)),
            "left": jnp.asarray(np.asarray(lefts, np.int32)),
            "right": jnp.asarray(np.asarray(rights, np.int32)),
        }

    def _forced_split_info(self, hist_group, f_enum, thr, sum_g, sum_h, cnt):
        """Split stats at a fixed (feature, bin) threshold (reference:
        FeatureHistogram::GatherInfoForThresholdNumerical,
        feature_histogram.hpp:502): reverse-scan semantics — the right side
        holds bins in (thr, bmax], the default bin is skipped for
        zero-missing features, missing goes left."""
        K_EPS = split_ops.K_EPSILON
        feat_hist = self._feat_view(hist_group, sum_g, sum_h)
        fh = feat_hist[f_enum]                                 # (BF, 2)
        nb = self.ctx.num_bin[f_enum]
        mtype = self.ctx.missing_type[f_enum]
        dbin = self.ctx.default_bin[f_enum]
        bins = jnp.arange(self.BF)
        is_nan = mtype == split_ops.MISSING_NAN
        is_zero = mtype == split_ops.MISSING_ZERO
        bmax = nb - 1 - is_nan.astype(jnp.int32)
        rmask = (bins > thr) & (bins <= bmax) & \
            ~(is_zero & (bins == dbin))
        rg = jnp.sum(fh[:, 0] * rmask)
        rh = jnp.sum(fh[:, 1] * rmask) + K_EPS
        sum_h_tot = sum_h + 2 * K_EPS
        cnt_factor = cnt.astype(jnp.float32) / sum_h_tot
        rc = jnp.sum(jnp.floor(fh[:, 1] * cnt_factor + 0.5).astype(jnp.int32)
                     * rmask)
        lg = sum_g - rg
        lh = sum_h_tot - rh
        lc = cnt - rc
        args = (self.l1, self.l2, self.max_delta_step)
        gain_shift = split_ops.leaf_gain(sum_g, sum_h_tot, *args)
        gain = (split_ops.leaf_gain(lg, lh, *args) +
                split_ops.leaf_gain(rg, rh, *args))
        rel = gain - (gain_shift + self.min_gain_to_split)
        valid = (lc >= 1) & (rc >= 1) & (rel >= 0) & (thr < nb - 1)
        return {
            "gain": rel, "valid": valid, "threshold": thr,
            "lsg": lg, "lsh": lh - K_EPS, "rsg": rg, "rsh": rh - K_EPS,
            "lcnt": lc.astype(jnp.int32), "rcnt": rc.astype(jnp.int32),
            "lout": split_ops.leaf_output(lg, lh, *args),
            "rout": split_ops.leaf_output(rg, rh, *args),
        }

    def _lazy_counts(self, part_aux, start, l_cnt, r_cnt):
        """(2, F) counts of rows whose feature bit is still 0 for the two
        children ranges [start, start+l_cnt) and [start+l_cnt, +r_cnt)
        (reference: the per-row feature-used tracking behind
        cegb_penalty_feature_lazy, cost_effective_gradient_boosting.hpp)."""
        C = self.row_chunk
        W = self.aux_rows
        F = self.F
        cnt = l_cnt + r_cnt
        n_chunks = (cnt + C - 1) // C

        def body(ci, acc):
            row0 = start + ci * C
            ach = jax.lax.dynamic_slice(part_aux, (0, row0), (W, C))
            pos = ci * C + jax.lax.iota(jnp.int32, C)
            valid = pos < cnt
            is_l = pos < l_cnt
            bits = jnp.stack([(ach >> k) & 1 for k in range(32)], axis=1)
            notused = 1 - bits.reshape(W * 32, C)[:F]          # (F, C)
            accl = acc[0] + jnp.sum(notused * (valid & is_l), axis=1)
            accr = acc[1] + jnp.sum(notused * (valid & ~is_l), axis=1)
            return jnp.stack([accl, accr])

        counts = jax.lax.fori_loop(0, n_chunks, body,
                                   self._pvary(jnp.zeros((2, F),
                                                         jnp.int32)))
        # data/voting parallel: counts are shard-local but _sync_best is a
        # no-op there (devices rely on identical psum'd inputs to pick
        # identical splits) — the lazy penalty must therefore be GLOBAL or
        # the replicated tree state silently diverges
        if self.axis_name is not None and self.parallel_mode in ("data",
                                                                 "voting"):
            counts = jax.lax.psum(counts, self.axis_name)
        return counts

    def _lazy_mark(self, part_aux, start, cnt, f_enum):
        """Set the used-bit of ``f_enum`` for rows [start, start+cnt)
        (reference: CostEfficientGradientBoosting::UpdateUsedFeatures)."""
        C = self.row_chunk
        W = self.aux_rows
        # OR the bit into the matching word row via a broadcast mask — a
        # dynamic_slice with a runtime SUBLANE offset lowers to a slow
        # per-tile path
        word_mask = (jax.lax.iota(jnp.int32, W) == f_enum // 32)[:, None]
        bit = (jnp.int32(1) << (f_enum % 32)) * word_mask       # (W, 1)
        n_chunks = (cnt + C - 1) // C

        def body(ci, pa):
            row0 = start + ci * C
            ach = jax.lax.dynamic_slice(pa, (0, row0), (W, C))
            valid = ((ci * C + jax.lax.iota(jnp.int32, C)) < cnt)[None, :]
            return jax.lax.dynamic_update_slice(
                pa, jnp.where(valid, ach | bit, ach), (0, row0))

        return jax.lax.fori_loop(0, n_chunks, body, part_aux)

    def _allowed_from_used(self, used):
        """Interaction constraints (reference: col_sampler.hpp GetByNode):
        a node may split on the union of all constraint sets that contain
        every feature already used on its path."""
        compat = ~jnp.any(used[None, :] & ~self.ic_masks, axis=1)   # (C,)
        return jnp.any(self.ic_masks & compat[:, None], axis=0)     # (F,)

    def _bynode_mask(self, key):
        """feature_fraction_bynode sampling (reference: col_sampler.hpp
        SampleUsedFeaturesByNode approximated with a uniform-score top-k)."""
        k = max(int(round(self.F * self.frac_bynode)), 1)
        scores = jax.random.uniform(key, (self.F,))
        kth = jnp.sort(scores)[self.F - k]
        return scores >= kth

    def _leaf_best_split(self, hist_group, sum_g, sum_h, cnt, local_cnt,
                         depth, cmin, cmax, parent_out, feature_mask,
                         feat_used, *rest):
        # trailing optional operands in a fixed order (vmap needs flat
        # positional args): cegb-lazy counts, then extra_trees rand bins
        i = 0
        lazy_cnt = None
        if self.cegb_lazy is not None and len(rest) > i:
            lazy_cnt = rest[i]
            i += 1
        rand_bins = rest[i] if (self.extra_trees and len(rest) > i) else None
        if self.F == 0:   # no usable features: every tree is a stub
            z = jnp.float32(0.0)
            zi = jnp.int32(0)
            return split_ops.BestSplit(
                gain=jnp.float32(-jnp.inf), feature=zi, threshold=zi,
                default_left=jnp.bool_(False),
                left_sum_g=z, left_sum_h=z, right_sum_g=z, right_sum_h=z,
                left_count=zi, right_count=zi, left_output=z, right_output=z,
                is_cat=jnp.bool_(False),
                cat_set=jnp.zeros((self.BF,), jnp.bool_))
        if self.parallel_mode == "voting" and self.axis_name is not None:
            return self._leaf_best_split_voting(
                hist_group, sum_g, sum_h, cnt, local_cnt, depth, cmin, cmax,
                parent_out, feature_mask, feat_used, lazy_cnt=lazy_cnt,
                rand_bins=rand_bins)
        if self._scatter_groups:
            # each device searches only the groups it owns post-scatter;
            # the election in _sync_best agrees on the global winner
            d = jax.lax.axis_index(self.axis_name)
            owned = (jax.lax.iota(jnp.int32, self.F)
                     // self._scatter_per) == d
            feature_mask = feature_mask & owned
        feat_hist = self._feat_view(hist_group, sum_g, sum_h)
        best = self._find_best(feat_hist, sum_g, sum_h, cnt, depth,
                               cmin, cmax, feature_mask, feat_used=feat_used,
                               parent_out=parent_out, lazy_cnt=lazy_cnt,
                               rand_bins=rand_bins)
        return self._depth_guard(best, depth)

    def _feat_view(self, hist_group, sum_g, sum_h):
        """(G, B, 2) group histogram -> (F, BF, 2) per-feature view with the
        default-bin stats of bundled features reconstructed from the leaf
        totals (reference: FixHistogram, cuda_histogram_constructor.cu:738)."""
        if self._plain_view:
            return hist_group[:, :self.BF]
        flat = hist_group.reshape(self.G * self.B, 2)
        flat = jnp.concatenate([flat, jnp.zeros((1, 2), dtype=flat.dtype)], axis=0)
        feat_hist = jnp.take(flat, self.feat_gather, axis=0)  # (F, BF, 2)
        known = feat_hist.sum(axis=1)
        fix = (jnp.stack([sum_g, sum_h]) - known) * self.fix_mask[:, None]
        return feat_hist.at[jnp.arange(self.F), self.default_pos].add(fix)

    def _find_best(self, feat_hist, sum_g, sum_h, cnt, depth, cmin, cmax,
                   feature_mask, feat_used=None, parent_out=None,
                   with_feature_gains=False, lazy_cnt=None,
                   rand_bins=None):
        cegb_delta = None
        if self.cegb_coupled is not None and feat_used is not None:
            cegb_delta = jnp.where(feat_used, 0.0, self.cegb_coupled)
        if self.cegb_lazy is not None and lazy_cnt is not None:
            lazy_term = self.cegb_lazy * lazy_cnt.astype(jnp.float32)
            cegb_delta = (lazy_term if cegb_delta is None
                          else cegb_delta + lazy_term)
        if self._linear_gain:
            return split_ops.find_best_split_linear(
                feat_hist, self.ctx, sum_g, sum_h, cnt,
                self.l2, self.min_gain_to_split, self.min_data_in_leaf,
                self.min_sum_hessian, self._rep_vals, self.linear_lambda,
                feature_mask, rand_bins=rand_bins)
        if (self._fast_search and cegb_delta is None
                and not with_feature_gains):
            return split_ops.find_best_split_fast(
                feat_hist, self.ctx, sum_g, sum_h, cnt,
                self.l1, self.l2, self.max_delta_step,
                self.min_gain_to_split, self.min_data_in_leaf,
                self.min_sum_hessian, feature_mask,
                rand_bins=rand_bins,
                feature_contri=self.feature_contri)
        return split_ops.find_best_split(
            feat_hist, self.ctx, sum_g, sum_h, cnt,
            self.l1, self.l2, self.max_delta_step, self.min_gain_to_split,
            self.min_data_in_leaf, self.min_sum_hessian, feature_mask,
            cat_params=self.cat_params,
            monotone=self.monotone if self.use_mc else None,
            cmin=cmin, cmax=cmax, depth=depth,
            monotone_penalty=self.monotone_penalty,
            cegb_count_coeff=self.cegb_count_coeff,
            cegb_feature_delta=cegb_delta,
            path_smooth=self.path_smooth,
            parent_output=parent_out,
            with_feature_gains=with_feature_gains,
            rand_bins=rand_bins,
            feature_contri=self.feature_contri)

    def _depth_guard(self, best, depth):
        depth_ok = (self.max_depth <= 0) | (depth < self.max_depth)
        gain = jnp.where(depth_ok, best.gain, -jnp.inf)
        return best._replace(gain=gain)

    # ------------------------------------------------------------------
    def _mc_refresh(self, st, lm, nleaves, feature_mask,
                    hist_scale=None):
        """Region-exact `intermediate` monotone mode.

        TPU-native replacement for the reference's recursive
        constraint-propagation walk (IntermediateLeafConstraints::
        GoUpToFindLeavesToUpdate + RecomputeBestSplitForLeaf,
        monotone_constraints.hpp:516-740, serial_tree_learner.cpp): every
        leaf carries its bin-range box (leaf_lo/leaf_hi over used
        features); two leaves are COMPARABLE along monotone feature m when
        their boxes overlap in every other feature and are disjoint along
        m.  Each split recomputes, from scratch, every leaf's output bounds
        from the current outputs of all comparable leaves — the sound
        fixed point the reference's incremental traversal approximates —
        then re-runs the split search for leaves whose bounds changed.
        Fully vectorized over (leaf x leaf) pairs; only traced when
        monotone_constraints_method selects it.
        """
        L = self.L
        lo = st["leaf_lo"][:L]                       # (L, F)
        hi = st["leaf_hi"][:L]
        vals = lm[LM_VALUE, :L]
        exist = jax.lax.iota(jnp.int32, L) < nleaves
        # pairwise per-feature box intersection: [row Y, col X, feature]
        inter = ((lo[:, None, :] <= hi[None, :, :]) &
                 (lo[None, :, :] <= hi[:, None, :]))
        miss = jnp.sum(~inter, axis=2)               # (L, L)
        pair_ok = exist[:, None] & exist[None, :]
        newmin = jnp.full((L,), -jnp.inf, jnp.float32)
        newmax = jnp.full((L,), jnp.inf, jnp.float32)
        for m, sign in zip(self.mono_enums, self.mono_signs):
            only_m = (miss - (~inter[:, :, m]).astype(jnp.int32)) == 0
            x_below = hi[None, :, m] < lo[:, None, m]    # X entirely below Y
            x_above = lo[None, :, m] > hi[:, None, m]
            lower = x_below if sign > 0 else x_above     # out(Y) >= out(X)
            upper = x_above if sign > 0 else x_below     # out(Y) <= out(X)
            lmask = only_m & lower & pair_ok
            umask = only_m & upper & pair_ok
            newmin = jnp.maximum(newmin, jnp.max(
                jnp.where(lmask, vals[None, :], -jnp.inf), axis=1))
            newmax = jnp.minimum(newmax, jnp.min(
                jnp.where(umask, vals[None, :], jnp.inf), axis=1))
        changed = exist & ((newmin != lm[LM_CMIN, :L]) |
                           (newmax != lm[LM_CMAX, :L]))
        lm = lm.at[LM_CMIN, :L].set(jnp.where(exist, newmin, lm[LM_CMIN, :L]))
        lm = lm.at[LM_CMAX, :L].set(jnp.where(exist, newmax, lm[LM_CMAX, :L]))
        # re-run the split search for every changed leaf (the reference
        # recomputes exactly the affected set; computing all-under-mask is
        # the vectorized equivalent)
        extra = ()
        if self.cegb_lazy is not None:
            # lazy counts are not re-derived on constraint refresh (the
            # cegb-lazy x intermediate-monotone interplay is not modeled)
            extra = (jnp.zeros((L, self.F), jnp.int32),)
        if self.extra_trees:
            # the constraint-refresh re-search draws fresh per-leaf random
            # thresholds from a fixed stream (the reference redraws on
            # every RecomputeBestSplitForLeaf call)
            base = jax.random.PRNGKey(self.extra_seed ^ 0x9E37)
            keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(
                jnp.arange(L))
            extra = extra + (jax.vmap(self._rand_bins)(keys),)
        # per-leaf effective masks: interaction-constraint/bynode masks are
        # stored per leaf; under feature-parallel the device-local feature
        # shards are UNIONed so every device recomputes the identical
        # refresh (no _sync_best needed for a replicated computation)
        mask0 = feature_mask
        if self.axis_name is not None and self.parallel_mode == "feature":
            mask0 = jax.lax.pmax(
                feature_mask.astype(jnp.int32), self.axis_name) > 0
        masks = jnp.broadcast_to(mask0, (L, self.F))
        if "leaf_fmask" in st:
            masks = masks & st["leaf_fmask"][:L]
        best = self._best_split_vmapped(
            self._scale_hist(st["hist"][:L], hist_scale),
            lm[LM_SUM_G, :L], lm[LM_SUM_H, :L],
            _f2i(lm[LM_CNT_G, :L]), _f2i(lm[LM_CNT, :L]),
            _f2i(lm[LM_DEPTH, :L]), newmin, newmax, lm[LM_VALUE, :L],
            masks, st["feat_used"],
            *extra)
        overlay = {
            LM_BGAIN: best.gain,
            LM_BFEAT: _i2f(best.feature),
            LM_BTHR: _i2f(best.threshold),
            LM_BDL: best.default_left.astype(jnp.float32),
            LM_BLCNT: _i2f(best.left_count),
            LM_BRCNT: _i2f(best.right_count),
            LM_BLSG: best.left_sum_g, LM_BLSH: best.left_sum_h,
            LM_BRSG: best.right_sum_g, LM_BRSH: best.right_sum_h,
            LM_BLOUT: best.left_output, LM_BROUT: best.right_output,
            LM_BISCAT: best.is_cat.astype(jnp.float32),
        }
        for row, new in overlay.items():
            lm = lm.at[row, :L].set(jnp.where(changed, new, lm[row, :L]))
        if not self.has_categorical:
            return lm, None
        cat = st["best_cat_set"]
        cat = cat.at[:L].set(jnp.where(changed[:, None], best.cat_set,
                                       cat[:L]))
        return lm, cat

    def _child_boxes(self, st, bl_oh, f_enum, is_cat, mtype, nb, dbin,
                     dl, thr):
        """The two children's bin-range boxes for the split being applied:
        parent box tightened along the split feature for numerical splits
        (categorical boxes stay whole — conservative).  Rows in the
        default/missing bin follow default_left regardless of the
        threshold: when that bin falls on the far side, the
        default-direction child's box must stay un-tightened along the
        split feature or the pairwise comparability test would wrongly
        exclude rows the child actually contains."""
        F = self.F
        prow_lo = jnp.max(
            jnp.where(bl_oh[:, None], st["leaf_lo"], 0), axis=0)
        prow_hi = jnp.max(
            jnp.where(bl_oh[:, None], st["leaf_hi"], 0), axis=0)
        f1h = jax.lax.broadcasted_iota(jnp.int32, (F,), 0) == f_enum
        tighten = f1h & ~is_cat
        d_eff = jnp.where(mtype == 2, nb - 1, dbin)
        has_miss = mtype != 0
        miss_l = has_miss & dl & (d_eff > thr)
        miss_r = has_miss & (~dl) & (d_eff <= thr)
        l_hi = jnp.where(tighten & ~miss_l,
                         jnp.minimum(prow_hi, thr), prow_hi)
        r_lo = jnp.where(tighten & ~miss_r,
                         jnp.maximum(prow_lo, thr + 1), prow_lo)
        return prow_lo, prow_hi, l_hi, r_lo

    def _advanced_bounds(self, lo_all, hi_all, vals, exist, c_lo, c_hi):
        """Per-(feature, threshold) output bounds for ONE candidate child
        box — the vectorized analog of the reference's advanced
        constraint segments (AdvancedLeafConstraints::UpdateConstraints +
        ComputeConstraintsPerThreshold, monotone_constraints.hpp:858).

        For a split of this child's box on feature f at threshold t, the
        LEFT grandchild covers f-bins [c_lo[f], t] and the RIGHT
        (t+1, c_hi[f]]; a leaf X constrains a grandchild iff X's box is
        disjoint from the child's range along the monotone feature m,
        overlaps it in every other feature, and overlaps the
        grandchild's f-range.  The t-dependence is monotone in t, so
        each bound array is a scatter of leaf outputs at box edges
        followed by a prefix (left) / shifted-suffix (right) running
        extremum over the bin axis.

        Args:
          lo_all/hi_all: (L, F) all leaves' bin boxes; vals: (L,) leaf
          outputs; exist: (L,) liveness; c_lo/c_hi: (F,) this child's box.
        Returns (cmin_l, cmax_l, cmin_r, cmax_r), each (F, BF).
        """
        F, BF, L = self.F, self.BF, lo_all.shape[0]
        inter = (lo_all <= c_hi[None, :]) & (c_lo[None, :] <= hi_all)
        miss = jnp.sum(~inter, axis=1)                    # (L,)
        f_idx = jnp.broadcast_to(jnp.arange(F)[None, :], (L, F))
        lo_c = jnp.clip(lo_all, 0, BF - 1)
        hi_c = jnp.clip(hi_all, 0, BF - 1)
        neg = jnp.float32(-jnp.inf)
        pos = jnp.float32(jnp.inf)
        cmin_l = jnp.full((F, BF), neg)
        cmax_l = jnp.full((F, BF), pos)
        cmin_r = jnp.full((F, BF), neg)
        cmax_r = jnp.full((F, BF), pos)

        def scat_max(mask, at):
            return jnp.full((F, BF), neg).at[f_idx, at].max(
                jnp.where(mask, vals[:, None], neg))

        def scat_min(mask, at):
            return jnp.full((F, BF), pos).at[f_idx, at].min(
                jnp.where(mask, vals[:, None], pos))

        def prefix_max(a):
            return jax.lax.associative_scan(jnp.maximum, a, axis=1)

        def prefix_min(a):
            return jax.lax.associative_scan(jnp.minimum, a, axis=1)

        def shifted_suffix_max(a):
            # out[t] = max over b > t of a[b]
            s = jax.lax.associative_scan(jnp.maximum, a, axis=1,
                                         reverse=True)
            return jnp.concatenate(
                [s[:, 1:], jnp.full((F, 1), neg)], axis=1)

        def shifted_suffix_min(a):
            s = jax.lax.associative_scan(jnp.minimum, a, axis=1,
                                         reverse=True)
            return jnp.concatenate(
                [s[:, 1:], jnp.full((F, 1), pos)], axis=1)

        for m, sign in zip(self.mono_enums, self.mono_signs):
            miss_ex_m = miss - (~inter[:, m]).astype(jnp.int32)
            x_below = hi_all[:, m] < c_lo[m]
            x_above = lo_all[:, m] > c_hi[m]
            # X whose outputs FLOOR this child (lower set) / CAP it
            lower = (x_below if sign > 0 else x_above) & exist
            upper = (x_above if sign > 0 else x_below) & exist

            # --- split feature f != m: X disjoint along m vs the FULL
            # child range, overlap in every feature except m and f, and
            # f-range overlap with the grandchild's shrunken f-range
            ok_f = (miss_ex_m[:, None]
                    - (~inter).astype(jnp.int32)) == 0     # (L, F)
            not_m = jnp.arange(F)[None, :] != m
            base_l = ok_f & not_m & (hi_all >= c_lo[None, :])
            base_r = ok_f & not_m & (lo_all <= c_hi[None, :])
            # left grandchild [c_lo, t]: applies once t >= X.lo[f]
            cmin_l = jnp.maximum(cmin_l, prefix_max(
                scat_max(base_l & lower[:, None], lo_c)))
            cmax_l = jnp.minimum(cmax_l, prefix_min(
                scat_min(base_l & upper[:, None], lo_c)))
            # right grandchild (t, c_hi]: applies while t < X.hi[f]
            cmin_r = jnp.maximum(cmin_r, shifted_suffix_max(
                scat_max(base_r & lower[:, None], hi_c)))
            cmax_r = jnp.minimum(cmax_r, shifted_suffix_min(
                scat_min(base_r & upper[:, None], hi_c)))

            # --- split ON m itself (the reference's inner-feature case):
            # the grandchild's m-range shrinks, so disjointness is judged
            # against it; only overlap-except-m is required of X
            ok_m = (miss_ex_m == 0) & exist
            onec = (jnp.arange(F) == m).astype(jnp.float32)[:, None]
            # left grandchild [c_lo[m], t]:
            #   X above it iff X.lo[m] > t  (bound fades as t grows)
            #   X below it iff X.hi[m] < c_lo[m]  (t-independent)
            above_l = shifted_suffix_max(
                scat_max((ok_m & ~x_below)[:, None]
                         & (jnp.arange(F)[None, :] == m), lo_c)) \
                if sign < 0 else shifted_suffix_min(
                scat_min((ok_m & ~x_below)[:, None]
                         & (jnp.arange(F)[None, :] == m), lo_c))
            below_vals_min = jnp.max(jnp.where(ok_m & x_below, vals, neg)) \
                if sign > 0 else None
            below_vals_max = jnp.min(jnp.where(ok_m & x_below, vals, pos)) \
                if sign < 0 else None
            if sign > 0:
                # above-X caps the left grandchild; below-X floors it
                cmax_l = jnp.minimum(cmax_l, jnp.where(
                    onec > 0, above_l, pos))
                cmin_l = jnp.maximum(cmin_l, jnp.where(
                    onec > 0, below_vals_min, neg))
            else:
                cmin_l = jnp.maximum(cmin_l, jnp.where(
                    onec > 0, above_l, neg))
                cmax_l = jnp.minimum(cmax_l, jnp.where(
                    onec > 0, below_vals_max, pos))
            # right grandchild (t, c_hi[m]]:
            #   X below it iff X.hi[m] <= t  (bound grows with t)
            #   X above it iff X.lo[m] > c_hi[m]  (t-independent)
            below_r = prefix_max(
                scat_max((ok_m & ~x_above)[:, None]
                         & (jnp.arange(F)[None, :] == m), hi_c)) \
                if sign > 0 else prefix_min(
                scat_min((ok_m & ~x_above)[:, None]
                         & (jnp.arange(F)[None, :] == m), hi_c))
            above_vals_max = jnp.min(jnp.where(ok_m & x_above, vals, pos)) \
                if sign > 0 else None
            above_vals_min = jnp.max(jnp.where(ok_m & x_above, vals, neg)) \
                if sign < 0 else None
            if sign > 0:
                cmin_r = jnp.maximum(cmin_r, jnp.where(
                    onec > 0, below_r, neg))
                cmax_r = jnp.minimum(cmax_r, jnp.where(
                    onec > 0, above_vals_max, pos))
            else:
                cmax_r = jnp.minimum(cmax_r, jnp.where(
                    onec > 0, below_r, pos))
                cmin_r = jnp.maximum(cmin_r, jnp.where(
                    onec > 0, above_vals_min, neg))
        return cmin_l, cmax_l, cmin_r, cmax_r

    def _leaf_best_split_voting(self, hist_local, sum_g, sum_h, cnt,
                                local_cnt, depth, cmin, cmax, parent_out,
                                feature_mask, feat_used=None, lazy_cnt=None,
                                rand_bins=None):
        """PV-Tree voting split search (reference:
        voting_parallel_tree_learner.cpp): each device votes its top-k
        features by LOCAL gain, the global top-2k features are elected by
        vote count (psum replaces the Allgather of LightSplitInfo votes,
        :364), and only the elected features' group histograms cross ICI —
        a fixed-size (<= 2*top_k, B, 2) gather-psum-scatter standing in for
        the sparse ReduceScatter (:387) — before the final, globally
        identical split evaluation (best-split sync, :465)."""
        ax = self.axis_name
        # local leaf totals: every feature group covers all rows, so group 0
        # sums to the local (grad, hess) totals of the leaf
        local_sum_g = hist_local[0, :, 0].sum()
        local_sum_h = hist_local[0, :, 1].sum()
        feat_hist_loc = self._feat_view(hist_local, local_sum_g, local_sum_h)
        _, gains_loc = self._find_best(
            feat_hist_loc, local_sum_g, local_sum_h, local_cnt, depth,
            cmin, cmax, feature_mask, feat_used=feat_used,
            parent_out=parent_out, with_feature_gains=True)
        k = min(self.top_k, self.F)
        topv, topi = jax.lax.top_k(gains_loc, k)
        votes = jnp.zeros((self.F,), jnp.int32).at[topi].add(
            jnp.isfinite(topv).astype(jnp.int32))
        votes_g = jax.lax.psum(votes, ax)
        # elect 2k features by vote count; smaller feature index breaks ties
        ek = min(2 * self.top_k, self.F)
        fiota = jnp.arange(self.F, dtype=jnp.int32)
        score = votes_g * jnp.int32(self.F) + (jnp.int32(self.F) - 1 - fiota)
        _, elected = jax.lax.top_k(score, ek)
        elected_mask = jnp.zeros((self.F,), jnp.bool_).at[elected].set(True)
        # sync ONLY the elected features' groups: ek is static, so the
        # collective payload is (ek, B, 2) regardless of G
        eg = self.f_group[elected]                      # (ek,) group ids
        sub_glob = jax.lax.psum(jnp.take(hist_local, eg, axis=0), ax)
        hist_glob = jnp.zeros_like(hist_local).at[eg].set(sub_glob)
        feat_hist = self._feat_view(hist_glob, sum_g, sum_h)
        best = self._find_best(feat_hist, sum_g, sum_h, cnt, depth,
                               cmin, cmax, feature_mask & elected_mask,
                               feat_used=feat_used, parent_out=parent_out,
                               lazy_cnt=lazy_cnt, rand_bins=rand_bins)
        return self._depth_guard(best, depth)

    # ------------------------------------------------------------------
    def _pvary(self, x):
        """Mark a value as device-varying for shard_map's vma type system
        (loop carries initialized from constants need this under SPMD);
        identity on runtimes without vma (utils/compat.py)."""
        if self.axis_name is None:
            return x
        from ..utils.compat import mark_device_varying
        return mark_device_varying(x, self.axis_name)

    def _psum(self, x):
        """Histogram sync: global sums only in data-parallel mode (voting
        keeps leaf histograms LOCAL and syncs only elected features at
        split-evaluation time).

        With tpu_data_hist_sync="scatter" the reference's ReduceScatter
        ownership is preserved (data_parallel_tree_learner.cpp:282-296):
        psum_scatter hands each device the GLOBAL sums of its OWN group
        slice only (each element crosses the wire once, vs ndev times
        for the full psum), the non-owned groups stay zero, the search
        masks to owned features, and the winner is elected by the same
        all-gather arg-max the feature-parallel mode uses."""
        if self.axis_name is not None and self.parallel_mode == "data":
            if self._scatter_groups:
                per = self._scatter_per
                Gp = per * self.num_shards
                xp = jnp.pad(x, ((0, Gp - self.G), (0, 0), (0, 0)))
                own = jax.lax.psum_scatter(
                    xp.reshape(self.num_shards, per, *x.shape[1:]),
                    self.axis_name, scatter_dimension=0, tiled=False)
                d = jax.lax.axis_index(self.axis_name)
                full = jnp.zeros((Gp,) + x.shape[1:], x.dtype)
                full = jax.lax.dynamic_update_slice(
                    full, own, (d * per,) + (0,) * (x.ndim - 1))
                return full[:self.G]
            return jax.lax.psum(x, self.axis_name)
        return x

    def _psum_scalar(self, x):
        """Row-statistic sync (counts, grad/hess totals): rows are sharded
        in both data- and voting-parallel modes."""
        if self.axis_name is not None and self.parallel_mode in ("data",
                                                                 "voting"):
            return jax.lax.psum(x, self.axis_name)
        return x

    def _sync_best(self, best):
        """Agree on the global best split across feature-sharded devices
        (reference: SyncUpGlobalBestSplit, parallel_tree_learner.h:209-232).
        Also elects the winner under ReduceScatter histogram ownership
        (data-parallel scatter mode): devices are ordered by owned
        feature range, so the arg-max's first-max tie-break matches the
        serial scan order."""
        if self.axis_name is None or not (
                self.parallel_mode == "feature" or self._scatter_groups):
            return best
        gathered = jax.tree.map(
            lambda a: jax.lax.all_gather(a, self.axis_name), best)
        winner = jnp.argmax(gathered.gain)
        return jax.tree.map(lambda a: a[winner], gathered)

    def _build_tree_impl(self, part_bins, part_ghi0, bag_cnt,
                         feature_mask, seed, feat_used_init=None, aux0=None,
                         hist_scale=None):
        """Core tree loop over a prebuilt (8, N_pad) row payload whose
        rows are (grad, hess, rowid-bits, extras...); the extras ride the
        partition untouched (physical-order fused step)."""
        if self.frontier_k > 1:
            # batched frontier growth (the eligibility gate guarantees
            # feat_used_init/aux0 are absent: no CEGB in batched mode)
            return self._build_tree_frontier(part_bins, part_ghi0, bag_cnt,
                                             feature_mask, hist_scale)
        L, G, B, F = self.L, self.G, self.B, self.F
        nodes = self.max_splits
        rng0 = jax.random.PRNGKey(seed)

        root_mask = feature_mask
        if self.ic_masks is not None:
            root_mask = root_mask & self._allowed_from_used(
                jnp.zeros((F,), jnp.bool_))
        if self.has_bynode:
            root_mask = root_mask & self._bynode_mask(
                jax.random.fold_in(rng0, 0))
        # coupled CEGB penalties persist across trees: the caller threads the
        # model-lifetime used-feature set back in each iteration (reference:
        # CostEfficientGradientBoosting::is_feature_used_in_split_)
        feat_used0 = (jnp.zeros((F,), jnp.bool_) if feat_used_init is None
                      else feat_used_init)

        root_local = self._hist_leaf(
            part_bins, part_ghi0, jnp.int32(self.row0), jnp.int32(self.N),
            scale=hist_scale)
        root_hist = self._psum(root_local)
        bag_cnt_g = self._psum_scalar(bag_cnt)
        # in voting mode root_hist stays LOCAL; in scatter mode only the
        # owned groups survive in root_hist — either way the leaf totals
        # come from the LOCAL histogram reduced across ranks
        if self.parallel_mode == "voting" or self._scatter_groups:
            sum_g = self._psum_scalar(root_local[0, :, 0].sum())
            sum_h = self._psum_scalar(root_local[0, :, 1].sum())
        else:
            sum_g = root_hist[0, :, 0].sum()
            sum_h = root_hist[0, :, 1].sum()
        if hist_scale is not None:
            # integer-domain quantized totals -> gain domain (once)
            sum_g = sum_g * hist_scale[0]
            sum_h = sum_h * hist_scale[1]
        neg_inf = jnp.float32(-jnp.inf)
        pos_inf = jnp.float32(jnp.inf)
        lazy_extra = ()
        if self.cegb_lazy is not None:
            if aux0 is None:
                aux0 = jnp.zeros((self.aux_rows, part_bins.shape[1]),
                                 jnp.int32)
            lazy_extra = (self._lazy_counts(
                aux0, jnp.int32(self.row0), jnp.int32(self.N),
                jnp.int32(0))[0],)
        rngx = None
        if self.extra_trees:
            rngx = jax.random.fold_in(
                jax.random.PRNGKey(self.extra_seed), seed)
            lazy_extra = lazy_extra + (
                self._rand_bins(jax.random.fold_in(rngx, 0)),)
        best0 = self._sync_best(self._leaf_best_split(
            self._scale_hist(root_hist, hist_scale), sum_g, sum_h,
            bag_cnt_g, bag_cnt, jnp.int32(0),
            neg_inf, pos_inf, jnp.float32(0.0), root_mask, feat_used0,
            *lazy_extra))

        # one TRASH slot is appended to every leaf/node-indexed buffer:
        # iterations whose split is invalid (stop, or an abandoned forced
        # split) still execute the body but write to the trash column, so the
        # while body needs NO lax.cond — conditionals force whole-state
        # copies of the multi-MB row buffers every iteration (measured ~60%
        # of the tree build).
        root_forced = jnp.int32(0 if self.forced is not None else -1)
        col0 = jnp.stack([
            _i2f(self.row0), _i2f(self.N), _i2f(bag_cnt_g),
            sum_g, sum_h, _i2f(0),
            jnp.float32(-jnp.inf), jnp.float32(jnp.inf),
            jnp.float32(0.0), _i2f(-1), _i2f(0),
            best0.gain, _i2f(best0.feature), _i2f(best0.threshold),
            best0.default_left.astype(jnp.float32),
            _i2f(best0.left_count), _i2f(best0.right_count),
            best0.left_sum_g, best0.left_sum_h,
            best0.right_sum_g, best0.right_sum_h,
            best0.left_output, best0.right_output,
            best0.is_cat.astype(jnp.float32), _i2f(root_forced)])
        if self._linear_gain:
            # the root's own whole-leaf model from its search (a
            # root-only tree still predicts linearly)
            col0 = jnp.concatenate([col0, jnp.stack([
                best0.self_const, best0.self_coeff,
                _i2f(best0.self_feature)])])
        leafmat = jnp.zeros((self._nlf, L + 1), jnp.float32) \
            .at[LM_BGAIN].set(jnp.float32(NEG_INF)) \
            .at[LM_CMIN].set(jnp.float32(-jnp.inf)) \
            .at[LM_CMAX].set(jnp.float32(jnp.inf)) \
            .at[LM_PARENT].set(_i2f(jnp.full((L + 1,), -1, jnp.int32))) \
            .at[LM_FORCED].set(_i2f(jnp.full((L + 1,), -1, jnp.int32))) \
            .at[:, 0].set(col0)

        use_mega = self._use_mega is not None
        use_flat = (self._use_flat_hist and hist_scale is None
                    and not use_mega)
        state = {
            "s": jnp.int32(0),
            "done": jnp.bool_(False),
            "part_bins": part_bins,
            "part_ghi": part_ghi0,
            "leafmat": leafmat,
            "nodemat": jnp.zeros((NND, nodes + 1), jnp.float32),
            "feat_used": feat_used0,
        }
        if not use_mega:
            # the mega path computes BOTH children's histograms per split
            # and consumes them in-register: no per-leaf histogram state
            # rides the while loop at all (and with it go the two
            # contextual full-state copies per split — PERF.md round 4)
            if use_flat:
                state["hist"] = jnp.zeros(
                    (L + 1, 8, self._flat_geom[2]), jnp.float32).at[0].set(
                    self._flatten_hist(root_hist))
            else:
                state["hist"] = jnp.zeros(
                    (L + 1, G, B, 2), dtype=jnp.float32).at[0].set(root_hist)
        if self.has_categorical:
            state["best_cat_set"] = jnp.zeros(
                (L + 1, self.BF), jnp.bool_).at[0].set(best0.cat_set)
            state["node_cat_set"] = jnp.zeros((nodes + 1, self.BF),
                                              jnp.bool_)
        if self._use_pallas_part:
            from ..ops.partition_pallas import sc_rows_for
            state["sc_packed"] = jnp.zeros(
                (sc_rows_for(self._pb_rows), part_bins.shape[1]),
                jnp.int32)
        else:
            state["sc32"] = jnp.zeros((G + self._ghi_rows,
                                       part_bins.shape[1]), jnp.int32)

        if self.ic_masks is not None:
            state["leaf_used"] = jnp.zeros((L + 1, F), jnp.bool_)

        if self.cegb_lazy is not None:
            state["part_aux"] = aux0
            state["sc_aux"] = jnp.zeros_like(aux0)

        if self.use_mc and self.mc_mode in ("intermediate", "advanced"):
            # root box covers every bin of every used feature
            state["leaf_lo"] = jnp.zeros((L + 1, F), jnp.int32)
            state["leaf_hi"] = jnp.broadcast_to(
                self.ctx.num_bin - 1, (L + 1, F)).astype(jnp.int32)
            if self.ic_masks is not None or self.has_bynode:
                # per-leaf effective feature masks so the constraint
                # refresh re-search honors interaction/bynode restrictions
                state["leaf_fmask"] = jnp.broadcast_to(
                    root_mask, (L + 1, F)).astype(jnp.bool_)

        # uniform vma typing under shard_map: mark the whole state varying
        state = self._pvary(state)

        def cond(st):
            return (st["s"] < nodes) & (~st["done"])

        def body(st):
            lm = st["leafmat"]
            bgain_row = lm[LM_BGAIN, :L]
            best_leaf = jnp.argmax(bgain_row).astype(jnp.int32)
            gain = bgain_row[best_leaf]

            # forced splits take precedence over the free search
            # (reference: ForceSplits, serial_tree_learner.cpp:614)
            forced_ok = jnp.bool_(False)
            skip_pending = jnp.bool_(False)
            forced_node = jnp.int32(0)
            forced_info = None
            if self.forced is not None:
                fids = _f2i(lm[LM_FORCED, :L])
                f_leaf = jnp.argmax(fids >= 0).astype(jnp.int32)
                has_f = jnp.any(fids >= 0)
                forced_node = jnp.maximum(fids[f_leaf], 0)
                fcol = jax.lax.dynamic_slice(
                    lm, (0, f_leaf), (self._nlf, 1))[:, 0]
                forced_info = self._forced_split_info(
                    self._scale_hist(st["hist"][f_leaf], hist_scale),
                    self.forced["feature"][forced_node],
                    self.forced["bin"][forced_node],
                    fcol[LM_SUM_G], fcol[LM_SUM_H], _f2i(fcol[LM_CNT_G]))
                depth_ok = (self.max_depth <= 0) | \
                    (_f2i(fcol[LM_DEPTH]) < self.max_depth)
                forced_ok = has_f & forced_info["valid"] & depth_ok
                # a failed forced split is abandoned WITHOUT consuming a
                # split step; free search resumes next iteration
                skip_pending = has_f & ~forced_ok
                st = {**st, "leafmat": jnp.where(
                    skip_pending,
                    lm.at[LM_FORCED, f_leaf].set(_i2f(-1)), lm)}
                lm = st["leafmat"]
                best_leaf = jnp.where(forced_ok, f_leaf, best_leaf)
                gain = jnp.where(forced_ok, forced_info["gain"], gain)

            # an invalid iteration still runs the body but writes to the
            # TRASH slots and processes 0 rows — no lax.cond, no copies
            valid = forced_ok | ((gain > 0) & ~skip_pending)

            # one read of the chosen leaf's packed scalars
            pcol = jax.lax.dynamic_slice(lm, (0, best_leaf),
                                         (self._nlf, 1))[:, 0]

            adv_cat_set = None
            adv_reject = jnp.bool_(False)
            if self.use_mc and self.mc_mode == "advanced":
                # re-search the CHOSEN leaf with per-threshold bounds
                # before executing its split: the stored (refresh) search
                # used whole-box scalars, which both clamps child outputs
                # and can reject splits the advanced segments allow.
                # Leaf SELECTION keeps the conservative stored gains (one
                # advanced search per executed split keeps the cost
                # linear; the reference's advanced mode is similarly the
                # slow path).
                bl1 = jax.lax.iota(jnp.int32, L + 1) == best_leaf
                y_lo = jnp.max(jnp.where(bl1[:, None], st["leaf_lo"], 0),
                               axis=0)
                y_hi = jnp.max(jnp.where(bl1[:, None], st["leaf_hi"], 0),
                               axis=0)
                ab = self._advanced_bounds(
                    st["leaf_lo"][:L], st["leaf_hi"][:L],
                    lm[LM_VALUE, :L],
                    jax.lax.iota(jnp.int32, L) < (st["s"] + 1),
                    y_lo, y_hi)
                # the advanced arrays already encode every comparable
                # leaf; the leaf's own whole-box scalars (LM_CMIN/CMAX)
                # bound its VALUE, not its children, and folding them in
                # would collapse advanced back to intermediate
                cmin_t = (ab[0], ab[2])
                cmax_t = (ab[1], ab[3])
                maskY = feature_mask
                if "leaf_fmask" in st:
                    maskY = maskY & jnp.any(
                        st["leaf_fmask"] & bl1[:, None], axis=0)
                adv_extra = ()
                if self.cegb_lazy is not None:
                    # cegb-lazy counts are not re-derived here (same
                    # stance as the constraint refresh)
                    adv_extra = (jnp.zeros((2, F), jnp.int32),)
                if self.extra_trees:
                    adv_extra = adv_extra + (self._rand_bins(
                        jax.random.fold_in(
                            jax.random.PRNGKey(self.extra_seed ^ 0x51AD),
                            st["s"])),)
                adv = self._sync_best(self._leaf_best_split(
                    self._scale_hist(st["hist"][best_leaf], hist_scale),
                    pcol[LM_SUM_G],
                    pcol[LM_SUM_H], _f2i(pcol[LM_CNT_G]),
                    _f2i(pcol[LM_CNT]), _f2i(pcol[LM_DEPTH]),
                    cmin_t, cmax_t, pcol[LM_VALUE], maskY,
                    st["feat_used"], *adv_extra))
                pcol = pcol.at[LM_BGAIN].set(adv.gain) \
                    .at[LM_BFEAT].set(_i2f(adv.feature)) \
                    .at[LM_BTHR].set(_i2f(adv.threshold)) \
                    .at[LM_BDL].set(adv.default_left.astype(jnp.float32)) \
                    .at[LM_BLCNT].set(_i2f(adv.left_count)) \
                    .at[LM_BRCNT].set(_i2f(adv.right_count)) \
                    .at[LM_BLSG].set(adv.left_sum_g) \
                    .at[LM_BLSH].set(adv.left_sum_h) \
                    .at[LM_BRSG].set(adv.right_sum_g) \
                    .at[LM_BRSH].set(adv.right_sum_h) \
                    .at[LM_BLOUT].set(adv.left_output) \
                    .at[LM_BROUT].set(adv.right_output) \
                    .at[LM_BISCAT].set(adv.is_cat.astype(jnp.float32))
                if self.has_categorical:
                    adv_cat_set = adv.cat_set
                stored_gain = gain
                gain = jnp.where(forced_ok, gain, adv.gain)
                valid = forced_ok | ((gain > 0) & ~skip_pending)
                # persist the advanced gain into the leafmat: when the
                # re-search REJECTS a split the stored (conservative)
                # positive gain would re-select this leaf forever; the
                # write also keeps future leaf selection on the advanced
                # basis.  (Lane-dynamic column write — the fast pattern.)
                lm = jnp.where(forced_ok, lm,
                               lm.at[LM_BGAIN, best_leaf].set(adv.gain))
                st = {**st, "leafmat": lm}
                # a rejection consumes NO split step and must not end
                # the tree: other leaves may still carry positive gains
                # (their next argmax sees the demoted gain just written)
                adv_reject = ~forced_ok & ~skip_pending \
                    & (adv.gain <= 0) & (stored_gain > 0)

            if True:
                s = st["s"]
                new_leaf = s + 1
                wr_a = jnp.where(valid, best_leaf, jnp.int32(L))
                wr_b = jnp.where(valid, new_leaf, jnp.int32(L))
                wr_s = jnp.where(valid, s, jnp.int32(nodes))
                f_enum = _f2i(pcol[LM_BFEAT])
                thr = _f2i(pcol[LM_BTHR])
                dl = pcol[LM_BDL] > 0.5
                is_cat = pcol[LM_BISCAT] > 0.5
                # row reads/writes on (L, ...) matrices use masked
                # reductions/selects: dynamic indexing on the SUBLANE axis
                # lowers to a slow per-tile path (~80us per occurrence,
                # measured; the masked forms are plain VPU passes)
                bl_oh = jax.lax.iota(jnp.int32, L + 1) == best_leaf
                if self.has_categorical:
                    cat_set = (adv_cat_set if adv_cat_set is not None else
                               jnp.any(st["best_cat_set"] & bl_oh[:, None],
                                       axis=0))
                else:
                    cat_set = jnp.zeros((1,), jnp.bool_)
                if forced_info is not None:
                    f_enum = jnp.where(forced_ok,
                                       self.forced["feature"][forced_node],
                                       f_enum)
                    thr = jnp.where(forced_ok, forced_info["threshold"], thr)
                    dl = jnp.where(forced_ok, True, dl)
                    is_cat = jnp.where(forced_ok, False, is_cat)
                    cat_set = jnp.where(forced_ok,
                                        jnp.zeros_like(cat_set), cat_set)
                # ONE lane-dynamic column slice replaces ~8 scalar
                # dynamic-indexes into the per-feature metadata vectors
                fcolm = jax.lax.dynamic_slice(
                    self._fmeta, (0, f_enum), (self._fmeta.shape[0], 1))[:, 0]
                (orig_feat, col, bstart, isb, nb, dbin, mtype,
                 mono_f) = (fcolm[0], fcolm[1], fcolm[2], fcolm[3],
                            fcolm[4], fcolm[5], fcolm[6], fcolm[7])
                start = _f2i(pcol[LM_START])
                cnt = jnp.where(valid, _f2i(pcol[LM_CNT]), 0)
                cnt_g = _f2i(pcol[LM_CNT_G])

                mega_hists = None
                if use_mega:
                    moved, left_cnt, mega_hists = self._split_leaf_mega(
                        st, start, cnt, col,
                        (bstart, isb, nb, dbin, mtype, thr, dl, is_cat,
                         cat_set), hist_scale)
                else:
                    moved, left_cnt = self._partition_leaf(
                        st, start, cnt, col,
                        (bstart, isb, nb, dbin, mtype, thr, dl, is_cat,
                         cat_set))
                right_cnt = cnt - left_cnt
                # bag-aware counts come from the (global) histogram estimate
                # cached with the best split, not from physical range sizes:
                # out-of-bag rows live in the ranges with zeroed gradients
                left_cnt_g = _f2i(pcol[LM_BLCNT])
                right_cnt_g = _f2i(pcol[LM_BRCNT])
                if forced_info is not None:
                    left_cnt_g = jnp.where(forced_ok, forced_info["lcnt"],
                                           left_cnt_g)
                    right_cnt_g = jnp.where(forced_ok, forced_info["rcnt"],
                                            right_cnt_g)
                l_start = start
                r_start = start + left_cnt

                # smaller child's histogram; larger by subtraction.  The
                # smaller/larger choice must use GLOBAL counts so every
                # device computes (and psums) the same child's histogram.
                # (On the mega path BOTH children came from the kernel —
                # no subtraction, no histogram state.)
                if not use_mega:
                    small_is_left = left_cnt_g <= right_cnt_g
                    sm_start = jnp.where(small_is_left, l_start, r_start)
                    sm_cnt = jnp.where(small_is_left, left_cnt, right_cnt)
                if use_mega:
                    hist = None
                    hist_left = hist_right = None
                    if not self._use_pallas_search:
                        hl_g, hl_h, hr_g, hr_h = mega_hists
                        hist_left = jnp.stack(
                            [hl_g[:, :B], hl_h[:, :B]], axis=2)
                        hist_right = jnp.stack(
                            [hr_g[:, :B], hr_h[:, :B]], axis=2)
                elif use_flat:
                    # in-place one-row DMA read/subtract/write of the
                    # lane-flattened state (ops/hist_state_pallas.py) —
                    # replaces the dynamic-slice formulation whose
                    # contextual full-state copies cost ~7 ms/iter
                    from ..ops.hist_state_pallas import hist_rmw_pallas
                    small_flat = self._hist_leaf_flat(
                        moved["part_bins"], moved["part_ghi"],
                        sm_start, sm_cnt)
                    hist, hl_flat, hr_flat = hist_rmw_pallas(
                        st["hist"], small_flat,
                        jnp.stack([best_leaf, wr_a, wr_b,
                                   small_is_left.astype(jnp.int32)]),
                        interpret=self._interp)
                    hist_left = hist_right = None
                else:
                    hist_small = self._psum(self._hist_leaf(
                        moved["part_bins"], moved["part_ghi"],
                        sm_start, sm_cnt, scale=hist_scale))
                    parent_hist = st["hist"][best_leaf]
                    hist_large = parent_hist - hist_small
                    hist_left = jnp.where(small_is_left, hist_small,
                                          hist_large)
                    hist_right = jnp.where(small_is_left, hist_large,
                                           hist_small)
                    hist = st["hist"].at[wr_a].set(hist_left).at[wr_b].set(
                        hist_right)

                lsg = pcol[LM_BLSG]
                lsh = pcol[LM_BLSH]
                rsg = pcol[LM_BRSG]
                rsh = pcol[LM_BRSH]
                lout = pcol[LM_BLOUT]
                rout = pcol[LM_BROUT]
                if forced_info is not None:
                    lsg = jnp.where(forced_ok, forced_info["lsg"], lsg)
                    lsh = jnp.where(forced_ok, forced_info["lsh"], lsh)
                    rsg = jnp.where(forced_ok, forced_info["rsg"], rsg)
                    rsh = jnp.where(forced_ok, forced_info["rsh"], rsh)
                    lout = jnp.where(forced_ok, forced_info["lout"], lout)
                    rout = jnp.where(forced_ok, forced_info["rout"], rout)
                depth_child = _f2i(pcol[LM_DEPTH]) + 1

                # basic-mode monotone bounds for the children (reference:
                # BasicLeafConstraints::Update, monotone_constraints.hpp:488)
                p_cmin = pcol[LM_CMIN]
                p_cmax = pcol[LM_CMAX]
                if self.use_mc:
                    mid = (lout + rout) * 0.5
                    num_split = ~is_cat
                    l_cmin = jnp.where(num_split & (mono_f < 0),
                                       jnp.maximum(p_cmin, mid), p_cmin)
                    l_cmax = jnp.where(num_split & (mono_f > 0),
                                       jnp.minimum(p_cmax, mid), p_cmax)
                    r_cmin = jnp.where(num_split & (mono_f > 0),
                                       jnp.maximum(p_cmin, mid), p_cmin)
                    r_cmax = jnp.where(num_split & (mono_f < 0),
                                       jnp.minimum(p_cmax, mid), p_cmax)
                else:
                    l_cmin = r_cmin = p_cmin
                    l_cmax = r_cmax = p_cmax

                # record the internal node (reference: Tree::Split, tree.cpp)
                upd = dict(moved)
                if self.has_categorical:
                    upd["node_cat_set"] = jnp.where(
                        (jax.lax.iota(jnp.int32, nodes + 1) == wr_s)[:, None],
                        cat_set[None, :], st["node_cat_set"])
                ncol = jnp.stack([
                    _i2f(orig_feat), _i2f(f_enum),
                    _i2f(thr), dl.astype(jnp.float32), gain,
                    _i2f(-(best_leaf + 1)), _i2f(-(new_leaf + 1)),
                    pcol[LM_VALUE], pcol[LM_SUM_H], _i2f(cnt_g),
                    _i2f(col), _i2f(bstart), _i2f(isb), _i2f(nb),
                    _i2f(dbin), _i2f(mtype), is_cat.astype(jnp.float32)])
                nm = st["nodemat"].at[:, wr_s].set(ncol)
                # fix the parent's child pointer (read-modify-write of ONE
                # nodemat column)
                p = _f2i(pcol[LM_PARENT])
                side = _f2i(pcol[LM_PSIDE])
                sp = jnp.where(valid, jnp.maximum(p, 0), jnp.int32(nodes))
                par = jax.lax.dynamic_slice(nm, (0, sp), (NND, 1))[:, 0]
                par = par.at[ND_LEFT].set(jnp.where(
                    (p >= 0) & (side == 0), _i2f(s), par[ND_LEFT]))
                par = par.at[ND_RIGHT].set(jnp.where(
                    (p >= 0) & (side == 1), _i2f(s), par[ND_RIGHT]))
                nm = nm.at[:, sp].set(par)
                upd["nodemat"] = nm

                # child best splits (single traced program via vmap over the
                # stacked pair — halves the while-body program size)
                # per-child feature masks: interaction constraints narrow to
                # sets compatible with the path, bynode sampling re-draws
                f_onehot = jax.lax.broadcasted_iota(
                    jnp.int32, (F,), 0) == f_enum
                feat_used_new = (st["feat_used"] | f_onehot
                                 if self.has_cegb else st["feat_used"])
                mask_l = mask_r = feature_mask
                if self.ic_masks is not None:
                    used_child = jnp.any(
                        st["leaf_used"] & bl_oh[:, None], axis=0) | f_onehot
                    allowed = self._allowed_from_used(used_child)
                    mask_l = mask_l & allowed
                    mask_r = mask_r & allowed
                if self.has_bynode:
                    kstep = jax.random.fold_in(rng0, s + 1)
                    kl, kr = jax.random.split(kstep)
                    mask_l = mask_l & self._bynode_mask(kl)
                    mask_r = mask_r & self._bynode_mask(kr)

                lazy_pair = ()
                if self.cegb_lazy is not None:
                    # mark the split feature used for the leaf's rows FIRST
                    # (children then see zero lazy penalty for it), then
                    # count still-unused rows per feature for both children
                    aux_m = self._lazy_mark(moved["part_aux"], start, cnt,
                                            f_enum)
                    upd["part_aux"] = aux_m
                    lazy_pair = (self._lazy_counts(
                        aux_m, start, left_cnt, cnt - left_cnt),)
                if self.extra_trees:
                    klx, krx = jax.random.split(
                        jax.random.fold_in(rngx, s + 1))
                    lazy_pair = lazy_pair + (jnp.stack(
                        [self._rand_bins(klx), self._rand_bins(krx)]),)

                if self.forced is not None:
                    forced_l = jnp.where(forced_ok,
                                         self.forced["left"][forced_node],
                                         jnp.int32(-1))
                    forced_r = jnp.where(forced_ok,
                                         self.forced["right"][forced_node],
                                         jnp.int32(-1))
                else:
                    forced_l = forced_r = jnp.int32(-1)

                def child_head(cstart, ccnt, ccnt_g, csg, csh, cout, cmin_,
                               cmax_, side):
                    return jnp.stack([
                        _i2f(cstart), _i2f(ccnt), _i2f(ccnt_g), csg, csh,
                        _i2f(depth_child), cmin_, cmax_, cout, _i2f(s),
                        _i2f(side)])

                head_l = child_head(l_start, left_cnt, left_cnt_g, lsg,
                                    lsh, lout, l_cmin, l_cmax, 0)
                head_r = child_head(r_start, right_cnt, right_cnt_g, rsg,
                                    rsh, rout, r_cmin, r_cmax, 1)

                if self._use_pallas_search:
                    # both children's searches as ONE kernel emitting the
                    # packed [LM_BGAIN..LM_BISCAT] leafmat segments
                    from ..ops.split_pallas import best_split_pair_pallas
                    BFs = self.BF
                    if use_mega:
                        hl_g, hl_h, hr_g, hr_h = mega_hists
                        hg = jnp.concatenate([hl_g[:, :BFs],
                                              hr_g[:, :BFs]], axis=0)
                        hh = jnp.concatenate([hl_h[:, :BFs],
                                              hr_h[:, :BFs]], axis=0)
                    elif use_flat:
                        Gf, Bf, _ = self._flat_geom
                        hl = hl_flat.reshape(2, Gf, Bf)
                        hr = hr_flat.reshape(2, Gf, Bf)
                        hg = jnp.concatenate([hl[0, :G, :BFs],
                                              hr[0, :G, :BFs]], axis=0)
                        hh = jnp.concatenate([hl[1, :G, :BFs],
                                              hr[1, :G, :BFs]], axis=0)
                    else:
                        hg = jnp.concatenate([hist_left[:, :BFs, 0],
                                              hist_right[:, :BFs, 0]],
                                             axis=0)
                        hh = jnp.concatenate([hist_left[:, :BFs, 1],
                                              hist_right[:, :BFs, 1]],
                                             axis=0)
                        if hist_scale is not None:
                            # integer-domain state -> gain domain
                            hg = hg * hist_scale[0]
                            hh = hh * hist_scale[1]
                    onesF = jnp.ones((F, 1), jnp.float32)
                    dep_f = depth_child.astype(jnp.float32)

                    def iblock(csg, csh, ccnt_g, mask):
                        return jnp.concatenate([
                            onesF * csg, onesF * csh,
                            onesF * ccnt_g.astype(jnp.float32),
                            onesF * dep_f,
                            mask.astype(jnp.float32)[:, None],
                            jnp.zeros((F, 3), jnp.float32)], axis=1)

                    info = jnp.concatenate(
                        [iblock(lsg, lsh, left_cnt_g, mask_l),
                         iblock(rsg, rsh, right_cnt_g, mask_r)], axis=0)
                    tile = best_split_pair_pallas(
                        hg, hh, self._fmeta_pair, info,
                        l1=self.l1, l2=self.l2,
                        max_delta_step=self.max_delta_step,
                        min_gain_to_split=self.min_gain_to_split,
                        min_data_in_leaf=self.min_data_in_leaf,
                        min_sum_hessian=self.min_sum_hessian,
                        max_depth=self.max_depth, interpret=self._interp)
                    if self._ab_double == "search":
                        # measurement-only in-context doubling: the
                        # opaque select blocks CSE; results bit-identical
                        opq = moved["part_ghi"][0, :1] * 0.0
                        tile2 = best_split_pair_pallas(
                            jnp.where(opq[0] < 1.0, hg, hg + 1.0), hh,
                            self._fmeta_pair, info,
                            l1=self.l1, l2=self.l2,
                            max_delta_step=self.max_delta_step,
                            min_gain_to_split=self.min_gain_to_split,
                            min_data_in_leaf=self.min_data_in_leaf,
                            min_sum_hessian=self.min_sum_hessian,
                            max_depth=self.max_depth,
                            interpret=self._interp)
                        tile = jnp.where(opq[0] < 1.0, tile2, tile)
                    col_l = jnp.concatenate(
                        [head_l, tile[0, :13],
                         _i2f(forced_l)[None]])
                    col_r = jnp.concatenate(
                        [head_r, tile[1, :13],
                         _i2f(forced_r)[None]])
                else:
                    if self.use_mc and self.mc_mode in ("intermediate",
                                                        "advanced"):
                        child_boxes = self._child_boxes(
                            st, bl_oh, f_enum, is_cat, mtype, nb, dbin,
                            dl, thr)
                    if self.use_mc and self.mc_mode == "advanced":
                        # per-threshold children bounds (the reference's
                        # AdvancedLeafConstraints segments) for the TWO
                        # candidate children, folded with their scalar
                        # (basic + refresh) bounds
                        prow_lo, prow_hi, l_hi_box, r_lo_box = child_boxes
                        lo_all = st["leaf_lo"][:L]
                        hi_all = st["leaf_hi"][:L]
                        vals_all = lm[LM_VALUE, :L]
                        exist_l = jax.lax.iota(jnp.int32, L) < (s + 1)
                        abl = self._advanced_bounds(
                            lo_all, hi_all, vals_all, exist_l,
                            prow_lo, l_hi_box)
                        abr = self._advanced_bounds(
                            lo_all, hi_all, vals_all, exist_l,
                            r_lo_box, prow_hi)
                        # fold ONLY the sibling mid-refinement (the
                        # reference's BasicLeafConstraints::Update for the
                        # split just applied); the parent's whole-box
                        # scalars would collapse advanced to intermediate
                        mid_v = (lout + rout) * 0.5
                        num_sp = ~is_cat
                        lmin_m = jnp.where(num_sp & (mono_f < 0), mid_v,
                                           -jnp.inf)
                        lmax_m = jnp.where(num_sp & (mono_f > 0), mid_v,
                                           jnp.inf)
                        rmin_m = jnp.where(num_sp & (mono_f > 0), mid_v,
                                           -jnp.inf)
                        rmax_m = jnp.where(num_sp & (mono_f < 0), mid_v,
                                           jnp.inf)
                        cmin_arg = (
                            jnp.stack([jnp.maximum(abl[0], lmin_m),
                                       jnp.maximum(abr[0], rmin_m)]),
                            jnp.stack([jnp.maximum(abl[2], lmin_m),
                                       jnp.maximum(abr[2], rmin_m)]))
                        cmax_arg = (
                            jnp.stack([jnp.minimum(abl[1], lmax_m),
                                       jnp.minimum(abr[1], rmax_m)]),
                            jnp.stack([jnp.minimum(abl[3], lmax_m),
                                       jnp.minimum(abr[3], rmax_m)]))
                    else:
                        cmin_arg = jnp.stack([l_cmin, r_cmin])
                        cmax_arg = jnp.stack([l_cmax, r_cmax])
                    both = self._best_split_vmapped(
                        self._scale_hist(jnp.stack([hist_left,
                                                    hist_right]),
                                         hist_scale),
                        jnp.stack([lsg, rsg]), jnp.stack([lsh, rsh]),
                        jnp.stack([left_cnt_g, right_cnt_g]),
                        jnp.stack([left_cnt, right_cnt]),
                        jnp.stack([depth_child, depth_child]),
                        cmin_arg, cmax_arg,
                        jnp.stack([lout, rout]),
                        jnp.stack([mask_l, mask_r]), feat_used_new,
                        *lazy_pair)
                    best_l = self._sync_best(
                        jax.tree.map(lambda a: a[0], both))
                    best_r = self._sync_best(
                        jax.tree.map(lambda a: a[1], both))

                    def seg13(bs):
                        return jnp.stack([
                            bs.gain, _i2f(bs.feature), _i2f(bs.threshold),
                            bs.default_left.astype(jnp.float32),
                            _i2f(bs.left_count), _i2f(bs.right_count),
                            bs.left_sum_g, bs.left_sum_h,
                            bs.right_sum_g, bs.right_sum_h,
                            bs.left_output, bs.right_output,
                            bs.is_cat.astype(jnp.float32)])

                    col_l = jnp.concatenate(
                        [head_l, seg13(best_l), _i2f(forced_l)[None]])
                    col_r = jnp.concatenate(
                        [head_r, seg13(best_r), _i2f(forced_r)[None]])
                    if self._linear_gain:
                        # each child's model comes from its OWN search
                        # (best whole-leaf single-feature fit)
                        col_l = jnp.concatenate([col_l, jnp.stack([
                            best_l.self_const, best_l.self_coeff,
                            _i2f(best_l.self_feature)])])
                        col_r = jnp.concatenate([col_r, jnp.stack([
                            best_r.self_const, best_r.self_coeff,
                            _i2f(best_r.self_feature)])])
                lm2 = lm.at[:, wr_a].set(col_l).at[:, wr_b].set(col_r)

                iot_l1 = jax.lax.iota(jnp.int32, L + 1)
                upd.update({
                    "s": s + valid.astype(jnp.int32),
                    "done": ~valid & ~skip_pending & ~adv_reject,
                    **({} if use_mega else {"hist": hist}),
                    "leafmat": lm2,
                    "feat_used": jnp.where(valid, feat_used_new,
                                           st["feat_used"]),
                    **({"leaf_used": jnp.where(
                        ((iot_l1 == wr_a) | (iot_l1 == wr_b))[:, None],
                        used_child[None, :], st["leaf_used"])}
                       if self.ic_masks is not None else {}),
                })
                if self.has_categorical:
                    new_cat = jnp.where(
                        (iot_l1 == wr_a)[:, None], best_l.cat_set[None, :],
                        jnp.where((iot_l1 == wr_b)[:, None],
                                  best_r.cat_set[None, :],
                                  st["best_cat_set"]))
                    upd["best_cat_set"] = new_cat
                if (self.use_mc and self.mc_mode in ("intermediate", "advanced")
                        and "leaf_fmask" in st):
                    upd["leaf_fmask"] = jnp.where(
                        (iot_l1 == wr_a)[:, None], mask_l[None, :],
                        jnp.where((iot_l1 == wr_b)[:, None],
                                  mask_r[None, :], st["leaf_fmask"]))
                if self.use_mc and self.mc_mode in ("intermediate", "advanced"):
                    # per-leaf bin-range boxes (computed once before the
                    # children search — see _child_boxes)
                    prow_lo, prow_hi, l_hi, r_lo = child_boxes
                    leaf_lo = jnp.where(
                        (iot_l1 == wr_a)[:, None], prow_lo[None, :],
                        jnp.where((iot_l1 == wr_b)[:, None], r_lo[None, :],
                                  st["leaf_lo"]))
                    leaf_hi = jnp.where(
                        (iot_l1 == wr_a)[:, None], l_hi[None, :],
                        jnp.where((iot_l1 == wr_b)[:, None],
                                  prow_hi[None, :], st["leaf_hi"]))
                    upd["leaf_lo"] = leaf_lo
                    upd["leaf_hi"] = leaf_hi
                    st2 = {**st, **upd}
                    lm3, cat3 = self._mc_refresh(
                        st2, lm2, upd["s"] + 1, feature_mask,
                        hist_scale=hist_scale)
                    upd["leafmat"] = jnp.where(valid, lm3, lm2)
                    if cat3 is not None:
                        upd["best_cat_set"] = jnp.where(valid, cat3,
                                                        upd["best_cat_set"])
                return self._pvary(upd)

        if self.F == 0:   # no splittable features: the root is the only leaf
            return self._unpack_state(state)
        final = jax.lax.while_loop(cond, body, state)
        return self._unpack_state(final)

    # ------------------------------------------------------------------
    # Frontier-batched growth (tpu_frontier_k > 1)
    # ------------------------------------------------------------------
    def _build_tree_frontier(self, part_bins, part_ghi0, bag_cnt,
                             feature_mask, hist_scale=None):
        """Grow the top-K frontier leaves per while-loop step.

        Splitting leaf A never changes leaf B's histogram or best split
        (per-leaf statistics depend only on the leaf's own rows), so K
        splits per step are semantics-preserving — EXCEPT that leaf-wise
        order decides WHICH splits fit the ``num_leaves`` budget and how
        nodes/leaves are numbered.  Both are restored exactly by an
        ORACLE-ORDER REPLAY carried in the loop:

        * Every potential leaf is an *item*: item 0 is the root, items
          ``1 + 2j + side`` are the children of our j-th executed split,
          the last item is a write-trash slot.  The replay maintains the
          K=1 oracle's priority queue over items (``avail``) and pops it
          with the oracle's exact election (max gain, smallest oracle
          leaf slot on ties — ops/split.py ``oracle_next_pick``).  A pop
          of a split item commits it with the next oracle split index; a
          pop of an UNSPLIT item stalls the replay: that item is the
          oracle's guaranteed next split and seeds the next step's batch.
        * Each step splits the stalled item plus the top-(K-1) remaining
          positive-gain frontier candidates (speculative: the oracle may
          or may not reach them within budget).  Including the stalled
          item commits >= 1 oracle split per step, and the batch width
          shrinks per the slot-reserve rule ``k <= slots_left - needed
          + 1`` so at most K-1 speculative splits ever outlive the
          budget — total splits are bounded by (L-1) + (K-1).
        * After the loop, ``_renumber_frontier`` prunes the uncommitted
          speculative splits and rebuilds leafmat/nodemat in oracle
          numbering (child pointers from the replay arrays, pruned-leaf
          records from per-split parent snapshots), yielding trees
          bit-identical to the K=1 learner.

        Pruned speculative partitions are UNDONE at tree end: f32
        histogram accumulation is not order-invariant, so a permuted
        row order inside a pruned leaf's range would ULP-perturb the
        NEXT tree's histograms.  The slot-reserve rule bounds live
        uncommitted splits by K-1, so a K-slot liveness ring of
        pre-step rowid-row snapshots suffices: each step stamps its
        snapshot into a ring slot whose previous occupants have all
        committed, and the tree-end undo pass inverse-gathers the (at
        most K-1, mutually disjoint) pruned ranges back into their
        snapshot order — restoring the exact physical layout the K=1
        oracle would hand the next iteration.

        The amortization: ONE top-k election, ONE (NLF, K) leafmat
        gather, ONE K-row parent-hist gather (replacing the K dynamic
        slices whose contextual full-state copies are the round-4
        fixed-cost smoking gun), ONE 2K-wide vmapped children search and
        ONE 2K-column scatter per step, with only the per-leaf
        partition/histogram passes (the payload-bound work) looping over
        the K selected leaves.
        """
        L, G, B, F, K = self.L, self.G, self.B, self.F, self.frontier_k
        MS = (L - 1) + (K - 1)      # split slots: budget + speculative slack
        SL = MS + 2                 # leaf slots incl. one trash slot
        TRASH = SL - 1
        NI = 2 * MS + 2             # items: root + 2 per split + trash
        IT = NI - 1                 # trash item
        use_mega = self._use_mega is not None
        neg_inf = jnp.float32(-jnp.inf)
        pos_inf = jnp.float32(jnp.inf)

        # ---- root (the K=1 path's root prep, serial-mode form) ----
        root_hist = self._hist_leaf(part_bins, part_ghi0,
                                    jnp.int32(self.row0),
                                    jnp.int32(self.N), scale=hist_scale)
        sum_g = root_hist[0, :, 0].sum()
        sum_h = root_hist[0, :, 1].sum()
        if hist_scale is not None:
            # integer-domain quantized totals -> gain domain (once)
            sum_g = sum_g * hist_scale[0]
            sum_h = sum_h * hist_scale[1]
        feat_used0 = jnp.zeros((F,), jnp.bool_)
        best0 = self._leaf_best_split(
            self._scale_hist(root_hist, hist_scale), sum_g, sum_h,
            bag_cnt, bag_cnt, jnp.int32(0),
            neg_inf, pos_inf, jnp.float32(0.0), feature_mask, feat_used0)
        col0 = jnp.stack([
            _i2f(self.row0), _i2f(self.N), _i2f(bag_cnt),
            sum_g, sum_h, _i2f(0),
            neg_inf, pos_inf,
            jnp.float32(0.0), _i2f(-1), _i2f(0),
            best0.gain, _i2f(best0.feature), _i2f(best0.threshold),
            best0.default_left.astype(jnp.float32),
            _i2f(best0.left_count), _i2f(best0.right_count),
            best0.left_sum_g, best0.left_sum_h,
            best0.right_sum_g, best0.right_sum_h,
            best0.left_output, best0.right_output,
            best0.is_cat.astype(jnp.float32), _i2f(-1)])
        leafmat = jnp.zeros((NLF, SL), jnp.float32) \
            .at[LM_BGAIN].set(neg_inf) \
            .at[LM_CMIN].set(neg_inf) \
            .at[LM_CMAX].set(pos_inf) \
            .at[LM_PARENT].set(_i2f(jnp.full((SL,), -1, jnp.int32))) \
            .at[LM_FORCED].set(_i2f(jnp.full((SL,), -1, jnp.int32))) \
            .at[:, 0].set(col0)

        state = {
            "made": jnp.int32(0),       # splits executed (incl. speculative)
            "m": jnp.int32(0),          # oracle splits committed by the replay
            "done": ~(best0.gain > 0),
            "part_bins": part_bins,
            "part_ghi": part_ghi0,
            "leafmat": leafmat,
            "nodemat": jnp.zeros((NND_FR, MS + 1), jnp.float32),
            "feat_used": feat_used0,
            # oracle-replay item arrays
            "it_gain": jnp.full((NI,), neg_inf).at[0].set(best0.gain),
            "it_slot": jnp.zeros((NI,), jnp.int32),
            "it_split": jnp.full((NI,), -1, jnp.int32),
            "it_oslot": jnp.full((NI,), 2 ** 30, jnp.int32).at[0].set(0),
            "avail": jnp.zeros((NI,), jnp.bool_).at[0].set(True),
            "u_item": jnp.int32(0),     # the oracle's guaranteed next split
            "pop_split": jnp.full((L,), -1, jnp.int32),
            "ora_of": jnp.full((MS + 1,), -1, jnp.int32),
            "slot_item": jnp.full((L + 1,), -1, jnp.int32).at[0].set(0),
            # pre-step rowid snapshots for the tree-end undo of pruned
            # speculative partitions (K slots suffice: live uncommitted
            # splits never exceed K-1, each pinning one ring slot)
            "ring": jnp.zeros((K, part_bins.shape[1]), jnp.float32),
            "ring_live": jnp.zeros((K,), jnp.int32),
            "rslot": jnp.zeros((MS + 1,), jnp.int32),
        }
        if not use_mega:
            state["hist"] = jnp.zeros((SL, G, B, 2),
                                      jnp.float32).at[0].set(root_hist)
        if self.has_categorical:
            state["best_cat_set"] = jnp.zeros(
                (SL, self.BF), jnp.bool_).at[0].set(best0.cat_set)
            state["node_cat_set"] = jnp.zeros((MS + 1, self.BF), jnp.bool_)
        if self._use_pallas_part:
            from ..ops.partition_pallas import sc_rows_for
            state["sc_packed"] = jnp.zeros(
                (sc_rows_for(self._pb_rows), part_bins.shape[1]), jnp.int32)
        else:
            state["sc32"] = jnp.zeros((G + self._ghi_rows,
                                       part_bins.shape[1]), jnp.int32)
        buf_keys = ("part_bins", "part_ghi",
                    "sc_packed" if self._use_pallas_part else "sc32")

        def cond(st):
            return (~st["done"]) & (st["made"] < MS)

        def body(st):
            lm = st["leafmat"]
            iotK = jax.lax.iota(jnp.int32, K)
            # ---- select the step's batch: the oracle's guaranteed-next
            # split plus the top-(K-1) speculative candidates ----
            cand = st["avail"] & (st["it_split"] < 0) & (st["it_gain"] > 0)
            scores = jnp.where(cand, st["it_gain"], neg_inf)
            sel_items, sel_ok = split_ops.frontier_topk(
                scores, st["u_item"], K)
            ncand = jnp.sum(sel_ok.astype(jnp.int32))
            # shrink K to the remaining budget on the final steps AND to
            # the slot-reserve rule (enough split slots must remain to
            # finish one committed split per step)
            needed = jnp.int32(L - 1) - st["m"]
            s_left = jnp.int32(MS) - st["made"]
            k_step = jnp.minimum(jnp.minimum(jnp.int32(K), needed),
                                 s_left - needed + 1)
            k_step = jnp.clip(jnp.minimum(k_step, ncand), 1, K)
            active = iotK < k_step
            sel_items = jnp.where(active, sel_items, IT)
            sel_slots = jnp.where(active,
                                  jnp.take(st["it_slot"], sel_items),
                                  TRASH)
            j_idx = jnp.where(active, st["made"] + iotK, jnp.int32(MS))
            wrb_slots = jnp.where(active, st["made"] + 1 + iotK,
                                  jnp.int32(TRASH))
            # stamp the pre-step rowid order into a free ring slot (one
            # always exists: live slots <= uncommitted splits <= K-1).
            # The row read pins the pre-mutation payload, which costs
            # two coherence copies of part_ghi per step (~2% of the
            # 262k-row iteration; barrier-sequencing did not remove
            # them — measured, PERF.md round 12)
            free_r = jnp.argmax(st["ring_live"] == 0).astype(jnp.int32)
            if getattr(self, "_frontier_no_undo", False):
                ring2 = st["ring"]        # measurement-only ablation
            else:
                ring2 = st["ring"].at[free_r].set(st["part_ghi"][2])
            ring_live2 = st["ring_live"].at[free_r].set(k_step)
            rslot2 = st["rslot"].at[j_idx].set(free_r)

            # ---- ONE gather of the K chosen leaves' packed scalars ----
            pcols = jnp.take(lm, sel_slots, axis=1)           # (NLF, K)
            f_enums = _f2i(pcols[LM_BFEAT])
            thrs = _f2i(pcols[LM_BTHR])
            dls = pcols[LM_BDL] > 0.5
            is_cats = pcols[LM_BISCAT] > 0.5
            starts = _f2i(pcols[LM_START])
            cnts = jnp.where(active, _f2i(pcols[LM_CNT]), 0)
            lcg = _f2i(pcols[LM_BLCNT])
            rcg = _f2i(pcols[LM_BRCNT])
            small_is_left = lcg <= rcg
            # one batched gather over the packed per-feature metadata
            # (replaces K per-split lane-dynamic slices)
            fmeta_k = jnp.take(self._fmeta, f_enums, axis=1)  # (8, K)
            if self.has_categorical:
                cat_sets = jnp.take(st["best_cat_set"], sel_slots, axis=0)
            else:
                cat_sets = jnp.zeros((K, 1), jnp.bool_)
            if not use_mega:
                # subtraction trick: ONE gather over the K parents
                # replaces K dynamic-slices of the histogram state (the
                # round-4 contextual double-copy pathology, PERF.md)
                parent_hists = jnp.take(st["hist"], sel_slots, axis=0)

            # ---- per-leaf payload passes: the k-loop runs ONLY the
            # partitions (selected leaves occupy disjoint row ranges, so
            # the passes commute and later lanes read ranges earlier
            # lanes never touched) ----
            depth_c = _f2i(pcols[LM_DEPTH]) + 1
            bufs0 = {kk: st[kk] for kk in buf_keys}
            use_ppair = use_mega and self._use_pallas_search
            if use_mega:
                acc0 = tuple(jnp.zeros((K, G, B), jnp.float32)
                             for _ in range(4))
            else:
                acc0 = (jnp.zeros((K, G, B, 2), jnp.float32),)
            carry0 = (bufs0, acc0, jnp.zeros((K,), jnp.int32),
                      jnp.zeros((13, 2 * K), jnp.float32))

            def kbody(k, carry):
                bufs, acc, lcnt, seg = carry
                fm = jax.lax.dynamic_slice(fmeta_k, (0, k), (8, 1))[:, 0]
                dsc = (fm[2], fm[3], fm[4], fm[5], fm[6],
                       thrs[k], dls[k], is_cats[k], cat_sets[k])
                start = starts[k]
                cnt = cnts[k]
                if use_mega:
                    moved, left_cnt, mh = self._split_leaf_mega(
                        bufs, start, cnt, fm[1], dsc, hist_scale)
                    acc = tuple(a.at[k].set(p[:, :B])
                                for a, p in zip(acc, mh))
                    if use_ppair:
                        # the Pallas pair-search kernel, one program per
                        # split exactly like the K=1 body (its last-ulp
                        # gemm rounding differs from the XLA search, so
                        # mixing implementations would break the
                        # bit-identity contract on kernel backends)
                        from ..ops.split_pallas import (
                            best_split_pair_pallas)
                        BFs = self.BF
                        hg = jnp.concatenate([mh[0][:, :BFs],
                                              mh[2][:, :BFs]], axis=0)
                        hh = jnp.concatenate([mh[1][:, :BFs],
                                              mh[3][:, :BFs]], axis=0)
                        onesF = jnp.ones((F, 1), jnp.float32)
                        dep_f = (depth_c[k]).astype(jnp.float32)

                        def iblock(csg, csh, ccnt_g):
                            return jnp.concatenate([
                                onesF * csg, onesF * csh,
                                onesF * ccnt_g.astype(jnp.float32),
                                onesF * dep_f,
                                feature_mask.astype(
                                    jnp.float32)[:, None],
                                jnp.zeros((F, 3), jnp.float32)], axis=1)

                        info = jnp.concatenate(
                            [iblock(pcols[LM_BLSG, k], pcols[LM_BLSH, k],
                                    lcg[k]),
                             iblock(pcols[LM_BRSG, k], pcols[LM_BRSH, k],
                                    rcg[k])], axis=0)
                        tile = best_split_pair_pallas(
                            hg, hh, self._fmeta_pair, info,
                            l1=self.l1, l2=self.l2,
                            max_delta_step=self.max_delta_step,
                            min_gain_to_split=self.min_gain_to_split,
                            min_data_in_leaf=self.min_data_in_leaf,
                            min_sum_hessian=self.min_sum_hessian,
                            max_depth=self.max_depth,
                            interpret=self._interp)
                        seg = jax.lax.dynamic_update_slice(
                            seg, jnp.transpose(tile[:1, :13]), (0, k))
                        seg = jax.lax.dynamic_update_slice(
                            seg, jnp.transpose(tile[1:2, :13]), (0, K + k))
                else:
                    moved, left_cnt = self._partition_leaf(
                        bufs, start, cnt, fm[1], dsc)
                    # the smaller-child histogram stays a PER-LEAF pass
                    # on the leaf's own chunk grid: a lane-batched vmap
                    # was measured and REJECTED (run-until-all-done
                    # semantics cost K x max-lane chunks — 1.9x e2e on
                    # skewed leaf sizes; PERF.md round 12)
                    sm_start = jnp.where(small_is_left[k], start,
                                         start + left_cnt)
                    sm_cnt = jnp.where(small_is_left[k], left_cnt,
                                       cnt - left_cnt)
                    acc = (acc[0].at[k].set(self._hist_leaf(
                        moved["part_bins"], moved["part_ghi"],
                        sm_start, sm_cnt, scale=hist_scale)),)
                return ({**bufs, **moved}, acc, lcnt.at[k].set(left_cnt),
                        seg)

            bufs, acc, left_cnts, seg_pp = jax.lax.fori_loop(
                0, K, kbody, carry0)
            right_cnts = cnts - left_cnts
            l_starts = starts
            r_starts = starts + left_cnts

            # ---- children histograms -> state / search inputs ----
            ch_slots = jnp.concatenate([sel_slots, wrb_slots])
            upd_hist = {}
            if use_mega:
                hist_left = jnp.stack([acc[0], acc[1]], axis=3)
                hist_right = jnp.stack([acc[2], acc[3]], axis=3)
            else:
                small = acc[0]
                large = parent_hists - small
                sel_b = small_is_left[:, None, None, None]
                hist_left = jnp.where(sel_b, small, large)
                hist_right = jnp.where(sel_b, large, small)
                # ONE 2K-row scatter replaces 2K per-split state updates
                upd_hist["hist"] = st["hist"].at[ch_slots].set(
                    jnp.concatenate([hist_left, hist_right], axis=0))

            def seg13(bs):
                return jnp.stack([
                    bs.gain, _i2f(bs.feature), _i2f(bs.threshold),
                    bs.default_left.astype(jnp.float32),
                    _i2f(bs.left_count), _i2f(bs.right_count),
                    bs.left_sum_g, bs.left_sum_h,
                    bs.right_sum_g, bs.right_sum_h,
                    bs.left_output, bs.right_output,
                    bs.is_cat.astype(jnp.float32)])

            two = jnp.concatenate
            sum_g2 = two([pcols[LM_BLSG], pcols[LM_BRSG]])
            sum_h2 = two([pcols[LM_BLSH], pcols[LM_BRSH]])
            cnt_g2 = two([lcg, rcg])
            depth2 = two([depth_c, depth_c])
            out2 = two([pcols[LM_BLOUT], pcols[LM_BROUT]])

            # ---- ONE 2K-wide batched best-split search over all the
            # step's children (vs 2 per split before: the vmapped search
            # is elementwise/scan-structured per lane, so batch width
            # cannot change per-lane rounding — re-verified empirically
            # by the bit-identity matrix in tests/test_frontier.py) ----
            if use_ppair:
                # the Pallas pair searches already ran per split inside
                # the k-loop and emitted the packed segments directly
                seg13_2k = seg_pp
                ccat_2k = jnp.zeros((2 * K, 1), jnp.bool_)
            else:
                hist2k = two([hist_left, hist_right], axis=0)
                if not use_mega:   # mega planes arrive already scaled
                    hist2k = self._scale_hist(hist2k, hist_scale)
                both = self._best_split_vmapped(
                    hist2k, sum_g2, sum_h2, cnt_g2,
                    two([left_cnts, right_cnts]), depth2,
                    jnp.full((2 * K,), neg_inf),
                    jnp.full((2 * K,), pos_inf),
                    out2, jnp.broadcast_to(feature_mask, (2 * K, F)),
                    st["feat_used"])
                seg13_2k = seg13(both)                    # (13, 2K)
                ccat_2k = both.cat_set

            head = jnp.stack([
                _i2f(two([l_starts, r_starts])),
                _i2f(two([left_cnts, right_cnts])),
                _i2f(cnt_g2),
                sum_g2, sum_h2,
                _i2f(depth2),
                jnp.full((2 * K,), neg_inf), jnp.full((2 * K,), pos_inf),
                out2,
                _i2f(two([j_idx, j_idx])),
                _i2f(two([jnp.zeros((K,), jnp.int32),
                          jnp.ones((K,), jnp.int32)]))])  # (11, 2K)
            cols = jnp.concatenate(
                [head, seg13_2k,
                 jnp.broadcast_to(_i2f(jnp.int32(-1)), (1, 2 * K))],
                axis=0)
            lm2 = lm.at[:, ch_slots].set(cols)

            # ---- nodemat: ONE K-column scatter (child pointers and the
            # parent fixups are derived at renumber time) ----
            ncols = jnp.stack([
                _i2f(fmeta_k[0]), _i2f(f_enums), _i2f(thrs),
                dls.astype(jnp.float32), pcols[LM_BGAIN],
                _i2f(-(sel_slots + 1)), _i2f(-(wrb_slots + 1)),
                pcols[LM_VALUE], pcols[LM_SUM_H], pcols[LM_CNT_G],
                _i2f(fmeta_k[1]), _i2f(fmeta_k[2]), _i2f(fmeta_k[3]),
                _i2f(fmeta_k[4]), _i2f(fmeta_k[5]), _i2f(fmeta_k[6]),
                is_cats.astype(jnp.float32),
                pcols[LM_START], pcols[LM_CNT], pcols[LM_SUM_G],
                pcols[LM_DEPTH]])                         # (NND_FR, K)
            nm2 = st["nodemat"].at[:, j_idx].set(ncols)

            # ---- replay item bookkeeping ----
            ch_items = two([jnp.where(active, 1 + 2 * j_idx, IT),
                            jnp.where(active, 2 + 2 * j_idx, IT)])
            it_gain2 = st["it_gain"].at[ch_items].set(seg13_2k[0]) \
                .at[IT].set(neg_inf)
            it_slot2 = st["it_slot"].at[ch_items].set(ch_slots)
            it_split2 = st["it_split"].at[sel_items].set(
                jnp.where(active, j_idx, -1)).at[IT].set(-1)

            upd_cat = {}
            if self.has_categorical:
                upd_cat["best_cat_set"] = st["best_cat_set"].at[
                    ch_slots].set(ccat_2k)
                upd_cat["node_cat_set"] = st["node_cat_set"].at[
                    j_idx].set(cat_sets)

            # ---- advance the oracle replay: pop committed splits until
            # it stalls on a leaf not yet split (next step's required
            # candidate), exhausts the num_leaves budget, or runs out of
            # positive gains (tree done).  Amortized: total pops over the
            # whole tree <= splits executed. ----
            sim0 = {
                "avail": st["avail"], "it_oslot": st["it_oslot"],
                "slot_item": st["slot_item"],
                "pop_split": st["pop_split"], "ora_of": st["ora_of"],
                "ring_live": ring_live2,
                "m": st["m"], "u_item": st["u_item"], "done": st["done"],
                "stop": jnp.bool_(False),
            }

            def sim_cond(c):
                return ~c["stop"]

            def sim_body(c):
                it, gmax = split_ops.oracle_next_pick(
                    it_gain2, c["it_oslot"], c["avail"])
                budget_done = c["m"] >= jnp.int32(L - 1)
                dead = ~(gmax > 0)       # covers the empty-queue case
                j2 = it_split2[it]
                can_pop = (~budget_done) & (~dead) & (j2 >= 0)
                stall = (~budget_done) & (~dead) & (j2 < 0)
                i = c["m"]
                j2c = jnp.maximum(j2, 0)
                cl = 1 + 2 * j2c
                cr = cl + 1
                itx = jnp.where(can_pop, it, IT)
                clx = jnp.where(can_pop, cl, IT)
                crx = jnp.where(can_pop, cr, IT)
                po = c["it_oslot"][it]
                avail2 = (c["avail"].at[itx].set(False)
                          .at[clx].set(True).at[crx].set(True)
                          .at[IT].set(False))
                oslot2 = (c["it_oslot"].at[clx].set(po)
                          .at[crx].set(i + 1)
                          .at[IT].set(jnp.int32(2 ** 30)))
                slot_item2 = (c["slot_item"]
                              .at[jnp.where(can_pop, po,
                                            jnp.int32(L))].set(cl)
                              .at[jnp.where(can_pop, i + 1,
                                            jnp.int32(L))].set(cr))
                pop_split2 = c["pop_split"].at[
                    jnp.where(can_pop, i, jnp.int32(L - 1))].set(j2c)
                ora2 = c["ora_of"].at[
                    jnp.where(can_pop, j2c, jnp.int32(MS))].set(i)
                # a committed split releases its undo-snapshot pin
                rl2 = c["ring_live"].at[
                    jnp.where(can_pop, rslot2[j2c], jnp.int32(K))].add(
                    -1, mode="drop")
                return {
                    "avail": avail2, "it_oslot": oslot2,
                    "slot_item": slot_item2, "pop_split": pop_split2,
                    "ora_of": ora2, "ring_live": rl2,
                    "m": c["m"] + can_pop.astype(jnp.int32),
                    "u_item": jnp.where(stall, it, c["u_item"]),
                    "done": c["done"] | budget_done | dead,
                    "stop": ~can_pop,
                }

            sim = jax.lax.while_loop(sim_cond, sim_body, sim0)

            return {
                "made": st["made"] + k_step,
                "m": sim["m"], "done": sim["done"],
                "leafmat": lm2, "nodemat": nm2,
                "feat_used": st["feat_used"],
                "it_gain": it_gain2, "it_slot": it_slot2,
                "it_split": it_split2,
                "it_oslot": sim["it_oslot"], "avail": sim["avail"],
                "u_item": sim["u_item"],
                "pop_split": sim["pop_split"], "ora_of": sim["ora_of"],
                "slot_item": sim["slot_item"],
                "ring": ring2, "ring_live": sim["ring_live"],
                "rslot": rslot2,
                **{kk: bufs[kk] for kk in buf_keys},
                **upd_hist, **upd_cat,
            }

        final = jax.lax.while_loop(cond, body, state)

        # ---- undo the pruned speculative partitions: restore each
        # pruned range to its snapshot (= oracle) row order so the next
        # iteration's f32 accumulation order is bit-identical to K=1.
        # Runs ONCE per tree, and only when something was actually
        # pruned: in the common all-committed case the cond skips the
        # O(N) position scatter and the two full-payload gathers.
        Np = part_bins.shape[1]
        jar = jnp.arange(MS, dtype=jnp.int32)
        is_pruned = (jar < final["made"]) & (final["ora_of"][:MS] < 0)

        def _undo(ops):
            pb0, pg0 = ops
            iota_n = jax.lax.iota(jnp.int32, Np)
            pr_j, _ = jax.lax.top_k(jnp.where(is_pruned, jar, -1),
                                    min(K, MS))
            src_bits = pg0[2]
            anymask = jnp.zeros((Np,), jnp.bool_)
            for t in range(min(K, MS)):
                jt = pr_j[t]
                jc = jnp.maximum(jt, 0)
                ncol = jax.lax.dynamic_slice(final["nodemat"], (0, jc),
                                             (NND_FR, 1))[:, 0]
                stt = _f2i(ncol[ND_START])
                cntt = _f2i(ncol[ND_CNTP])
                mask = (jt >= 0) & (iota_n >= stt) & (iota_n < stt + cntt)
                src_bits = jnp.where(
                    mask, final["ring"][final["rslot"][jc]], src_bits)
                anymask = anymask | mask
            cur = jnp.clip(_f2i(pg0[2]), 0, self.N)
            pos_of = jnp.zeros((self.N + 1,),
                               jnp.int32).at[cur].set(iota_n)
            perm = jnp.where(
                anymask,
                jnp.take(pos_of, jnp.clip(_f2i(src_bits), 0, self.N)),
                iota_n)
            return (jnp.take(pb0, perm, axis=1),
                    jnp.take(pg0, perm, axis=1))

        pb1, pg1 = jax.lax.cond(
            jnp.any(is_pruned), _undo, lambda ops: ops,
            (final["part_bins"], final["part_ghi"]))
        final = {**final, "part_bins": pb1, "part_ghi": pg1}
        return self._unpack_state(self._renumber_frontier(final))

    def _renumber_frontier(self, st: Dict[str, Any]) -> Dict[str, Any]:
        """Prune uncommitted speculative splits and renumber the batched
        build into the K=1 oracle's numbering.

        Runs ONCE per tree, outside the while loop, fully vectorized (no
        per-split loop): oracle split i executed our split pop_split[i];
        oracle leaf slot l holds item slot_item[l].  A leaf whose item we
        speculatively split (pruned) is reconstructed from that split's
        parent-snapshot nodemat rows; its speculative best-split columns
        LM_BLCNT..LM_BROUT are zeroed (the oracle stores the candidate
        children stats there, but nothing downstream of _unpack_state
        reads them — only LM_BGAIN, which the snapshot preserves).
        Output shapes match the K=1 path exactly: leafmat (NLF, L+1),
        nodemat (NND, L), s = committed split count."""
        L, K = self.L, self.frontier_k
        MS = (L - 1) + (K - 1)
        NI = 2 * MS + 2
        nodes = self.max_splits
        m = st["m"]
        neg_inf = jnp.float32(-jnp.inf)
        pos_inf = jnp.float32(jnp.inf)
        it_split = st["it_split"]
        it_oslot = st["it_oslot"]
        ora_of = st["ora_of"]

        # ---- leaves ----
        lidx = jax.lax.iota(jnp.int32, L)
        items = st["slot_item"][:L]
        has = (lidx <= m) & (items >= 0)
        itc = jnp.clip(items, 0, NI - 1)
        slots = jnp.take(st["it_slot"], itc)
        from_lm = jnp.take(st["leafmat"], slots, axis=1)      # (NLF, L)
        par_j = jnp.clip((itc - 1) // 2, 0, MS)
        par_pop = jnp.where(itc > 0, jnp.take(ora_of, par_j), -1)
        par_side = jnp.where(itc > 0, (itc - 1) % 2, 0)
        from_lm = from_lm.at[LM_PARENT].set(_i2f(par_pop)) \
                         .at[LM_PSIDE].set(_i2f(par_side))
        jw = jnp.take(it_split, itc)
        jwc = jnp.clip(jw, 0, MS)
        snap = jnp.take(st["nodemat"], jwc, axis=1)           # (NND_FR, L)
        zer = jnp.zeros((L,), jnp.float32)
        recon = jnp.stack([
            snap[ND_START], snap[ND_CNTP], snap[ND_ICOUNT],
            snap[ND_SUM_G], snap[ND_IWEIGHT], snap[ND_DEPTH],
            jnp.full((L,), neg_inf), jnp.full((L,), pos_inf),
            snap[ND_IVALUE], _i2f(par_pop), _i2f(par_side),
            snap[ND_GAIN], snap[ND_FEATURE_ENUM], snap[ND_THRESHOLD],
            snap[ND_DL], zer, zer, zer, zer, zer, zer, zer, zer,
            snap[ND_IS_CAT],
            _i2f(jnp.full((L,), -1, jnp.int32))])             # (NLF, L)
        init_col = jnp.zeros((NLF, 1), jnp.float32) \
            .at[LM_BGAIN].set(neg_inf).at[LM_CMIN].set(neg_inf) \
            .at[LM_CMAX].set(pos_inf) \
            .at[LM_PARENT].set(_i2f(jnp.int32(-1))) \
            .at[LM_FORCED].set(_i2f(jnp.int32(-1)))
        init_cols = jnp.broadcast_to(init_col, (NLF, L))
        pruned = has & (jw >= 0)
        lm_f = jnp.where(pruned[None, :], recon,
                         jnp.where(has[None, :], from_lm, init_cols))
        lm_f = jnp.concatenate([lm_f, init_col], axis=1)      # (NLF, L+1)

        # ---- nodes ----
        nidx = jax.lax.iota(jnp.int32, nodes)
        jvec = st["pop_split"][:nodes]
        nvalid = nidx < m
        jc = jnp.clip(jvec, 0, MS)
        ncols = jnp.take(st["nodemat"], jc, axis=1)           # (NND_FR, nodes)
        cl = 1 + 2 * jc
        cr = cl + 1
        jl = jnp.take(it_split, cl)
        jr = jnp.take(it_split, cr)
        ol = jnp.take(ora_of, jnp.clip(jl, 0, MS))
        orr = jnp.take(ora_of, jnp.clip(jr, 0, MS))
        left_ptr = jnp.where((jl >= 0) & (ol >= 0), ol,
                             -(jnp.take(it_oslot, cl) + 1))
        right_ptr = jnp.where((jr >= 0) & (orr >= 0), orr,
                              -(jnp.take(it_oslot, cr) + 1))
        ncols = ncols.at[ND_LEFT].set(_i2f(left_ptr)) \
                     .at[ND_RIGHT].set(_i2f(right_ptr))
        nm_f = jnp.where(nvalid[None, :], ncols[:NND],
                         jnp.zeros((NND, nodes), jnp.float32))
        nm_f = jnp.concatenate(
            [nm_f, jnp.zeros((NND, 1), jnp.float32)], axis=1)  # (NND, L)

        drop = ("leafmat", "nodemat", "hist", "it_gain", "it_slot",
                "it_split", "it_oslot", "avail", "u_item", "pop_split",
                "ora_of", "slot_item", "made", "m", "best_cat_set",
                "node_cat_set", "ring", "ring_live", "rslot")
        out = {k: v for k, v in st.items() if k not in drop}
        if getattr(self, "_frontier_debug", False):
            # test-only introspection of the replay (tests/test_frontier)
            out["frontier_debug"] = {k: st[k] for k in drop if k in st}
        out["s"] = m
        out["leafmat"] = lm_f
        out["nodemat"] = nm_f
        if self.has_categorical:
            leaf_cs = jnp.take(st["best_cat_set"], slots, axis=0)
            prn_cs = jnp.take(st["node_cat_set"], jwc, axis=0)
            bcs = jnp.where(pruned[:, None], prn_cs,
                            jnp.where(has[:, None], leaf_cs, False))
            out["best_cat_set"] = jnp.concatenate(
                [bcs, jnp.zeros((1, self.BF), jnp.bool_)], axis=0)
            ncs = jnp.where(nvalid[:, None],
                            jnp.take(st["node_cat_set"], jc, axis=0),
                            False)
            out["node_cat_set"] = jnp.concatenate(
                [ncs, jnp.zeros((1, self.BF), jnp.bool_)], axis=0)
        return out

    def _unpack_state(self, st: Dict[str, Any]) -> Dict[str, Any]:
        """Expand the packed leaf/node matrices back into the per-field
        record the rest of the framework consumes (runs ONCE per tree,
        outside the while loop)."""
        L = self.L
        nodes = self.max_splits
        lm = st["leafmat"][:, :L]         # drop the trash slots
        nm = st["nodemat"][:, :nodes]
        # the histogram state is while-loop carry only: nothing
        # downstream consumes it, and exporting it materialized an
        # (L+1, G, B, 2) buffer per tree on the eager path (the PR-10
        # frontier path already dropped it — now both paths agree)
        rec = {k: v for k, v in st.items()
               if k not in ("leafmat", "nodemat", "hist")}
        if "best_cat_set" in st:
            rec["best_cat_set"] = st["best_cat_set"][:L]
            rec["node_cat_set"] = st["node_cat_set"][:nodes]
        rec["indices"] = _f2i(st["part_ghi"][2])
        rec["part_grad"] = st["part_ghi"][0]
        rec["part_hess"] = st["part_ghi"][1]

        def li(r):
            return _f2i(lm[r])

        def ni(r):
            return _f2i(nm[r])

        rec.update({
            "leaf_start": li(LM_START), "leaf_cnt": li(LM_CNT),
            "leaf_cnt_g": li(LM_CNT_G), "leaf_sum_g": lm[LM_SUM_G],
            "leaf_sum_h": lm[LM_SUM_H], "leaf_depth": li(LM_DEPTH),
            "leaf_value": lm[LM_VALUE], "best_gain": lm[LM_BGAIN],
            "node_feature": ni(ND_FEATURE),
            "node_feature_enum": ni(ND_FEATURE_ENUM),
            "node_threshold": ni(ND_THRESHOLD),
            "node_default_left": nm[ND_DL] > 0.5,
            "node_gain": nm[ND_GAIN],
            "node_left": ni(ND_LEFT), "node_right": ni(ND_RIGHT),
            "node_internal_value": nm[ND_IVALUE],
            "node_internal_weight": nm[ND_IWEIGHT],
            "node_internal_count": ni(ND_ICOUNT),
            "node_col": ni(ND_COL), "node_bin_start": ni(ND_BIN_START),
            "node_is_bundled": ni(ND_IS_BUNDLED),
            "node_num_bin": ni(ND_NUM_BIN),
            "node_default_bin": ni(ND_DEFAULT_BIN),
            "node_missing_type": ni(ND_MISSING),
            "node_is_cat": nm[ND_IS_CAT] > 0.5,
        })
        if self._linear_gain:
            # per-leaf linear model: const + coeff over the raw value
            # of leaf_lin_feat (ORIGINAL feature id, from the leaf's
            # own search — boosting._set_leafwise_linear consumes it)
            rec.update({
                "leaf_lin_const": lm[LM_LIN_CONST],
                "leaf_lin_coeff": lm[LM_LIN_COEF],
                "leaf_lin_feat": li(LM_LIN_FEAT),
            })
        return rec

    # ------------------------------------------------------------------
    def _build_impl(self, part_bins0, grad, hess, bag_cnt, feature_mask,
                    seed=jnp.int32(0), feat_used_init=None, aux0=None,
                    hist_scale=None):
        """Front/tail-pad the per-row arrays and run the tree loop.

        ``grad``/``hess`` are (N,) in ORIGINAL row order with out-of-bag rows
        already zeroed by the caller (bagging/GOSS never gather rows — TPU
        row gathers are latency-bound); ``bag_cnt`` is the in-bag row count
        used for count estimation.  ``aux0`` is the model-lifetime cegb-lazy
        used-feature bitset, (aux_rows, N) in ORIGINAL row order.
        """
        C = self.row0
        tail = self.N_pad - C - self.N
        grad_p = jnp.pad(grad, (C, tail))
        hess_p = jnp.pad(hess, (C, tail))
        iota = jax.lax.iota(jnp.int32, self.N_pad)
        rowid = jnp.where((iota >= C) & (iota < C + self.N), iota - C, self.N)
        # row writes, NOT jnp.stack+concat: the stack-of-padded-rows
        # fusion MISCOMPILES on the tunnel's XLA at N_pad ~> 32k, zeroing
        # the bitcast rowid row (verified minimal repro, round 3)
        part_ghi0 = jnp.zeros((self._ghi_rows, self.N_pad), jnp.float32) \
            .at[0].set(grad_p).at[1].set(hess_p) \
            .at[2].set(jax.lax.bitcast_convert_type(rowid, jnp.float32))
        if aux0 is not None:
            aux0 = jnp.pad(aux0, ((0, 0), (C, tail)))
        return self._build_tree_impl(part_bins0, part_ghi0,
                                     bag_cnt, feature_mask, seed,
                                     feat_used_init, aux0, hist_scale)

    def lazy_aux_to_original_order(self, rec) -> jnp.ndarray:
        """Scatter the partitioned used-feature bitset back to original row
        order (for carrying across boosting iterations)."""
        idx = rec["indices"]
        return jnp.zeros((self.aux_rows, self.N), jnp.int32).at[:, idx].set(
            rec["part_aux"], mode="drop")

    def build_tree(self, grad, hess, bag_cnt=None,
                   feature_mask=None, seed: int = 0,
                   feat_used=None, lazy_aux=None,
                   hist_scale=None) -> Dict[str, Any]:
        """Train one tree; returns the device state record."""
        if feature_mask is None:
            feature_mask = jnp.ones((self.F,), dtype=bool)
        if feat_used is None:
            feat_used = jnp.zeros((self.F,), dtype=bool)
        grad = jnp.asarray(grad, dtype=jnp.float32)
        hess = jnp.asarray(hess, dtype=jnp.float32)
        if bag_cnt is None:
            bag_cnt = self.N
        if self.cegb_lazy is not None and lazy_aux is None:
            lazy_aux = jnp.zeros((self.aux_rows, self.N), jnp.int32)
        return self._build(self._part0, grad, hess, jnp.int32(bag_cnt),
                           feature_mask, jnp.int32(seed), feat_used,
                           lazy_aux, hist_scale)

    def node_arrays_for_predict(self, st: Dict[str, Any]) -> Dict[str, Any]:
        node = {
            "col": st["node_col"],
            "bin_start": st["node_bin_start"],
            "is_bundled": st["node_is_bundled"],
            "num_bin": st["node_num_bin"],
            "default_bin": st["node_default_bin"],
            "missing_type": st["node_missing_type"],
            "threshold": st["node_threshold"],
            "default_left": st["node_default_left"],
            "left": st["node_left"],
            "right": st["node_right"],
            "num_nodes": st["s"],
        }
        if self.has_categorical:   # keys gate the cat arm in predict_leaf_binned
            node["is_cat"] = st["node_is_cat"]
            node["cat_set"] = st["node_cat_set"]
        return node
