"""Boosting engines: GBDT, DART, RF, with bagging and GOSS sampling.

TPU-native re-design of the reference boosting layer (src/boosting/gbdt.cpp,
dart.hpp, rf.hpp, bagging.hpp, goss.hpp): the per-iteration loop
(gbdt.cpp TrainOneIter:338-441) orchestrates device-resident state — scores,
gradients, the binned dataset, and the tree learner's partition arrays all
stay in HBM; the host only sequences iterations and pulls finished trees.
"""

from __future__ import annotations

import functools
import math
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..dataset import BinnedDataset
from ..obs import memory as obs_memory
from ..obs import telemetry as obs
from ..ops.predict import predict_leaf_binned, predict_leaf_binned_t
from ..robustness import faultinject
from ..robustness.guard import NonFiniteGuard
from ..utils import log
from ..utils.log import LightGBMError
from .learner import SerialTreeLearner
from .metric import Metric, create_metrics
from .objective import ObjectiveFunction
from .serving import ServingEngine
from .tree import Tree, tree_from_device_record

K_EPSILON = 1e-15
# linear-leaf refit: relative ridge added to the normal-equation
# diagonal so near-singular systems degrade toward the constant leaf
# instead of emitting large coefficients (_fit_linear_leaves)
_LINEAR_RIDGE_EPS = 1e-10


import os as _os

DEBUG_CHECKS = _os.environ.get("LIGHTGBM_TPU_DEBUG", "") == "1"


def debug_validate_record(host_record, num_nodes: int, num_data: int,
                          row0: int) -> None:
    """LIGHTGBM_TPU_DEBUG=1 invariant checks on a materialized tree
    record — the analog of the reference's DEBUG CheckSplit /
    CheckAllDataInLeaf validation (serial_tree_learner.h:174-176):

      * child pointers reference valid nodes/leaves and every leaf is
        reached exactly once;
      * the physical leaf ranges partition [row0, row0 + num_data);
      * leaf values and gains are finite.
    Raises AssertionError with a diagnostic on violation."""
    L = num_nodes + 1
    if num_nodes == 0:
        return
    left = np.asarray(host_record["node_left"])[:num_nodes]
    right = np.asarray(host_record["node_right"])[:num_nodes]
    seen_leaves = []
    for arr in (left, right):
        for v in arr:
            if v < 0:
                seen_leaves.append(~v)
            else:
                assert 0 <= v < num_nodes, f"child node {v} out of range"
    assert sorted(seen_leaves) == list(range(L)), \
        f"leaves reached {sorted(seen_leaves)} != 0..{L - 1}"
    lv = np.asarray(host_record["leaf_value"])[:L]
    assert np.isfinite(lv).all(), "non-finite leaf value"
    starts = np.asarray(host_record["leaf_start"])[:L]
    cnts = np.asarray(host_record["leaf_cnt"])[:L]
    order = np.argsort(starts)
    s, c = starts[order], cnts[order]
    assert int(c.sum()) == num_data, \
        f"leaf counts sum {int(c.sum())} != {num_data}"
    assert s[0] == row0, f"first leaf starts at {s[0]} != {row0}"
    assert (s[1:] == s[:-1] + c[:-1]).all(), \
        "leaf ranges are not disjoint-contiguous"


@functools.partial(jax.jit, static_argnames=("l1", "l2", "mds"))
def _quant_renew_device(idx, grad, hess, starts, cnts, old_values,
                        l1, l2, mds):
    """Per-leaf true-gradient sums via prefix-sum differencing over the
    partitioned row order (pad rows sit outside every leaf range, so
    their clipped-gather values never enter a difference)."""
    from ..ops.split import leaf_output
    nmax = grad.shape[0] - 1
    gp = jnp.take(grad, jnp.minimum(idx, nmax))
    hp = jnp.take(hess, jnp.minimum(idx, nmax))
    cg = jnp.concatenate([jnp.zeros((1,), jnp.float32), jnp.cumsum(gp)])
    ch = jnp.concatenate([jnp.zeros((1,), jnp.float32), jnp.cumsum(hp)])
    sum_g = jnp.take(cg, starts + cnts) - jnp.take(cg, starts)
    sum_h = jnp.take(ch, starts + cnts) - jnp.take(ch, starts)
    new = leaf_output(sum_g, sum_h + 2e-15, l1, l2, mds)
    return jnp.where(cnts > 0, new, old_values)


@functools.partial(jax.jit, static_argnums=(1,))
def _scores_from_phys(ghi, num_data):
    """Scatter the physically-ordered score row back to original row
    order (rowid rides as bitcast row 2; pad rows carry the sentinel
    ``num_data`` and drop)."""
    rowid = jax.lax.bitcast_convert_type(ghi[2], jnp.int32)
    return jnp.zeros((num_data,), jnp.float32).at[rowid].set(
        ghi[3], mode="drop")


def _scores_from_phys_multiproc(ghi, local_num_data, sb):
    """Rank-sharded fused state -> this process's LOCAL scores, on the
    host: rowids are GLOBAL mesh ids (device d owns [d*local_n, ...)),
    so under multi-process each rank folds only its addressable shards
    back to its local row order.  (A single SPMD scatter cannot produce
    a per-rank local array from global ids.)"""
    out = np.zeros((local_num_data,), np.float32)
    if sb.mode == "feature":
        # rows replicated: any shard carries every row with ids 0..N
        blk = np.asarray(ghi.addressable_shards[0].data)
        rowid = blk[2].view(np.int32)
        valid = (rowid >= 0) & (rowid < local_num_data)
        out[rowid[valid]] = blk[3][valid]
        return jnp.asarray(out)
    proc_off = jax.process_index() * sb.local_ndev * sb.local_n
    for shard in ghi.addressable_shards:
        blk = np.asarray(shard.data)
        lid = blk[2].view(np.int32) - proc_off
        valid = (lid >= 0) & (lid < local_num_data)
        out[lid[valid]] = blk[3][valid]
    return jnp.asarray(out)


@functools.partial(jax.jit, static_argnums=(1, 2))
def _scores_from_phys_mc(ghi, num_data, num_class):
    """Multiclass variant: rows 3..3+K-1 are the per-class score rows."""
    rowid = jax.lax.bitcast_convert_type(ghi[2], jnp.int32)
    return jnp.zeros((num_data, num_class), jnp.float32).at[rowid].set(
        ghi[3:3 + num_class].T, mode="drop")


def _renew_leaves_percentile(rec, resid, pweight, sel, alpha: float,
                             Npad: int):
    """Per-leaf (weighted) percentile of residuals over the PARTITIONED
    row order — the device analog of the L1-family RenewTreeOutput
    (regression_objective.hpp:18-80 PercentileFun/WeightedPercentileFun
    applied through SerialTreeLearner::RenewTreeOutput).

    Leaves are contiguous physical row ranges, so one global sort keyed
    by ``(leaf_id << 23) | global_residual_rank`` groups every leaf's
    IN-BAG rows contiguously in residual order (out-of-bag and pad rows
    carry rank +inf and fall to each group's tail); the percentile then
    reads one or two gathered elements per leaf.  Requires
    N_pad <= 2^23 and <= 256 leaf slots so the key fits a non-negative
    int32 (the caller gates on both).

    resid/sel/pweight are (Npad,) physical-order arrays; sel False marks
    out-of-bag and pad rows.  Returns the renewed leaf-value vector
    (old values where a leaf has no in-bag rows)."""
    leaf_start = rec["leaf_start"]
    leaf_cnt = rec["leaf_cnt"]
    old = rec["leaf_value"]
    Lslots = old.shape[0]
    iota = jax.lax.iota(jnp.int32, Npad)

    # leaf id per physical position: count starts <= p, then map the
    # ordinal through the starts sorted by position.  Pad rows attach to
    # a neighboring leaf's group but always sort beyond its in-bag count.
    starts_valid = jnp.where(leaf_cnt > 0, leaf_start, Npad + 1)
    order_starts = jnp.argsort(starts_valid).astype(jnp.int32)
    marks = jnp.zeros((Npad,), jnp.int32).at[starts_valid].add(
        1, mode="drop")
    o = jnp.cumsum(marks)
    leaf_at = jnp.take(order_starts, jnp.clip(o - 1, 0, Lslots - 1))

    sort_val = jnp.where(sel, resid, jnp.inf)
    ord1 = jnp.argsort(sort_val).astype(jnp.int32)
    rank = jnp.zeros((Npad,), jnp.int32).at[ord1].set(iota)
    key = (leaf_at << 23) | rank
    ord2 = jnp.argsort(key).astype(jnp.int32)
    r_s = jnp.take(resid, ord2)

    # group offsets: keys ascend with leaf id, so groups are laid out in
    # id order and offsets are an exclusive prefix over group sizes
    sizes = jnp.zeros((Lslots,), jnp.int32).at[leaf_at].add(1)
    off = jnp.cumsum(sizes) - sizes

    selc = jnp.concatenate([jnp.zeros((1,), jnp.float32),
                            jnp.cumsum(sel.astype(jnp.float32))])
    nb = (jnp.take(selc, leaf_start + leaf_cnt)
          - jnp.take(selc, leaf_start)).astype(jnp.int32)

    if pweight is None:
        fp = (nb - 1).astype(jnp.float32) * alpha
        lo = jnp.floor(fp).astype(jnp.int32)
        bias = fp - lo.astype(jnp.float32)
        i1 = off + jnp.clip(lo, 0, jnp.maximum(nb - 1, 0))
        i2 = off + jnp.clip(lo + 1, 0, jnp.maximum(nb - 1, 0))
        v1 = jnp.take(r_s, i1)
        v2 = jnp.take(r_s, i2)
        v = v1 + (v2 - v1) * bias
        v = jnp.where(nb == 1, jnp.take(r_s, off), v)
    else:
        # reference WeightedPercentileFun (regression_objective.hpp:50-88):
        # pos = upper_bound(weighted cdf, alpha * total), interpolate
        # only when the next point's weight >= 1 and pos is interior.
        # Matches _weighted_percentile_host exactly (stable sort order).
        wsel = pweight * sel.astype(jnp.float32)
        w_s = jnp.take(wsel, ord2)
        wc = jnp.concatenate([jnp.zeros((1,), jnp.float32),
                              jnp.cumsum(wsel)])
        sw = jnp.take(wc, leaf_start + leaf_cnt) - jnp.take(wc, leaf_start)
        wc_s = jnp.cumsum(w_s)
        base = jnp.where(off > 0, jnp.take(wc_s, jnp.maximum(off - 1, 0)),
                         0.0)
        leaf_s = jnp.take(leaf_at, ord2)
        local_j = iota - jnp.take(off, leaf_s)
        cum = wc_s - jnp.take(base, leaf_s)        # inclusive per-leaf cdf
        thr_s = alpha * jnp.take(sw, leaf_s)
        cond = (cum > thr_s) & (local_j < jnp.take(nb, leaf_s))
        big = jnp.int32(Npad + 1)
        first = jnp.full((Lslots,), big, jnp.int32).at[leaf_s].min(
            jnp.where(cond, iota, big))
        last = off + jnp.maximum(nb - 1, 0)
        pos = jnp.where(first < big, first, last)
        pos = jnp.clip(pos, off, last)
        lpos = pos - off
        v2 = jnp.take(r_s, pos)
        v1 = jnp.take(r_s, jnp.maximum(pos - 1, off))
        w_next = jnp.take(w_s, jnp.minimum(pos + 1, last))
        cdf_pos = jnp.take(wc_s, pos) - base
        cdf_next = cdf_pos + w_next
        thr = alpha * sw
        interp = (thr - cdf_pos) / jnp.maximum(cdf_next - cdf_pos,
                                               jnp.float32(1e-30)) * (v2 - v1) + v1
        use_i = (lpos > 0) & (lpos < nb - 1) & (w_next >= 1.0)
        v = jnp.where(use_i, interp, v2)
    return jnp.where(nb > 0, v, old)


def _phys_leaf_delta(rec, Npad: int):
    """Per-row score delta from the physical leaf ranges: leaves are
    disjoint contiguous row windows, so scatter +/- leaf values at the
    range boundaries and prefix-sum — the +v/-v pairs of each closed
    range cancel exactly before the next range opens.  The flat prefix
    sum runs as a 2-D lane cumsum + small row-carry pass (a 1-D cumsum
    over N_pad lowers lane-serial on TPU, ~1.1 ms/Mrow measured)."""
    d = jnp.zeros((Npad,), jnp.float32)
    d = d.at[rec["leaf_start"]].add(rec["leaf_value"], mode="drop")
    d = d.at[rec["leaf_start"] + rec["leaf_cnt"]].add(
        -rec["leaf_value"], mode="drop")
    d2 = d.reshape(Npad // 256, 256)
    within = jnp.cumsum(d2, axis=1)
    carry = jnp.cumsum(within[:, -1]) - within[:, -1]   # (rows,)
    return (within + carry[:, None]).reshape(Npad)


def _learner_memory_arrays(lr):
    """Telemetry memory provider: the learner's resident device
    buffers (master binned partition buffer + helper tables)."""
    return [v for v in vars(lr).values()
            if getattr(v, "nbytes", None) is not None]


def _gbdt_memory_arrays(g):
    """Telemetry memory provider: training-side score/physical state
    plus the per-tree device arrays.  The binned residency is fully
    visible here: the live ``_phys`` carrier or the retired
    ``_phys_carrier`` (bins + rowid row) IS the training copy of the
    binned matrix once the fused path adopts the master buffer."""
    out = [g._scores_arr]
    phys = getattr(g, "_phys", None)
    if phys is not None:
        out.extend(phys)
    carrier = getattr(g, "_phys_carrier", None)
    if carrier is not None:
        out.extend(carrier)
    for dt in g.device_trees:
        if dt is not None:
            out.append(dt["nodes"])
            out.append(dt["leaf_value"])
    return out


def _unpermute_bins(part_bins, rowid_bits, N, C, Npad):
    """Invert the partition permutation of a physical bins carrier back
    to the pristine identity layout: column ``C + i`` of the output
    holds original row ``i``'s bins, all pad columns are zero — exactly
    the ingest buffer the carrier adopted.  Exact (integer gather), so
    re-initializing from the result is bit-identical to initializing
    from the never-donated master buffer."""
    iota = jax.lax.iota(jnp.int32, Npad)
    rowid = jnp.where((iota >= C) & (iota < C + N), iota - C, N)
    old = jax.lax.bitcast_convert_type(rowid_bits, jnp.int32)
    # pos[i] = physical column currently holding original row i
    pos = jnp.zeros((N,), jnp.int32).at[old].set(iota, mode="drop")
    src = jnp.take(pos, jnp.minimum(rowid, N - 1))
    bins = jnp.take(part_bins, src, axis=1)
    return jnp.where((rowid < N)[None, :], bins, 0).astype(part_bins.dtype)


class GBDT:
    """Gradient Boosting Decision Tree engine (reference: src/boosting/gbdt.cpp)."""

    def __init__(self, config: Config, train_data: Optional[BinnedDataset],
                 objective: Optional[ObjectiveFunction]):
        self.config = config
        self.train_data = train_data
        self.objective = objective
        # non-finite guard rails (robustness/guard.py); active policy
        # keeps training on the eager path (fused gating below)
        self._nf_guard = NonFiniteGuard.from_config(config)
        self.models: List[Tree] = []
        self.device_trees: List[Dict[str, Any]] = []  # node arrays + leaf values
        self._continued = False        # set by continue_from
        # bumped on every structural model change (append/pop/scale) so
        # derived caches (the serving engine's packed forests) can never
        # serve a stale model of the same length
        self._model_version = 0
        # device-resident serving engine: packed forests, bucketed
        # batches, compiled-predictor cache (models/serving.py)
        self.serving = ServingEngine(self)
        self.iter = 0
        self.shrinkage_rate = float(config.learning_rate)
        self.num_tree_per_iteration = (objective.num_model_per_iteration
                                       if objective else max(config.num_class, 1))
        self.num_class = max(config.num_class, 1)
        self.average_output = False
        self.init_scores = [0.0] * self.num_tree_per_iteration
        self.max_feature_idx = 0
        self.feature_names: List[str] = []
        self.label_idx = 0
        self.valid_sets: List[Tuple[BinnedDataset, List[Metric], jnp.ndarray]] = []
        self.valid_scores: List[jnp.ndarray] = []
        self.train_metrics: List[Metric] = []
        self.best_iter: Dict[str, int] = {}
        self.es_first_metric_only = bool(config.first_metric_only)
        # physical-order fused state: (part_bins, part_ghi) kept permuted
        # across consecutive fused iterations (see _setup_fused_phys)
        self._phys = None
        # retired carrier: (part_bins, rowid_bits) kept after the scores
        # materialize — under single-copy residency this pair IS the
        # binned training data (the master buffer was donated into it),
        # so it must survive every score read/write until a pristine
        # copy is rebuilt (_ensure_part0) or training resumes
        self._phys_carrier = None
        self._fused_phys = None
        self._init_phys_fn = None
        self._init_phys_adopt = None
        self._init_phys_perm = None
        self._scores_arr = None
        # model & data health (obs/health.py): the training flight
        # recorder (None when health=off) and the reference data profile
        # persisted with the model; all host-side bookkeeping
        self.flight = None
        self.health_profile = None

        if train_data is not None:
            self._setup_training(train_data)

    # ------------------------------------------------------------------
    # Train scores.  In the physical fused mode the authoritative scores
    # live PERMUTED as a row of the partition payload; reading `.scores`
    # materializes them back to original row order (one scatter) and
    # drops the physical state, and any external write invalidates it —
    # the next fused iteration rebuilds the physical layout from scratch.
    @property
    def scores(self):
        if getattr(self, "_phys", None) is not None:
            pb, ghi = self._phys
            self._phys = None
            # the bins + rowid row stay resident as the retired carrier:
            # they are the ONLY binned copy (single-copy residency) and
            # the next fused init / traversal / recovery reads them
            self._phys_carrier = (pb, ghi[2])
            K = self.num_tree_per_iteration
            sb = self.sharded_builder
            if K > 1:
                self._scores_arr = _scores_from_phys_mc(
                    ghi, self.num_data, K)
            elif sb is not None and sb.nproc > 1:
                self._scores_arr = _scores_from_phys_multiproc(
                    ghi, self.num_data, sb)
            else:
                self._scores_arr = _scores_from_phys(ghi, self.num_data)
        return self._scores_arr

    @scores.setter
    def scores(self, v):
        if getattr(self, "_phys", None) is not None:
            # an external write drops the physical scores but must NOT
            # drop the bins: they may be the only binned copy left
            pb, ghi = self._phys
            self._phys = None
            self._phys_carrier = (pb, ghi[2])
        self._scores_arr = v

    # ------------------------------------------------------------------
    # Train-set leaf traversal over the live binned resident.  There is
    # no standing row-major train matrix anymore (single-copy binned
    # residency): leaf lookups read whichever resident is live — the
    # fused physical carrier (bins permuted, scattered back to original
    # order through the rowid row), the learner's pristine master
    # buffer, or as a last resort a TRANSIENT device copy of the host
    # matrix — and always return (N,) leaf ids in original row order.
    def _traverse_train(self, nodes):
        src = self._phys if self._phys is not None \
            else self._phys_carrier
        sb = self.sharded_builder
        if src is not None and (sb is None or sb.nproc == 1):
            pb, second = src
            rowid_bits = second[2] if second.ndim == 2 else second
            return self._traverse_phys_fn(nodes, pb, rowid_bits)
        p0 = getattr(self.learner, "_part0", None)
        if p0 is not None and not p0.is_deleted():
            return self._traverse_part0_fn(nodes, p0)
        binned = self.train_data.binned
        if binned is None:
            binned = self.train_data.host_binned()
        return self._traverse_rows_fn(nodes, jnp.asarray(binned))

    def _recover_pristine_part0(self):
        """Rebuild the pristine (pb_rows, N_pad) master buffer from the
        live physical carrier (one exact unpermute gather).  Serves the
        ingest's recovery callback (pickle / save_binary / a second
        booster on the same dataset) and the eager-path crossing."""
        src = self._phys if self._phys is not None \
            else self._phys_carrier
        if src is None:
            raise LightGBMError(
                "binned master buffer was donated to the fused trainer "
                "and no physical carrier is live to recover it from")
        pb, second = src
        rowid_bits = second[2] if second.ndim == 2 else second
        return self._unpermute_fn(pb, rowid_bits)

    def _adopt_master_buffer(self) -> None:
        """Called right after the identity init forwards the learner's
        master buffer into the physical carrier: the fused step donates
        that buffer in place every iteration, so every OTHER reference
        must let go now (a later read would observe donated memory).
        The ingest keeps a recovery callback instead of the buffer."""
        lr = self.learner
        p0 = lr._part0
        lr._part0 = None
        ing = getattr(lr, "_ingest", None)
        if ing is None:
            return
        if (getattr(ing, "buffer", None) is p0
                or getattr(lr, "_part0_from_ingest", False)):
            # the flag also covers the sublane-padded case (_pb_rows >
            # G): part0 is then pad(buffer) — the recovered carrier's
            # first G rows ARE the master buffer, so the ingest's own
            # copy is redundant either way
            ing.release_buffer(self._recover_pristine_part0)

    def _ensure_part0(self) -> None:
        """The eager tree build reads the learner's pristine master
        buffer; if the fused carrier adopted it, rebuild it (and hand
        the ingest its buffer back) so eager and fused iterations can
        interleave.  Residency returns to ONE pristine copy and the
        next fused init restarts from the identity layout — the exact
        state a never-fused run would be in."""
        lr = self.learner
        if getattr(lr, "_part0", None) is not None:
            return
        if self._phys is None and self._phys_carrier is None:
            return
        _ = self.scores          # materialize pending fused scores first
        pb = self._recover_pristine_part0()
        self._phys_carrier = None
        lr._part0 = pb
        ing = getattr(lr, "_ingest", None)
        if (ing is not None and getattr(ing, "buffer", None) is None
                and pb.shape[1] == ing.n_pad and pb.shape[0] >= ing.G):
            # extra sublane-pad rows beyond G are zeros; every ingest
            # consumer slices [:G]
            ing.buffer = pb
            ing._recover = None

    # ------------------------------------------------------------------
    def _setup_training(self, train_data: BinnedDataset) -> None:
        cfg = self.config
        self.learner = SerialTreeLearner(train_data, cfg)
        # one line of truth about which device kernels actually engaged
        # (init-time probes fall back silently; the A/B harness and the
        # bench read these flags to validate an arm really ran what its
        # params asked for — PERF.md round 5 "kernels confirmed active")
        _lr = self.learner
        log.debug(
            "tree kernels: partition=%s search=%s hist_state=%s mega=%s "
            "compact=%s",
            "pallas" if _lr._use_pallas_part else "xla",
            "pallas" if _lr._use_pallas_search else "xla",
            "flat" if _lr._use_flat_hist else "xla",
            _lr._use_mega or "off",
            "radix4" if _lr._compact_radix else "binary")
        self.sharded_builder = None
        if cfg.tree_learner != "serial":
            import jax as _jax
            ndev = len(_jax.devices())
            if ndev > 1:
                from ..parallel.trainer import ShardedTreeBuilder
                self.sharded_builder = ShardedTreeBuilder(train_data, cfg)
                log.info("Using %s-parallel tree learner over %d devices",
                         cfg.tree_learner, ndev)
            else:
                log.warning("tree_learner=%s requested but only one device is "
                            "visible; training serially", cfg.tree_learner)
        self.num_data = train_data.num_data
        self.max_feature_idx = train_data.num_total_features - 1
        self.feature_names = list(train_data.feature_names)
        from ..obs import health as obs_health
        obs_health.configure_from_config(cfg)
        if obs_health.enabled():
            self.flight = obs_health.FlightRecorder.from_config(cfg)
            self.health_profile = train_data.reference_profile()
        if self.objective is not None:
            self.objective.init(train_data.metadata)
        self.train_metrics = create_metrics(
            cfg, self.objective.name if self.objective else None)
        for m in self.train_metrics:
            m.init(train_data.metadata)

        K = self.num_tree_per_iteration
        shape = (self.num_data,) if K == 1 else (self.num_data, K)
        self.scores = jnp.zeros(shape, dtype=jnp.float32)
        if train_data.metadata.init_score is not None:
            init = np.asarray(train_data.metadata.init_score, dtype=np.float32)
            if K > 1:
                init = init.reshape(K, self.num_data).T
            self.scores = jnp.asarray(init.reshape(shape))
            self.has_init_score = True
        else:
            self.has_init_score = False

        # boost from average (reference: gbdt.cpp:313-336)
        if (self.objective is not None and not self.has_init_score
                and cfg.boost_from_average):
            from ..parallel import network
            for k in range(K):
                s = self.objective.boost_from_score(k)
                # ObtainAutomaticInitialScore (gbdt.cpp:303-311): the
                # per-rank init scores agree by mean across processes
                # (objectives with internal sum-syncs are already equal,
                # the mean is then the identity)
                if network.num_machines() > 1:
                    s = network.global_sync_by_mean(s)
                if abs(s) > K_EPSILON:
                    self.init_scores[k] = s
                    if K == 1:
                        self.scores = self.scores + s
                    else:
                        self.scores = self.scores.at[:, k].add(s)
                    log.info("Start training from score %f", s)

        # quantized-gradient training state
        # (reference: gradient_discretizer.{hpp,cpp})
        self.use_quant = bool(cfg.use_quantized_grad)
        if self.use_quant:
            self.quant_rng = jax.random.PRNGKey(
                cfg.seed if cfg.seed is not None else 12345)

        # model-lifetime CEGB used-feature set (reference:
        # CostEfficientGradientBoosting::is_feature_used_in_split_)
        self._cegb_feat_used = None
        # model-lifetime cegb-lazy per-(row, feature) used bitset
        self._cegb_lazy_aux = None
        # lagged fused-iteration records awaiting host materialization
        self._pending_recs: List[Dict[str, Any]] = []
        # consecutive empty trees (stop detection across class trees)
        self._empty_run = 0

        # sampling state
        self.bag_rng = jax.random.PRNGKey(cfg.bagging_seed)
        self.feat_rng = jax.random.PRNGKey(cfg.feature_fraction_seed)
        self.goss = cfg.data_sample_strategy == "goss"
        # HBM attribution for telemetry (obs/memory.py): the learner's
        # master binned buffer and the training-side score state are
        # the two big per-booster residents besides the serving packs
        obs_memory.register("train.binned", self.learner,
                            _learner_memory_arrays)
        obs_memory.register("train.state", self, _gbdt_memory_arrays)
        # balanced (per-class) bagging engages whenever either class
        # fraction is below 1 (reference: bagging.hpp:88)
        self.balanced_bagging = (
            cfg.bagging_freq > 0
            and (cfg.pos_bagging_fraction < 1.0
                 or cfg.neg_bagging_fraction < 1.0)
            and train_data.metadata.label is not None)
        self.need_bagging = (not self.goss and cfg.bagging_freq > 0
                             and (cfg.bagging_fraction < 1.0
                                  or self.balanced_bagging))
        if cfg.bagging_by_query:
            log.warning("bagging_by_query is accepted for config "
                        "compatibility but is not implemented by the "
                        "reference this framework tracks; it is IGNORED")
        self._cached_bag = None
        # ---- train-set traversal programs (single-copy residency) ----
        # each reads a different live binned resident; the dispatcher
        # (_traverse_train) picks per call.  The phys variant traverses
        # the PERMUTED carrier and scatters leaf ids back to original
        # row order through the bitcast rowid row (sentinel ids >= N
        # drop out of the scatter).
        _G = self.learner.G
        _C = self.learner.row0
        _N = self.num_data

        def _tr_phys(nodes, pb, rowid_bits):
            rowid = jax.lax.bitcast_convert_type(rowid_bits, jnp.int32)
            leaf = predict_leaf_binned_t(pb[:_G], nodes)
            return jnp.zeros((_N,), jnp.int32).at[rowid].set(
                leaf, mode="drop")

        self._traverse_phys_fn = jax.jit(_tr_phys)
        self._traverse_part0_fn = jax.jit(
            lambda nodes, p0: predict_leaf_binned_t(
                p0[:_G, _C:_C + _N], nodes))
        self._traverse_rows_fn = jax.jit(
            lambda nodes, binned: predict_leaf_binned(binned, nodes))
        self._unpermute_fn = jax.jit(functools.partial(
            _unpermute_bins, N=_N, C=_C, Npad=self.learner.N_pad))

        # ---- fused training step ----
        # One jitted program per boosting iteration: gradients -> tree build
        # -> score update, with only two host round-trips (dispatch + small
        # record fetch).  Vital on TPU where per-dispatch latency dominates
        # the eager path (the TPU analog of the reference keeping the whole
        # iteration inside C++, gbdt.cpp:338-441).
        self._fused = None
        # GOSS and plain bagging fold into the fused physical program
        # (their masks are pure jnp); balanced/query bagging do not yet
        fused_on = bool(getattr(cfg, "tpu_fused_iteration", True))
        common_ok = (
            fused_on and self._nf_guard is None
            and self.sharded_builder is None and self.objective is not None
            and getattr(self.objective, "is_jit_safe", True)
            and not cfg.linear_tree
            and not cfg.cegb_penalty_feature_lazy)
        if common_ok and K == 1:
            self._setup_fused_step()
        elif (common_ok and K > 1 and not self.use_quant and not self.goss
              and not (self.need_bagging and self.balanced_bagging)
              and not self.objective.is_renew_tree_output
              and self._mc_fused_kind() is not None):
            # multiclass: all K class trees build inside ONE program per
            # iteration (gbdt.cpp:379's per-class Train loop, device-side)
            self._setup_fused_multiclass()
        elif (fused_on and self._nf_guard is None
              and self.sharded_builder is not None
              and self.objective is not None
              and getattr(self.objective, "is_jit_safe", True)
              and K == 1 and not cfg.linear_tree
              and not cfg.cegb_penalty_feature_lazy
              and not self.use_quant and not self.goss
              and not (self.need_bagging and self.balanced_bagging)
              and not self.objective.is_renew_tree_output):
            # distributed learners: the fused physical program runs
            # shard_map'd over the mesh — same per-shard state the
            # serial path keeps, with the collectives the sharded build
            # already contains
            self._setup_fused_sharded()
        if self._fused is None and train_data is not None:
            reasons = []
            if self._nf_guard is not None:
                reasons.append(f"nonfinite_policy={self._nf_guard.policy} "
                               "(the per-iteration guard verdict needs "
                               "the eager path)")
            if self.sharded_builder is not None:
                why = getattr(self, "_fused_sharded_reason",
                              "sampling/renewal combo not yet fused")
                reasons.append(f"tree_learner={cfg.tree_learner} ({why})")
            if K != 1:
                reasons.append(f"num_class={self.num_class} (payload rows "
                               "or sampling combo unsupported)")
            if cfg.linear_tree:
                reasons.append("linear_tree")
            if self.need_bagging and self.balanced_bagging:
                reasons.append("balanced bagging (needs a label-sign "
                               "payload row)")
            if cfg.cegb_penalty_feature_lazy:
                reasons.append("cegb_penalty_feature_lazy")
            if self.objective is not None \
                    and self.objective.is_renew_tree_output:
                reasons.append(f"objective={self.objective.name} "
                               "(renewal needs the physical path: GOSS/"
                               "quantized combo or size limits exceeded)")
            if self.objective is not None \
                    and not getattr(self.objective, "is_jit_safe", True):
                reasons.append(f"objective={self.objective.name} "
                               "(not jit-safe)")
            log.info("fused single-program iteration DISABLED (%s): each "
                     "iteration pays per-dispatch host latency",
                     ", ".join(reasons) or
                     "objective lacks gradients_from_payload")

    def _setup_fused_step(self) -> None:
        lr_ = self.learner
        obj = self.objective
        shrink = self.shrinkage_rate
        N = self.num_data
        L = lr_.L
        Npad = lr_.N_pad

        # physical-order fast path: the objective's row-aligned gradient
        # inputs and the scores RIDE the partition payload, so the score
        # update is a boundary prefix sum + row add — no O(N) scatter
        # back to original order (5.5 ms/Mrow, the single largest
        # per-iteration row cost).  Requires the concrete objective class
        # to define gradients_from_payload (inheriting it would silently
        # pair a subclass's overridden gradients with the base formula).
        if obj.is_renew_tree_output and (
                self.use_quant or self.goss
                or Npad > (1 << 23) or lr_.L > 255):
            # leaf renewal fuses only through the physical path's packed
            # percentile sort ((leaf << 23) | rank int32 key), and the
            # GOSS in-bag set is not recoverable post-partition
            return
        if self.need_bagging and self.balanced_bagging:
            # balanced bagging reads the label sign per row inside the
            # program; only payloads carrying a sign row support it
            fields = obj.payload_fields or ()
            if not any(n in ("label", "signed_label_weight")
                       for n in fields if getattr(obj, n, None) is not None):
                return
        if (type(obj).__dict__.get("gradients_from_payload") is not None
                and obj.gradient_payload() is not None):
            names = [n for n in obj.payload_fields
                     if getattr(obj, n) is not None]
            if 4 + len(names) <= lr_._ghi_rows:
                self._setup_fused_phys(names)
                return
        if self.use_quant or self.goss or self.need_bagging \
                or obj.is_renew_tree_output:
            # these fold only into the physical path (discretizer,
            # renewal and sampling masks live inside that program)
            return

        def step(part_bins, scores, feature_mask, seed, feat_used):
            # trace-time-only host hook: one call == one XLA compile of
            # this program (obs retrace detector; zero HLO)
            obs.compile_event("train.fused_step")
            grad, hess = obj.get_gradients(scores)
            rec = lr_._build_impl(part_bins, grad, hess, jnp.int32(N),
                                  feature_mask, seed, feat_used)
            # per-row score delta from the physical leaf ranges: leaves are
            # disjoint contiguous row windows, so scatter +/- leaf values at
            # the range boundaries and prefix-sum — the +v/-v pairs of each
            # closed range cancel exactly before the next range opens — then
            # ONE scatter maps physical rows back to original row order
            d = jnp.zeros((Npad + 1,), jnp.float32)
            d = d.at[rec["leaf_start"]].add(rec["leaf_value"], mode="drop")
            d = d.at[rec["leaf_start"] + rec["leaf_cnt"]].add(
                -rec["leaf_value"], mode="drop")
            delta_phys = jnp.cumsum(d)[:-1]
            delta = jnp.zeros((N,), jnp.float32).at[rec["indices"]].set(
                delta_phys, mode="drop")
            new_scores = scores + delta * shrink
            small = {k: v for k, v in rec.items()
                     if k.startswith(("node_", "leaf_")) or k in
                     ("s", "feat_used")}
            small["leaf_delta"] = rec["leaf_value"] * shrink
            return new_scores, small

        self._fused = jax.jit(step, donate_argnums=(1,))

    def _setup_fused_phys(self, names) -> None:
        """Physical-order fused iteration (see _setup_fused_step).

        Payload row layout: 0 grad, 1 hess, 2 rowid-bits, 3 score,
        4.. the objective's ``names`` arrays, zero-padded to 8 rows.
        The TPU analog of the reference keeping gradients, scores and
        the data partition resident across an iteration
        (gbdt.cpp:338-441 + data_partition.hpp) — with the row order
        itself device-owned."""
        lr_ = self.learner
        obj = self.objective
        shrink = self.shrinkage_rate
        N = self.num_data
        Npad = lr_.N_pad
        C = lr_.row0
        # quantized renewal needs the TRUE gradients in POST-partition
        # order: they ride two extra payload rows through the partition
        q_renew_rows = 2 if (self.use_quant
                             and self.config.quant_train_renew_leaf) else 0
        tg_row = 4 + len(names)
        th_row = tg_row + 1
        lr_._ghi_live = 4 + len(names) + q_renew_rows
        payload_arrs = [jnp.asarray(getattr(obj, n), jnp.float32)
                        for n in names]

        def ghi0(scores):
            iota = jax.lax.iota(jnp.int32, Npad)
            rowid = jnp.where((iota >= C) & (iota < C + N), iota - C, N)
            rows = [jnp.zeros((Npad,), jnp.float32),
                    jnp.zeros((Npad,), jnp.float32),
                    jax.lax.bitcast_convert_type(rowid, jnp.float32),
                    jnp.pad(scores, (C, Npad - C - N))]
            rows += [jnp.pad(a, (C, Npad - C - N)) for a in payload_arrs]
            rows += [jnp.zeros((Npad,), jnp.float32)
                     for _ in range(lr_._ghi_rows - len(rows))]
            return jnp.stack(rows)

        def init_phys(part_bins, scores):
            # the bins pass through UNTOUCHED; with the bins argument
            # DONATED, XLA aliases the output onto the input buffer, so
            # the physical carrier ADOPTS the learner's master buffer
            # instead of copying it (single-copy residency) —
            # _adopt_master_buffer retires every other reference right
            # after.  The non-donating jit keeps the pre-adoption
            # semantics for lowering-only probes (jaxlint).
            return part_bins, ghi0(scores)

        def init_phys_perm(part_bins, rowid_bits, scores):
            # resume from a RETIRED carrier (scores were read between
            # iterations): unpermute the bins back to the identity
            # layout, so the rebuilt state — and every tree after it —
            # is bit-identical to an init from the pristine buffer
            bins = _unpermute_bins(part_bins, rowid_bits, N, C, Npad)
            return bins, ghi0(scores)

        self._init_phys = jax.jit(init_phys)
        self._init_phys_adopt = jax.jit(init_phys, donate_argnums=(0,))
        self._init_phys_perm = jax.jit(init_phys_perm, donate_argnums=(0,))

        use_quant = self.use_quant
        cfg = self.config
        q_bins = float(cfg.num_grad_quant_bins)
        q_stoch = bool(cfg.stochastic_rounding)
        q_renew = bool(cfg.quant_train_renew_leaf)
        q_const_h = bool(obj.is_constant_hessian)
        q_key = jax.random.PRNGKey(cfg.seed if cfg.seed is not None
                                   else 12345)
        l1_, l2_, mds_ = (float(cfg.lambda_l1), float(cfg.lambda_l2),
                          float(cfg.max_delta_step))
        use_goss = self.goss
        use_bag = self.need_bagging and not self.balanced_bagging
        use_balanced = self.need_bagging and self.balanced_bagging
        bag_key = jax.random.PRNGKey(cfg.bagging_seed)
        bag_freq = max(int(cfg.bagging_freq), 1)
        bag_frac = float(cfg.bagging_fraction)
        pos_frac = float(cfg.pos_bagging_fraction)
        neg_frac = float(cfg.neg_bagging_fraction)
        sign_idx = None
        if use_balanced:
            sign_idx = names.index("label") if "label" in names \
                else names.index("signed_label_weight")
        g_top_k = max(int(N * cfg.top_rate), 1)
        g_other_k = max(int(N * cfg.other_rate), 1)
        # L1-family renewal state (the gate in _setup_fused_step already
        # excluded GOSS/quantized combos and oversize payloads)
        renew_alpha = (float(obj.renew_leaf_alpha())
                       if obj.is_renew_tree_output else None)
        label_idx = names.index("label") if "label" in names else None
        weight_idx = names.index("weight") if "weight" in names else None
        renew_w_fn = (obj.renew_weights_from_payload
                      if hasattr(type(obj), "renew_weights_from_payload")
                      else None)

        def step(part_bins, ghi, feature_mask, seed, feat_used):
            obs.compile_event("train.fused_step")   # trace-time only
            rowid = jax.lax.bitcast_convert_type(ghi[2], jnp.int32)
            vf = (rowid != N).astype(jnp.float32)   # pad rows: grad/hess 0
            payload = {n: ghi[4 + i] for i, n in enumerate(names)}
            g, h = obj.gradients_from_payload(ghi[3], **payload)
            g = g * vf
            h = h * vf
            bag_cnt = jnp.int32(N)
            if use_goss:
                # in-program GOSS (goss.hpp Helper:116-165): pad rows
                # carry zero importance and never select
                imp = jnp.abs(g * h)
                threshold = jax.lax.top_k(imp, g_top_k)[0][-1]
                is_top = (imp >= threshold) & (vf > 0)
                kg = jax.random.fold_in(bag_key, seed)
                n_top = jnp.sum(is_top.astype(jnp.int32))
                rest = jnp.maximum(N - n_top, 1)
                prob = g_other_k / rest.astype(jnp.float32)
                keep_other = ((~is_top) & (vf > 0) &
                              (jax.random.uniform(kg, g.shape) < prob))
                multiply = (N - g_top_k) / g_other_k
                scale = jnp.where(is_top, 1.0,
                                  jnp.where(keep_other, multiply, 0.0))
                g = g * scale
                h = h * scale
                bag_cnt = jnp.sum((is_top | keep_other).astype(jnp.int32))
            elif use_bag:
                # bag redrawn per bagging_freq period: the key depends on
                # the PERIOD index, so iterations inside one period see
                # the identical mask (bagging.hpp semantics).  Draws are
                # indexed by ORIGINAL row id — the physical permutation
                # changes every iteration, so a draw over physical
                # positions would silently re-bag mid-period
                kb = jax.random.fold_in(bag_key, (seed - 1) // bag_freq)
                u = jax.random.uniform(kb, (N + 1,))
                sel = (jnp.take(u, jnp.minimum(rowid, N)) < bag_frac) \
                    & (vf > 0)
                sf = sel.astype(jnp.float32)
                g = g * sf
                h = h * sf
                bag_cnt = jnp.sum(sel.astype(jnp.int32))
            elif use_balanced:
                # per-class Bernoulli (reference: bagging.hpp
                # BalancedBaggingHelper:180-200); label signs ride the
                # payload, draws are indexed by original row id
                kb = jax.random.fold_in(bag_key, (seed - 1) // bag_freq)
                u = jnp.take(jax.random.uniform(kb, (N + 1,)),
                             jnp.minimum(rowid, N))
                posr = ghi[4 + sign_idx] > 0
                sel = jnp.where(posr, u < pos_frac, u < neg_frac) \
                    & (vf > 0)
                sf = sel.astype(jnp.float32)
                g = g * sf
                h = h * sf
                # the ACTUAL drawn count, not the sizing estimate
                # (bagging.hpp:46 bag_data_cnt_ = left_cnt)
                bag_cnt = jnp.sum(sel.astype(jnp.int32))
            hist_scale = None
            if use_quant:
                # in-program discretizer (reference:
                # GradientDiscretizer::DiscretizeGradients); integer
                # carriers ride the payload, the scale goes to the
                # histogram (bf16 int-exact accumulation)
                gs = jnp.maximum(jnp.max(jnp.abs(g)) / (q_bins / 2.0),
                                 1e-30)
                max_h = jnp.max(jnp.abs(h))
                hs = jnp.maximum(max_h if q_const_h else max_h / q_bins,
                                 1e-30)
                if q_stoch:
                    kg, kh = jax.random.split(
                        jax.random.fold_in(q_key, seed))
                    rg = jax.random.uniform(kg, g.shape)
                    rh = jax.random.uniform(kh, h.shape)
                else:
                    rg = rh = 0.5
                ig = jnp.trunc(g / gs + jnp.where(g >= 0, rg, -rg))
                ih = (jnp.ones_like(h) if q_const_h
                      else jnp.trunc(h / hs + rh))
                g_q = ig * vf
                h_q = ih * vf
                hist_scale = jnp.stack([gs, hs])
            else:
                g_q, h_q = g, h
            ghi = ghi.at[0].set(g_q).at[1].set(h_q)
            if use_quant and q_renew:
                # true grads ride the partition so the renewal reads
                # them in the record's row order
                ghi = ghi.at[tg_row].set(g).at[th_row].set(h)
            rec = lr_._build_tree_impl(part_bins, ghi, bag_cnt,
                                       feature_mask, seed, feat_used,
                                       None, hist_scale)
            if use_quant and q_renew:
                # leaf renewal from the TRUE gradients in POST-partition
                # order: per-leaf sums are prefix differences at the
                # range boundaries (reference: RenewIntGradTreeOutput)
                from ..ops.split import leaf_output as _leaf_out
                cg = jnp.concatenate([jnp.zeros((1,), jnp.float32),
                                      jnp.cumsum(rec["part_ghi"][tg_row])])
                ch = jnp.concatenate([jnp.zeros((1,), jnp.float32),
                                      jnp.cumsum(rec["part_ghi"][th_row])])
                ls = rec["leaf_start"]
                lc = rec["leaf_cnt"]
                sum_g = jnp.take(cg, ls + lc) - jnp.take(cg, ls)
                sum_h = jnp.take(ch, ls + lc) - jnp.take(ch, ls)
                renewed = _leaf_out(sum_g, sum_h + 2e-15, l1_, l2_, mds_)
                rec["leaf_value"] = jnp.where(lc > 0, renewed,
                                              rec["leaf_value"])
            if renew_alpha is not None:
                # L1-family leaf renewal: per-leaf residual percentile in
                # POST-partition order (RegressionL1loss::RenewTreeOutput)
                ghi_p = rec["part_ghi"]
                rowid_p = jax.lax.bitcast_convert_type(ghi_p[2], jnp.int32)
                valid_p = rowid_p != N
                if use_bag:
                    kb = jax.random.fold_in(bag_key,
                                            (seed - 1) // bag_freq)
                    u = jax.random.uniform(kb, (N + 1,))
                    sel_p = (jnp.take(u, jnp.minimum(rowid_p, N))
                             < bag_frac) & valid_p
                elif use_balanced:
                    kb = jax.random.fold_in(bag_key,
                                            (seed - 1) // bag_freq)
                    u = jnp.take(jax.random.uniform(kb, (N + 1,)),
                                 jnp.minimum(rowid_p, N))
                    posr = ghi_p[4 + sign_idx] > 0
                    sel_p = jnp.where(posr, u < pos_frac,
                                      u < neg_frac) & valid_p
                else:
                    sel_p = valid_p
                resid = ghi_p[4 + label_idx] - ghi_p[3]
                if renew_w_fn is not None:
                    pw = renew_w_fn(
                        ghi_p[4 + label_idx],
                        ghi_p[4 + weight_idx] if weight_idx is not None
                        else None)
                elif weight_idx is not None:
                    pw = ghi_p[4 + weight_idx]
                else:
                    pw = None
                rec["leaf_value"] = _renew_leaves_percentile(
                    rec, resid, pw, sel_p, renew_alpha, Npad)
            ghi_out = rec["part_ghi"].at[3].add(
                shrink * _phys_leaf_delta(rec, Npad))
            small = {k: v for k, v in rec.items()
                     if k.startswith(("node_", "leaf_")) or k in
                     ("s", "feat_used")}
            small["leaf_delta"] = rec["leaf_value"] * shrink
            return rec["part_bins"], ghi_out, small

        self._fused_phys = jax.jit(step, donate_argnums=(0, 1))
        self._fused = self._fused_phys    # gate for train_one_iter

    def _mc_fused_kind(self):
        """Which fused-multiclass formula the CONCRETE objective class
        provides: 'snapshot' (softmax family) or 'perclass' (OVA), else
        None.  Checked on the concrete class's own __dict__ — a subclass
        overriding get_gradients must not silently inherit the base
        fused formula (same guard as the K==1 payload gate)."""
        d = type(self.objective).__dict__
        if (d.get("fused_prob_snapshot") is not None
                and d.get("fused_class_gradients_from_prob") is not None):
            return "snapshot"
        if d.get("fused_class_gradients") is not None:
            return "perclass"
        return None

    def _setup_fused_multiclass(self) -> None:
        """Physical-order fused multiclass iteration: all K class trees
        build inside ONE jitted program (the device analog of gbdt.cpp:379's
        per-class Train loop).  Payload rows: 0 grad, 1 hess, 2 rowid-bits,
        3..3+K-1 per-class scores, 3+K label, [3+K+1 weight] — every row
        rides each class tree's partition, so after tree k the whole block
        (including the other classes' scores) is consistently permuted and
        tree k+1 reads softmax inputs in the CURRENT physical order."""
        lr_ = self.learner
        obj = self.objective
        cfg = self.config
        K = self.num_tree_per_iteration
        shrink = self.shrinkage_rate
        N = self.num_data
        Npad = lr_.N_pad
        C = lr_.row0
        has_w = obj.weight is not None
        need = 4 + K + (1 if has_w else 0)
        if need > lr_._ghi_rows:
            return    # Pallas partition caps the payload at 8 f32 rows
        lr_._ghi_live = need
        lbl_row = 3 + K
        w_row = lbl_row + 1
        label_arr = jnp.asarray(obj.label, jnp.float32)
        weight_arr = obj.weight

        def ghi0(scores):
            iota = jax.lax.iota(jnp.int32, Npad)
            rowid = jnp.where((iota >= C) & (iota < C + N), iota - C, N)
            ghi = jnp.zeros((lr_._ghi_rows, Npad), jnp.float32)
            ghi = ghi.at[2].set(
                jax.lax.bitcast_convert_type(rowid, jnp.float32))
            for k in range(K):
                ghi = ghi.at[3 + k].set(
                    jnp.pad(scores[:, k], (C, Npad - C - N)))
            ghi = ghi.at[lbl_row].set(jnp.pad(label_arr, (C, Npad - C - N)))
            if has_w:
                ghi = ghi.at[w_row].set(
                    jnp.pad(weight_arr, (C, Npad - C - N)))
            return ghi

        def init_phys(part_bins, scores):
            # bins pass through untouched; donated in the _adopt
            # variant so the carrier adopts the master buffer (see
            # _setup_fused_phys / single-copy residency);
            # _adopt_master_buffer retires the other refs
            return part_bins, ghi0(scores)

        def init_phys_perm(part_bins, rowid_bits, scores):
            bins = _unpermute_bins(part_bins, rowid_bits, N, C, Npad)
            return bins, ghi0(scores)

        self._init_phys = jax.jit(init_phys)
        self._init_phys_adopt = jax.jit(init_phys, donate_argnums=(0,))
        self._init_phys_perm = jax.jit(init_phys_perm, donate_argnums=(0,))

        use_bag = self.need_bagging and not self.balanced_bagging
        bag_key = jax.random.PRNGKey(cfg.bagging_seed)
        bag_freq = max(int(cfg.bagging_freq), 1)
        bag_frac = float(cfg.bagging_fraction)

        needs_snap = self._mc_fused_kind() == "snapshot"

        def step(part_bins, ghi, feature_mask, seed, feat_used):
            obs.compile_event("train.fused_step")   # trace-time only
            smalls = []
            P = None
            if needs_snap:
                # softmax couples the classes: ALL K gradients come from
                # the PRE-iteration scores (gbdt.cpp Boosting computes
                # them before any class tree).  Snapshot the
                # probabilities by ORIGINAL row id; each class tree
                # gathers them back through its own permutation.
                rowid0 = jax.lax.bitcast_convert_type(ghi[2], jnp.int32)
                p0 = obj.fused_prob_snapshot(ghi[3:3 + K])
                P = jnp.zeros((K, N + 1), jnp.float32).at[
                    :, jnp.minimum(rowid0, N)].set(p0)
            for k in range(K):
                rowid = jax.lax.bitcast_convert_type(ghi[2], jnp.int32)
                vf = (rowid != N).astype(jnp.float32)
                if needs_snap:
                    p_k = jnp.take(P[k], jnp.minimum(rowid, N))
                    g, h = obj.fused_class_gradients_from_prob(
                        k, p_k, ghi[lbl_row],
                        ghi[w_row] if has_w else None)
                else:
                    g, h = obj.fused_class_gradients(
                        k, ghi[3:3 + K], ghi[lbl_row],
                        ghi[w_row] if has_w else None)
                bag_cnt = jnp.int32(N)
                if use_bag:
                    # one bag per ITERATION shared by all K class trees
                    # (bagging.hpp), drawn by original row id (see the
                    # binary fused step)
                    kb = jax.random.fold_in(bag_key,
                                            (seed - 1) // bag_freq)
                    u = jax.random.uniform(kb, (N + 1,))
                    sel = (jnp.take(u, jnp.minimum(rowid, N)) < bag_frac) \
                        & (vf > 0)
                    sf = sel.astype(jnp.float32)
                    g = g * sf
                    h = h * sf
                    bag_cnt = jnp.sum(sel.astype(jnp.int32))
                else:
                    g = g * vf
                    h = h * vf
                ghi = ghi.at[0].set(g).at[1].set(h)
                rec = lr_._build_tree_impl(part_bins, ghi, bag_cnt,
                                           feature_mask, seed * K + k,
                                           feat_used)
                part_bins = rec["part_bins"]
                ghi = rec["part_ghi"]
                ghi = ghi.at[3 + k].add(
                    shrink * _phys_leaf_delta(rec, Npad))
                feat_used = rec["feat_used"]
                small = {kk: v for kk, v in rec.items()
                         if kk.startswith(("node_", "leaf_")) or kk in
                         ("s", "feat_used")}
                small["leaf_delta"] = rec["leaf_value"] * shrink
                smalls.append(small)
            return part_bins, ghi, smalls

        self._fused_phys = jax.jit(step, donate_argnums=(0, 1))
        self._fused = self._fused_phys

    def _setup_fused_sharded(self) -> None:
        """Fused physical iteration over the device mesh: the per-shard
        analog of _setup_fused_phys, shard_map'd so one dispatch per
        iteration covers gradients -> sharded tree build (with its psum
        collectives) -> score update.  The eager sharded path pays
        several host round-trips per iteration (~100 ms floor on
        remote-attached chips) that this removes.

        Rows stay in each shard's PHYSICAL order; rowids carry GLOBAL
        original indices (shard d owns [d*local_n, d*local_n+count_d)),
        so bagging draws and the original-order score materialization
        are shard-layout independent."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        sb = self.sharded_builder
        lr_ = sb.learner
        obj = self.objective
        cfg = self.config
        if (type(obj).__dict__.get("gradients_from_payload") is None
                or obj.gradient_payload() is None):
            self._fused_sharded_reason = \
                "objective lacks gradients_from_payload"
            return
        names = [n for n in obj.payload_fields
                 if getattr(obj, n) is not None]
        if 4 + len(names) > lr_._ghi_rows:
            self._fused_sharded_reason = "payload exceeds the ghi rows"
            return
        lr_._ghi_live = 4 + len(names)
        shrink = self.shrinkage_rate
        # rowid space is GLOBAL across the whole mesh so bagging draws
        # agree on every process.  Mesh ids are GAPPED when ranks hold
        # unequal row counts (device d owns [d*local_n, d*local_n+cnt_d)
        # with local_n the max over ranks), so the pad sentinel must sit
        # ABOVE the whole id space — ndev*local_n — not at sb.N: a
        # sentinel of sb.N would collide with a real row's id and
        # silently drop it from training
        N = sb.N
        SENT = sb.ndev * sb.local_n
        Npad = lr_.N_pad
        C = lr_.row0
        ndev = sb.ndev
        local_n = sb.local_n
        mesh = sb.mesh
        AXIS = "data"
        repl_rows = sb.mode == "feature"
        payload_arrs = [np.asarray(getattr(obj, n), np.float32)
                        for n in names]

        def shard_rows(arr):
            # this process's rows, laid out as one local_n block per
            # LOCAL device (mirroring the builder's binned blocking);
            # sb._put assembles the global mesh array across processes
            arr = np.asarray(arr, np.float32)
            if repl_rows:
                return sb._put(arr, NamedSharding(mesh, P()))
            total = sb.local_ndev * local_n if sb.nproc > 1 \
                else ndev * local_n
            if len(arr) < total:
                arr = np.concatenate(
                    [arr, np.zeros(total - len(arr), np.float32)])
            return sb._put(arr, NamedSharding(mesh, P(AXIS)))

        row_spec = P() if repl_rows else P(AXIS)
        state_spec = P() if repl_rows else P(None, AXIS)

        def init_shard(binned, scores, counts, *payloads):
            # binned (rows+1, G); scores/payloads (rows,); counts (1,)
            pb = jnp.pad(
                binned.T,
                ((0, lr_._pb_rows - binned.shape[1]),
                 (C, Npad - C - binned.shape[0])))
            iota = jax.lax.iota(jnp.int32, Npad)
            li = iota - C
            valid = (li >= 0) & (li < counts[0])
            base = (jnp.int32(0) if repl_rows
                    else jax.lax.axis_index(AXIS) * local_n)
            rowid = jnp.where(valid, base + li, SENT)
            nrows = scores.shape[0]

            def rowpad(a):
                return jnp.pad(a, (C, Npad - C - nrows))
            rows = [jnp.zeros((Npad,), jnp.float32),
                    jnp.zeros((Npad,), jnp.float32),
                    jax.lax.bitcast_convert_type(rowid, jnp.float32),
                    rowpad(scores)]
            rows += [rowpad(p) for p in payloads]
            rows += [jnp.zeros((Npad,), jnp.float32)
                     for _ in range(lr_._ghi_rows - len(rows))]
            return pb, jnp.stack(rows)

        n_pay = len(payload_arrs)
        cnt_spec = P() if repl_rows else P(AXIS)
        # feature mode: every device computes the IDENTICAL state (split
        # decisions are synced by the build's all-gather), but the vma
        # checker can't see through the varying intermediates — disable
        # the static check for the replicated layout only
        from ..utils.compat import shard_map as _compat_shard_map
        smap = functools.partial(_compat_shard_map, mesh=mesh,
                                 check_vma=not repl_rows)
        init_sharded = jax.jit(smap(
            init_shard,
            in_specs=(row_spec, row_spec, cnt_spec) + (row_spec,) * n_pay,
            out_specs=(state_spec, state_spec)))

        def init_fn():
            scores_sh = shard_rows(np.asarray(self._scores_arr))
            pays = [shard_rows(p) for p in payload_arrs]
            counts = (sb._put(np.asarray([N], np.int32),
                              NamedSharding(mesh, P()))
                      if repl_rows else sb.local_counts)
            return init_sharded(sb.binned_sharded, scores_sh,
                                counts, *pays)

        self._init_phys_fn = init_fn

        use_bag = self.need_bagging and not self.balanced_bagging
        bag_key = jax.random.PRNGKey(cfg.bagging_seed)
        bag_freq = max(int(cfg.bagging_freq), 1)
        bag_frac = float(cfg.bagging_fraction)
        mode = sb.mode
        F = lr_.F

        def step_shard(pb, ghi, feature_mask, seed, feat_used):
            obs.compile_event("train.fused_step")   # trace-time only
            rowid = jax.lax.bitcast_convert_type(ghi[2], jnp.int32)
            vf = (rowid != SENT).astype(jnp.float32)
            payload = {n: ghi[4 + i] for i, n in enumerate(names)}
            g, h = obj.gradients_from_payload(ghi[3], **payload)
            g = g * vf
            h = h * vf
            if use_bag:
                # draws by GLOBAL row id: every shard layout sees the
                # same bag for a given period (bagging.hpp semantics)
                kb = jax.random.fold_in(bag_key, (seed - 1) // bag_freq)
                u = jax.random.uniform(kb, (SENT + 1,))
                sel = (jnp.take(u, jnp.minimum(rowid, SENT)) < bag_frac) \
                    & (vf > 0)
                sf = sel.astype(jnp.float32)
                g = g * sf
                h = h * sf
                bag_cnt = jnp.sum(sel.astype(jnp.int32))
            else:
                bag_cnt = jnp.sum(vf).astype(jnp.int32)
            if mode == "feature":
                d = jax.lax.axis_index(AXIS)
                per = (F + ndev - 1) // ndev
                fidx = jnp.arange(F)
                feature_mask = feature_mask & (fidx >= d * per) \
                    & (fidx < (d + 1) * per)
            ghi = ghi.at[0].set(g).at[1].set(h)
            rec = lr_._build_tree_impl(pb, ghi, bag_cnt, feature_mask,
                                       seed, feat_used)
            ghi_out = rec["part_ghi"].at[3].add(
                shrink * _phys_leaf_delta(rec, Npad))
            small = {k: v for k, v in rec.items()
                     if k.startswith(("node_", "leaf_")) or k in
                     ("s", "feat_used")}
            # per-shard leaf offsets must not leak out replicated
            small.pop("leaf_start", None)
            small.pop("leaf_cnt", None)
            small["leaf_delta"] = small["leaf_value"] * shrink

            def replicate(x):
                if x.dtype == jnp.bool_:
                    return jax.lax.pmax(x.astype(jnp.int32),
                                        AXIS).astype(jnp.bool_)
                return jax.lax.pmax(x, AXIS)

            small = jax.tree.map(replicate, small)
            return rec["part_bins"], ghi_out, small

        self._fused_phys = jax.jit(smap(
            step_shard,
            in_specs=(state_spec, state_spec, P(), P(), P()),
            out_specs=(state_spec, state_spec, P())),
            donate_argnums=(0, 1))
        self._fused = self._fused_phys
        log.info("fused sharded iteration ENABLED (%s-parallel over %d "
                 "devices)", mode, ndev)

    def _train_one_iter_fused(self) -> bool:
        """Fast path: the whole iteration in one device program.

        Host round-trips are the per-iteration floor on remote-attached
        TPUs, so the small tree record is copied to the host ASYNCHRONOUSLY
        and materialized with a one-iteration lag (its transfer overlaps the
        next iteration's device compute).  Consumers of `models` call
        `_flush_pending()` first."""
        from ..utils.timer import global_timer
        feature_mask = self._feature_mask(self.iter)
        if self._cegb_feat_used is not None:
            feat_used = self._cegb_feat_used
        else:
            if not hasattr(self, "_zeros_fused"):
                self._zeros_fused = jnp.zeros((self.learner.F,), dtype=bool)
            feat_used = self._zeros_fused
        if self._fused_phys is not None:
            if self._phys is None:
                if self._init_phys_fn is not None:   # sharded layout
                    self._phys = tuple(self._init_phys_fn())
                    self._phys_carrier = None
                elif self._phys_carrier is not None:
                    # resume from the retired carrier: the bins are
                    # unpermuted back to the identity layout in-program,
                    # bit-identical to an init from the master buffer
                    pb, rowid_bits = self._phys_carrier
                    self._phys_carrier = None
                    self._phys = tuple(self._init_phys_perm(
                        pb, rowid_bits, self._scores_arr))
                else:
                    self._phys = tuple(self._init_phys_adopt(
                        self.learner._part0, self._scores_arr))
                    # the donating identity init aliased the master
                    # buffer into the carrier; retire the (now stale)
                    # learner/ingest references
                    self._adopt_master_buffer()
            with global_timer.section("GBDT::FusedIter",
                                      sync=lambda: self._phys[1]):
                pb, ghi, rec = self._fused_phys(
                    self._phys[0], self._phys[1], feature_mask,
                    self.iter + 1, feat_used)
                self._phys = (pb, ghi)
        else:
            with global_timer.section("GBDT::FusedIter",
                                      sync=lambda: self.scores):
                self.scores, rec = self._fused(
                    self.learner._part0, self.scores, feature_mask,
                    self.iter + 1, feat_used)
        recs = rec if isinstance(rec, list) else [rec]
        if self.learner.has_cegb:
            self._cegb_feat_used = recs[-1]["feat_used"]
        for r in recs:
            small = {k: v for k, v in r.items()
                     if k.startswith(("node_", "leaf_")) or k == "s"}
            for v in small.values():
                try:
                    v.copy_to_host_async()
                except Exception:
                    break
            self._pending_recs.append(small)
        self.iter += 1
        # with validation sets the record is needed NOW (scores update per
        # iteration); otherwise records accumulate and are drained in
        # BATCHES with one device_get each: on remote-attached TPUs every
        # host materialization costs a full tunnel round-trip (~100 ms
        # measured), so draining per iteration put a latency floor on the
        # whole training loop
        lag = 0 if self.valid_sets else 32
        should_stop = False
        if len(self._pending_recs) > (2 * lag if lag else 0):
            should_stop = self._drain_pending(lag)
        if should_stop:
            log.warning("Stopped training because there are no more leaves "
                        "that meet the split requirements")
        return should_stop

    def _drain_pending(self, lag: int) -> bool:
        """Materialize pending records down to ``lag``, fetching them all
        with ONE host transfer."""
        n = len(self._pending_recs) - lag
        if n <= 0:
            return False
        batch_host = jax.device_get(self._pending_recs[:n])
        K = self.num_tree_per_iteration
        for host_record in batch_host:
            if self._materialize_pending(host_record):
                # stop fires only at an iteration boundary, so the
                # remaining records are whole discarded iterations
                self.iter -= len(self._pending_recs) // K
                self._pending_recs.clear()
                return True
        return False

    def _materialize_pending(self, host_record=None) -> bool:
        """Convert the oldest pending device record into a host tree."""
        small = self._pending_recs.pop(0)
        if host_record is None:
            host_record = jax.device_get(small)
        num_nodes = int(host_record["s"])
        if DEBUG_CHECKS and "leaf_start" in host_record:
            debug_validate_record(host_record, num_nodes, self.num_data,
                                  self.learner.row0)
        nodes = self.learner.node_arrays_for_predict(small)
        delta_leaf = small["leaf_delta"]
        K = self.num_tree_per_iteration
        k_cls = len(self.models) % K
        for vi, (vd, metrics, binned) in enumerate(self.valid_sets):
            leaf_v = predict_leaf_binned(binned, nodes)
            if K == 1:
                self.valid_scores[vi] = self.valid_scores[vi] + \
                    jnp.take(delta_leaf, leaf_v)
            else:
                self.valid_scores[vi] = self.valid_scores[vi].at[
                    :, k_cls].add(jnp.take(delta_leaf, leaf_v))
        tree = tree_from_device_record(
            host_record, num_nodes, self.train_data.bin_mappers,
            None, shrinkage=self.shrinkage_rate)
        if (len(self.models) < K
                and abs(self.init_scores[k_cls]) > K_EPSILON):
            if num_nodes > 0:
                tree.leaf_value = tree.leaf_value + self.init_scores[k_cls]
                tree.internal_value = (tree.internal_value
                                       + self.init_scores[k_cls])
            else:
                tree.leaf_value = np.asarray([self.init_scores[k_cls]])
        self._health_record_tree(host_record, num_nodes)
        self._telemetry_chunk_waste(host_record, num_nodes)
        self.models.append(tree)
        self.device_trees.append({
            "nodes": nodes, "leaf_value": delta_leaf,
            "has_cat_split": bool(
                np.any(host_record["node_is_cat"][:num_nodes]))})
        self._model_version += 1
        self.serving.invalidate()
        # stop only when a FULL iteration's K class trees are all empty
        # (gbdt.cpp TrainOneIter's per-class should_continue)
        self._empty_run = self._empty_run + 1 if num_nodes == 0 else 0
        return self._empty_run >= K and len(self.models) % K == 0

    def _flush_pending(self) -> None:
        """Materialize all lagged fused-iteration records (no-op usually)."""
        if getattr(self, "_pending_recs", None):
            self._drain_pending(0)

    # -- health flight recorder (obs/health.py) -------------------------
    def _health_effective_rows(self) -> int:
        """This iteration's effective sample count under GOSS/bagging —
        the host-side derivation (the actual balanced-bagging draw is a
        device scalar; reading it here would add the exact JL001 host
        sync the sampling paths were scrubbed of)."""
        cfg = self.config
        N = self.num_data
        if getattr(self, "goss", False):
            top_k = max(int(N * cfg.top_rate), 1)
            other_k = max(int(N * cfg.other_rate), 1)
            return min(top_k + other_k, N)
        if getattr(self, "need_bagging", False):
            if self.balanced_bagging:
                label = self.train_data.metadata.label
                pos = int((np.asarray(label) > 0).sum())
                return max(int(pos * cfg.pos_bagging_fraction
                               + (N - pos) * cfg.neg_bagging_fraction), 1)
            return max(int(N * cfg.bagging_fraction), 1)
        return N

    def _health_record_tree(self, host_record, num_nodes: int) -> None:
        """Feed one just-materialized host tree record to the flight
        recorder (a no-op unless health != off armed one at setup).
        Called at BOTH materialization sites — the lagged fused drain
        and the eager loop — with values already on the host, so it
        adds zero device ops and zero syncs by construction (the
        jaxlint ``health.off`` budget pins the lowering either way)."""
        if self.flight is None:
            return
        K = self.num_tree_per_iteration
        idx = len(self.models)             # the tree about to append
        self.flight.record_tree(idx // K, idx % K, host_record,
                                num_nodes,
                                effective_rows=self._health_effective_rows())

    # -- chunk-policy padding-waste gauges (obs/telemetry.py) -----------
    def _telemetry_chunk_waste(self, host_record, num_nodes: int) -> None:
        """Per-band live-row occupancy + padding-waste gauges of the
        just-materialized tree under the active chunk policy
        (``train.chunk.*``, surfaced in ``Booster.telemetry_report()``).
        Host arithmetic on leaf counts the trainer already transferred
        — zero device ops, zero syncs, no-op with telemetry off."""
        sess = obs.get()
        if sess.mode == "off" or "leaf_cnt" not in host_record:
            return
        policy = getattr(self.learner, "_chunk_policy", None)
        if policy is None:
            return
        from ..ops.chunkpolicy import waste_stats
        counts = np.asarray(host_record["leaf_cnt"])[:num_nodes + 1]
        stats = waste_stats(counts, policy)
        sess.gauge("train.chunk.waste", stats["waste"])
        sess.gauge("train.chunk.fixed_waste", stats["fixed_waste"])
        for k, v in stats.items():
            if k.startswith("band_"):
                sess.gauge(f"train.chunk.{k}", v)

    # ------------------------------------------------------------------
    def continue_from(self, trees, train_pred: np.ndarray) -> None:
        """Continued training from a loaded model (reference:
        application.cpp:94-97 — a Predictor over the input model seeds the
        scores — plus GBDT::MergeFrom, gbdt.h:70, and the python engine's
        ``train(init_model=)``, python-package/lightgbm/engine.py:150-186).

        ``trees`` become the head of the model list; train scores are
        rebuilt as (dataset init_score) + ``train_pred`` (the init model's
        raw prediction over the RAW train rows — bin-space evaluation
        would be wrong whenever this dataset's bin boundaries differ from
        the loaded model's thresholds).  The caller (Booster) owns the raw
        matrices and computes the predictions.
        """
        import copy as _copy
        self._flush_pending()
        if self.models:
            raise ValueError("continue_from requires a fresh booster")
        K = self.num_tree_per_iteration
        self.models = [_copy.deepcopy(t) for t in trees]
        # loaded trees carry real-valued thresholds only — no device (bin)
        # node arrays.  Rollback past the continuation boundary is refused.
        self.device_trees = [None] * len(self.models)
        self.iter = len(self.models) // K
        self._model_version += 1
        self.serving.invalidate()
        # DART continuation: init-model trees are excluded from dropping
        # (reference: dart.hpp:108-122 draws over the session's iter_ only,
        # offset by num_init_iteration_)
        if hasattr(self, "init_iters"):
            self.init_iters = self.iter
        self._continued = True
        # the loaded model's boost_from_average lives in its first tree
        # (folded at materialization), so the fresh booster's must not
        # apply on top
        self.init_scores = [0.0] * K

        n = self.num_data
        shape = (n,) if K == 1 else (n, K)
        base = np.zeros(shape, dtype=np.float32)
        meta = self.train_data.metadata
        if meta.init_score is not None:
            init = np.asarray(meta.init_score, dtype=np.float32)
            if K > 1:
                init = init.reshape(K, n).T
            base = init.reshape(shape)
        pred = np.asarray(train_pred, dtype=np.float32)
        self.scores = jnp.asarray(base + pred.reshape(shape))

    def add_valid_data(self, valid_data: BinnedDataset,
                       extra_score=None) -> None:
        metrics = create_metrics(
            self.config, self.objective.name if self.objective else None)
        for m in metrics:
            m.init(valid_data.metadata)
        binned = jnp.asarray(valid_data.binned)
        K = self.num_tree_per_iteration
        shape = (valid_data.num_data,) if K == 1 else (valid_data.num_data, K)
        score = jnp.zeros(shape, dtype=jnp.float32)
        if valid_data.metadata.init_score is not None:
            init = np.asarray(valid_data.metadata.init_score, dtype=np.float32)
            if K > 1:
                init = init.reshape(K, valid_data.num_data).T
            score = jnp.asarray(init.reshape(shape))
        else:
            for k in range(K):
                if abs(self.init_scores[k]) > K_EPSILON:
                    if K == 1:
                        score = score + self.init_scores[k]
                    else:
                        score = score.at[:, k].add(self.init_scores[k])
        if extra_score is not None:
            # continued training: the loaded model's contribution (its own
            # average-boost folded into tree 0) rides on top of init_score
            extra = np.asarray(extra_score, dtype=np.float32)
            score = score + jnp.asarray(extra.reshape(score.shape))
        elif self._continued:
            raise ValueError("validation sets added to a continued booster "
                             "need the init model's predictions "
                             "(Booster.add_valid computes them)")
        self.valid_sets.append((valid_data, metrics, binned))
        self.valid_scores.append(score)

    # ------------------------------------------------------------------
    def _compute_gradients(self):
        g, h = self.objective.get_gradients(self.scores)
        return g, h

    def _bagging_mask(self, it: int):
        """Row sampling (reference: bagging.hpp).  Returns (mask (N,) bool or
        None, bag_cnt).  The learner never gathers rows: out-of-bag rows keep
        their place with zeroed gradients (TPU row gathers are latency-bound,
        masking is bandwidth-free)."""
        cfg = self.config
        N = self.num_data
        if not self.need_bagging:
            return None, None
        if it % cfg.bagging_freq == 0 or self._cached_bag is None:
            self.bag_rng, sub = jax.random.split(self.bag_rng)
            if self.balanced_bagging:
                # per-class Bernoulli (reference: bagging.hpp
                # BalancedBaggingHelper:180-200); the bag count estimate
                # is the reference's bag_data_cnt_ (:100)
                label = jnp.asarray(self.train_data.metadata.label)
                pos = label > 0
                u = jax.random.uniform(sub, (N,))
                mask = jnp.where(pos, u < cfg.pos_bagging_fraction,
                                 u < cfg.neg_bagging_fraction)
                # the ACTUAL drawn count (bagging.hpp:46
                # bag_data_cnt_ = left_cnt), not the sizing estimate —
                # kept as a device scalar: build_tree takes it traced,
                # so an int() here is a host sync per bagging redraw
                # for nothing (jaxlint JL001)
                cnt = jnp.maximum(jnp.sum(mask.astype(jnp.int32)),
                                  jnp.int32(1))
            else:
                cnt = max(int(N * cfg.bagging_fraction), 1)
                mask = jnp.zeros((N,), bool).at[
                    jax.random.permutation(sub, N)[:cnt]].set(True)
            self._cached_bag = (mask, cnt)
        return self._cached_bag

    def _goss_sample(self, grad, hess, it: int):
        """GOSS (reference: goss.hpp Helper:116-165): keep the top_rate fraction
        by |g*h|, sample other_rate of the rest and up-weight by
        (1-top_rate)/other_rate.  Unselected rows get zeroed gradients."""
        cfg = self.config
        N = self.num_data
        if grad.ndim == 2:
            imp = jnp.sum(jnp.abs(grad * hess), axis=1)
        else:
            imp = jnp.abs(grad * hess)
        top_k = max(int(N * cfg.top_rate), 1)
        other_k = max(int(N * cfg.other_rate), 1)
        threshold = jax.lax.top_k(imp, top_k)[0][-1]
        is_top = imp >= threshold
        self.bag_rng, sub = jax.random.split(self.bag_rng)
        n_top = jnp.sum(is_top.astype(jnp.int32))
        rest = jnp.maximum(N - n_top, 1)
        prob = other_k / rest.astype(jnp.float32)
        keep_other = (~is_top) & (jax.random.uniform(sub, (N,)) < prob)
        selected = is_top | keep_other
        multiply = (N - top_k) / other_k
        scale = jnp.where(keep_other, multiply, 0.0)
        scale = jnp.where(is_top, 1.0, scale)
        if grad.ndim == 2:
            grad = grad * scale[:, None]
            hess = hess * scale[:, None]
        else:
            grad = grad * scale
            hess = hess * scale
        cnt = jnp.sum(selected.astype(jnp.int32))
        return grad, hess, selected, cnt

    def _feature_mask(self, it: int):
        frac = float(self.config.feature_fraction)
        F = self.learner.F
        if frac >= 1.0 or F <= 1:
            if not hasattr(self, "_ones_fmask"):
                self._ones_fmask = jnp.ones((F,), dtype=bool)
            return self._ones_fmask
        k = max(int(F * frac), 1)
        self.feat_rng, sub = jax.random.split(self.feat_rng)
        perm = jax.random.permutation(sub, F)
        mask = jnp.zeros((F,), dtype=bool).at[perm[:k]].set(True)
        return mask

    def _discretize_gradients(self, grad, hess, row_sampling=False):
        """Quantized-gradient training: stochastic rounding of (g, h) onto a
        `num_grad_quant_bins`-level integer grid, returned on float carriers
        so histogram sums equal integer-sum x scale exactly (f32 holds int
        sums < 2^24 losslessly).  Mirrors GradientDiscretizer::
        DiscretizeGradients (src/treelearner/gradient_discretizer.cpp:70):
        grad_scale = max|g| / (bins/2), hess_scale = max|h| / bins (or
        max|h| for constant-hessian objectives), truncation toward zero with
        a uniform random offset away from zero.

        The TPU-native histogram already accumulates on the MXU, so the
        reference's 8/16/32-bit per-leaf accumulator selection
        (SetNumBitsInHistogramBin) is unnecessary: the win retained here is
        the regularization/accuracy semantics of quantized training."""
        cfg = self.config
        bins = float(cfg.num_grad_quant_bins)
        max_g = jnp.max(jnp.abs(grad))
        max_h = jnp.max(jnp.abs(hess))
        # the constant-hessian shortcut (every int hessian := 1) is only
        # valid when hessians are untouched by sampling: bagging zeroes
        # out-of-bag rows and GOSS re-weights, so those paths must quantize
        # hessians like any non-constant objective
        const_h = (self.objective is not None
                   and self.objective.is_constant_hessian
                   and not row_sampling)
        gs = jnp.maximum(max_g / (bins / 2.0), 1e-30)
        hs = jnp.maximum(max_h if const_h else max_h / bins, 1e-30)
        if cfg.stochastic_rounding:
            self.quant_rng, sub = jax.random.split(self.quant_rng)
            kg, kh = jax.random.split(sub)
            rg = jax.random.uniform(kg, grad.shape)
            rh = jax.random.uniform(kh, hess.shape)
        else:
            rg = rh = 0.5
        ig = jnp.trunc(grad / gs + jnp.where(grad >= 0, rg, -rg))
        ih = jnp.ones_like(hess) if const_h else jnp.trunc(hess / hs + rh)
        # INTEGER carriers + a separate (2,) scale: the histogram then
        # accumulates exact small integers, which the learner computes
        # with bfloat16 one-hot matmuls at double MXU rate — the TPU
        # analog of the reference's int16 histogram fast path
        # (feature_histogram.hpp:293-374) — and scales once per leaf
        return ig, ih, jnp.stack([gs, hs])

    def _leaf_rows(self, record, num_nodes: int):
        """Per-leaf train row lookup via device traversal of the built tree.

        Partition-record-independent (the sharded learners never replicate
        their per-shard partition arrays off the mesh), so renewal / linear
        fitting work identically for serial and distributed training.
        Returns ``rows(leaf) -> np.ndarray`` of original row ids.
        """
        nodes = self.learner.node_arrays_for_predict(record)
        leaf_idx = np.asarray(self._traverse_train(nodes))
        order = np.argsort(leaf_idx, kind="stable")
        bounds = np.searchsorted(leaf_idx[order],
                                 np.arange(num_nodes + 2))

        def rows(leaf: int) -> np.ndarray:
            return order[bounds[leaf]:bounds[leaf + 1]]

        return rows

    def _renew_quant_leaf_outputs(self, record, num_nodes: int, grad, hess):
        """Recompute leaf outputs from the TRUE (un-quantized) gradient sums
        (reference: GradientDiscretizer::RenewIntGradTreeOutput,
        gradient_discretizer.cpp:209).

        Serial records carry the physical leaf ranges, so the renewal is
        one device program: permute the true gradients into partition
        order and difference their prefix sums at the range boundaries.
        Sharded records (no partition arrays off the mesh) fall back to a
        traversal-based host loop."""
        from ..ops.split import leaf_output
        cfg = self.config
        if "indices" in record:
            return _quant_renew_device(
                record["indices"], jnp.asarray(grad), jnp.asarray(hess),
                record["leaf_start"], record["leaf_cnt"],
                record["leaf_value"],
                cfg.lambda_l1, cfg.lambda_l2, cfg.max_delta_step)
        num_leaves = num_nodes + 1
        leaf_rows = self._leaf_rows(record, num_nodes)
        g = np.asarray(grad)
        h = np.asarray(hess)
        new_values = np.asarray(record["leaf_value"]).copy()
        for leaf in range(num_leaves):
            rows = leaf_rows(leaf)
            if len(rows) == 0:
                continue
            sum_g = float(g[rows].sum())
            sum_h = float(h[rows].sum())
            new_values[leaf] = float(leaf_output(
                sum_g, sum_h + 2e-15, cfg.lambda_l1, cfg.lambda_l2,
                cfg.max_delta_step))
        return jnp.asarray(new_values)

    def _fit_linear_leaves(self, tree, record, num_nodes: int, grad, hess):
        """Fit per-leaf linear models on the raw features along each leaf's
        path (reference: LinearTreeLearner::CalculateLinear,
        linear_tree_learner.cpp:173): weighted normal equations
        coeffs = -(X^T H X + linear_lambda I)^-1 X^T g over the leaf's
        non-NaN rows, constant fallback when the system is under-determined.
        The Eigen fullPivLu solve becomes numpy lstsq."""
        cfg = self.config
        raw = self.train_data.raw_data
        num_leaves = num_nodes + 1
        nf = np.asarray(record["node_feature"])
        nl = np.asarray(record["node_left"])
        nr = np.asarray(record["node_right"])
        nc = (np.asarray(record["node_is_cat"])
              if "node_is_cat" in record else np.zeros(len(nf), bool))
        paths = [[] for _ in range(num_leaves)]
        if num_nodes > 0:
            stack = [(0, [])]
            while stack:
                node, path = stack.pop()
                feats = path if nc[node] else path + [int(nf[node])]
                for child in (int(nl[node]), int(nr[node])):
                    if child < 0:
                        paths[~child] = feats
                    else:
                        stack.append((child, feats))
        leaf_rows = self._leaf_rows(record, num_nodes)
        g = np.asarray(grad, dtype=np.float64)
        h = np.asarray(hess, dtype=np.float64)
        lam = float(cfg.linear_lambda)
        shr = self.shrinkage_rate
        tree.is_linear = True
        for leaf in range(num_leaves):
            feats = list(dict.fromkeys(paths[leaf]))
            rows = leaf_rows(leaf)
            tree.leaf_features[leaf] = []
            tree.leaf_coeff[leaf] = []
            tree.leaf_const[leaf] = float(tree.leaf_value[leaf])
            if not feats or len(rows) == 0:
                continue
            Xl = raw[np.ix_(rows, np.asarray(feats, np.intp))] \
                .astype(np.float64)
            ok = ~np.isnan(Xl).any(axis=1)
            Xl, gi, hi = Xl[ok], g[rows][ok], h[rows][ok]
            if len(Xl):
                # a constant column carries no signal but makes its
                # normal-equation row a multiple of the intercept's:
                # lstsq on the (numerically) singular system returned
                # huge mutually-cancelling coefficients that explode
                # away from the training rows.  The reference drops
                # such features from the leaf before solving
                # (linear_tree_learner.cpp CalculateLinear)
                varying = np.ptp(Xl, axis=0) > 0
                feats = [f for f, v in zip(feats, varying) if v]
                Xl = Xl[:, varying]
            d = len(feats)
            if d == 0 or len(Xl) < d + 1:
                continue
            Xa = np.concatenate([Xl, np.ones((len(Xl), 1))], axis=1)
            XTHX = (Xa * hi[:, None]).T @ Xa
            XTHX[np.arange(d), np.arange(d)] += lam
            # the reference's ridge epsilon on the whole diagonal keeps
            # a near-singular system (collinear columns survive the
            # constant-column drop) from emitting large coefficients
            diag = np.arange(d + 1)
            XTHX[diag, diag] += _LINEAR_RIDGE_EPS * (1.0 +
                                                     XTHX[diag, diag])
            XTg = Xa.T @ gi
            try:
                coeffs = -np.linalg.solve(XTHX, XTg)
            except np.linalg.LinAlgError:
                continue                    # keep the constant leaf
            if not np.all(np.isfinite(coeffs)):
                continue                    # keep the constant leaf
            keep = np.abs(coeffs[:d]) > 1e-35   # reference: kZeroThreshold
            tree.leaf_features[leaf] = [feats[i] for i in range(d)
                                        if keep[i]]
            tree.leaf_coeff[leaf] = [float(coeffs[i] * shr)
                                     for i in range(d) if keep[i]]
            tree.leaf_const[leaf] = float(coeffs[d] * shr)

    def _set_leafwise_linear(self, tree, record, num_nodes: int) -> None:
        """linear_tree_mode=leafwise_gain: per-leaf linear models come out
        of the device record — each leaf's (const, coeff, feature) is its
        OWN best whole-leaf single-feature fit, read off the leaf's own
        split search (models/learner.py LM_LIN_* rows; ops/split.py:
        find_best_split_linear self_* fields), so there is NO extra data
        pass and NO host solve.  ``leaf_lin_feat`` is already an ORIGINAL
        feature id; shrinkage scales (const, coeff) exactly like the
        refit path, and ``leaf_value`` stays the constant fallback for
        NaN rows."""
        tree.is_linear = True
        num_leaves = num_nodes + 1
        shr = self.shrinkage_rate
        const = np.asarray(record["leaf_lin_const"],
                           np.float64)[:num_leaves]
        coeff = np.asarray(record["leaf_lin_coeff"],
                           np.float64)[:num_leaves]
        feat = np.asarray(record["leaf_lin_feat"])[:num_leaves]
        for leaf in range(num_leaves):
            c = float(coeff[leaf])
            if abs(c) <= 1e-35:             # reference: kZeroThreshold
                tree.leaf_features[leaf] = []
                tree.leaf_coeff[leaf] = []
                tree.leaf_const[leaf] = float(tree.leaf_value[leaf])
            else:
                tree.leaf_features[leaf] = [int(feat[leaf])]
                tree.leaf_coeff[leaf] = [c * shr]
                tree.leaf_const[leaf] = float(const[leaf]) * shr

    def _linear_tree_deltas(self, nodes, tree, init_score_adjust=0.0):
        """Per-row (train, [valid...]) deltas through the linear leaves;
        recomputable at any time from the host tree, so nothing per-row needs
        to be retained for rollback (reference: Tree::AddPredictionToScore
        linear arm)."""
        leaf_train = np.asarray(self._traverse_train(nodes))
        delta = tree._linear_output(self.train_data.raw_data, leaf_train) \
            - init_score_adjust
        out = [jnp.asarray(delta.astype(np.float32))]
        for vd, metrics, binned in self.valid_sets:
            leaf_v = np.asarray(predict_leaf_binned(binned, nodes))
            dv = tree._linear_output(vd.raw_data, leaf_v) - init_score_adjust
            out.append(jnp.asarray(dv.astype(np.float32)))
        return out

    def _apply_score_update_linear(self, nodes, tree, k: int) -> None:
        deltas = self._linear_tree_deltas(nodes, tree)
        if self.num_tree_per_iteration == 1:
            self.scores = self.scores + deltas[0]
        else:
            self.scores = self.scores.at[:, k].add(deltas[0])
        for vi in range(len(self.valid_sets)):
            dv = deltas[vi + 1]
            if self.num_tree_per_iteration == 1:
                self.valid_scores[vi] = self.valid_scores[vi] + dv
            else:
                self.valid_scores[vi] = self.valid_scores[vi].at[:, k].add(dv)

    # ------------------------------------------------------------------
    def _assert_trainable(self) -> None:
        if getattr(self, "_serving_only", False):
            # refit(inplace=True) rewrote the leaf values: the training
            # scores (and any physical fused state) no longer match the
            # model, so another update would silently train on stale
            # state (the PR 6 known hazard — now a loud error)
            raise LightGBMError(
                "cannot update() a serving-only booster: "
                "refit(inplace=True) rewrote its leaf values, so the "
                "training-side scores no longer match the model; "
                "continue training from a fresh booster "
                "(train(init_model=...)) instead")

    def train_one_iter(self, grad=None, hess=None) -> bool:
        """One boosting iteration (reference: gbdt.cpp TrainOneIter:338).

        Returns True when training should stop (no further splits possible).
        """
        from ..utils.timer import global_timer
        self._assert_trainable()
        if grad is None and hess is None and self._fused is not None:
            return self._train_one_iter_fused()
        # the eager path appends trees directly: any lagged fused records
        # must land first so model order matches training order
        self._flush_pending()
        # eager builds read the learner's pristine master buffer; rebuild
        # it if the fused carrier adopted it (mixed fused/eager training)
        self._ensure_part0()
        if grad is None or hess is None:
            with global_timer.section("GBDT::Boosting (gradients)"):
                grad, hess = self._compute_gradients()
        else:
            grad = jnp.asarray(grad, dtype=jnp.float32)
            hess = jnp.asarray(hess, dtype=jnp.float32)
            if self.num_tree_per_iteration > 1 and grad.ndim == 1:
                grad = grad.reshape(self.num_tree_per_iteration, self.num_data).T
                hess = hess.reshape(self.num_tree_per_iteration, self.num_data).T

        if faultinject.is_active():
            grad, hess = faultinject.maybe_corrupt_gradients(
                self.iter, grad, hess)
        if self._nf_guard is not None:
            # one device-side reduction over (grad, hess, scores) BEFORE
            # sampling (bagging's zeroing could mask a poisoned row); a
            # skipped iteration builds no tree from the bad batch
            grad, hess, skip = self._nf_guard.filter(
                self.iter, grad, hess, self.scores)
            if skip:
                self.iter += 1
                return False

        use_sharded = self.sharded_builder is not None
        bag_mask = bag_cnt = None
        # sampling is a full-length row predicate + gradient masking, so it
        # composes with the sharded learners exactly as with the serial one
        # (reference: bagging.hpp:13 / goss.hpp:18 compose with every
        # parallel learner); only the per-shard in-bag counts differ
        if self.goss:
            grad, hess, bag_mask, bag_cnt = self._goss_sample(
                grad, hess, self.iter)
        else:
            bag_mask, bag_cnt = self._bagging_mask(self.iter)
            if bag_mask is not None:
                m = bag_mask if grad.ndim == 1 else bag_mask[:, None]
                grad = jnp.where(m, grad, 0.0)
                hess = jnp.where(m, hess, 0.0)
        self._bag_mask_host = (np.asarray(bag_mask)
                               if bag_mask is not None else None)

        feature_mask = self._feature_mask(self.iter)
        K = self.num_tree_per_iteration
        should_stop = True
        for k in range(K):
            gk = grad[:, k] if K > 1 else grad
            hk = hess[:, k] if K > 1 else hess
            gk_true, hk_true = gk, hk
            qscale = None
            if self.use_quant:
                gk, hk, qscale = self._discretize_gradients(
                    gk, hk,
                    row_sampling=self.goss or (bag_mask is not None))
                if use_sharded:
                    # the sharded builders take pre-scaled carriers
                    gk = gk * qscale[0]
                    hk = hk * qscale[1]
                    qscale = None
            tree_seed = self.iter * K + k + 1
            with global_timer.section("TreeLearner::Train",
                                      sync=lambda: record["leaf_value"]):
                if use_sharded:
                    record = self.sharded_builder.build_tree(
                        gk, hk, feature_mask, seed=tree_seed,
                        feat_used=self._cegb_feat_used,
                        bag_mask=self._bag_mask_host,
                        lazy_aux=self._cegb_lazy_aux)
                    if isinstance(record, tuple):
                        record, self._cegb_lazy_aux = record
                else:
                    record = self.learner.build_tree(
                        gk, hk, bag_cnt, feature_mask, seed=tree_seed,
                        feat_used=self._cegb_feat_used,
                        lazy_aux=self._cegb_lazy_aux,
                        hist_scale=qscale)
            if self.learner.has_cegb:
                # coupled AND lazy penalties persist for the model
                # lifetime (the sharded builder already returned its
                # mesh-layout lazy aux above)
                self._cegb_feat_used = record["feat_used"]
                if (not use_sharded
                        and self.learner.cegb_lazy is not None):
                    self._cegb_lazy_aux = \
                        self.learner.lazy_aux_to_original_order(record)
            num_nodes = int(record["s"])
            if num_nodes > 0:
                should_stop = False
            leaf_value_dev = record["leaf_value"]
            if (self.use_quant and self.config.quant_train_renew_leaf
                    and num_nodes > 0):
                leaf_value_dev = self._renew_quant_leaf_outputs(
                    record, num_nodes, gk_true, hk_true)
            if (self.objective is not None
                    and self.objective.is_renew_tree_output and num_nodes > 0):
                leaf_value_dev = self._renew_tree_output(record, num_nodes, k)
            # device score update via traversal
            nodes = self.learner.node_arrays_for_predict(record)
            delta_leaf = leaf_value_dev * self.shrinkage_rate
            use_linear = self.config.linear_tree
            if not use_linear:
                with global_timer.section("GBDT::UpdateScore",
                                          sync=lambda: self.scores):
                    self._apply_score_update(nodes, delta_leaf, k)
            # host tree for the model
            host_record = {key: np.asarray(val) for key, val in record.items()
                           if key.startswith(("node_", "leaf_"))}
            host_record["leaf_value"] = np.asarray(leaf_value_dev)
            if DEBUG_CHECKS and "leaf_start" in host_record \
                    and not use_sharded:
                debug_validate_record(host_record, num_nodes,
                                      self.num_data, self.learner.row0)
            tree = tree_from_device_record(
                host_record, num_nodes, self.train_data.bin_mappers,
                None, shrinkage=self.shrinkage_rate)
            if use_linear:
                if "leaf_lin_const" in record:
                    # leafwise_gain: the models came out of the winning
                    # split candidates — no host refit pass
                    self._set_leafwise_linear(tree, record, num_nodes)
                else:
                    # fit on the TRUE gradients, not the quantized
                    # carriers
                    self._fit_linear_leaves(tree, record, num_nodes,
                                            gk_true, hk_true)
                self._apply_score_update_linear(nodes, tree, k)
            # fold the boost-from-average init score into the first
            # iteration's trees (reference: gbdt.cpp:408-424 AddBias /
            # AsConstantTree) so the saved model is self-contained
            if (len(self.models) < K and abs(self.init_scores[k]) > K_EPSILON):
                if num_nodes > 0:
                    tree.leaf_value = tree.leaf_value + self.init_scores[k]
                    tree.internal_value = tree.internal_value + self.init_scores[k]
                    if tree.is_linear:
                        tree.leaf_const = tree.leaf_const + self.init_scores[k]
                else:
                    tree.leaf_value = np.asarray([self.init_scores[k]])
                    if tree.is_linear:
                        tree.leaf_const = np.asarray([self.init_scores[k]])
            self._health_record_tree(host_record, num_nodes)
            self._telemetry_chunk_waste(host_record, num_nodes)
            self.models.append(tree)
            self.device_trees.append({
                "nodes": nodes, "leaf_value": delta_leaf,
                "has_cat_split": bool(
                    np.any(host_record["node_is_cat"][:num_nodes]))})
        self.iter += 1
        if should_stop:
            log.warning("Stopped training because there are no more leaves "
                        "that meet the split requirements")
        return should_stop

    def _apply_score_update(self, nodes, delta_leaf, k: int) -> None:
        leaf_train = self._traverse_train(nodes)
        delta = jnp.take(delta_leaf, leaf_train)
        if self.num_tree_per_iteration == 1:
            self.scores = self.scores + delta
        else:
            self.scores = self.scores.at[:, k].add(delta)
        for vi, (vd, metrics, binned) in enumerate(self.valid_sets):
            leaf_v = predict_leaf_binned(binned, nodes)
            dv = jnp.take(delta_leaf, leaf_v)
            if self.num_tree_per_iteration == 1:
                self.valid_scores[vi] = self.valid_scores[vi] + dv
            else:
                self.valid_scores[vi] = self.valid_scores[vi].at[:, k].add(dv)

    def _renew_tree_output(self, record, num_nodes: int, k: int):
        """L1-family leaf renewal (reference: RegressionL1loss::RenewTreeOutput;
        applied through SerialTreeLearner::RenewTreeOutput)."""
        alpha = self.objective.renew_leaf_alpha()
        weights = self.objective.renew_weights()
        num_leaves = num_nodes + 1
        leaf_rows = self._leaf_rows(record, num_nodes)
        label = np.asarray(self.objective.label)
        score = np.asarray(self.scores if self.num_tree_per_iteration == 1
                           else self.scores[:, k])
        w = np.asarray(weights) if weights is not None else None
        new_values = np.asarray(record["leaf_value"]).copy()
        from .objective import _weighted_percentile_host
        for leaf in range(num_leaves):
            rows = leaf_rows(leaf)
            if len(rows) == 0:
                continue
            bm = getattr(self, "_bag_mask_host", None)
            if bm is not None:
                rows = rows[bm[rows]]
                if len(rows) == 0:
                    continue
            resid = label[rows] - score[rows]
            new_values[leaf] = _weighted_percentile_host(
                resid, None if w is None else w[rows], alpha)
        return jnp.asarray(new_values, dtype=jnp.float32)

    # ------------------------------------------------------------------
    def eval_metrics(self) -> Dict[str, List[Tuple[str, float, bool]]]:
        """Evaluate all metrics; returns {dataset_name: [(metric, value, is_max_better)]}."""
        from ..utils.timer import global_timer
        with obs.span("train.eval"):
            with global_timer.section("Metric::Eval"):
                return self._eval_metrics_impl()

    def _eval_metrics_impl(self):
        out: Dict[str, List[Tuple[str, float, bool]]] = {}
        if self.train_metrics and self.config.is_provide_training_metric:
            res = []
            for m in self.train_metrics:
                for name, val in m.eval(self.scores, self.objective):
                    res.append((name, val, m.is_max_better))
            out["training"] = res
        for vi, (vd, metrics, _) in enumerate(self.valid_sets):
            res = []
            for m in metrics:
                for name, val in m.eval(self.valid_scores[vi], self.objective):
                    res.append((name, val, m.is_max_better))
            out[f"valid_{vi}"] = res
        return out

    def eval_valid(self, vi: int = 0):
        if vi >= len(self.valid_sets):
            return []
        _, metrics, _ = self.valid_sets[vi]
        res = []
        for m in metrics:
            for name, val in m.eval(self.valid_scores[vi], self.objective):
                res.append((name, val, m.is_max_better))
        return res

    def eval_train(self):
        res = []
        for m in self.train_metrics:
            for name, val in m.eval(self.scores, self.objective):
                res.append((name, val, m.is_max_better))
        return res

    # ------------------------------------------------------------------
    def num_trees(self) -> int:
        self._flush_pending()
        return len(self.models)

    @property
    def current_iteration(self) -> int:
        return self.iter

    def _cat_sentinel_ok(self) -> bool:
        """Whether the categorical OOV-sentinel device-predict scheme is
        sound for this dataset: every categorical feature must be alone
        in its group (EFB bundling folds bins so an out-of-range sentinel
        can't ride through) and leave headroom for one extra bin code in
        the binned dtype."""
        td = self.train_data
        if td is None or not getattr(td, "groups", None):
            return False
        from ..ops.binning import BIN_CATEGORICAL
        u8 = td._bin_dtype() == np.uint8
        for grp in td.groups:
            for f in grp.feature_indices:
                bm = td.bin_mappers[f]
                if bm.bin_type == BIN_CATEGORICAL:
                    if len(grp.feature_indices) > 1:
                        return False
                    if u8 and bm.num_bin >= 256:
                        return False
        return True

    def _predict_raw_device(self, data: np.ndarray, start_iteration: int,
                            end_iter: int):
        """Batch prediction on device via the serving engine
        (models/serving.py): rows are binned with the TRAINING mappers
        (exact for in-session trees — thresholds are bin uppers), padded
        to a power-of-two bucket, and traverse the packed forest in one
        jitted vmap — the TPU replacement for the reference's OpenMP
        batch predictor (predictor.hpp:30).  ``start``/``end`` slicing
        is a tree mask, so repeated serving calls never re-stack or
        re-trace.  Piece-wise linear forests take this path too (the
        pack carries coefficient planes and the engine applies them to
        the raw rows).  Returns None when this model can't take the
        device path (loaded trees, no train data)."""
        return self.serving.raw_insession(np.asarray(data),
                                          start_iteration, end_iter)

    def _predict_raw_device_loaded(self, data: np.ndarray,
                                   start_iteration: int, end_iter: int,
                                   leaves_only: bool = False):
        """Device batch prediction for LOADED models (real thresholds, no
        bin mappers) via the serving engine: raw values convert to
        per-feature threshold-index space with exact float64
        searchsorted on the host, and the trees traverse on device in
        integer space (ops/predict.py predict_leaf_thridx) — the device
        analog of the reference's OpenMP batch predictor
        (predictor.hpp:30) for model_file boosters.  Returns None for
        categorical/linear trees."""
        if leaves_only:
            return self.serving.leaves_loaded(np.asarray(data),
                                              start_iteration, end_iter)
        return self.serving.raw_loaded(np.asarray(data),
                                       start_iteration, end_iter)

    def predict_raw(self, data: np.ndarray, start_iteration: int = 0,
                    num_iteration: int = -1,
                    pred_early_stop: bool = False,
                    pred_early_stop_freq: int = 10,
                    pred_early_stop_margin: float = 10.0) -> np.ndarray:
        """Raw-score batch prediction on host feature values
        (reference: gbdt_prediction.cpp PredictRaw).

        With ``pred_early_stop``, rows whose margin already exceeds
        ``pred_early_stop_margin`` stop accumulating trees every
        ``pred_early_stop_freq`` iterations (reference:
        prediction_early_stop.cpp CreatePredictionEarlyStopInstance —
        |score| for binary, top1-top2 gap for multiclass)."""
        self._flush_pending()
        data = np.asarray(data, dtype=np.float64)
        n = data.shape[0]
        K = self.num_tree_per_iteration
        # init scores are folded into the first iteration's trees (AddBias),
        # so raw prediction is a plain sum over trees
        out = np.zeros((n, K), dtype=np.float64)
        total_iters = len(self.models) // K
        end_iter = total_iters if num_iteration <= 0 else min(
            total_iters, start_iteration + num_iteration)
        use_es = (pred_early_stop and not self.average_output
                  and (K > 1 or (self.objective is not None
                                 and self.objective.name in
                                 ("binary", "cross_entropy",
                                  "cross_entropy_lambda"))))
        if not use_es:
            dev = self._predict_raw_device(data, start_iteration, end_iter)
            if dev is None:
                dev = self._predict_raw_device_loaded(
                    data, start_iteration, end_iter)
            if dev is not None:
                if self.average_output and end_iter > start_iteration:
                    dev /= (end_iter - start_iteration)
                return dev[:, 0] if K == 1 else dev
        else:
            # early stopping routes through the same engine: blocks of
            # ``freq`` iterations accumulate on device (tree-masked) and
            # settled rows leave the bucket between blocks
            dev = self.serving.raw_early_stop(
                data, start_iteration, end_iter, pred_early_stop_freq,
                pred_early_stop_margin)
            if dev is not None:
                return dev[:, 0] if K == 1 else dev
        active = np.ones(n, dtype=bool) if use_es else None
        any_stopped = False
        for it in range(start_iteration, end_iter):
            if use_es and (it - start_iteration) > 0 and \
                    (it - start_iteration) % pred_early_stop_freq == 0:
                if K == 1:
                    margin = np.abs(out[:, 0])
                else:
                    part = np.partition(out, K - 2, axis=1)
                    margin = part[:, K - 1] - part[:, K - 2]
                active &= margin < pred_early_stop_margin
                any_stopped = not active.all()
                if not active.any():
                    break
            # avoid copying the full matrix while every row is still active
            if use_es and any_stopped:
                rows = np.nonzero(active)[0]
                sub = data[rows]
            else:
                rows = slice(None)
                sub = data
            for k in range(K):
                out[rows, k] += self.models[it * K + k].predict(sub)
        if self.average_output and end_iter > start_iteration:
            out /= (end_iter - start_iteration)
        return out[:, 0] if K == 1 else out

    def predict(self, data: np.ndarray, raw_score: bool = False, **kw) -> np.ndarray:
        raw = self.predict_raw(data, **kw)
        if raw_score or self.objective is None:
            return raw
        conv = self.objective.convert_output(jnp.asarray(raw))
        return np.asarray(conv)

    def predict_leaf_index(self, data: np.ndarray, start_iteration: int = 0,
                           num_iteration: int = -1) -> np.ndarray:
        """Leaf index per (row, tree) over iterations [start, start+num)
        (reference: predictor.hpp predict_leaf_index + the c_api's
        start_iteration/num_iteration slicing)."""
        self._flush_pending()
        data = np.asarray(data, dtype=np.float64)
        K = self.num_tree_per_iteration
        total_iters = len(self.models) // max(K, 1)
        end_iter = total_iters if num_iteration <= 0 else min(
            total_iters, start_iteration + num_iteration)
        # a start past the model end yields an empty (n, 0) result like
        # the other pred kinds, not a negative-dimension crash
        end_iter = max(end_iter, start_iteration)
        dev = self.serving.leaves_insession(data, start_iteration, end_iter)
        if dev is None:
            dev = self._predict_raw_device_loaded(
                data, start_iteration, end_iter, leaves_only=True)
        if dev is not None:
            return dev
        out = np.zeros((data.shape[0], (end_iter - start_iteration) * K),
                       dtype=np.int32)
        for t in range(start_iteration * K, end_iter * K):
            out[:, t - start_iteration * K] = \
                self.models[t].predict_leaf(data)
        return out

    def predict_contrib(self, data: np.ndarray, start_iteration: int = 0,
                        num_iteration: int = -1) -> np.ndarray:
        """SHAP feature contributions (reference: c_api predict with
        predict_contrib=true): the serving engine's vectorized device
        TreeSHAP (ops/shap.py) when the model is device-eligible, else
        the exact host recursion (models/shap.py, the oracle)."""
        from .shap import predict_contrib as host_contrib
        self._flush_pending()
        data = np.asarray(data, dtype=np.float64)
        K = self.num_tree_per_iteration
        total_iters = len(self.models) // max(K, 1)
        # 0 means "all iterations", matching predict_raw /
        # predict_leaf_index (the reference wrapper's num_iteration<=0)
        if num_iteration <= 0:
            num_iteration = -1
        end_iter = total_iters if num_iteration < 0 else min(
            total_iters, start_iteration + num_iteration)
        dev = self.serving.contrib(data, start_iteration, end_iter)
        if dev is not None:
            n = data.shape[0]
            nf = self.max_feature_idx + 1
            if K == 1:
                return dev[:, 0, :]
            return dev.reshape(n, K * (nf + 1))
        return host_contrib(self, data, start_iteration, num_iteration)

    def apply_refit_leaf_values(self, new_values) -> None:
        """Commit refit leaf values IN PLACE (Booster.refit(inplace=True)
        and the continual-training runtime's per-tick refit): rewrite
        every host tree's leaf values, mirror them into the device-tree
        delta arrays, and bump the serving mutation counter EAGERLY —
        like update/rollback already do — so a pack warmed before the
        refit can never serve pre-refit values.  The warm in-session
        pack takes the leaf-only fast path (serving.refit_leaf_values):
        its stacked node arrays survive and only the small delta rows
        re-transfer, so a refit tick never re-packs or re-traces.

        ``new_values`` holds one array per tree, already shrunk and
        (for the first iteration's trees) already carrying the
        boost-from-average fold — the refit accumulation is
        self-contained, so ``init_scores`` zeroes like continue_from.

        In-place refit is a SERVING mutation: the training-side scores
        and physical fused state are no longer consistent with the
        model, so continued ``train_one_iter`` after it is unsupported
        (train via a fresh booster / init_model instead)."""
        self._flush_pending()
        if len(new_values) != len(self.models):
            raise ValueError(
                f"refit produced {len(new_values)} leaf arrays for "
                f"{len(self.models)} trees")
        for ti, vals in enumerate(new_values):
            vals = np.asarray(vals, dtype=np.float64)
            tree = self.models[ti]
            tree.leaf_value = vals.copy()
            if ti < len(self.device_trees):
                dt = self.device_trees[ti]
                if dt is not None:
                    slot = np.zeros(dt["leaf_value"].shape, np.float32)
                    n = min(len(vals), slot.shape[0])
                    slot[:n] = vals[:n]
                    dt["leaf_value"] = jnp.asarray(slot)
        self.init_scores = [0.0] * self.num_tree_per_iteration
        # training-side state is stale from here on (see docstring);
        # train_one_iter refuses serving-only boosters loudly.  The
        # bins must survive as the retired carrier though — under
        # single-copy residency they may be the dataset's only binned
        # copy (pickle / save_binary / a second booster recover it)
        if self._phys is not None:
            pb, ghi = self._phys
            self._phys = None
            self._phys_carrier = (pb, ghi[2])
        self._serving_only = True
        self._model_version += 1
        self.serving.refit_leaf_values(
            [np.asarray(v, np.float64) for v in new_values])

    def rollback_one_iter(self) -> None:
        """reference: gbdt.cpp RollbackOneIter:443."""
        self._flush_pending()
        self._empty_run = 0
        if self.iter <= 0:
            return
        K = self.num_tree_per_iteration
        if any(self.device_trees[-k] is None for k in range(1, K + 1)):
            log.warning("cannot roll back past the init_model boundary "
                        "(loaded trees have no device arrays)")
            return
        self._model_version += 1
        self.serving.invalidate()
        for k in range(K):
            dt = self.device_trees.pop()
            tree = self.models.pop()
            nodes, delta_leaf = dt["nodes"], dt["leaf_value"]
            kk = K - 1 - k
            if tree.is_linear:
                # recompute the per-row deltas from the host tree; undo the
                # init-score fold if this was a first-iteration tree
                t_idx = len(self.models)
                adj = (self.init_scores[kk]
                       if t_idx < K and abs(self.init_scores[kk]) > K_EPSILON
                       else 0.0)
                deltas = self._linear_tree_deltas(nodes, tree,
                                                  init_score_adjust=adj)
                delta = deltas[0]
                valid_dvs = deltas[1:]
            else:
                leaf_train = self._traverse_train(nodes)
                delta = jnp.take(delta_leaf, leaf_train)
                valid_dvs = None
            if K == 1:
                self.scores = self.scores - delta
            else:
                self.scores = self.scores.at[:, kk].add(-delta)
            for vi, (vd, metrics, binned) in enumerate(self.valid_sets):
                if valid_dvs is not None:
                    dv = valid_dvs[vi]
                else:
                    leaf_v = predict_leaf_binned(binned, nodes)
                    dv = jnp.take(delta_leaf, leaf_v)
                if K == 1:
                    self.valid_scores[vi] = self.valid_scores[vi] - dv
                else:
                    self.valid_scores[vi] = self.valid_scores[vi].at[:, kk].add(-dv)
        self.iter -= 1


class DART(GBDT):
    """DART boosting (reference: src/boosting/dart.hpp:23)."""

    def __init__(self, config: Config, train_data, objective):
        if config.linear_tree:
            log.fatal("Cannot use linear tree with DART boosting "
                      "(reference: config.cpp linear_tree checks)")
        super().__init__(config, train_data, objective)
        # DART's drop/normalize bookkeeping needs each tree materialized
        # IMMEDIATELY after its iteration; the fused path's lag breaks that
        self._fused = None
        self.drop_rng = np.random.RandomState(config.drop_seed)
        self.tree_weights: List[float] = []  # per SESSION iteration (dart.hpp:196)
        self.sum_weight = 0.0
        # continuation boundary: trees below this iteration came from an
        # init model and are never dropped (dart.hpp num_init_iteration_)
        self.init_iters = 0

    def train_one_iter(self, grad=None, hess=None) -> bool:
        # select trees to drop (reference: dart.hpp DroppingTrees:97 —
        # per-tree Bernoulli draws; non-uniform mode weights each tree by
        # its stored weight relative to the average, capped by max_drop)
        # (serving-only guard BEFORE the drop bookkeeping mutates scores)
        self._assert_trainable()
        self._flush_pending()
        cfg = self.config
        K = self.num_tree_per_iteration
        # only the session's own iterations are droppable; init-model trees
        # sit below the boundary (dart.hpp:108-122, num_init_iteration_)
        n_droppable = len(self.models) // K - self.init_iters
        base_lr = float(cfg.learning_rate)
        drop_iters: List[int] = []
        if n_droppable > 0 and self.drop_rng.rand() >= cfg.skip_drop:
            drop_rate = float(cfg.drop_rate)
            max_drop = int(cfg.max_drop)
            if cfg.uniform_drop:
                if max_drop > 0:
                    drop_rate = min(drop_rate, max_drop / n_droppable)
                for i in range(n_droppable):
                    if self.drop_rng.rand() < drop_rate:
                        drop_iters.append(self.init_iters + i)
                        if max_drop > 0 and len(drop_iters) >= max_drop:
                            break
            else:
                inv_avg = (len(self.tree_weights) / self.sum_weight
                           if self.sum_weight > 0 else 0.0)
                if max_drop > 0 and self.sum_weight > 0:
                    drop_rate = min(drop_rate,
                                    max_drop * inv_avg / self.sum_weight)
                for i in range(n_droppable):
                    p = drop_rate * self.tree_weights[i] * inv_avg
                    if self.drop_rng.rand() < p:
                        drop_iters.append(self.init_iters + i)
                        if max_drop > 0 and len(drop_iters) >= max_drop:
                            break
        k_drop = len(drop_iters)
        # remove dropped trees' contributions from the TRAIN scores only
        # (validation scores are corrected in the normalize step, exactly
        # like the reference's Shrinkage(-1)+AddScore / Normalize dance)
        for it in drop_iters:
            for k in range(K):
                self._add_tree_to_scores(it * K + k, -1.0, valid=False)
        # the NEW tree trains at reduced shrinkage so its score update and
        # stored values agree from the start (dart.hpp:131-146)
        if cfg.xgboost_dart_mode:
            self.shrinkage_rate = (base_lr if k_drop == 0
                                   else base_lr / (base_lr + k_drop))
        else:
            self.shrinkage_rate = base_lr / (1.0 + k_drop)
        stop = super().train_one_iter(grad, hess)
        # normalize dropped trees (reference: dart.hpp Normalize:158):
        # each dropped tree's final weight is old * k/(k+1) (non-xgboost)
        # or old * k/(k+lr) (xgboost mode); train scores lost the full
        # tree, valid scores lost nothing yet
        if k_drop > 0:
            kf = float(k_drop)
            final = (kf / (kf + 1.0) if not cfg.xgboost_dart_mode
                     else kf / (kf + base_lr))
            for it in drop_iters:
                for k in range(K):
                    t_idx = it * K + k
                    self._add_tree_to_scores(t_idx, final, valid=False)
                    self._add_tree_to_scores(t_idx, final - 1.0, train=False)
                    self._scale_tree(t_idx, final)
                if not cfg.uniform_drop:
                    self.tree_weights[it - self.init_iters] *= final
            if not cfg.uniform_drop:
                self.sum_weight = sum(self.tree_weights)
        if not cfg.uniform_drop:
            self.tree_weights.append(self.shrinkage_rate)
            self.sum_weight += self.shrinkage_rate
        return stop

    def rollback_one_iter(self) -> None:
        # keep the non-uniform drop bookkeeping aligned: the rolled-back
        # iteration's weight must leave tree_weights/sum_weight or every
        # later selection and normalize step reads a shifted entry
        n_before = len(self.models)
        super().rollback_one_iter()
        if (len(self.models) < n_before and not self.config.uniform_drop
                and self.tree_weights):
            self.sum_weight -= self.tree_weights.pop()

    def _scale_tree(self, t_idx: int, factor: float) -> None:
        self.models[t_idx].leaf_value *= factor
        self.models[t_idx].internal_value *= factor
        dt = self.device_trees[t_idx]
        dt["leaf_value"] = dt["leaf_value"] * factor
        self._model_version += 1
        self.serving.invalidate()

    def _add_tree_to_scores(self, t_idx: int, factor: float,
                            train: bool = True, valid: bool = True) -> None:
        dt = self.device_trees[t_idx]
        K = self.num_tree_per_iteration
        k = t_idx % K
        if train:
            leaf_train = self._traverse_train(dt["nodes"])
            delta = jnp.take(dt["leaf_value"], leaf_train) * factor
            if K == 1:
                self.scores = self.scores + delta
            else:
                self.scores = self.scores.at[:, k].add(delta)
        if not valid:
            return
        for vi, (vd, metrics, binned) in enumerate(self.valid_sets):
            leaf_v = predict_leaf_binned(binned, dt["nodes"])
            dv = jnp.take(dt["leaf_value"], leaf_v) * factor
            if K == 1:
                self.valid_scores[vi] = self.valid_scores[vi] + dv
            else:
                self.valid_scores[vi] = self.valid_scores[vi].at[:, k].add(dv)


class RF(GBDT):
    """Random forest mode (reference: src/boosting/rf.hpp:25)."""

    def __init__(self, config: Config, train_data, objective):
        if config.bagging_freq <= 0 or config.bagging_fraction >= 1.0:
            if config.feature_fraction >= 1.0:
                log.fatal("Random forest mode requires bagging "
                          "(bagging_freq > 0 and bagging_fraction < 1) or "
                          "feature_fraction < 1")
        super().__init__(config, train_data, objective)
        # the fused fast path captures GBDT gradient/shrinkage semantics at
        # trace time; RF overrides both (fixed-score gradients, shrinkage 1)
        self._fused = None
        self.average_output = True
        self.shrinkage_rate = 1.0
        # gradients are always taken at the init score
        self._base_grad = None

    def _compute_gradients(self):
        if self._base_grad is None:
            K = self.num_tree_per_iteration
            shape = ((self.num_data,) if K == 1 else (self.num_data, K))
            base = jnp.zeros(shape, dtype=jnp.float32)
            for k in range(K):
                if abs(self.init_scores[k]) > K_EPSILON:
                    if K == 1:
                        base = base + self.init_scores[k]
                    else:
                        base = base.at[:, k].add(self.init_scores[k])
            self._base_grad = self.objective.get_gradients(base)
        return self._base_grad

    def _apply_score_update(self, nodes, delta_leaf, k: int) -> None:
        # scores store the running SUM; metrics divide by iteration count via
        # average_output handling in eval (approximated by scaling on read)
        super()._apply_score_update(nodes, delta_leaf, k)


def create_boosting(config: Config, train_data, objective) -> GBDT:
    """reference: Boosting::CreateBoosting (include/LightGBM/boosting.h:314)."""
    b = config.boosting
    if b == "gbdt":
        return GBDT(config, train_data, objective)
    if b == "dart":
        return DART(config, train_data, objective)
    if b == "rf":
        return RF(config, train_data, objective)
    log.fatal("Unknown boosting type %s", b)
