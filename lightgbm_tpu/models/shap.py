"""SHAP feature contributions (TreeSHAP).

TPU-native re-implementation of the reference's PredictContrib path
(include/LightGBM/tree.h TreeSHAP, src/io/tree.cpp): the exact polynomial-time
TreeSHAP recursion over decision paths, evaluated per (row, tree) on the host.
"""

from __future__ import annotations

import math
import os
from typing import List

import numpy as np

from .tree import K_DEFAULT_LEFT_MASK, K_CATEGORICAL_MASK, MISSING_NAN, MISSING_ZERO

K_ZERO_THRESHOLD = 1e-35


class _PathElement:
    __slots__ = ("feature_index", "zero_fraction", "one_fraction", "pweight")

    def __init__(self, feature_index=-1, zero_fraction=0.0, one_fraction=0.0,
                 pweight=0.0):
        self.feature_index = feature_index
        self.zero_fraction = zero_fraction
        self.one_fraction = one_fraction
        self.pweight = pweight


def _extend_path(path: List[_PathElement], unique_depth: int,
                 zero_fraction: float, one_fraction: float,
                 feature_index: int) -> None:
    path[unique_depth].feature_index = feature_index
    path[unique_depth].zero_fraction = zero_fraction
    path[unique_depth].one_fraction = one_fraction
    path[unique_depth].pweight = 1.0 if unique_depth == 0 else 0.0
    for i in range(unique_depth - 1, -1, -1):
        path[i + 1].pweight += one_fraction * path[i].pweight * (i + 1) / (unique_depth + 1)
        path[i].pweight = zero_fraction * path[i].pweight * (unique_depth - i) / (unique_depth + 1)


def _unwind_path(path: List[_PathElement], unique_depth: int, path_index: int) -> None:
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0:
            tmp = path[i].pweight
            path[i].pweight = next_one_portion * (unique_depth + 1) / ((i + 1) * one_fraction)
            next_one_portion = tmp - path[i].pweight * zero_fraction * (unique_depth - i) / (unique_depth + 1)
        else:
            path[i].pweight = path[i].pweight * (unique_depth + 1) / (
                zero_fraction * (unique_depth - i))
    for i in range(path_index, unique_depth):
        path[i].feature_index = path[i + 1].feature_index
        path[i].zero_fraction = path[i + 1].zero_fraction
        path[i].one_fraction = path[i + 1].one_fraction


def _unwound_path_sum(path: List[_PathElement], unique_depth: int,
                      path_index: int) -> float:
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    total = 0.0
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0:
            tmp = next_one_portion * (unique_depth + 1) / ((i + 1) * one_fraction)
            total += tmp
            next_one_portion = path[i].pweight - tmp * zero_fraction * (
                (unique_depth - i) / (unique_depth + 1))
        else:
            total += path[i].pweight / (zero_fraction * (unique_depth - i) / (unique_depth + 1))
    return total


def _decision(tree, node: int, value: float) -> bool:
    dtp = int(tree.decision_type[node])
    mtype = (dtp >> 2) & 3
    default_left = bool(dtp & K_DEFAULT_LEFT_MASK)
    if math.isnan(value) and mtype != MISSING_NAN:
        value = 0.0
    if (mtype == MISSING_ZERO and abs(value) <= K_ZERO_THRESHOLD) or \
            (mtype == MISSING_NAN and math.isnan(value)):
        return default_left
    return value <= tree.threshold[node]


def _tree_shap(tree, x: np.ndarray, phi: np.ndarray, node: int,
               unique_depth: int, parent_path: List[_PathElement],
               parent_zero_fraction: float, parent_one_fraction: float,
               parent_feature_index: int) -> None:
    path = [ _PathElement(p.feature_index, p.zero_fraction, p.one_fraction,
                          p.pweight) for p in parent_path[:unique_depth] ]
    path += [_PathElement() for _ in range(len(parent_path) - unique_depth)]
    _extend_path(path, unique_depth, parent_zero_fraction,
                 parent_one_fraction, parent_feature_index)

    if node < 0:  # leaf
        leaf = ~node
        for i in range(1, unique_depth + 1):
            w = _unwound_path_sum(path, unique_depth, i)
            el = path[i]
            phi[el.feature_index] += w * (el.one_fraction - el.zero_fraction) * \
                tree.leaf_value[leaf]
        return

    hot, cold = ((tree.left_child[node], tree.right_child[node])
                 if _decision(tree, node, x[tree.split_feature[node]])
                 else (tree.right_child[node], tree.left_child[node]))
    w_node = _node_weight(tree, node)
    hot_zero_fraction = _child_weight(tree, hot) / w_node
    cold_zero_fraction = _child_weight(tree, cold) / w_node
    incoming_zero_fraction = 1.0
    incoming_one_fraction = 1.0

    # if we split on the same feature as an ancestor, undo that path entry
    path_index = 0
    f = int(tree.split_feature[node])
    while path_index <= unique_depth:
        if path[path_index].feature_index == f:
            break
        path_index += 1
    if path_index != unique_depth + 1:
        incoming_zero_fraction = path[path_index].zero_fraction
        incoming_one_fraction = path[path_index].one_fraction
        _unwind_path(path, unique_depth, path_index)
        unique_depth -= 1

    _tree_shap(tree, x, phi, int(hot), unique_depth + 1, path,
               hot_zero_fraction * incoming_zero_fraction,
               incoming_one_fraction, f)
    _tree_shap(tree, x, phi, int(cold), unique_depth + 1, path,
               cold_zero_fraction * incoming_zero_fraction, 0.0, f)


def _node_weight(tree, node: int) -> float:
    cnt = float(tree.internal_count[node])
    return cnt if cnt > 0 else 1.0


def _child_weight(tree, child: int) -> float:
    if child < 0:
        c = float(tree.leaf_count[~child])
    else:
        c = float(tree.internal_count[child])
    return c if c > 0 else 1.0


def _expected_value(tree) -> float:
    """Weighted average output of the tree (for the bias term)."""
    total = tree.leaf_count[:tree.num_leaves].sum()
    if total <= 0:
        return float(tree.leaf_value[0]) if tree.num_leaves else 0.0
    return float(np.sum(tree.leaf_value[:tree.num_leaves] *
                        tree.leaf_count[:tree.num_leaves]) / total)


# ---------------------------------------------------------------------------
# Row-batched TreeSHAP: the recursion's control flow (DFS order, ancestor
# same-feature unwinds, zero fractions = count ratios) is row-INDEPENDENT;
# only one_fraction / pweight carry per-row data.  Vectorizing those as
# (n,) arrays runs the exact tree.cpp recursion once per tree instead of
# once per (row, tree).
# ---------------------------------------------------------------------------

class _BPath:
    __slots__ = ("fi", "zf", "of", "pw")

    def __init__(self, fi=-1, zf=0.0, of=None, pw=None):
        self.fi = fi        # feature index (scalar)
        self.zf = zf        # zero fraction (scalar: count ratio)
        self.of = of        # one fraction (n,)
        self.pw = pw        # pweight (n,)


def _b_extend(path, ud, zf, of, fi, n):
    path[ud] = _BPath(fi, zf, of,
                      np.ones(n) if ud == 0 else np.zeros(n))
    for i in range(ud - 1, -1, -1):
        path[i + 1].pw = path[i + 1].pw + of * path[i].pw * (i + 1) / (ud + 1)
        path[i].pw = zf * path[i].pw * (ud - i) / (ud + 1)


def _b_unwind(path, ud, pi):
    of = path[pi].of
    zf = path[pi].zf
    nz = of != 0
    next_one = path[ud].pw.copy()
    for i in range(ud - 1, -1, -1):
        tmp = path[i].pw
        with np.errstate(divide="ignore", invalid="ignore"):
            pw_a = next_one * (ud + 1) / ((i + 1) * of)
        pw_b = tmp * (ud + 1) / (zf * (ud - i))
        path[i].pw = np.where(nz, pw_a, pw_b)
        next_one = np.where(nz, tmp - path[i].pw * zf * (ud - i) / (ud + 1),
                            next_one)
    for i in range(pi, ud):
        path[i] = _BPath(path[i + 1].fi, path[i + 1].zf,
                         path[i + 1].of, path[i].pw)


def _b_unwound_sum(path, ud, pi):
    of = path[pi].of
    zf = path[pi].zf
    nz = of != 0
    next_one = path[ud].pw
    total = np.zeros_like(next_one)
    for i in range(ud - 1, -1, -1):
        with np.errstate(divide="ignore", invalid="ignore"):
            tmp = next_one * (ud + 1) / ((i + 1) * of)
        alt = path[i].pw / (zf * (ud - i) / (ud + 1))
        total = total + np.where(nz, tmp, alt)
        next_one = np.where(nz,
                            path[i].pw - tmp * zf * (ud - i) / (ud + 1),
                            next_one)
    return total


def _b_unwound_sum_all(path, ud):
    """All path positions' unwound sums at once: (ud, n) with row pi-1 ==
    _b_unwound_sum(path, ud, pi).  Bit-identical element expressions —
    the per-pi inner loops are independent, so stacking them turns
    ud**2 (n,) numpy calls per leaf into ud (ud, n) calls (the dominant
    host cost of batched TreeSHAP, ~50% before this)."""
    n = path[ud].pw.shape[0]
    of = np.stack([path[pi].of for pi in range(1, ud + 1)])     # (ud, n)
    zf = np.asarray([path[pi].zf
                     for pi in range(1, ud + 1)])[:, None]      # (ud, 1)
    nz = of != 0
    next_one = np.broadcast_to(path[ud].pw, (ud, n)).copy()
    total = np.zeros((ud, n))
    for i in range(ud - 1, -1, -1):
        # one_fractions are BINARY in hard-routed trees (products of
        # 0/1 routing masks), so (i+1)*of == i+1 exactly where nz and
        # the division by `of` folds away bit-identically — halves the
        # f64 divides, which dominate this host loop
        tmp = next_one * (ud + 1) / (i + 1)
        alt = path[i].pw / (zf * (ud - i) / (ud + 1))
        np.add(total, np.where(nz, tmp, alt), out=total)
        next_one = np.where(nz,
                            path[i].pw - tmp * zf * (ud - i) / (ud + 1),
                            next_one)
    return total


def _b_decision(tree, node, col_vals):
    """(n,) goes-left decisions at one node (reference: tree.h Decision,
    incl. the categorical bitset arm the per-row path also uses)."""
    dtp = int(tree.decision_type[node])
    if dtp & K_CATEGORICAL_MASK:
        nid = np.full(len(col_vals), node, dtype=np.int64)
        return tree._categorical_decision(nid, col_vals)
    default_left = bool(dtp & K_DEFAULT_LEFT_MASK)
    mtype = (dtp >> 2) & 3
    nan_mask = np.isnan(col_vals)
    fv = np.where(nan_mask & (mtype != MISSING_NAN), 0.0, col_vals)
    is_missing = ((mtype == MISSING_ZERO) &
                  (np.abs(fv) <= K_ZERO_THRESHOLD)) | \
                 ((mtype == MISSING_NAN) & nan_mask)
    return np.where(is_missing, default_left, fv <= tree.threshold[node])


def _tree_shap_batch(tree, X, phi):
    """Accumulate this tree's SHAP values for every row of ``X`` into
    ``phi`` ((n, F+1)); exact port of the per-row recursion above with
    (n,)-vector one_fractions/pweights."""
    n = X.shape[0]
    # column-major: per-node feature-column reads become contiguous
    # (no-op when the caller already converted once for all trees)
    X = np.asfortranarray(X, dtype=np.float64)
    stacked = bool(os.environ.get("LIGHTGBM_TPU_SHAP_STACKED"))

    def recurse(node, ud, parent_path, pzf, pof, pfi):
        path = [_BPath(p.fi, p.zf, p.of, None if p.pw is None
                       else p.pw.copy()) for p in parent_path[:ud]]
        path += [_BPath() for _ in range(2)]
        _b_extend(path, ud, pzf, pof, pfi, n)

        if node < 0:
            leaf = ~node
            lv = float(tree.leaf_value[leaf])
            # per-position unwound sums: the stacked (ud, n) variant
            # (_b_unwound_sum_all) measured SLOWER on a 1-core host
            # (larger temporaries outweigh the saved numpy calls);
            # kept for wide-core hosts via the env knob
            if ud > 0 and stacked:
                w_all = _b_unwound_sum_all(path, ud)
                for i in range(1, ud + 1):
                    el = path[i]
                    phi[:, el.fi] += w_all[i - 1] * (el.of - el.zf) * lv
                return
            for i in range(1, ud + 1):
                w = _b_unwound_sum(path, ud, i)
                el = path[i]
                phi[:, el.fi] += w * (el.of - el.zf) * lv
            return

        f = int(tree.split_feature[node])
        goes_left = np.asarray(_b_decision(tree, node,
                                           np.ascontiguousarray(X[:, f])))
        lc, rc = int(tree.left_child[node]), int(tree.right_child[node])
        w_node = _node_weight(tree, node)
        zf_l = _child_weight(tree, lc) / w_node
        zf_r = _child_weight(tree, rc) / w_node
        inc_zf = 1.0
        inc_of = np.ones(n)
        pi = 0
        while pi <= ud:
            if path[pi].fi == f:
                break
            pi += 1
        if pi != ud + 1:
            inc_zf = path[pi].zf
            inc_of = path[pi].of.copy()
            _b_unwind(path, ud, pi)
            ud -= 1

        recurse(lc, ud + 1, path, zf_l * inc_zf,
                np.where(goes_left, inc_of, 0.0), f)
        recurse(rc, ud + 1, path, zf_r * inc_zf,
                np.where(goes_left, 0.0, inc_of), f)

    recurse(0, 0, [], 1.0, np.ones(n), -1)


# ---------------------------------------------------------------------------
# Per-leaf unique-path extraction for the DEVICE TreeSHAP kernel
# (ops/shap.py).  The recursion's path state at a leaf is row-independent
# except for the one_fractions: each unique path element is one feature
# with a scalar zero fraction (product of count ratios over the merged
# same-feature nodes) and a set of (node, direction) conditions whose
# conjunction is the row's one_fraction.  Extracting those per leaf turns
# the recursion into dense per-(element, row) array ops.
# ---------------------------------------------------------------------------

def tree_leaf_paths(tree):
    """Per-leaf unique path elements of a host tree.

    Returns ``{leaf_id: [(feature, zero_fraction, [(node, dir), ...]),
    ...]}`` where ``dir`` is 1 when the leaf path goes LEFT at ``node``
    (a row is "hot" on the element iff its decision agrees at every
    listed node).  Merged duplicate-feature elements multiply their
    zero fractions exactly like the recursion's unwind+re-extend."""
    out = {}

    def rec(node, elems):
        if node < 0:
            out[~node] = elems
            return
        f = int(tree.split_feature[node])
        w = _node_weight(tree, node)
        lc = int(tree.left_child[node])
        rc = int(tree.right_child[node])
        for child, zc, d in ((lc, _child_weight(tree, lc) / w, 1),
                             (rc, _child_weight(tree, rc) / w, 0)):
            new = list(elems)
            hit = next((i for i, e in enumerate(new) if e[0] == f), None)
            if hit is not None:
                prev = new.pop(hit)
                new.append((f, prev[1] * zc, prev[2] + [(node, d)]))
            else:
                new.append((f, zc, [(node, d)]))
            rec(child, new)

    if tree.num_leaves > 1:
        rec(0, [])
    return out


def tree_path_arrays(tree):
    """Padded per-tree path matrices for the device kernel.

    Returns a dict of numpy arrays (tree-local padding; the serving
    engine pads to forest maxima before stacking):
      ``zf``    (L, D) f64  zero fraction per element (pad 1.0)
      ``feat``  (L, D) i32  feature id (pad 0 — contributes 0, see below)
      ``node``  (L, D, M) i32  node-condition ids (pad 0)
      ``dir``   (L, D, M) i8   1=left, 0=right, 2=pad (always agrees)
      ``leaf_value`` (L,) f64  (pad 0.0)
    Pad elements use zf=1 with an always-hot condition, making their
    factor exactly 1 and their contribution (hot - zf) == 0."""
    paths = tree_leaf_paths(tree)
    L = max(tree.num_leaves, 1)
    D = max((len(e) for e in paths.values()), default=0)
    M = max((len(el[2]) for e in paths.values() for el in e), default=0)
    zf = np.ones((L, max(D, 1)), dtype=np.float64)
    feat = np.zeros((L, max(D, 1)), dtype=np.int32)
    nodec = np.zeros((L, max(D, 1), max(M, 1)), dtype=np.int32)
    dirc = np.full((L, max(D, 1), max(M, 1)), 2, dtype=np.int8)
    lv = np.zeros(L, dtype=np.float64)
    for leaf, elems in paths.items():
        lv[leaf] = float(tree.leaf_value[leaf])
        for d, (f, z, conds) in enumerate(elems):
            zf[leaf, d] = z
            feat[leaf, d] = f
            for m, (nid, dr) in enumerate(conds):
                nodec[leaf, d, m] = nid
                dirc[leaf, d, m] = dr
    return {"zf": zf, "feat": feat, "node": nodec, "dir": dirc,
            "leaf_value": lv}


def predict_contrib(gbdt, data: np.ndarray, start_iteration: int = 0,
                    num_iteration: int = -1) -> np.ndarray:
    """SHAP values with the expected-value bias in the last column
    (reference: c_api predict with predict_contrib=true)."""
    n, nf = data.shape
    num_features = gbdt.max_feature_idx + 1
    K = gbdt.num_tree_per_iteration
    total_iters = len(gbdt.models) // K
    end_iter = total_iters if num_iteration < 0 else min(
        total_iters, start_iteration + num_iteration)
    out = np.zeros((n, K, num_features + 1), dtype=np.float64)
    # one column-major conversion shared by every tree's batch walk
    data = np.asfortranarray(data, dtype=np.float64)
    for it in range(start_iteration, end_iter):
        for k in range(K):
            tree = gbdt.models[it * K + k]
            if tree.num_leaves <= 1:
                out[:, k, -1] += tree.leaf_value[0] if len(tree.leaf_value) else 0.0
                continue
            expected = _expected_value(tree)
            phi = np.zeros((n, num_features + 1))
            _tree_shap_batch(tree, data, phi)
            out[:, k, :-1] += phi[:, :-1]
            out[:, k, -1] += expected
    if K == 1:
        return out[:, 0, :]
    return out.reshape(n, K * (num_features + 1))
