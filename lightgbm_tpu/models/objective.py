"""Objective functions (vectorized JAX).

TPU-native re-implementation of the reference objective matrix
(src/objective/objective_function.cpp:20-108 factory;
regression_objective.hpp, binary_objective.hpp, multiclass_objective.hpp,
xentropy_objective.hpp, rank_objective.hpp): per-row gradient/hessian
computation becomes one fused elementwise jnp program on device; lambdarank's
ragged per-query pairwise loops become padded per-bucket pairwise matrices.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..dataset import Metadata
from ..utils import log

K_EPSILON = 1e-15


def _dist_sums(*vals: float) -> Tuple[float, ...]:
    """Sum scalars across the process group (reference:
    Network::GlobalSyncUpBySum calls inside binary_objective.hpp:75-77,
    155-157 and multiclass_objective.hpp:75-78).  Identity when
    single-process."""
    from ..parallel import network
    if network.num_machines() <= 1:
        return vals
    return tuple(float(v) for v in network.global_sum(list(vals)))


class ObjectiveFunction:
    """Base objective (reference: include/LightGBM/objective_function.h)."""

    name = "custom"
    num_model_per_iteration = 1
    is_constant_hessian = False
    is_renew_tree_output = False
    # get_gradients is pure jnp and may be traced into a fused training step
    # (False for objectives with python-level per-iteration state)
    is_jit_safe = True

    def __init__(self, config: Config):
        self.config = config
        self.label: Optional[jnp.ndarray] = None
        self.weight: Optional[jnp.ndarray] = None
        self.num_data = 0

    def init(self, metadata: Metadata) -> None:
        self.num_data = metadata.num_data
        if metadata.label is None:
            log.fatal("Label should not be None for objective %s", self.name)
        self.label = jnp.asarray(metadata.label, dtype=jnp.float32)
        self.weight = (jnp.asarray(metadata.weight, dtype=jnp.float32)
                       if metadata.weight is not None else None)

    # returns (grad, hess), each shaped like score
    def get_gradients(self, score: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        raise NotImplementedError

    # --- checkpoint support (robustness/checkpoint.py) -----------------
    # JSON-serializable python-side per-iteration state (e.g. a host PRNG
    # counter).  Stateless objectives return {}; objectives whose
    # gradients consume host-side randomness MUST round-trip it here or
    # crash resume will not be bit-exact.
    def state_dict(self) -> dict:
        return {}

    def load_state_dict(self, state: dict) -> None:
        pass

    # --- physical-order fused training support -------------------------
    # Names of the row-aligned attribute arrays the gradient computation
    # reads; they ride the tree builder's partition payload so gradients
    # are computed in PHYSICAL row order without a per-iteration scatter
    # (models/boosting.py _setup_fused_phys).  A class opting in defines
    # BOTH ``payload_fields`` and ``gradients_from_payload``; the fused
    # step additionally requires gradients_from_payload in the concrete
    # class's own __dict__, so a subclass overriding get_gradients can
    # never silently inherit the wrong payload formula.
    payload_fields: Optional[Tuple[str, ...]] = None

    def gradient_payload(self) -> Optional[Tuple[jnp.ndarray, ...]]:
        if self.payload_fields is None:
            return None
        return tuple(getattr(self, n) for n in self.payload_fields
                     if getattr(self, n) is not None)

    def boost_from_score(self, class_id: int) -> float:
        return 0.0

    def convert_output(self, raw: jnp.ndarray) -> jnp.ndarray:
        return raw

    def renew_leaf_alpha(self) -> float:
        """Percentile used by RenewTreeOutput (L1-family objectives)."""
        return 0.5

    def renew_weights(self) -> Optional[jnp.ndarray]:
        return self.weight

    def _apply_weight(self, grad, hess):
        if self.weight is not None:
            return grad * self.weight, hess * self.weight
        return grad, hess

    def to_string(self) -> str:
        return self.name


# ---------------------------------------------------------------------------
# Regression family (reference: src/objective/regression_objective.hpp)
# ---------------------------------------------------------------------------
class RegressionL2(ObjectiveFunction):
    name = "regression"
    is_constant_hessian = True

    def __init__(self, config: Config):
        super().__init__(config)
        self.sqrt = bool(config.reg_sqrt)

    def init(self, metadata: Metadata) -> None:
        super().init(metadata)
        if self.sqrt:
            lbl = np.asarray(metadata.label, dtype=np.float64)
            self.label = jnp.asarray(
                np.sign(lbl) * np.sqrt(np.abs(lbl)), dtype=jnp.float32)
        if self.weight is not None:
            self.is_constant_hessian = False

    def get_gradients(self, score):
        grad = score - self.label
        hess = jnp.ones_like(score)
        return self._apply_weight(grad, hess)

    payload_fields = ("label", "weight")

    def gradients_from_payload(self, score, label, weight=None):
        grad = score - label
        hess = jnp.ones_like(score)
        if weight is not None:
            return grad * weight, hess * weight
        return grad, hess

    def boost_from_score(self, class_id):
        lbl = self.label
        if self.weight is not None:
            return float(jnp.sum(lbl * self.weight) / jnp.sum(self.weight))
        return float(jnp.mean(lbl))

    def convert_output(self, raw):
        if self.sqrt:
            return jnp.sign(raw) * raw * raw
        return raw

    def to_string(self):
        return "regression" + (" sqrt" if self.sqrt else "")


class RegressionL1(RegressionL2):
    name = "regression_l1"
    is_renew_tree_output = True

    def get_gradients(self, score):
        diff = score - self.label
        grad = jnp.sign(diff)
        hess = jnp.ones_like(score)
        return self._apply_weight(grad, hess)

    payload_fields = ("label", "weight")

    def gradients_from_payload(self, score, label, weight=None):
        grad = jnp.sign(score - label)
        hess = jnp.ones_like(score)
        if weight is not None:
            return grad * weight, hess * weight
        return grad, hess

    def boost_from_score(self, class_id):
        return _weighted_percentile_host(
            np.asarray(self.label), None if self.weight is None
            else np.asarray(self.weight), 0.5)


class RegressionHuber(RegressionL2):
    name = "huber"

    def __init__(self, config: Config):
        super().__init__(config)
        self.alpha = float(config.alpha)

    def get_gradients(self, score):
        diff = score - self.label
        grad = jnp.where(jnp.abs(diff) <= self.alpha, diff,
                         jnp.sign(diff) * self.alpha)
        hess = jnp.ones_like(score)
        return self._apply_weight(grad, hess)


class RegressionFair(ObjectiveFunction):
    name = "fair"

    def __init__(self, config: Config):
        super().__init__(config)
        self.c = float(config.fair_c)

    def get_gradients(self, score):
        x = score - self.label
        ax = jnp.abs(x)
        grad = self.c * x / (ax + self.c)
        hess = self.c * self.c / ((ax + self.c) ** 2)
        return self._apply_weight(grad, hess)


class RegressionPoisson(ObjectiveFunction):
    name = "poisson"

    def __init__(self, config: Config):
        super().__init__(config)
        self.max_delta_step = float(config.poisson_max_delta_step)

    def init(self, metadata: Metadata) -> None:
        super().init(metadata)
        if float(jnp.min(self.label)) < 0:
            log.fatal("[poisson]: at least one target label is negative")

    def get_gradients(self, score):
        exp_score = jnp.exp(score)
        grad = exp_score - self.label
        hess = exp_score * math.exp(self.max_delta_step)
        return self._apply_weight(grad, hess)

    payload_fields = ("label", "weight")

    def gradients_from_payload(self, score, label, weight=None):
        exp_score = jnp.exp(score)
        grad = exp_score - label
        hess = exp_score * math.exp(self.max_delta_step)
        if weight is not None:
            return grad * weight, hess * weight
        return grad, hess

    def boost_from_score(self, class_id):
        if self.weight is not None:
            mean = float(jnp.sum(self.label * self.weight) / jnp.sum(self.weight))
        else:
            mean = float(jnp.mean(self.label))
        return math.log(max(mean, 1e-20))

    def convert_output(self, raw):
        return jnp.exp(raw)


class RegressionQuantile(ObjectiveFunction):
    name = "quantile"
    is_renew_tree_output = True

    def __init__(self, config: Config):
        super().__init__(config)
        self.alpha = float(config.alpha)

    def get_gradients(self, score):
        delta = score - self.label
        grad = jnp.where(delta >= 0, 1.0 - self.alpha, -self.alpha)
        hess = jnp.ones_like(score)
        return self._apply_weight(grad, hess)

    payload_fields = ("label", "weight")

    def gradients_from_payload(self, score, label, weight=None):
        delta = score - label
        grad = jnp.where(delta >= 0, 1.0 - self.alpha, -self.alpha)
        hess = jnp.ones_like(score)
        if weight is not None:
            return grad * weight, hess * weight
        return grad, hess

    def boost_from_score(self, class_id):
        return _weighted_percentile_host(
            np.asarray(self.label), None if self.weight is None
            else np.asarray(self.weight), self.alpha)

    def renew_leaf_alpha(self):
        return self.alpha


class RegressionMAPE(ObjectiveFunction):
    name = "mape"
    is_renew_tree_output = True

    def init(self, metadata: Metadata) -> None:
        super().init(metadata)
        lw = 1.0 / jnp.maximum(1.0, jnp.abs(self.label))
        if self.weight is not None:
            lw = lw * self.weight
        self.label_weight = lw

    def get_gradients(self, score):
        diff = score - self.label
        grad = jnp.sign(diff) * self.label_weight
        hess = self.weight if self.weight is not None else jnp.ones_like(score)
        return grad, hess

    payload_fields = ("label", "weight")

    def gradients_from_payload(self, score, label, weight=None):
        lw = 1.0 / jnp.maximum(1.0, jnp.abs(label))
        if weight is not None:
            lw = lw * weight
        grad = jnp.sign(score - label) * lw
        hess = weight if weight is not None else jnp.ones_like(score)
        return grad, hess

    def boost_from_score(self, class_id):
        return _weighted_percentile_host(
            np.asarray(self.label), np.asarray(self.label_weight), 0.5)

    def renew_weights(self):
        return self.label_weight

    def renew_weights_from_payload(self, label, weight):
        lw = 1.0 / jnp.maximum(1.0, jnp.abs(label))
        if weight is not None:
            lw = lw * weight
        return lw


class RegressionGamma(RegressionPoisson):
    name = "gamma"

    def get_gradients(self, score):
        exp_neg = jnp.exp(-score)
        grad = 1.0 - self.label * exp_neg
        hess = self.label * exp_neg
        return self._apply_weight(grad, hess)

    def gradients_from_payload(self, score, label, weight=None):
        exp_neg = jnp.exp(-score)
        grad = 1.0 - label * exp_neg
        hess = label * exp_neg
        if weight is not None:
            return grad * weight, hess * weight
        return grad, hess


class RegressionTweedie(RegressionPoisson):
    name = "tweedie"

    def __init__(self, config: Config):
        super().__init__(config)
        self.rho = float(config.tweedie_variance_power)

    def get_gradients(self, score):
        e1 = jnp.exp((1.0 - self.rho) * score)
        e2 = jnp.exp((2.0 - self.rho) * score)
        grad = -self.label * e1 + e2
        hess = -self.label * (1.0 - self.rho) * e1 + (2.0 - self.rho) * e2
        return self._apply_weight(grad, hess)

    def gradients_from_payload(self, score, label, weight=None):
        e1 = jnp.exp((1.0 - self.rho) * score)
        e2 = jnp.exp((2.0 - self.rho) * score)
        grad = -label * e1 + e2
        hess = -label * (1.0 - self.rho) * e1 + (2.0 - self.rho) * e2
        if weight is not None:
            return grad * weight, hess * weight
        return grad, hess


# ---------------------------------------------------------------------------
# Binary (reference: src/objective/binary_objective.hpp)
# ---------------------------------------------------------------------------
class BinaryLogloss(ObjectiveFunction):
    name = "binary"

    def __init__(self, config: Config, is_pos=None):
        super().__init__(config)
        self.sigmoid = float(config.sigmoid)
        self.is_unbalance = bool(config.is_unbalance)
        self.scale_pos_weight = float(config.scale_pos_weight)
        if self.is_unbalance and self.scale_pos_weight != 1.0:
            log.fatal("Cannot set is_unbalance and scale_pos_weight at the same time")
        self.need_train = True
        self._is_pos = is_pos or (lambda lbl: lbl > 0)

    def init(self, metadata: Metadata) -> None:
        super().init(metadata)
        pos = self._is_pos(np.asarray(metadata.label))
        # class counts are GLOBAL under multi-process training so the
        # unbalance weights agree on every rank (binary_objective.hpp:75-77)
        cnt_pos, cnt_neg = _dist_sums(int(pos.sum()),
                                      self.num_data - int(pos.sum()))
        self.need_train = cnt_pos > 0 and cnt_neg > 0
        if not self.need_train:
            log.warning("Contains only one class")
        w_neg, w_pos = 1.0, 1.0
        if self.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                w_neg = cnt_pos / cnt_neg
            else:
                w_pos = cnt_neg / cnt_pos
        w_pos *= self.scale_pos_weight
        self.cnt_pos, self.cnt_neg = cnt_pos, cnt_neg
        # scalar class weights kept for the fused-multiclass OVA path,
        # which reconstructs per-row weights from the payload label row
        self._w_pos, self._w_neg = float(w_pos), float(w_neg)
        self.sign_label = jnp.where(jnp.asarray(pos), 1.0, -1.0)
        self.label_weight = jnp.where(jnp.asarray(pos), w_pos, w_neg)
        # sign and combined weight packed into ONE payload row (the
        # partition payload is compaction-cost-proportional to its row
        # count): sign(signed_lw) is the label sign, |signed_lw| the
        # effective weight.  Zero-weight (and pad) rows decode sign +1
        # and weight 0, which zeroes grad and hess.
        lw = (self.label_weight * self.weight
              if self.weight is not None else self.label_weight)
        self.signed_label_weight = self.sign_label * lw

    payload_fields = ("signed_label_weight",)

    def gradients_from_payload(self, score, signed_label_weight):
        sign_label = jnp.where(signed_label_weight < 0, -1.0, 1.0)
        lw = jnp.abs(signed_label_weight)
        response = -sign_label * self.sigmoid / (
            1.0 + jnp.exp(sign_label * self.sigmoid * score))
        abs_response = jnp.abs(response)
        grad = response * lw
        hess = abs_response * (self.sigmoid - abs_response) * lw
        if not self.need_train:
            return jnp.zeros_like(grad), jnp.zeros_like(hess)
        return grad, hess

    def get_gradients(self, score):
        # reference: binary_objective.hpp:105-137
        response = -self.sign_label * self.sigmoid / (
            1.0 + jnp.exp(self.sign_label * self.sigmoid * score))
        abs_response = jnp.abs(response)
        lw = self.label_weight
        if self.weight is not None:
            lw = lw * self.weight
        grad = response * lw
        hess = abs_response * (self.sigmoid - abs_response) * lw
        if not self.need_train:
            grad = jnp.zeros_like(grad)
            hess = jnp.zeros_like(hess)
        return grad, hess

    def boost_from_score(self, class_id):
        # suml/sumw are summed across ranks before the ratio
        # (binary_objective.hpp:155-157 GlobalSyncUpBySum)
        pos = (self.sign_label > 0).astype(jnp.float32)
        if self.weight is not None:
            suml = float(jnp.sum(pos * self.weight))
            sumw = float(jnp.sum(self.weight))
        else:
            suml = float(jnp.sum(pos))
            sumw = float(self.num_data)
        suml, sumw = _dist_sums(suml, sumw)
        pavg = suml / max(sumw, K_EPSILON)
        pavg = min(max(pavg, K_EPSILON), 1.0 - K_EPSILON)
        init_score = math.log(pavg / (1.0 - pavg)) / self.sigmoid
        log.info("[binary:BoostFromScore]: pavg=%f -> initscore=%f", pavg, init_score)
        return init_score

    def convert_output(self, raw):
        return 1.0 / (1.0 + jnp.exp(-self.sigmoid * raw))

    def to_string(self):
        return f"binary sigmoid:{self.sigmoid:g}"


# ---------------------------------------------------------------------------
# Multiclass (reference: src/objective/multiclass_objective.hpp)
# ---------------------------------------------------------------------------
class MulticlassSoftmax(ObjectiveFunction):
    name = "multiclass"

    def __init__(self, config: Config):
        super().__init__(config)
        self.num_class = int(config.num_class)
        self.num_model_per_iteration = self.num_class
        self.factor = self.num_class / max(self.num_class - 1, 1)

    def init(self, metadata: Metadata) -> None:
        super().init(metadata)
        lbl = np.asarray(metadata.label).astype(np.int32)
        if lbl.min() < 0 or lbl.max() >= self.num_class:
            log.fatal("Label must be in [0, %d), but found %d in label",
                      self.num_class, int(lbl.min() if lbl.min() < 0 else lbl.max()))
        self.label_int = jnp.asarray(lbl)
        self.onehot = jax.nn.one_hot(self.label_int, self.num_class, dtype=jnp.float32)
        # weighted class counts, summed across ranks with the total weight
        # (multiclass_objective.hpp:58-83 incl. the :75-78 GlobalSyncUpBySum)
        if metadata.weight is not None:
            w = np.asarray(metadata.weight, dtype=np.float64)
            counts = np.bincount(lbl, weights=w, minlength=self.num_class)
            sum_weight = float(w.sum())
        else:
            counts = np.bincount(lbl, minlength=self.num_class).astype(np.float64)
            sum_weight = float(len(lbl))
        synced = _dist_sums(*counts, sum_weight)
        counts = np.asarray(synced[:-1], dtype=np.float64)
        sum_weight = synced[-1]
        self.class_init_probs = counts / max(sum_weight, K_EPSILON)

    def get_gradients(self, score):
        # score: (N, K)
        p = jax.nn.softmax(score, axis=1)
        grad = p - self.onehot
        hess = self.factor * p * (1.0 - p)
        if self.weight is not None:
            grad = grad * self.weight[:, None]
            hess = hess * self.weight[:, None]
        return grad, hess

    def boost_from_score(self, class_id):
        return math.log(max(K_EPSILON, self.class_init_probs[class_id]))

    def fused_prob_snapshot(self, score_rows):
        """(K, N_pad) softmax of the pre-iteration score rows.

        Softmax couples the classes, and the reference computes ALL K
        gradients from the pre-iteration scores before any class tree
        builds (gbdt.cpp Boosting -> GetGradients once per iteration),
        so the fused iteration snapshots the probabilities first."""
        m = jnp.max(score_rows, axis=0)
        e = jnp.exp(score_rows - m)
        return e / jnp.sum(e, axis=0)

    def fused_class_gradients_from_prob(self, k, p_k, label_row,
                                        weight_row):
        """Class-k gradients from the snapshotted probability row
        (multiclass_objective.hpp:86-130 restricted to one class)."""
        y = (label_row == k).astype(jnp.float32)
        grad = p_k - y
        hess = self.factor * p_k * (1.0 - p_k)
        if weight_row is not None:
            grad = grad * weight_row
            hess = hess * weight_row
        return grad, hess

    def convert_output(self, raw):
        return jax.nn.softmax(raw, axis=-1)

    def to_string(self):
        return f"multiclass num_class:{self.num_class}"


class MulticlassOVA(ObjectiveFunction):
    name = "multiclassova"

    def __init__(self, config: Config):
        super().__init__(config)
        self.num_class = int(config.num_class)
        self.num_model_per_iteration = self.num_class
        self.sigmoid = float(config.sigmoid)
        self.binaries = [BinaryLogloss(config, is_pos=_make_is_pos(k))
                         for k in range(self.num_class)]

    def init(self, metadata: Metadata) -> None:
        super().init(metadata)
        for b in self.binaries:
            b.init(metadata)

    def get_gradients(self, score):
        grads, hesses = [], []
        for k in range(self.num_class):
            g, h = self.binaries[k].get_gradients(score[:, k])
            grads.append(g)
            hesses.append(h)
        return jnp.stack(grads, axis=1), jnp.stack(hesses, axis=1)

    def boost_from_score(self, class_id):
        return self.binaries[class_id].boost_from_score(0)

    def fused_class_gradients(self, k, score_rows, label_row, weight_row):
        """Per-class one-vs-all binary gradients from payload rows; the
        class weights are host scalars from the binary init
        (binary_objective.hpp:105-137 with is_pos = label == k)."""
        b = self.binaries[k]
        is_pos = label_row == k
        sign = jnp.where(is_pos, 1.0, -1.0)
        lw = jnp.where(is_pos, b._w_pos, b._w_neg)
        if weight_row is not None:
            lw = lw * weight_row
        response = -sign * b.sigmoid / (
            1.0 + jnp.exp(sign * b.sigmoid * score_rows[k]))
        abs_response = jnp.abs(response)
        grad = response * lw
        hess = abs_response * (b.sigmoid - abs_response) * lw
        if not b.need_train:
            return jnp.zeros_like(grad), jnp.zeros_like(hess)
        return grad, hess

    def convert_output(self, raw):
        return 1.0 / (1.0 + jnp.exp(-self.sigmoid * raw))

    def to_string(self):
        return f"multiclassova num_class:{self.num_class} sigmoid:{self.sigmoid:g}"


def _make_is_pos(k):
    return lambda lbl: lbl == k


# ---------------------------------------------------------------------------
# Cross-entropy (reference: src/objective/xentropy_objective.hpp)
# ---------------------------------------------------------------------------
class CrossEntropy(ObjectiveFunction):
    name = "cross_entropy"

    def init(self, metadata: Metadata) -> None:
        super().init(metadata)
        lbl = np.asarray(metadata.label)
        if lbl.min() < 0 or lbl.max() > 1:
            log.fatal("[cross_entropy]: label must be in interval [0, 1]")

    def get_gradients(self, score):
        z = jax.nn.sigmoid(score)
        grad = z - self.label
        hess = z * (1.0 - z)
        return self._apply_weight(grad, hess)

    payload_fields = ("label", "weight")

    def gradients_from_payload(self, score, label, weight=None):
        z = jax.nn.sigmoid(score)
        grad = z - label
        hess = z * (1.0 - z)
        if weight is not None:
            return grad * weight, hess * weight
        return grad, hess

    def boost_from_score(self, class_id):
        if self.weight is not None:
            pavg = float(jnp.sum(self.label * self.weight) / jnp.sum(self.weight))
        else:
            pavg = float(jnp.mean(self.label))
        pavg = min(max(pavg, K_EPSILON), 1.0 - K_EPSILON)
        return math.log(pavg / (1.0 - pavg))

    def convert_output(self, raw):
        return jax.nn.sigmoid(raw)

    def to_string(self):
        return "cross_entropy"


class CrossEntropyLambda(ObjectiveFunction):
    name = "cross_entropy_lambda"

    def init(self, metadata: Metadata) -> None:
        super().init(metadata)

    def get_gradients(self, score):
        # reference: xentropy_objective.hpp:223-252
        w = self.weight if self.weight is not None else jnp.ones_like(score)
        y = self.label
        epf = jnp.exp(score)
        hhat = jnp.log1p(epf)
        z = 1.0 - jnp.exp(-w * hhat)
        enf = 1.0 / epf
        grad = (1.0 - y / jnp.maximum(z, K_EPSILON)) * w / (1.0 + enf)
        c = 1.0 / jnp.maximum(1.0 - z, K_EPSILON)
        d = 1.0 + epf
        a = w * epf / (d * d)
        d2 = c - 1.0
        b = (c / jnp.maximum(d2 * d2, K_EPSILON)) * (1.0 + w * epf - c)
        hess = a * (1.0 + y * b)
        return grad, hess

    def boost_from_score(self, class_id):
        if self.weight is not None:
            pavg = float(jnp.sum(self.label * self.weight) / jnp.sum(self.weight))
        else:
            pavg = float(jnp.mean(self.label))
        pavg = min(max(pavg, K_EPSILON), 1.0 - K_EPSILON)
        return math.log(pavg / (1.0 - pavg))

    def convert_output(self, raw):
        return jnp.log1p(jnp.exp(raw))

    def to_string(self):
        return "cross_entropy_lambda"


# ---------------------------------------------------------------------------
# Ranking (reference: src/objective/rank_objective.hpp)
# ---------------------------------------------------------------------------
def _default_label_gain(max_label: int = 31) -> np.ndarray:
    return (2.0 ** np.arange(max_label + 1)) - 1.0


class LambdarankNDCG(ObjectiveFunction):
    """LambdaRank with NDCG weighting (reference: rank_objective.hpp:132-300).

    The ragged per-query pairwise loops become padded pairwise matrices:
    queries are bucketed by padded size (powers of two) and processed as
    batched (Q_b, P, P) elementwise computations on the VPU.
    """

    name = "lambdarank"

    def __init__(self, config: Config):
        super().__init__(config)
        self.sigmoid = float(config.sigmoid)
        self.norm = bool(config.lambdarank_norm)
        self.truncation_level = int(config.lambdarank_truncation_level)
        if config.label_gain:
            self.label_gain = np.asarray(
                [float(x) for x in str(config.label_gain).split(",")])
        else:
            self.label_gain = _default_label_gain()

    def init(self, metadata: Metadata) -> None:
        super().init(metadata)
        if metadata.query_boundaries is None:
            log.fatal("Ranking tasks require query information")
        qb = np.asarray(metadata.query_boundaries)
        self.query_boundaries = qb
        sizes = np.diff(qb)
        lbl = np.asarray(metadata.label).astype(np.int32)
        if lbl.max() >= len(self.label_gain):
            log.fatal("Label %d exceeds label_gain size %d", int(lbl.max()),
                      len(self.label_gain))
        # per-query inverse max DCG at the truncation level
        # (reference: DCGCalculator::CalMaxDCGAtK, src/metric/dcg_calculator.cpp)
        inv_max_dcg = np.zeros(len(sizes), dtype=np.float64)
        gains = self.label_gain[lbl]
        for q in range(len(sizes)):
            g = np.sort(gains[qb[q]:qb[q + 1]])[::-1][: self.truncation_level]
            dcg = np.sum(g / np.log2(np.arange(2, len(g) + 2)))
            inv_max_dcg[q] = 1.0 / dcg if dcg > 0 else 0.0
        # bucket queries by padded size
        buckets: Dict[int, List[int]] = {}
        for q, sz in enumerate(sizes):
            p = 1
            while p < sz:
                p <<= 1
            buckets.setdefault(max(p, 2), []).append(q)
        self.buckets = []
        for p, qs in sorted(buckets.items()):
            doc_idx = np.full((len(qs), p), -1, dtype=np.int32)
            for row, q in enumerate(qs):
                n = sizes[q]
                doc_idx[row, :n] = np.arange(qb[q], qb[q + 1])
            self.buckets.append({
                "P": p,
                "doc_idx": jnp.asarray(doc_idx),
                "inv_max_dcg": jnp.asarray(inv_max_dcg[qs].astype(np.float32)),
            })
        self.label_gain_dev = jnp.asarray(self.label_gain.astype(np.float32))
        self.label_dev = jnp.asarray(lbl)
        self._grad_fns = {}
        # position bias state (reference: rank_objective.hpp:43-56)
        self.positions = None
        if metadata.positions is not None:
            # per-iteration bias updates mutate python state: not fusable
            self.is_jit_safe = False
            self.positions = jnp.asarray(metadata.positions)
            self.pos_biases = jnp.zeros(len(metadata.position_ids),
                                        dtype=jnp.float32)
            self.position_bias_regularization = float(
                self.config.lambdarank_position_bias_regularization)
            self.bias_learning_rate = float(self.config.learning_rate)

    def _bucket_grad_fn(self, P: int):
        if P in self._grad_fns:
            return self._grad_fns[P]
        sigmoid = self.sigmoid
        norm = self.norm
        trunc = self.truncation_level

        def one_query(doc_idx, inv_max_dcg, score_all):
            valid = doc_idx >= 0
            idx = jnp.maximum(doc_idx, 0)
            score = jnp.where(valid, score_all[idx], -jnp.inf)
            lbl = jnp.where(valid, self.label_dev[idx], -1)
            order = jnp.argsort(-score, stable=True)
            ss = score[order]
            sl = lbl[order]
            svalid = valid[order]
            gains = self.label_gain_dev[jnp.maximum(sl, 0)]
            pos = jnp.arange(P)
            discount = 1.0 / jnp.log2(2.0 + pos)
            # pairwise (i, j) in sorted order
            ii = pos[:, None]
            jj = pos[None, :]
            upper = (ii < jj) & svalid[:, None] & svalid[None, :] & (ii < trunc)
            sym = upper | upper.T
            li = sl[:, None]
            lj = sl[None, :]
            sym &= li != lj
            gi = gains[:, None]
            gj = gains[None, :]
            si = ss[:, None]
            sj = ss[None, :]
            di = discount[:, None]
            dj = discount[None, :]
            dcg_gap = jnp.abs(gi - gj)
            paired_discount = jnp.abs(di - dj)
            delta_ndcg = dcg_gap * paired_discount * inv_max_dcg
            i_is_high = li > lj
            delta_score = jnp.where(i_is_high, si - sj, sj - si)
            if norm:
                best = ss[0]
                worst_i = jnp.maximum(jnp.sum(svalid.astype(jnp.int32)) - 1, 0)
                worst = ss[worst_i]
                scale = jnp.where(best != worst,
                                  1.0 / (0.01 + jnp.abs(delta_score)), 1.0)
                delta_ndcg = delta_ndcg * scale
            p_lambda0 = 1.0 / (1.0 + jnp.exp(sigmoid * delta_score))
            p_hess0 = p_lambda0 * (1.0 - p_lambda0)
            p_lambda = -sigmoid * delta_ndcg * p_lambda0
            p_hess = sigmoid * sigmoid * delta_ndcg * p_hess0
            sign_i = jnp.where(i_is_high, 1.0, -1.0)
            lam_sorted = jnp.sum(jnp.where(sym, sign_i * p_lambda, 0.0), axis=1)
            hes_sorted = jnp.sum(jnp.where(sym, p_hess, 0.0), axis=1)
            sum_lambdas = -jnp.sum(jnp.where(sym, p_lambda, 0.0))
            if norm:
                nf = jnp.where(sum_lambdas > 0,
                               jnp.log2(1.0 + sum_lambdas) / jnp.maximum(sum_lambdas, K_EPSILON),
                               1.0)
                lam_sorted = lam_sorted * nf
                hes_sorted = hes_sorted * nf
            # unsort back to query-document order
            lam = jnp.zeros(P).at[order].set(lam_sorted)
            hes = jnp.zeros(P).at[order].set(hes_sorted)
            return lam, hes

        fn = jax.vmap(one_query, in_axes=(0, 0, None))
        self._grad_fns[P] = fn
        return fn

    def get_gradients(self, score):
        # unbiased lambdarank: scores are adjusted by the learned per-position
        # bias factors before lambda computation (reference:
        # rank_objective.hpp:66-71)
        if self.positions is not None:
            score = score + self.pos_biases[self.positions]
        grad = jnp.zeros_like(score)
        hess = jnp.zeros_like(score)
        for b in self.buckets:
            fn = self._bucket_grad_fn(b["P"])
            lam, hes = fn(b["doc_idx"], b["inv_max_dcg"], score)
            flat_idx = b["doc_idx"].reshape(-1)
            grad = grad.at[flat_idx].add(lam.reshape(-1), mode="drop")
            hess = hess.at[flat_idx].add(hes.reshape(-1), mode="drop")
        if self.positions is not None:
            self._update_position_bias(grad, hess)
        return grad, hess

    def _update_position_bias(self, grad, hess):
        """Newton-Raphson step on the per-position bias factors with L2
        regularization (reference: UpdatePositionBiasFactors,
        rank_objective.hpp:290-328)."""
        npos = len(self.pos_biases)
        seg = self.positions
        first = jnp.zeros(npos).at[seg].add(-grad)
        second = jnp.zeros(npos).at[seg].add(-hess)
        counts = jnp.zeros(npos).at[seg].add(1.0)
        reg = self.position_bias_regularization
        first = first - self.pos_biases * reg * counts
        second = second - reg * counts
        self.pos_biases = self.pos_biases + \
            self.bias_learning_rate * first / (jnp.abs(second) + 0.001)

    def to_string(self):
        return "lambdarank"


class RankXENDCG(ObjectiveFunction):
    """XE-NDCG ranking objective (reference: rank_objective.hpp RankXENDCG:303).

    Per query: gradients of a softmax cross-entropy against gumbel-perturbed
    relevance targets.
    """

    name = "rank_xendcg"
    is_jit_safe = False   # fresh gumbel noise (python-side PRNG state) per iter

    def __init__(self, config: Config):
        super().__init__(config)
        self.seed = int(config.objective_seed)
        self._iter = 0

    def state_dict(self) -> dict:
        # the gumbel-noise key is fold_in(seed, _iter): the counter IS
        # the whole per-iteration RNG state
        return {"iter": int(self._iter)}

    def load_state_dict(self, state: dict) -> None:
        self._iter = int(state.get("iter", self._iter))

    def init(self, metadata: Metadata) -> None:
        super().init(metadata)
        if metadata.query_boundaries is None:
            log.fatal("Ranking tasks require query information")
        qb = np.asarray(metadata.query_boundaries)
        self.query_boundaries = qb
        sizes = np.diff(qb)
        buckets: Dict[int, List[int]] = {}
        for q, sz in enumerate(sizes):
            p = 1
            while p < sz:
                p <<= 1
            buckets.setdefault(max(p, 2), []).append(q)
        self.buckets = []
        for p, qs in sorted(buckets.items()):
            doc_idx = np.full((len(qs), p), -1, dtype=np.int32)
            for row, q in enumerate(qs):
                n = sizes[q]
                doc_idx[row, :n] = np.arange(qb[q], qb[q + 1])
            self.buckets.append({"P": p, "doc_idx": jnp.asarray(doc_idx)})
        self.label_dev = jnp.asarray(np.asarray(metadata.label, dtype=np.float32))

    def get_gradients(self, score):
        # reference: rank_objective.hpp:330-394 (GetGradientsForOneQuery)
        self._iter += 1
        key = jax.random.fold_in(jax.random.PRNGKey(self.seed), self._iter)
        grad = jnp.zeros_like(score)
        hess = jnp.zeros_like(score)
        for bi, b in enumerate(self.buckets):
            P = b["P"]
            doc_idx = b["doc_idx"]
            valid = doc_idx >= 0
            idx = jnp.maximum(doc_idx, 0)
            s = jnp.where(valid, score[idx], -jnp.inf)
            lbl = jnp.where(valid, self.label_dev[idx], 0.0)
            k = jax.random.fold_in(key, bi)
            # gumbel-perturbed relevance -> target distribution "rho"
            eps = jax.random.gumbel(k, shape=s.shape)
            phi = jnp.where(valid, (2.0 ** lbl - 1.0) + eps, -jnp.inf)
            rho_tgt = jax.nn.softmax(phi, axis=1)
            rho_tgt = jnp.where(valid, rho_tgt, 0.0)
            rho = jax.nn.softmax(s, axis=1)
            rho = jnp.where(valid, rho, 0.0)
            # first-order terms of the XE-NDCG gradient
            l1 = rho - rho_tgt
            g = l1
            h = rho * (1.0 - rho)
            flat_idx = doc_idx.reshape(-1)
            grad = grad.at[flat_idx].add(jnp.where(valid, g, 0.0).reshape(-1),
                                         mode="drop")
            hess = hess.at[flat_idx].add(
                jnp.where(valid, jnp.maximum(h, K_EPSILON), 0.0).reshape(-1),
                mode="drop")
        return grad, hess

    def to_string(self):
        return "rank_xendcg"


# ---------------------------------------------------------------------------
def _weighted_percentile_host(values: np.ndarray, weights: Optional[np.ndarray],
                              alpha: float) -> float:
    """Percentile matching the reference PercentileFun / WeightedPercentileFun
    (src/objective/regression_objective.hpp:18-80)."""
    n = len(values)
    if n == 0:
        return 0.0
    if n == 1:
        return float(values[0])
    if weights is None:
        order = np.argsort(values)
        v = values[order]
        float_pos = (n - 1) * alpha
        lo = int(math.floor(float_pos))
        bias = float_pos - lo
        if lo + 1 >= n:
            return float(v[-1])
        return float(v[lo] + (v[lo + 1] - v[lo]) * bias)
    order = np.argsort(values, kind="stable")
    v = values[order]
    w = weights[order].astype(np.float64)
    # reference WeightedPercentileFun (regression_objective.hpp:50-88):
    # upper_bound on the full cumulative weight, then interpolation only
    # when the next point carries weight >= 1
    cdf = np.cumsum(w)
    threshold = alpha * cdf[-1]
    pos = int(np.searchsorted(cdf, threshold, side="right"))
    pos = min(pos, n - 1)
    if pos == 0 or pos == n - 1:
        return float(v[pos])
    v1, v2 = float(v[pos - 1]), float(v[pos])
    if cdf[pos + 1] - cdf[pos] >= 1.0:
        return float((threshold - cdf[pos]) /
                     (cdf[pos + 1] - cdf[pos]) * (v2 - v1) + v1)
    return v2


_OBJECTIVES = {
    "regression": RegressionL2,
    "regression_l1": RegressionL1,
    "huber": RegressionHuber,
    "fair": RegressionFair,
    "poisson": RegressionPoisson,
    "quantile": RegressionQuantile,
    "mape": RegressionMAPE,
    "gamma": RegressionGamma,
    "tweedie": RegressionTweedie,
    "binary": BinaryLogloss,
    "multiclass": MulticlassSoftmax,
    "multiclassova": MulticlassOVA,
    "cross_entropy": CrossEntropy,
    "cross_entropy_lambda": CrossEntropyLambda,
    "lambdarank": LambdarankNDCG,
    "rank_xendcg": RankXENDCG,
}


def create_objective(config: Config) -> Optional[ObjectiveFunction]:
    """reference: ObjectiveFunction::CreateObjectiveFunction
    (src/objective/objective_function.cpp:20)."""
    name = config.objective
    if name in ("none", "custom", ""):
        return None
    cls = _OBJECTIVES.get(name)
    if cls is None:
        log.fatal("Unknown objective type name: %s", name)
    return cls(config)
