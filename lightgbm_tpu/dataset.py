"""Binned dataset resident in TPU HBM.

TPU-native re-design of the reference Dataset / DatasetLoader / Metadata
(src/io/dataset.cpp, src/io/dataset_loader.cpp, include/LightGBM/dataset.h):
host-side NumPy builds the per-feature BinMappers from sampled values
(reference: DatasetLoader::ConstructFromSampleData, dataset_loader.cpp:593),
then the full data matrix is binned into a packed integer tensor that is
uploaded once to device HBM.  Histogram construction consumes this tensor via
MXU one-hot matmuls instead of the reference's per-thread scatter loops.

Feature grouping (EFB, reference dataset.cpp:60-244 FindGroups /
FastFeatureBundling) bundles mutually-exclusive sparse features into shared
columns with bin offsets.
"""

from __future__ import annotations

import itertools
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .config import Config
from .obs import memory as obs_memory
from .obs import telemetry as obs
from .ops.binning import (BIN_CATEGORICAL, BIN_NUMERICAL, MISSING_NAN,
                          MISSING_NONE, MISSING_ZERO, BinMapper)
from .utils import log


def _dataset_memory_arrays(ds):
    """Telemetry memory provider: the packed binned matrix (host) and
    the direct-to-device ingest buffers, when present."""
    out = [ds.binned, getattr(ds, "raw_data", None)]
    di = getattr(ds, "device_ingest", None)
    if di is not None:
        out.extend(v for v in vars(di).values()
                   if getattr(v, "nbytes", None) is not None)
    # a donated/adopted buffer (single-copy residency) stays reachable
    # as a deleted jax Array: it holds no memory, so skip it
    def _alive(a):
        deleted = getattr(a, "is_deleted", None)
        return a is not None and not (deleted is not None and deleted())
    return [a for a in out if _alive(a)]


def _fill_rows_t(dst: np.ndarray, start: int, packed_cols: np.ndarray
                 ) -> None:
    """``dst[start:start+rows] = packed_cols.T`` in cache-sized blocks:
    the naive full transpose-assign streams the whole strided source
    per destination row; 8k-row blocks keep the working set (~G x 8k)
    L2-resident."""
    rows = packed_cols.shape[1]
    blk = 8192
    for s in range(0, rows, blk):
        e = min(s + blk, rows)
        dst[start + s:start + e] = packed_cols[:, s:e].T


def _construct_workers(config) -> int:
    """Host threads for the vectorized construction path: the explicit
    ``num_threads`` param when set, else one per core.  The parallel
    sections are GIL-releasing numpy (searchsorted, copies, sorts), so
    plain threads scale them without changing any result — work is
    split per-feature / per-chunk and merged in deterministic order."""
    nt = int(getattr(config, "num_threads", 0) or 0)
    return nt if nt > 0 else max(1, os.cpu_count() or 1)


class _TextFileSequenceImpl:
    """File-backed text/CSV row reader for streaming construction (the
    concrete body of :class:`lightgbm_tpu.TextFileSequence`, which mixes
    this with the :class:`~lightgbm_tpu.basic.Sequence` protocol — the
    split avoids a dataset<->basic import cycle).

    Indexes line byte-offsets in ONE pass at open (12 bytes of index per
    row), then serves ``__getitem__`` slices by seek+read of exactly the
    requested rows — the raw matrix never materializes in host memory,
    so the PR-17 two-pass sketch construction streams straight off disk
    (first slice of the ROADMAP "Arrow/text readers" remainder).

    Fields parse as float64 via Python ``float`` (empty / NA-ish fields
    -> NaN), so a file round-tripped through ``repr`` is bit-identical
    to the in-memory matrix it came from — the chunk-boundary parity
    test relies on that.
    """

    _NA = frozenset(("", "na", "nan", "n/a", "null", "none", "?"))

    def __init__(self, path: str, delimiter: str = ",",
                 header: Any = "auto", batch_size: int = 4096,
                 usecols: Optional[List[int]] = None):
        self.path = str(path)
        self.delimiter = delimiter
        self.batch_size = int(batch_size)
        self.usecols = list(usecols) if usecols is not None else None
        starts: List[int] = []
        lens: List[int] = []
        off = 0
        first_line = None
        with open(self.path, "rb") as f:
            for line in f:
                if line.strip():
                    if first_line is None:
                        first_line = line
                    starts.append(off)
                    lens.append(len(line))
                off += len(line)
        if header == "auto":
            header = (first_line is not None
                      and not self._parses(first_line))
        if header and starts:
            starts, lens = starts[1:], lens[1:]
        self._starts = np.asarray(starts, dtype=np.int64)
        self._lens = np.asarray(lens, dtype=np.int32)
        if len(self._starts):
            self.ncols = len(self._fields(self._read_block(0, 1)[0]))
        else:
            self.ncols = 0

    # -- parsing --------------------------------------------------------
    def _fields(self, line: bytes) -> List[str]:
        txt = line.decode("utf-8").strip()
        parts = (txt.split(self.delimiter) if self.delimiter != " "
                 else txt.split())
        if self.usecols is not None:
            parts = [parts[c] for c in self.usecols]
        return parts

    def _parses(self, line: bytes) -> bool:
        try:
            self._row(line)
            return True
        except (ValueError, IndexError):
            return False

    def _row(self, line: bytes) -> List[float]:
        return [float("nan") if p.strip().lower() in self._NA else float(p)
                for p in self._fields(line)]

    def _read_block(self, lo: int, hi: int) -> List[bytes]:
        with open(self.path, "rb") as f:
            f.seek(int(self._starts[lo]))
            raw = f.read(int(self._starts[hi - 1] + self._lens[hi - 1]
                             - self._starts[lo]))
        return [ln for ln in raw.split(b"\n") if ln.strip()]

    # -- Sequence protocol ---------------------------------------------
    def __len__(self) -> int:
        return len(self._starts)

    def __getitem__(self, idx):
        n = len(self._starts)
        if isinstance(idx, slice):
            lo, hi, step = idx.indices(n)
            if step != 1:
                raise ValueError("TextFileSequence slices must be "
                                 "contiguous (step 1)")
            if hi <= lo:
                return np.empty((0, self.ncols), dtype=np.float64)
            lines = self._read_block(lo, hi)
            out = np.empty((len(lines), self.ncols), dtype=np.float64)
            for i, ln in enumerate(lines):
                out[i] = self._row(ln)
            return out
        if idx < 0:
            idx += n
        if not 0 <= idx < n:
            raise IndexError(idx)
        return np.asarray(self._row(self._read_block(idx, idx + 1)[0]),
                          dtype=np.float64)

    def read_column(self, col: int) -> np.ndarray:
        """Stream one ORIGINAL-file column (e.g. a label column excluded
        from ``usecols``) in ``batch_size`` row blocks."""
        saved = self.usecols
        self.usecols = [col]
        try:
            out = np.empty((len(self),), dtype=np.float64)
            for lo in range(0, len(self), self.batch_size):
                hi = min(lo + self.batch_size, len(self))
                out[lo:hi] = self[lo:hi][:, 0]
            return out
        finally:
            self.usecols = saved


class Metadata:
    """Per-row side data: label / weight / query groups / init_score.

    reference: include/LightGBM/dataset.h:47-398 (Metadata).
    """

    def __init__(self, num_data: int):
        self.num_data = num_data
        self.label: Optional[np.ndarray] = None
        self.weight: Optional[np.ndarray] = None
        self.query_boundaries: Optional[np.ndarray] = None  # int32 [nq+1]
        self.init_score: Optional[np.ndarray] = None
        self.positions: Optional[np.ndarray] = None         # int32 ids/row
        self.position_ids: Optional[List[str]] = None       # id -> label

    def set_position(self, position) -> None:
        """Per-row presentation positions for unbiased lambdarank
        (reference: Metadata::SetPosition, metadata.cpp; positions factorize
        to compact ids like the `.position` file loader)."""
        if position is None:
            self.positions = None
            self.position_ids = None
            return
        vals = np.asarray(position).reshape(-1)
        if vals.shape[0] != self.num_data:
            log.fatal("Length of position (%d) != num_data (%d)",
                      vals.shape[0], self.num_data)
        # vectorized first-seen factorization (compact ids in order of
        # first appearance, matching the reference's `.position` loader)
        uniq, first, inv = np.unique(vals, return_index=True,
                                     return_inverse=True)
        order = np.argsort(first, kind="stable")
        remap = np.empty(len(uniq), dtype=np.int32)
        remap[order] = np.arange(len(uniq), dtype=np.int32)
        self.positions = remap[inv.reshape(-1)]
        self.position_ids = [str(uniq[o]) for o in order]

    def set_label(self, label) -> None:
        arr = np.asarray(label, dtype=np.float32).reshape(-1)
        if len(arr) != self.num_data:
            log.fatal("Length of label (%d) != num_data (%d)", len(arr), self.num_data)
        self.label = arr

    def set_weight(self, weight) -> None:
        if weight is None:
            self.weight = None
            return
        arr = np.asarray(weight, dtype=np.float32).reshape(-1)
        if len(arr) != self.num_data:
            log.fatal("Length of weight (%d) != num_data (%d)", len(arr), self.num_data)
        self.weight = arr

    def set_group(self, group) -> None:
        """Accepts per-query sizes (like the reference's query counts)."""
        if group is None:
            self.query_boundaries = None
            return
        arr = np.asarray(group, dtype=np.int64).reshape(-1)
        if arr.sum() != self.num_data:
            log.fatal("Sum of query counts (%d) != num_data (%d)", arr.sum(), self.num_data)
        self.query_boundaries = np.concatenate(
            [[0], np.cumsum(arr)]).astype(np.int32)

    def set_init_score(self, init_score) -> None:
        if init_score is None:
            self.init_score = None
            return
        arr = np.asarray(init_score, dtype=np.float64).reshape(-1)
        self.init_score = arr

    @property
    def num_queries(self) -> int:
        return 0 if self.query_boundaries is None else len(self.query_boundaries) - 1


class FeatureGroupInfo:
    """One packed bin column, possibly bundling several exclusive features.

    reference: include/LightGBM/feature_group.h:25 (FeatureGroup).  Bundled
    features occupy disjoint bin ranges [bin_offset[i], bin_offset[i+1]) of the
    shared column; bin 0 is the shared "all-default" bin.
    """

    def __init__(self, feature_indices: List[int], num_total_bin: int,
                 bin_offsets: List[int]):
        self.feature_indices = feature_indices
        self.num_total_bin = num_total_bin
        self.bin_offsets = bin_offsets  # per sub-feature start bin


class BinnedDataset:
    """The training matrix in binned form (reference: dataset.h:486 Dataset)."""

    def __init__(self, config: Config):
        self.config = config
        self.num_data: int = 0
        self.num_total_features: int = 0
        self.feature_names: List[str] = []
        self.bin_mappers: List[BinMapper] = []       # per original feature
        self.used_features: List[int] = []           # original idx of non-trivial
        self.groups: List[FeatureGroupInfo] = []
        self.binned: Optional[np.ndarray] = None     # (num_data, num_groups) int
        self.metadata: Optional[Metadata] = None
        self.monotone_constraints: Optional[List[int]] = None
        self.raw_data: Optional[np.ndarray] = None   # retained for linear trees
        self._device_cache: Dict[str, Any] = {}
        # construction path (ops/construct.py, construct_device param):
        # _vec = vectorized bin-finding/binning, _ingest_ok = stream the
        # packed chunks into the learner's (G, N_pad) device layout,
        # _keep_host = materialize the row-major host binned matrix
        self._vec: bool = False
        self._ingest_ok: bool = False
        self._keep_host: bool = True
        self._batched = None                         # cached BatchedMapper
        self.device_ingest = None                    # ops.construct.DeviceIngest
        # data-health reference profile (obs/digest.py), captured lazily
        # at construction when health != off and persisted with models
        self._health_profile = None

    # jitted device buffers and the padded mapper tables are neither
    # picklable nor worth shipping; a host-binned-free dataset
    # materializes its matrix back first so no data is lost
    def __getstate__(self):
        st = dict(self.__dict__)
        if st.get("binned") is None and st.get("device_ingest") is not None:
            st["binned"] = self.device_ingest.host_binned()
        st["device_ingest"] = None
        st["_batched"] = None
        return st

    def batched_mapper(self):
        """The padded-table batched values->bins mapper over all used
        features (built once, reused by binning / bin_matrix)."""
        if self._batched is None:
            from .ops.construct import BatchedMapper
            self._batched = BatchedMapper(self.bin_mappers,
                                          self.used_features)
        return self._batched

    def reference_profile(self):
        """The data-health reference profile of THIS dataset's rows
        (obs/digest.py): per-feature bin occupancy, missing/zero rates
        and categorical cardinalities, computed with one reduction over
        the packed bin matrix — on device (one sync) when only the
        ingest buffer holds the data, on host otherwise.  Cached; None
        when no binned data exists."""
        if self._health_profile is not None:
            return self._health_profile
        from .obs import digest as _digest
        with obs.span("dataset.profile", rows=self.num_data):
            if self.binned is not None:
                counts = _digest.bin_counts_host(self.binned,
                                                 self.max_group_bins)
            elif self.device_ingest is not None:
                di = self.device_ingest
                # live_buffer: recovers the pristine layout if the fused
                # trainer adopted the buffer (single-copy residency);
                # [:G] drops carrier sublane-pad rows
                snap = _digest.snapshot_device(
                    di.live_buffer()[:di.G], self.max_group_bins,
                    transposed=True, pad_cols=di.n_pad - di.N)
                counts = snap["group_counts"]
            else:
                return None
            self._health_profile = _digest.build_reference_profile(
                self, counts)
        return self._health_profile

    def host_binned(self) -> Optional[np.ndarray]:
        """The row-major (num_data, num_groups) host bin matrix,
        materialized from the device ingest buffer when the host copy
        was freed (construct_device=on / free_host_binned)."""
        if self.binned is not None:
            return self.binned
        if self.device_ingest is not None:
            return self.device_ingest.host_binned()
        return None

    # -- construction ---------------------------------------------------
    @staticmethod
    def from_matrix(data: np.ndarray, config: Config,
                    label=None, weight=None, group=None, init_score=None,
                    feature_names: Optional[List[str]] = None,
                    categorical_features: Optional[Sequence[int]] = None,
                    reference: Optional["BinnedDataset"] = None,
                    position=None) -> "BinnedDataset":
        data = np.asarray(data)
        if data.ndim != 2:
            log.fatal("Data must be 2-dimensional")
        obs.configure_from_config(config)
        with obs.span("dataset.construct", rows=int(data.shape[0]),
                      features=int(data.shape[1])):
            return BinnedDataset._from_matrix_impl(
                data, config, label, weight, group, init_score,
                feature_names, categorical_features, reference, position)

    @staticmethod
    def _from_matrix_impl(data, config, label, weight, group, init_score,
                          feature_names, categorical_features, reference,
                          position) -> "BinnedDataset":
        ds = BinnedDataset(config)
        obs_memory.register("dataset.binned", ds, _dataset_memory_arrays)
        ds._resolve_construct_mode(is_reference=reference is not None)
        ds.num_data, ds.num_total_features = data.shape
        ds.feature_names = feature_names or [
            f"Column_{i}" for i in range(ds.num_total_features)]
        ds.metadata = Metadata(ds.num_data)
        if label is not None:
            ds.metadata.set_label(label)
        ds.metadata.set_weight(weight)
        ds.metadata.set_group(group)
        ds.metadata.set_init_score(init_score)
        ds.metadata.set_position(position)

        if reference is not None:
            # validation data: reuse the training mappers & grouping
            # (reference: dataset_loader.cpp LoadFromFileAlignWithOtherDataset:299)
            ds.bin_mappers = reference.bin_mappers
            ds.used_features = reference.used_features
            ds.groups = reference.groups
            ds.feature_names = reference.feature_names
            ds._bin_data(data)
            if config.linear_tree:
                ds.raw_data = np.ascontiguousarray(data, dtype=np.float32)
            return ds

        ds._construct_mappers(data, categorical_features or [])
        ds._build_groups()
        ds._bin_data(data)
        if config.linear_tree:
            ds.raw_data = np.ascontiguousarray(data, dtype=np.float32)
        # data-health reference profile, captured while the binned data
        # is guaranteed fresh (obs/health.py; persisted with the model)
        from .obs import health as obs_health
        obs_health.configure_from_config(config)
        if obs_health.enabled():
            ds.reference_profile()
        return ds

    @staticmethod
    def from_sequences(seqs, config: Config, label=None, weight=None,
                       group=None, init_score=None,
                       feature_names: Optional[List[str]] = None,
                       categorical_features: Optional[Sequence[int]] = None,
                       position=None,
                       reference: Optional["BinnedDataset"] = None
                       ) -> "BinnedDataset":
        """Streaming construction from chunk-accessible sequences
        (reference: the Sequence ABC path, python-package basic.py:896 +
        LGBM_DatasetCreateFromSampledColumn/PushRows in c_api.cpp): bin
        mappers and feature groups are built from a row SAMPLE, then each
        sequence is binned chunk by chunk — the full raw matrix is never
        materialized."""
        if not isinstance(seqs, (list, tuple)):
            seqs = [seqs]
        lens = [len(s) for s in seqs]
        total = int(sum(lens))
        if total == 0:
            log.fatal("Cannot construct a Dataset from empty sequences")
        first_nonempty = next(s for s, ln in zip(seqs, lens) if ln > 0)
        probe = np.asarray(first_nonempty[0:1], dtype=np.float64)
        F = probe.reshape(1, -1).shape[1]
        ds = BinnedDataset(config)
        obs.configure_from_config(config)
        obs_memory.register("dataset.binned", ds, _dataset_memory_arrays)
        ds._resolve_construct_mode(is_reference=reference is not None)
        ds.num_data = total
        ds.num_total_features = F
        ds.feature_names = feature_names or [f"Column_{i}" for i in range(F)]
        ds.metadata = Metadata(total)
        if label is not None:
            ds.metadata.set_label(label)
        ds.metadata.set_weight(weight)
        ds.metadata.set_group(group)
        ds.metadata.set_init_score(init_score)
        ds.metadata.set_position(position)

        mode = None
        if reference is not None:
            # validation data: reuse the training mappers & grouping so bin
            # ids live in the SAME space (reference:
            # LoadFromFileAlignWithOtherDataset, dataset_loader.cpp:299)
            ds.bin_mappers = reference.bin_mappers
            ds.used_features = reference.used_features
            ds.groups = reference.groups
            ds.feature_names = reference.feature_names
        else:
            cfg = config
            from .ops.sketch import resolve_bin_mode
            from .parallel import network as _net
            mode = resolve_bin_mode(cfg, total)
            sample_cnt = min(total, cfg.bin_construct_sample_cnt)
            rng = np.random.RandomState(cfg.data_random_seed)
            idx = np.sort(rng.choice(total, size=sample_cnt, replace=False)) \
                if sample_cnt < total else np.arange(total)
            if mode == "sketch":
                # pass 1 of 2 (out-of-core): fold every chunk into the
                # mergeable per-feature sketches; the SAME rng-chosen
                # row sample the exact path would block-fetch is
                # gathered chunk-by-chunk for the EFB conflict graph,
                # so the bundling decision — and its rng consumption —
                # is identical across modes
                from .ops.sketch import SketchSet
                sset = SketchSet(F, cfg.sketch_k)
                want_sample = bool(cfg.enable_bundle) \
                    and _net.num_machines() <= 1
                # idx is sorted and chunks arrive in row order, so the
                # sample rows land contiguously: fill a preallocated
                # matrix instead of concatenating parts (a parts list
                # would hold 2x the sample at the concat)
                sample = np.empty((len(idx) if want_sample else 0, F),
                                  dtype=np.float64)
                w = 0
                for start, chunk in BinnedDataset._iter_seq_chunks(seqs):
                    sset.update_chunk(chunk)
                    if want_sample:
                        sel = idx[(idx >= start)
                                  & (idx < start + len(chunk))] - start
                        if len(sel):
                            sample[w:w + len(sel)] = chunk[sel]
                            w += len(sel)
                sample = sample[:w]
                ds._construct_mappers_from_sketches(
                    sset, categorical_features or [])
            else:
                # sample rows across all sequences for binning; contiguous
                # index runs are fetched through the slice protocol in
                # blocks so disk-backed sequences see few large reads, not
                # one per row
                sample_rows = []
                offset = 0
                for s, ln in zip(seqs, lens):
                    sel = idx[(idx >= offset) & (idx < offset + ln)] - offset
                    i = 0
                    while i < len(sel):
                        j = i
                        while j + 1 < len(sel) and sel[j + 1] == sel[j] + 1:
                            j += 1
                        block = np.asarray(s[int(sel[i]):int(sel[j]) + 1],
                                           dtype=np.float64)
                        sample_rows.append(block.reshape(-1, F))
                        i = j + 1
                    offset += ln
                sample = np.concatenate(sample_rows, axis=0)
                ds._construct_mappers_from_sample(sample,
                                                  categorical_features or [])
            ds._build_groups()
            # resolve any pending sparse bundling with the SAMPLE columns
            # (skip the binning pass entirely when nothing is pending)
            if getattr(ds, "_pending_sparse", None):
                if ds._vec and ds.used_features:
                    # map the sample in row blocks: the used-features
                    # fancy index copies its input, so a one-shot call
                    # would hold a second full-f64 sample at peak
                    bm = ds.batched_mapper()
                    parts = [bm.map_chunk(sample[b:b + 65536,
                                                 ds.used_features])
                             for b in range(0, len(sample), 65536)]
                    smat = (np.concatenate(parts, axis=0) if parts else
                            np.empty((0, len(ds.used_features)),
                                     dtype=ds._bin_dtype()))
                    del parts
                    sample_cols = {f: np.asarray(smat[:, i]) for i, f
                                   in enumerate(ds.used_features)}
                else:
                    sample_cols = {
                        f: ds.bin_mappers[f].values_to_bins(sample[:, f])
                        for f in ds.used_features}
                ds._finalize_groups(sample_cols)
                del sample_cols
            else:
                ds._finalize_groups({})
            # the raw sample has served binning + bundling; drop it
            # before the pack pass so it doesn't ride the whole stream
            sample = None

        # stream (pass 2 of 2): bin each chunk, pack, and push it into the
        # host matrix and/or the device ingest buffer — chunk boundaries
        # never change the result (the mapping is per-row;
        # tests/test_construct_device straddles sequence boundaries to
        # prove it)
        dtype = ds._bin_dtype()
        ingest = ds._make_ingest(dtype)
        # out-of-core default: when the sketch path streamed the data and
        # the device ingest buffer holds it, the host binned matrix is NOT
        # kept unless free_host_binned was set explicitly — geometry
        # changes at train time re-stream from the retained source instead
        # (restream_ingest)
        free_host = bool(getattr(config, "free_host_binned", False))
        if (mode == "sketch" and ingest is not None
                and "free_host_binned" not in getattr(config, "_raw", {})):
            free_host = True
        keep = ds._keep_host and not (ingest is not None and free_host)
        out = (np.zeros((total, len(ds.groups)), dtype=dtype)
               if keep or ingest is None else None)
        raw = (np.zeros((total, F), dtype=np.float32)
               if config.linear_tree else None)
        ds._stream_map_pack(seqs, dtype, ingest=ingest, out=out, raw=raw)
        ds.binned = out
        if ingest is not None:
            ingest.finish()
            ds.device_ingest = ingest
        ds.raw_data = raw
        if reference is None and ingest is not None and out is None:
            # keep the chunk source: epoch re-streaming (a geometry
            # change at train time rebuilds the ingest buffer from here
            # instead of materializing the full host matrix)
            ds._stream_src = list(seqs)
        if reference is None:
            from .obs import health as obs_health
            obs_health.configure_from_config(config)
            if obs_health.enabled():
                ds.reference_profile()
        return ds

    @staticmethod
    def _iter_seq_chunks(seqs):
        """Yield (global_row_offset, float64 chunk) across sequences,
        honoring EACH sequence's own ``batch_size`` — the one chunk
        iterator every streaming pass shares, so a mixed-batch-size
        sequence list chunks identically in the sketch pass, the
        map-and-pack pass and epoch re-streaming (bit-parity asserted
        by tests/test_sketch.py)."""
        row = 0
        for s in seqs:
            ln = len(s)
            bs = int(getattr(s, "batch_size", 4096) or 4096)
            for startr in range(0, ln, bs):
                chunk = np.asarray(s[startr:startr + bs],
                                   dtype=np.float64)
                if chunk.ndim == 1:
                    chunk = chunk.reshape(1, -1)
                yield row + startr, chunk
            row += ln

    def _stream_map_pack(self, seqs, dtype, ingest=None, out=None,
                         raw=None) -> None:
        """Map-and-pack every sequence chunk into the given sinks (the
        shared body of construction pass 2 and epoch re-streaming)."""
        bmap = self.batched_mapper() if (self._vec and self.used_features) \
            else None
        for start, chunk in self._iter_seq_chunks(seqs):
            if bmap is not None:
                mat = bmap.map_chunk(chunk[:, self.used_features])
                cols = {f: np.asarray(mat[:, i]) for i, f
                        in enumerate(self.used_features)}
            else:
                cols = {f: self.bin_mappers[f].values_to_bins(chunk[:, f])
                        for f in self.used_features}
            packed = self._pack_groups(cols, len(chunk), dtype)
            if out is not None:
                out[start:start + len(chunk)] = packed
            if ingest is not None:
                ingest.push(packed)
            if raw is not None:
                raw[start:start + len(chunk)] = chunk.astype(np.float32)

    def restream_ingest(self, tpu_row_chunk: int):
        """Re-stream the retained chunk source into a FRESH DeviceIngest
        with the requested row geometry — the out-of-core twin of
        ``DeviceIngest.host_binned()`` for the learner's recovery path
        when the construct-time geometry no longer matches: one more
        pass over the source instead of materializing the full host
        binned matrix.  Returns None when there is no retained source
        or the device path is unavailable."""
        seqs = getattr(self, "_stream_src", None)
        if not seqs:
            return None
        dtype = self._bin_dtype()
        try:
            from .ops.construct import DeviceIngest
            ingest = DeviceIngest(len(self.groups), self.num_data, dtype,
                                  int(tpu_row_chunk))
        except Exception as exc:
            log.warning("restream ingest unavailable (%s)",
                        str(exc).split("\n")[0][:120])
            return None
        self._stream_map_pack(seqs, dtype, ingest=ingest)
        ingest.finish()
        self.device_ingest = ingest
        return ingest

    def _resolve_construct_mode(self, is_reference: bool) -> None:
        """Pick the construction path for this dataset from
        ``construct_device`` (see ops/construct.py resolve_mode)."""
        from .parallel import network as _net
        from .ops.construct import resolve_mode
        self._vec, self._ingest_ok, self._keep_host = resolve_mode(
            self.config, is_reference, _net.num_machines() > 1)

    def _make_ingest(self, dtype):
        """A DeviceIngest streaming target for this dataset's geometry,
        or None when the device path is unavailable."""
        if not self._ingest_ok:
            return None
        try:
            from .ops.chunkpolicy import resolve_base
            from .ops.construct import DeviceIngest
            return DeviceIngest(len(self.groups), self.num_data, dtype,
                                resolve_base(self.config, self.num_data,
                                             self.num_total_features))
        except Exception as exc:
            log.warning("device ingest unavailable (%s); keeping the "
                        "host binned matrix", str(exc).split("\n")[0][:120])
            return None

    def _construct_mappers_from_sample(self, sample: np.ndarray,
                                       categorical_features) -> None:
        """Build per-feature BinMappers from an already-sampled row matrix
        (reference: DatasetLoader::ConstructFromSampleData,
        dataset_loader.cpp:593 — the streaming/in-memory path)."""
        self._construct_mappers(sample, categorical_features,
                                _presampled=True)

    def _mapper_param_table(self):
        """Per-feature bin-finding knobs shared by the exact and sketch
        paths: (max_bin_by_feature list or None, forced bounds dict)."""
        cfg = self.config
        max_bin_by_feature = None
        if cfg.max_bin_by_feature:
            max_bin_by_feature = [int(x) for x in str(cfg.max_bin_by_feature).split(",")]
        # forced bin upper bounds (reference: DatasetLoader reads
        # forcedbins_filename as [{"feature": i, "bin_upper_bound": [...]}]
        # and threads them into BinMapper::FindBin, dataset_loader.cpp)
        forced_bounds: dict = {}
        if getattr(cfg, "forcedbins_filename", ""):
            import json as _json
            try:
                with open(cfg.forcedbins_filename) as fh:
                    for entry in _json.load(fh):
                        forced_bounds[int(entry["feature"])] = [
                            float(v) for v in entry["bin_upper_bound"]]
            except (OSError, ValueError, KeyError, TypeError) as exc:
                log.warning("could not read forcedbins file %s (%s); "
                            "ignoring", cfg.forcedbins_filename, exc)
        return max_bin_by_feature, forced_bounds

    def _finish_mappers(self) -> None:
        """Shared epilogue of every mapper-construction path."""
        self.used_features = [f for f in range(self.num_total_features)
                              if not self.bin_mappers[f].is_trivial]
        if not self.used_features:
            log.warning("There are no meaningful features which satisfy the "
                        "provided configuration. Decreasing Dataset parameters "
                        "min_data_in_bin or min_data_in_leaf and re-constructing "
                        "Dataset might resolve this warning.")

    def _construct_mappers_from_sketches(self, sset,
                                         categorical_features) -> None:
        """BinMappers from accumulated per-feature sketches
        (ops/sketch.py).  Under multi-process construction each rank
        sketched only its ROW shard; the fixed-size sketch states are
        allgathered and canonically merged, so every rank derives
        bit-identical global mappers without any rank ever holding the
        global matrix (the rank-sharded out-of-core path)."""
        cfg = self.config
        from .parallel import network as _net
        self._distributed = _net.num_machines() > 1
        if self._distributed:
            from .parallel.distributed import allgather_feature_sketches
            sset = allgather_feature_sketches(sset)
            # feature widths agree by max, like allgather_bin_mappers
            self.num_total_features = max(self.num_total_features,
                                          len(sset))
        cat_set = set(int(c) for c in categorical_features)
        max_bin_by_feature, forced_bounds = self._mapper_param_table()
        # the sketch pass consumes the FULL stream, so the pre-filter's
        # sample/population ratio is exactly 1
        filter_cnt = int(cfg.min_data_in_leaf)

        def _mb(f):
            if max_bin_by_feature and f < len(max_bin_by_feature):
                return max_bin_by_feature[f]
            return cfg.max_bin

        trivial = BinMapper()
        self.bin_mappers = [
            sset.sketches[f].to_mapper(
                _mb(f), min_data_in_bin=cfg.min_data_in_bin,
                min_split_data=filter_cnt,
                pre_filter=cfg.feature_pre_filter,
                bin_type=(BIN_CATEGORICAL if f in cat_set
                          else BIN_NUMERICAL),
                use_missing=cfg.use_missing,
                zero_as_missing=cfg.zero_as_missing,
                forced_upper_bounds=forced_bounds.get(f))
            if f < len(sset) else trivial
            for f in range(self.num_total_features)]
        self._finish_mappers()

    def _construct_mappers(self, data: np.ndarray,
                           categorical_features: Sequence[int],
                           _presampled: bool = False) -> None:
        cfg = self.config
        n = self.num_data
        if not _presampled:
            from .ops.sketch import resolve_bin_mode
            if resolve_bin_mode(cfg, n) == "sketch":
                # sketch-based bin finding over row chunks: no full
                # sample materialization, no full column sort — and the
                # distributed branch inside merges rank ROW shards
                from .ops.sketch import SketchSet
                sset = SketchSet(self.num_total_features, cfg.sketch_k)
                step = self.CONSTRUCT_CHUNK
                for start in range(0, n, step):
                    sset.update_chunk(np.asarray(
                        data[start:min(start + step, n)],
                        dtype=np.float64))
                self._construct_mappers_from_sketches(
                    sset, categorical_features)
                return
        if _presampled:
            sample_cnt = len(data)
            sample_idx = np.arange(sample_cnt)
        else:
            sample_cnt = min(n, cfg.bin_construct_sample_cnt)
            rng = np.random.RandomState(cfg.data_random_seed)
            if sample_cnt < n:
                sample_idx = np.sort(
                    rng.choice(n, size=sample_cnt, replace=False))
            else:
                sample_idx = np.arange(n)
        cat_set = set(int(c) for c in categorical_features)
        max_bin_by_feature, forced_bounds = self._mapper_param_table()
        # feature_pre_filter threshold (reference: dataset_loader.cpp FindBin call)
        filter_cnt = int(cfg.min_data_in_leaf * sample_cnt / max(n, 1))
        # multi-process construction: each rank finds bins only for its
        # FEATURE shard (from its local sample) and the serialized
        # mappers are allgathered so every rank agrees
        # (dataset_loader.cpp:658-740, :1228-1236)
        from .parallel import network as _net
        nmach = _net.num_machines()
        my_rank = _net.rank() if nmach > 1 else 0
        self._distributed = nmach > 1
        my_feats = [f for f in range(self.num_total_features)
                    if not self._distributed or (f % nmach) == my_rank]

        def _mb(f):
            if max_bin_by_feature and f < len(max_bin_by_feature):
                return max_bin_by_feature[f]
            return cfg.max_bin

        self.bin_mappers = [None] * self.num_total_features
        if self._vec and my_feats:
            # vectorized bin finding (ops/construct.py): ONE column-wise
            # sort of the whole (sample_cnt, F) matrix replaces F stable
            # argsorts; the per-feature non-zero/NaN filtering becomes
            # two index ranges of the sorted column
            from .ops.construct import find_bin_sorted, sorted_sample_columns
            rows = (data if len(sample_idx) == len(data)
                    else data[sample_idx])
            sub = np.asarray(
                rows if my_feats == list(range(data.shape[1]))
                else rows[:, my_feats], dtype=np.float64)
            info = sorted_sample_columns(
                sub, workers=_construct_workers(cfg))
            sv = info["sorted"]

            def _find_one(j: int) -> "BinMapper":
                f = my_feats[j]
                lo, hi, m = (info["lo"][j], info["hi"][j],
                             info["non_nan"][j])
                nz_sorted = np.concatenate([sv[:lo, j], sv[hi:m, j]])
                return find_bin_sorted(
                    nz_sorted, na_cnt=int(info["nan_cnt"][j]),
                    total_sample_cnt=sample_cnt, max_bin=_mb(f),
                    min_data_in_bin=cfg.min_data_in_bin,
                    min_split_data=filter_cnt,
                    pre_filter=cfg.feature_pre_filter,
                    bin_type=(BIN_CATEGORICAL if f in cat_set
                              else BIN_NUMERICAL),
                    use_missing=cfg.use_missing,
                    zero_as_missing=cfg.zero_as_missing,
                    forced_upper_bounds=forced_bounds.get(f))

            workers = _construct_workers(cfg)
            if workers > 1 and len(my_feats) > 1:
                # per-feature bin finding is independent; the numpy
                # parts (concatenate, cumsum, searchsorted) release the
                # GIL, and results land by index — deterministic
                from concurrent.futures import ThreadPoolExecutor
                with ThreadPoolExecutor(max_workers=workers) as ex:
                    found = list(ex.map(_find_one,
                                        range(len(my_feats))))
            else:
                found = [_find_one(j) for j in range(len(my_feats))]
            for j, f in enumerate(my_feats):
                self.bin_mappers[f] = found[j]
        else:
            for f in my_feats:
                col = np.asarray(data[sample_idx, f], dtype=np.float64)
                # mirror the reference's sparse sampling: non-zero values
                # + implied zeros
                nonzero = col[(np.abs(col) > 1e-35) | np.isnan(col)]
                bm = BinMapper()
                bm.find_bin(
                    nonzero, total_sample_cnt=len(col), max_bin=_mb(f),
                    min_data_in_bin=cfg.min_data_in_bin,
                    min_split_data=filter_cnt,
                    pre_filter=cfg.feature_pre_filter,
                    bin_type=(BIN_CATEGORICAL if f in cat_set
                              else BIN_NUMERICAL),
                    use_missing=cfg.use_missing,
                    zero_as_missing=cfg.zero_as_missing,
                    forced_upper_bounds=forced_bounds.get(f))
                self.bin_mappers[f] = bm
        if self._distributed:
            from .parallel.distributed import allgather_bin_mappers
            local = {f: bm for f, bm in enumerate(self.bin_mappers)
                     if bm is not None}
            merged, num_total = allgather_bin_mappers(
                local, self.num_total_features)
            # a feature past some rank's local width may be binned by no
            # rank (num_total agrees by max); degrade it to a trivial
            # mapper instead of crashing
            trivial = BinMapper()
            self.bin_mappers = [merged.get(f, trivial)
                                for f in range(num_total)]
            self.num_total_features = num_total
        self._finish_mappers()

    def _build_groups(self) -> None:
        """EFB bundling (reference: dataset.cpp FindGroups:60 / FastFeatureBundling:246).

        Greedy graph-coloring over conflict counts on sampled rows.  Features
        whose non-default rows overlap less than ``max_conflict`` share one
        packed column with per-feature bin offsets.  Dense features (low sparse
        rate) stay in their own group.
        """
        self.groups = []
        if not self.config.enable_bundle or getattr(self, "_distributed",
                                                    False):
            if (getattr(self, "_distributed", False)
                    and self.config.enable_bundle):
                # conflict counts are rank-local samples; divergent
                # bundles would give each process a different physical
                # layout (the reference reaches group agreement through
                # its synced sample — not modeled here yet)
                log.warning("EFB disabled under multi-process construction")
            for f in self.used_features:
                nb = self.bin_mappers[f].num_bin
                self.groups.append(FeatureGroupInfo([f], nb, [0]))
            return
        # Candidate selection here; the conflict graph itself runs later
        # in _bin_data / _finalize_groups over the binned columns.
        sparse, dense = [], []
        for f in self.used_features:
            bm = self.bin_mappers[f]
            # Any feature whose shared "all-default" bin is bin 0 may
            # bundle (the learner's bundled-bin decode — bin b ->
            # offset+b-1, b>=1 — and FixHistogram reconstruction assume
            # it).  The conflict graph decides who actually shares a
            # group, like the reference's FindGroups over ALL features
            # (dataset.cpp:60-244): dense features conflict with
            # everything and come out as singletons on their own.
            if bm.most_freq_bin == 0 and bm.default_bin == 0:
                sparse.append(f)
            else:
                dense.append(f)
        for f in dense:
            self.groups.append(FeatureGroupInfo([f], self.bin_mappers[f].num_bin, [0]))
        # defer true conflict-graph bundling to _bin_data (needs the columns)
        self._pending_sparse = sparse

    def _finalize_groups(self, cols: Dict[int, np.ndarray]) -> None:
        """Resolve pending sparse bundling against binned columns, or fall
        back to singleton groups (shared by the in-memory and streaming
        construction paths)."""
        pending = getattr(self, "_pending_sparse", None)
        if pending:
            self._bundle_sparse(pending, cols)
            self._pending_sparse = None
        elif not self.groups and self.used_features:
            for f in self.used_features:
                self.groups.append(FeatureGroupInfo(
                    [f], self.bin_mappers[f].num_bin, [0]))

    def _bin_data(self, data: np.ndarray) -> None:
        if self._vec:
            self._bin_data_vectorized(data)
            return
        # oracle: bin all used features column-wise first
        cols: Dict[int, np.ndarray] = {}
        for f in self.used_features:
            cols[f] = self.bin_mappers[f].values_to_bins(data[:, f])
        self._finalize_groups(cols)

        self.binned = self._pack_groups(cols, self.num_data,
                                        self._bin_dtype())

    # rows per vectorized binning chunk: big enough to amortize the
    # batched searchsorted, small enough that the packed chunk + its
    # transpose stay cache/transfer friendly
    CONSTRUCT_CHUNK = 1 << 16

    def _bin_data_vectorized(self, data: np.ndarray) -> None:
        """The batched construction path: groups are finalized from a
        <=50k-row binned sample, then row chunks are mapped with ONE
        vectorized searchsorted over all features, packed, and (for
        training datasets) streamed straight into the learner's
        transposed (G, N_pad) device layout — the full host binned
        matrix only materializes when ``_keep_host`` asks for it."""
        n = self.num_data
        uf = self.used_features
        bmap = self.batched_mapper() if uf else None
        pending = getattr(self, "_pending_sparse", None)
        if pending:
            # identical rng consumption to the oracle's _bundle_sparse:
            # one choice() for the conflict sample, then the probe draws
            rng = np.random.RandomState(self.config.data_random_seed)
            sample = (rng.choice(n, size=min(n, 50000), replace=False)
                      if n > 50000 else np.arange(n))
            smat = bmap.map_chunk(np.asarray(data[np.ix_(sample, uf)],
                                             dtype=np.float64))
            nz = {f: np.asarray(smat[:, i]
                                != self.bin_mappers[f].most_freq_bin)
                  for i, f in enumerate(uf) if f in set(pending)}
            self._bundle_greedy(pending, nz, rng)
            self._pending_sparse = None
        else:
            self._finalize_groups({})

        dtype = self._bin_dtype()
        ingest = self._make_ingest(dtype)
        keep = self._keep_host and not (
            ingest is not None
            and bool(getattr(self.config, "free_host_binned", False)))
        out = (np.zeros((n, len(self.groups)), dtype=dtype)
               if keep or ingest is None else None)
        step = self.CONSTRUCT_CHUNK
        # identity feature selection: the chunk is a contiguous row
        # slice, no (rows, F) fancy-index copy needed
        uf_all = uf == list(range(data.shape[1]))

        def _map_pack(start: int) -> np.ndarray:
            """One chunk, feature-major end to end: (F, rows) bins ->
            (G, rows) packed — the ingest buffer's native orientation,
            so no stage writes a strided column."""
            stop = min(start + step, n)
            rows = stop - start
            if uf:
                sl = data[start:stop]
                sub = sl if uf_all else sl[:, uf]
                matT = bmap.map_chunk_T(np.asarray(sub,
                                                   dtype=np.float64))
                cols = {f: matT[i] for i, f in enumerate(uf)}
            else:
                cols = {}
            packed = self._pack_groups_T(cols, rows, dtype)
            if out is not None:
                # disjoint row slices: safe (and faster) to fill from
                # the worker that produced the chunk
                _fill_rows_t(out, start, packed)
            return packed

        starts = [s for s in range(0, max(n, 1), step)
                  if min(s + step, n) > s]
        workers = _construct_workers(self.config)
        if workers > 1 and len(starts) > 1:
            # overlap chunk k+1's map+pack (GIL-releasing numpy:
            # searchsorted, copies) with chunk k's ordered device push —
            # results are consumed in submission order, so the binned
            # matrix and the ingest stream are bit-identical to the
            # sequential loop
            from concurrent.futures import ThreadPoolExecutor
            from collections import deque
            with ThreadPoolExecutor(max_workers=workers) as ex:
                pend: deque = deque()
                it = iter(starts)
                for s in itertools.islice(it, workers + 1):
                    pend.append((s, ex.submit(_map_pack, s)))
                while pend:
                    start, fut = pend.popleft()
                    packed = fut.result()
                    nxt = next(it, None)
                    if nxt is not None:
                        pend.append((nxt, ex.submit(_map_pack, nxt)))
                    if ingest is not None:
                        ingest.push_t(packed)
        else:
            for start in starts:
                packed = _map_pack(start)
                if ingest is not None:
                    ingest.push_t(packed)
        self.binned = out
        if ingest is not None:
            ingest.finish()
            self.device_ingest = ingest

    def _bin_dtype(self):
        max_bin_overall = max((grp.num_total_bin for grp in self.groups),
                              default=2)
        return np.uint8 if max_bin_overall <= 256 else np.uint16

    def bin_matrix(self, data: np.ndarray,
                   cat_oov_sentinel: bool = False) -> np.ndarray:
        """Bin NEW raw rows with this dataset's mappers into the packed
        (n, num_groups) layout — the same transform validation sets get
        (reference: LoadFromFileAlignWithOtherDataset).  For trees trained
        against this dataset, bin-space traversal of the result is EXACT
        (split thresholds are bin uppers).

        cat_oov_sentinel: prediction-path flag — unseen categories map to
        an out-of-range sentinel bin so categorical splits send them to
        the right child like the reference's raw-value predictor (see
        BinMapper.values_to_bins).  Only valid when no categorical
        feature is EFB-bundled (the caller checks)."""
        data = np.asarray(data)
        from .ops.binning import BIN_CATEGORICAL
        if self._vec and self.used_features:
            # one batched mapping over all features (the serving hot
            # path binning); oov_sentinel applies to categorical
            # columns only, like the per-feature oracle below
            mat = self.batched_mapper().map_chunk(
                np.asarray(data[:, self.used_features], dtype=np.float64),
                oov_sentinel=cat_oov_sentinel)
            cols = {f: np.asarray(mat[:, i])
                    for i, f in enumerate(self.used_features)}
        else:
            cols = {f: self.bin_mappers[f].values_to_bins(
                        data[:, f],
                        oov_sentinel=(cat_oov_sentinel and
                                      self.bin_mappers[f].bin_type
                                      == BIN_CATEGORICAL))
                    for f in self.used_features}
        return self._pack_groups(cols, data.shape[0],
                                 self._bin_dtype())

    def _pack_groups(self, cols: Dict[int, np.ndarray], n: int,
                     out_dtype=np.int32) -> np.ndarray:
        """Pack per-feature bin columns into the (n, num_groups) matrix.
        ``out_dtype`` lets callers pack straight into the bin dtype —
        the column assignments C-cast exactly like the ``.astype`` the
        callers used to do, minus one full-matrix pass."""
        out = np.zeros((n, len(self.groups)), dtype=out_dtype)
        for g, grp in enumerate(self.groups):
            if len(grp.feature_indices) == 1:
                out[:, g] = cols[grp.feature_indices[0]]
            else:
                # bundled: shift non-default bins by the feature's offset
                acc = np.zeros(n, dtype=np.int32)
                for sub, f in enumerate(grp.feature_indices):
                    bm = self.bin_mappers[f]
                    # cols may arrive uint8 (map_chunk_T); the offset
                    # arithmetic below needs a wide dtype
                    c = np.asarray(cols[f], dtype=np.int32)
                    offset = grp.bin_offsets[sub]
                    nz = c != bm.most_freq_bin
                    # conflicts resolved last-writer-wins like reference push order
                    shifted = c + offset - (1 if bm.most_freq_bin == 0 else 0)
                    acc = np.where(nz, shifted, acc)
                out[:, g] = acc
        return out

    def _pack_groups_T(self, cols: Dict[int, np.ndarray], n: int,
                       out_dtype=np.int32) -> np.ndarray:
        """Feature-major twin of ``_pack_groups``: (G, n) packed matrix
        from per-feature bin ROWS — every read and write is contiguous,
        and the result is the device ingest buffer's native orientation.
        Same offset/last-writer-wins arithmetic, so ``out.T`` is
        bit-identical to ``_pack_groups``'s output."""
        out = np.zeros((len(self.groups), n), dtype=out_dtype)
        for g, grp in enumerate(self.groups):
            if len(grp.feature_indices) == 1:
                out[g] = cols[grp.feature_indices[0]]
            else:
                acc = np.zeros(n, dtype=np.int32)
                for sub, f in enumerate(grp.feature_indices):
                    bm = self.bin_mappers[f]
                    # cols may arrive uint8 (map_chunk_T); the offset
                    # arithmetic below needs a wide dtype
                    c = np.asarray(cols[f], dtype=np.int32)
                    offset = grp.bin_offsets[sub]
                    nz = c != bm.most_freq_bin
                    shifted = c + offset - (1 if bm.most_freq_bin == 0
                                            else 0)
                    acc = np.where(nz, shifted, acc)
                out[g] = acc
        return out

    def _bundle_sparse(self, sparse: List[int], cols: Dict[int, np.ndarray]) -> None:
        """Greedy conflict-count bundling (reference: dataset.cpp FindGroups).

        ``cols`` may hold fewer rows than the dataset (the streaming path
        passes SAMPLE columns), so row indices are drawn over the columns'
        actual length."""
        n = len(next(iter(cols.values()))) if cols else 0
        # sample rows for conflict counting to bound cost
        rng = np.random.RandomState(self.config.data_random_seed)
        sample = rng.choice(
            n, size=min(n, 50000), replace=False) if n > 50000 else np.arange(n)
        nz_masks = {f: (cols[f][sample] != self.bin_mappers[f].most_freq_bin)
                    for f in sparse}
        self._bundle_greedy(sparse, nz_masks, rng)

    def _bundle_greedy(self, sparse: List[int],
                       nz_masks: Dict[int, np.ndarray], rng) -> None:
        """The greedy coloring over conflict counts.  With the reference
        max_conflict_rate = 0.0 a feature may join a bundle iff it has
        ZERO pairwise overlap with every member, so on the vectorized
        path the per-(feature, bundle) union-mask AND loop collapses to
        lookups in ONE (F_sparse, F_sparse) nonzero-mask matmul
        (ops/construct.py conflict_matrix) — bit-identical bundles,
        asserted by tests/test_construct_device.py."""
        max_conflict = 0  # int(max_conflict_rate * n) with rate = 0.0
        pair = None
        fpos = {f: i for i, f in enumerate(sparse)}
        if self._vec and sparse:
            from .ops.construct import conflict_matrix
            pair = conflict_matrix(np.stack([nz_masks[f] for f in sparse]))
            counts = {f: int(pair[fpos[f], fpos[f]]) for f in sparse}
        else:
            counts = {f: int(nz_masks[f].sum()) for f in sparse}
        bundles: List[List[int]] = []
        bundle_masks: List[Optional[np.ndarray]] = []
        bundle_bins: List[int] = []
        order = sorted(sparse, key=lambda f: -counts[f])
        # reference FindGroups' random-search fallback (dataset.cpp:92):
        # with many groups, each feature probes a random subset instead
        # of every group, bounding the O(F x groups) conflict scan
        max_search = 100
        # a bundle stays within one u8 bin column: groups beyond 256
        # total bins would force the whole matrix to u16 and off the
        # Pallas partition kernel
        max_group_bins = 256
        for f in order:
            nb_add = self.bin_mappers[f].num_bin - 1
            placed = False
            if len(bundles) <= max_search:
                probe = range(len(bundles))
            else:
                probe = rng.choice(len(bundles), size=max_search,
                                   replace=False)
            for bi in probe:
                if bundle_bins[bi] + nb_add > max_group_bins:
                    continue
                if pair is not None:
                    # zero overlap with the union mask == zero pairwise
                    # overlap with every member (counts are non-negative)
                    row = pair[fpos[f]]
                    conflict = int(max((int(row[fpos[g]])
                                        for g in bundles[bi]), default=0))
                else:
                    conflict = int((bundle_masks[bi] & nz_masks[f]).sum())
                if conflict <= max_conflict:
                    bundles[bi].append(f)
                    if pair is None:
                        bundle_masks[bi] |= nz_masks[f]
                    bundle_bins[bi] += nb_add
                    placed = True
                    break
            if not placed:
                bundles.append([f])
                bundle_masks.append(None if pair is not None
                                    else nz_masks[f].copy())
                bundle_bins.append(1 + nb_add)
        for bundle in bundles:
            bundle.sort()
            if len(bundle) == 1:
                f = bundle[0]
                self.groups.append(FeatureGroupInfo(
                    [f], self.bin_mappers[f].num_bin, [0]))
            else:
                # shared column: bin 0 = all-default; feature i occupies
                # [offset_i, offset_i + num_bin_i - 1) (skipping its default bin)
                offsets = []
                cur = 1
                for f in bundle:
                    offsets.append(cur)
                    bm = self.bin_mappers[f]
                    cur += bm.num_bin - (1 if bm.most_freq_bin == 0 else 0)
                self.groups.append(FeatureGroupInfo(bundle, cur, offsets))

    # -- views used by the tree learner ---------------------------------
    def feature_meta_arrays(self) -> Dict[str, np.ndarray]:
        """Per used-feature metadata arrays for the device split finder.

        Features are enumerated in (group, sub-feature) order; ``sub_feature_map``
        translates back to original feature indices.
        """
        feats: List[int] = []
        group_idx: List[int] = []
        bin_start: List[int] = []
        num_bin: List[int] = []
        missing_type: List[int] = []
        default_bin: List[int] = []
        is_cat: List[int] = []
        for g, grp in enumerate(self.groups):
            for sub, f in enumerate(grp.feature_indices):
                bm = self.bin_mappers[f]
                offset = grp.bin_offsets[sub]
                feats.append(f)
                group_idx.append(g)
                if len(grp.feature_indices) == 1:
                    bin_start.append(0)
                    num_bin.append(bm.num_bin)
                    default_bin.append(bm.default_bin)
                else:
                    # bundled feature: bin b (≠ default) lives at offset+b-(mfb==0)
                    shift = offset - (1 if bm.most_freq_bin == 0 else 0)
                    bin_start.append(shift)
                    num_bin.append(bm.num_bin)
                    default_bin.append(bm.default_bin)
                missing_type.append(bm.missing_type)
                is_cat.append(1 if bm.bin_type == BIN_CATEGORICAL else 0)
        return {
            "feature": np.asarray(feats, dtype=np.int32),
            "group": np.asarray(group_idx, dtype=np.int32),
            "bin_start": np.asarray(bin_start, dtype=np.int32),
            "num_bin": np.asarray(num_bin, dtype=np.int32),
            "missing_type": np.asarray(missing_type, dtype=np.int32),
            "default_bin": np.asarray(default_bin, dtype=np.int32),
            "is_categorical": np.asarray(is_cat, dtype=np.int32),
        }

    @property
    def num_groups(self) -> int:
        return len(self.groups)

    @property
    def max_group_bins(self) -> int:
        return max((g.num_total_bin for g in self.groups), default=2)

    def num_used_features(self) -> int:
        return sum(len(g.feature_indices) for g in self.groups)

    # -- binary serialization -------------------------------------------
    # TPU-native replacement for the reference's Dataset binary file
    # (dataset.h:691 SaveBinaryFile / dataset_loader.cpp:417 LoadFromBinFile):
    # one .npz holding the packed bin matrix plus a JSON header with the
    # mappers/groups, so re-binning is skipped entirely on reload.
    BINARY_VERSION = 1

    def save_binary(self, path: str) -> None:
        import json as _json
        header = {
            "version": self.BINARY_VERSION,
            "num_data": self.num_data,
            "num_total_features": self.num_total_features,
            "feature_names": self.feature_names,
            "used_features": self.used_features,
            "bin_mappers": [bm.to_dict() for bm in self.bin_mappers],
            "groups": [{"feature_indices": g.feature_indices,
                        "num_total_bin": g.num_total_bin,
                        "bin_offsets": g.bin_offsets}
                       for g in self.groups],
        }
        host = self.host_binned()
        arrays = {"binned": host if host is not None
                  else np.zeros((self.num_data, 0), np.uint8)}
        md = self.metadata
        if md is not None:
            for name in ("label", "weight", "query_boundaries", "init_score",
                         "positions"):
                v = getattr(md, name)
                if v is not None:
                    arrays[f"meta_{name}"] = np.asarray(v)
            if md.position_ids is not None:
                header["position_ids"] = list(md.position_ids)
        if self.raw_data is not None:
            arrays["raw_data"] = self.raw_data
        with open(path, "wb") as fh:   # keep the exact filename (no .npz)
            np.savez_compressed(fh, header=np.frombuffer(
                _json.dumps(header).encode(), dtype=np.uint8), **arrays)

    @classmethod
    def load_binary(cls, path: str, config: Config) -> "BinnedDataset":
        import json as _json
        with np.load(path) as z:
            header = _json.loads(bytes(z["header"]).decode())
            if header.get("version") != cls.BINARY_VERSION:
                log.fatal("Unsupported binary dataset version: %s",
                          header.get("version"))
            ds = cls(config)
            # re-binning is skipped, but the batched mapper still serves
            # bin_matrix (the serving path) when the config allows it
            ds._resolve_construct_mode(is_reference=False)
            ds._ingest_ok = False
            ds.num_data = int(header["num_data"])
            ds.num_total_features = int(header["num_total_features"])
            ds.feature_names = list(header["feature_names"])
            ds.used_features = [int(f) for f in header["used_features"]]
            ds.bin_mappers = [BinMapper.from_dict(d)
                              for d in header["bin_mappers"]]
            ds.groups = [FeatureGroupInfo(list(g["feature_indices"]),
                                          int(g["num_total_bin"]),
                                          list(g["bin_offsets"]))
                         for g in header["groups"]]
            ds.binned = np.ascontiguousarray(z["binned"])
            ds.metadata = Metadata(ds.num_data)
            for name in ("label", "weight", "query_boundaries", "init_score",
                         "positions"):
                key = f"meta_{name}"
                if key in z:
                    setattr(ds.metadata, name, np.ascontiguousarray(z[key]))
            if "position_ids" in header:
                ds.metadata.position_ids = list(header["position_ids"])
            if "raw_data" in z:
                ds.raw_data = np.ascontiguousarray(z["raw_data"])
            elif config.linear_tree:
                log.fatal(
                    "linear_tree=true requires raw feature values, but the "
                    "binary dataset file was saved without them; re-save it "
                    "with linear_tree=true in the dataset params")
        return ds

    @staticmethod
    def is_binary_file(path: str) -> bool:
        """True when `path` is a saved binary dataset (a .npz zip with our
        header member)."""
        try:
            with open(path, "rb") as fh:
                if fh.read(2) != b"PK":
                    return False
            with np.load(path) as z:
                return "header" in z and "binned" in z
        except Exception:
            return False
