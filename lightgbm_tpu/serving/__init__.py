"""Production serving plane over the device ServingEngine.

``models/serving.py`` solved the hard compilation problem — one
compiled program per (pred kind, bucket, forest signature), mutation-
counter invalidation, per-range sub-pack LRU — but nothing fronted it:
no request queue, no tenancy, no deadlines, and a single slow or
poisoned model could stall every caller.  This package is the queueing
discipline on top (the Booster accelerator paper, arXiv:2011.02022,
shows GBDT inference is a short-request/high-QPS workload where the
queue, not the kernel, sets p99; LLM serving on TPU won its latency
numbers the same way — continuous batching plus strict admission,
cf. the Gemma serving comparison, arXiv:2605.25645):

* :mod:`.batcher` — the coalescing micro-batcher: concurrent
  single-row/small requests merge into the engine's existing
  power-of-two buckets, flushed by size-or-deadline, so N concurrent
  clients cost exactly the compile counts ``test_predict_engine.py``
  pins and one dispatch per flushed bucket;
* :mod:`.registry` — N resident forests with versioned hot-swap/
  rollback (the PR 6 candidate-gate warm-up: at most one compile per
  (kind, bucket) per swap, zero retraces for in-flight traffic) and
  pack eviction by memory budget via the PR 7 HBM ledger;
* :mod:`.admission` — per-tenant bounded queues with backpressure,
  token-bucket rate limits, deadline budgets (expired work is shed
  BEFORE dispatch, never after), a per-model circuit breaker with a
  seeded ``robustness/retry.py`` backoff probe, and the degradation
  ladder (shed ``pred_contrib`` before raw; fall back to the last-good
  model version on a tripped breaker);
* :mod:`.service` — the deterministic core tying them together: an
  injectable clock, a synchronous ``pump()`` the async shell and the
  drill harness both drive, per-(model, kind) latency histograms;
* :mod:`.httpd` — the ``lightgbm_tpu serve`` stdlib-HTTP front end;
* :mod:`.drill` — deterministic fault drills (breaker trip, deadline
  shed, queue flood, swap-under-load) on injected clocks: same seed,
  identical trip ticks / shed counts / recovery sequence.
"""

from .admission import AdmissionController, CircuitBreaker, TokenBucket
from .batcher import CoalescingBatcher
from .drill import run_serve_drill
from .registry import ModelRegistry
from .service import ServeTicket, ServingService

__all__ = [
    "AdmissionController", "CircuitBreaker", "TokenBucket",
    "CoalescingBatcher", "ModelRegistry", "ServeTicket",
    "ServingService", "run_serve_drill",
]
