"""The serving service core: submit -> admit -> coalesce -> dispatch.

Deliberately a synchronous state machine over an injectable clock.
``submit`` admits and queues a request; ``pump`` flushes due lanes,
sheds expired work, runs the circuit-breaker/degradation policy and
dispatches coalesced batches through the registry's boosters.  The
async shell (:meth:`ServingService.start` worker thread, the HTTP
front end) and the deterministic drill harness both drive exactly this
machine — which is why breaker trips, deadline sheds and swap-under-
load replay bit-for-bit under a ManualClock with no sleeps.

Failure policy (the teeth):

* an expired deadline sheds BEFORE dispatch, never after — device
  work is never spent on an answer nobody is waiting for;
* a dispatch failure counts against the model's breaker; a tripped
  breaker fails fast, and when the registry holds a last-good previous
  version the batch degrades to it instead of erroring (the
  model-level rung of the degradation ladder — the queue-level rung,
  shedding ``pred_contrib`` before raw, lives in admission);
* every failure mode is injectable (``robustness/faultinject.py``
  slow-predict / failing-model injectors) so tier-1 replays them
  deterministically.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..obs import health as obs_health
from ..obs import telemetry as obs
from ..obs.telemetry import Histogram
from ..robustness import faultinject
from ..utils import log
from ..utils.log import LightGBMError
from .admission import AdmissionController, CircuitBreaker
from .batcher import CoalescingBatcher
from .registry import ModelRegistry


class ServeTicket:
    """A caller's handle on one submitted request."""

    __slots__ = ("status", "result", "reason", "latency_s", "_event")

    def __init__(self):
        self.status = "pending"      # pending | ok | shed | error
        self.result = None
        self.reason: Optional[str] = None
        self.latency_s: Optional[float] = None
        self._event = threading.Event()

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    def _finish(self, status: str, result=None, reason=None,
                latency=None) -> None:
        self.status = status
        self.result = result
        self.reason = reason
        self.latency_s = latency
        self._event.set()


class _Request:
    __slots__ = ("rid", "tenant", "model", "kind", "rows",
                 "start_iteration", "num_iteration", "deadline",
                 "t_submit", "ticket", "cost")

    def __init__(self, rid, tenant, model, kind, rows, start, num,
                 deadline, t_submit, ticket):
        self.rid = rid
        self.tenant = tenant
        self.model = model
        self.kind = kind
        self.rows = rows
        self.start_iteration = start
        self.num_iteration = num
        self.deadline = deadline
        self.t_submit = t_submit
        self.ticket = ticket
        # the token bucket meters REQUESTS (serve_rate_limit is
        # documented as requests/s): a batch request must not be
        # permanently unpayable because its row count exceeds burst
        self.cost = 1.0


_KINDS = ("raw", "leaf", "contrib")


class ServingService:
    """See the module docstring.  All policy knobs mirror the
    ``serve_*`` config parameters (config.py); ``clock`` is the single
    time source for queues, deadlines, breakers and latency stats."""

    # distinct tenant ids tracked in per-tenant latency (and the
    # telemetry span names they mint); later tenants fold into
    # "~other" so a client rotating ids cannot grow memory unbounded
    TENANT_MAX = 256

    def __init__(self, registry: ModelRegistry, *,
                 flush_rows: int = 256, max_delay: float = 0.002,
                 queue_depth: int = 256, rate: float = 0.0,
                 burst: float = 64.0, breaker_threshold: int = 5,
                 breaker_attempts: int = 6, breaker_base: float = 0.05,
                 breaker_max_delay: float = 30.0,
                 breaker_jitter: float = 0.0, seed: int = 0,
                 default_deadline: Optional[float] = None,
                 max_request_rows: int = 65536,
                 cohort: bool = False, cohort_min: int = 2,
                 clock: Callable[[], float] = time.monotonic):
        self.registry = registry
        self._clock = clock
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        self._pump_lock = threading.Lock()
        self.admission = AdmissionController(queue_depth=queue_depth,
                                             rate=rate, burst=burst,
                                             clock=clock)
        self.batcher = CoalescingBatcher(flush_rows=flush_rows,
                                         max_delay=max_delay,
                                         clock=clock)
        self._breaker_kw = dict(threshold=breaker_threshold,
                                attempts=breaker_attempts,
                                base_delay=breaker_base,
                                max_delay=breaker_max_delay,
                                jitter=breaker_jitter)
        self._seed = int(seed)
        self.breakers: Dict[str, CircuitBreaker] = {}
        self.default_deadline = default_deadline
        self.max_request_rows = int(max_request_rows)
        self._budget_checked_at = float("-inf")
        self._rid = 0
        # multi-forest batched execution: a pump wave whose due raw
        # full-range lanes span >= cohort_min registry models dispatches
        # them all as ONE compiled program (registry cohort packs)
        self.cohort = bool(cohort)
        self.cohort_min = max(int(cohort_min), 2)
        self.counters: Dict[str, int] = {
            "submitted": 0, "served": 0, "shed": 0, "errors": 0,
            "dispatches": 0, "dispatch_failures": 0,
            "fallback_served": 0, "cohort_dispatches": 0,
            "cohort_models": 0}
        self.latency: Dict[str, Histogram] = {}
        # per-tenant submit->complete latency (the admission layer's
        # tenant id): p50/p99 per tenant readable from /stats even with
        # telemetry off; with a telemetry session on, the same samples
        # also feed `serve.tenant.<tenant>.<kind>` span histograms so
        # the Prometheus export carries them
        self.tenant_latency: Dict[str, Histogram] = {}
        self._worker: Optional[threading.Thread] = None
        self._running = False
        # a publish/rollback installs a DIFFERENT forest: the old
        # version's consecutive-failure history (and an open breaker's
        # backoff ladder) must not gate the fresh one — without this, a
        # fixed model keeps serving the stale fallback until the broken
        # version's next scheduled probe
        registry.subscribe_version_change(self._on_version_change)

    # -- submit ----------------------------------------------------------
    def submit(self, rows, *, model: str = "default",
               tenant: str = "default", kind: str = "raw",
               start_iteration: int = 0, num_iteration: int = -1,
               deadline_s: Optional[float] = None) -> ServeTicket:
        """Admit one request; returns immediately with a ticket the
        caller waits on.  ``deadline_s`` is a RELATIVE budget from now
        (``serve_default_deadline_ms`` when omitted); the request is
        shed unanswered once it expires un-dispatched."""
        if kind not in _KINDS:
            raise LightGBMError(f"unknown serve kind {kind!r} "
                                f"(want one of {_KINDS})")
        rows = np.asarray(rows, dtype=np.float64)
        if rows.ndim == 1:
            rows = rows.reshape(1, -1)
        if rows.ndim != 2 or rows.shape[0] == 0:
            # reject at the door (the HTTP layer maps this to 400): a
            # 3-d array would bypass predict's ndim==2 feature-count
            # check and charge ITS failure to the model's breaker
            raise LightGBMError("serve rows must be a non-empty 2-d "
                                f"(n, F) matrix; got shape {rows.shape}")
        if rows.shape[0] > self.max_request_rows:
            # the rate limiter meters requests: without this cap a
            # single huge-row request buys unbounded device work for
            # one token (serve_max_request_rows)
            raise LightGBMError(
                f"serve request of {rows.shape[0]} rows exceeds "
                f"serve_max_request_rows={self.max_request_rows}; "
                "split the batch")
        # peek, not get: a request that may yet be rate-limited must
        # not bump the model's LRU clock (pack-eviction priority)
        bst = self.registry.peek(model)
        expected = bst.num_feature() if bst is not None else None
        if expected is not None and rows.shape[1] != expected:
            # structural width check at the door: a wrong-width tenant
            # reads a 400 and can never charge the model's breaker
            # (_client_fault stays as the dispatch-time backstop)
            raise LightGBMError(
                f"serve rows have {rows.shape[1]} features but model "
                f"{model!r} expects {expected}")
        ticket = ServeTicket()
        if deadline_s is None:
            deadline_s = self.default_deadline
        with self._cv:
            self._rid += 1
            now = self._clock()
            req = _Request(self._rid, str(tenant), str(model), kind,
                           rows, int(start_iteration),
                           int(num_iteration),
                           None if deadline_s is None
                           else now + float(deadline_s),
                           now, ticket)
            self.counters["submitted"] += 1
            victim, reason = self.admission.admit(req)
            if victim is not None:
                if victim is not req:
                    # ladder eviction: the victim was already queued on
                    # a lane — pull it out before failing its ticket
                    self.batcher.remove(victim)
                self.counters["shed"] += 1
                victim.ticket._finish("shed", reason=reason)
                if victim is req:
                    return ticket
            self.batcher.add(req)
            self._cv.notify_all()
        return ticket

    # -- pump ------------------------------------------------------------
    def pump(self, force: bool = False) -> int:
        """Flush every due lane (all lanes when ``force``); returns the
        number of dispatched batches.  Safe to call from any thread;
        one pump runs at a time.  Lanes flush one at a time with a
        FRESH deadline check immediately before each dispatch — a
        stall in batch k must expire batch k+1's overdue requests
        BEFORE device work is spent on them, never after."""
        dispatched = 0
        with self._pump_lock:
            while True:
                with self._lock:
                    # one scan yields the whole due wave; each lane
                    # still gets a FRESH pre-dispatch deadline check
                    # below (due-ness is monotone in time, so a lane
                    # due at scan time is still due at drain time)
                    keys = self.batcher.due(self._clock(), force=force)
                if not keys:
                    break
                cohort_keys = self._cohort_wave(keys)
                if cohort_keys:
                    dispatched += self._pump_cohort(cohort_keys)
                    keys = [k for k in keys if k not in cohort_keys]
                for key in keys:
                    live = self._drain_live(key)
                    self._dispatch_guarded(key, live)
                    if live:
                        dispatched += 1
            if dispatched and self.registry.pack_budget_bytes:
                # evicted models lazily re-pack when traffic returns,
                # so the budget must be re-enforced between publishes
                # — but the walk over every resident pack's metadata
                # is throttled (it holds the registry lock the
                # publish/get paths also need)
                t = self._clock()
                if t - self._budget_checked_at >= 5.0:
                    self._budget_checked_at = t
                    self.registry.enforce_budget()
        return dispatched

    def _drain_live(self, key) -> List[_Request]:
        """Drain one lane (bucket-capped) with the pre-dispatch
        deadline shed: expired requests answer "shed" before any
        device work is spent on them."""
        with self._lock:
            t = self._clock()
            live = []
            for req in self.batcher.drain(
                    key, max_rows=self.batcher.flush_rows):
                self.admission.queue_for(req.tenant).take(req.rid)
                # deadline shed BEFORE dispatch, never after
                if self.admission.expired(req, t):
                    self.counters["shed"] += 1
                    req.ticket._finish("shed", reason="deadline")
                    continue
                live.append(req)
        return live

    # -- cohort lanes (multi-forest batched execution) -------------------
    def _cohort_wave(self, keys) -> List[Any]:
        """The subset of a due wave eligible for ONE cohort dispatch:
        raw full-range lanes of >= cohort_min DISTINCT registry models
        whose breakers are closed.  Anything else (sliced ranges,
        leaf/contrib kinds, tripped models) keeps the per-model path —
        the cohort is a fast path, never a change in failure policy."""
        if not self.cohort:
            return []
        by_model: Dict[str, Any] = {}
        for k in keys:
            model, kind, start, num = k[0], k[1], k[2], k[3]
            if kind != "raw" or start != 0 or num != -1:
                continue
            if model in by_model:       # two widths for one model: a
                by_model[model] = None  # malformed lane — skip both
                continue
            if model not in self.registry:
                continue
            br = self.breakers.get(model)
            if br is not None and br.state != "closed":
                continue
            by_model[model] = k
        out = [k for k in by_model.values() if k is not None]
        return out if len(out) >= self.cohort_min else []

    def _pump_cohort(self, cohort_keys) -> int:
        """Dispatch a cohort wave as ONE compiled program; falls back
        to per-model dispatch when the pack can't build or the
        dispatch fails (injected faults and ineligible members keep
        their normal per-model semantics)."""
        live_by_key = [(k, self._drain_live(k)) for k in cohort_keys]
        live_by_key = [(k, live) for k, live in live_by_key if live]

        def singles():
            n = 0
            for k, live in live_by_key:
                self._dispatch_guarded(k, live)
                n += 1
            return n

        if len(live_by_key) < self.cohort_min:
            return singles()
        try:
            # planted faults (drills) degrade the wave to the
            # per-model path WITHOUT spending the counted injection
            # budget: the per-model dispatch then fires the injection
            # exactly once and breaker policy owns it, so arming N
            # failures records N failures whether cohort lanes are on
            # or off
            if any(faultinject.predict_fault_armed(k[0])
                   for k, _ in live_by_key):
                return singles()
            pack = self.registry.cohort_pack(
                [k[0] for k, _ in live_by_key])
            if pack is None:
                return singles()
            reqs_by_model = {k[0]: live for k, live in live_by_key}
            Xs, total = [], 0
            for name in pack.names:
                reqs = reqs_by_model[name]
                self.registry.get(name)      # bump the LRU clock
                X = (reqs[0].rows if len(reqs) == 1
                     else np.concatenate([r.rows for r in reqs],
                                         axis=0))
                Xs.append(X)
                total += X.shape[0]
            with (obs.span("serve.dispatch.cohort",
                           models=",".join(pack.names), rows=total)
                  if obs.enabled() else obs.NULL):
                outs = pack.predict_raw(Xs)
        except Exception as exc:  # noqa: BLE001 — the cohort is an
            # optimization: ANY failure between the drain and the
            # dispatch (a concurrently removed member, a pack that
            # cannot build, a member fault) degrades the WAVE to the
            # per-model path, whose breaker/fallback policy then
            # attributes the failure to the model that owns it.
            # Nothing before this point completes a ticket, so the
            # fallback can never double-answer and drained requests
            # are never stranded.
            log.warning("serve: cohort dispatch failed (%s); "
                        "falling back to per-model dispatch", exc)
            return singles()
        with self._lock:
            self.counters["dispatches"] += 1
            self.counters["cohort_dispatches"] += 1
            self.counters["cohort_models"] += len(pack.names)
        for name, out in zip(pack.names, outs):
            # a cohort dispatch IS a successful serve of the member:
            # reset its consecutive-failure count like the per-model
            # path does, else stray failures accumulate across an
            # arbitrarily long window of cohort successes and trip a
            # "consecutive"-failure breaker
            br = self.breakers.get(name)
            if br is not None:
                br.record_success()
            self._complete(reqs_by_model[name], out, name, "raw")
        return 1

    def _dispatch_guarded(self, key, live: List[_Request]) -> None:
        if not live:
            return
        try:
            self._dispatch(key, live)
        except Exception as exc:  # noqa: BLE001 — an unexpected
            # dispatch-layer fault must answer the tickets, not strand
            # their callers at the HTTP timeout — and must hand back
            # any half-open probe token this dispatch was carrying
            # (idempotent when none is out)
            self._fail_all(live, f"dispatch_error: {exc}")
            br = self.breakers.get(key[0])
            if br is not None:
                br.probe_inconclusive()

    def _on_version_change(self, name: str) -> None:
        """Registry listener: retire the outgoing version's breaker.
        Fires OUTSIDE the registry lock (see _notify_version_change),
        so taking the service lock here adds no lock-order edge."""
        with self._lock:
            self.breakers.pop(name, None)

    def _breaker(self, model: str) -> CircuitBreaker:
        br = self.breakers.get(model)   # single read: GIL-atomic
        if br is None:
            # per-model seed offset from a STABLE name hash (not dict
            # size, which shifts as breakers are minted/retired): two
            # models' jittered probe schedules must not be forced into
            # lockstep, and re-minting after a version change must
            # reproduce the same schedule
            import zlib
            with self._lock:
                br = self.breakers.get(model)
                if br is None:          # re-check: lost the mint race
                    br = self.breakers[model] = CircuitBreaker(
                        seed=self._seed
                        + (zlib.crc32(model.encode()) & 0xffff),
                        clock=self._clock, **self._breaker_kw)
        return br

    def _hist(self, model: str, kind: str) -> Histogram:
        key = f"{model}.{kind}"
        h = self.latency.get(key)
        if h is None:
            h = self.latency[key] = Histogram()
        return h

    # -- dispatch --------------------------------------------------------
    def _predict(self, booster, kind: str, X: np.ndarray, start: int,
                 num: int, inject_model: Optional[str] = None):
        if inject_model is not None:
            faultinject.maybe_fail_predict(inject_model)
            slow = faultinject.maybe_slow_predict(inject_model)
            if slow > 0.0:
                # a planted slow model advances the INJECTED clock
                # (drills pair a ManualClock whose sleep is virtual);
                # under the real clock the injection is a real stall
                sleep = getattr(self._clock, "sleep", None)
                (sleep or time.sleep)(slow)
        if kind == "raw":
            return np.asarray(booster.predict(
                X, raw_score=True, start_iteration=start,
                num_iteration=num))
        if kind == "leaf":
            return np.asarray(booster.predict(
                X, pred_leaf=True, start_iteration=start,
                num_iteration=num))
        return np.asarray(booster.predict(
            X, pred_contrib=True, start_iteration=start,
            num_iteration=num))

    def _fail_all(self, reqs, reason: str) -> None:
        with self._lock:
            self.counters["errors"] += len(reqs)
        for req in reqs:
            req.ticket._finish("error", reason=reason)

    @staticmethod
    def _client_fault(exc: BaseException) -> bool:
        """A failure the REQUEST caused (wrong feature count), not the
        model: it must answer 400-shaped, and must not count toward
        the model's breaker — one misbehaving tenant cannot be allowed
        to trip every tenant's traffic onto the fallback."""
        return isinstance(exc, LightGBMError) and \
            "number of features in data" in str(exc)

    def _dispatch(self, key, reqs: List[_Request]) -> None:
        model, kind, start, num = key[:4]
        if model not in self.registry:
            # reject BEFORE minting a breaker: model names are
            # client-supplied, and a breaker (with its event ring) per
            # unique bogus name would grow without bound
            self._fail_all(reqs, "unknown_model")
            return
        breaker = self._breaker(model)
        gate = breaker.allow()
        fallback = False
        if gate == "open":
            # model-level degradation rung: a tripped breaker serves
            # from the last-good previous version when one exists,
            # fails fast otherwise — never blocks the queue
            booster = self.registry.last_good(model)
            if booster is None:
                self._fail_all(reqs, "breaker_open")
                return
            fallback = True
        else:
            try:
                booster = self.registry.get(model)
            except LightGBMError:
                if gate == "probe":
                    # the model vanished under the probe: count it as
                    # failed or the breaker waits on an outcome that
                    # can never arrive
                    breaker.record_failure()
                self._fail_all(reqs, "unknown_model")
                return
        X = (reqs[0].rows if len(reqs) == 1
             else np.concatenate([r.rows for r in reqs], axis=0))
        with self._lock:
            self.counters["dispatches"] += 1
        # the tenant id the admission layer already knows rides the
        # dispatch span (coalesced multi-tenant batches tag "multi" —
        # per-tenant latency is exact in _complete either way)
        tenants = {r.tenant for r in reqs}
        tenant = tenants.pop() if len(tenants) == 1 else "multi"
        try:
            with (obs.span(f"serve.dispatch.{kind}",
                           model=model, tenant=tenant,
                           rows=int(X.shape[0]))
                  if obs.enabled() else obs.NULL):
                # the booster's SkewMonitor observes deep inside the
                # predict path; the ambient scope keys its rolling
                # digests by the SAME tenant id the latency histograms
                # use, so /stats lines up PSI next to p50/p99
                with obs_health.tenant_scope(tenant):
                    out = self._predict(booster, kind, X, start, num,
                                        inject_model=None if fallback
                                        else model)
        except Exception as exc:   # noqa: BLE001 — any model fault
            with self._lock:
                self.counters["dispatch_failures"] += 1
            # fallback dispatches never blame the client: its rows
            # passed the door check against the ACTIVE version — a
            # width mismatch here means the SERVER chose an
            # incompatible last-good version
            if not fallback and self._client_fault(exc):
                if gate == "probe":
                    # the probe batch itself was malformed: no verdict
                    # on the model — hand the probe token back or the
                    # breaker waits forever on an outcome that never
                    # arrives
                    breaker.probe_inconclusive()
                self._fail_all(reqs, f"bad_request: {exc}")
                return
            if not fallback:
                breaker.record_failure()
                if breaker.state == "open":
                    # the failure that TRIPPED it: this batch still
                    # degrades instead of dying with the model
                    prev = self.registry.last_good(model)
                    if prev is not None:
                        try:
                            with obs_health.tenant_scope(tenant):
                                out = self._predict(prev, kind, X,
                                                    start, num)
                            self._complete(reqs, out, model, kind,
                                           fallback=True)
                            return
                        except Exception:
                            pass
            self._fail_all(reqs, f"model_error: {exc}")
            return
        if not fallback and gate in ("closed", "probe"):
            breaker.record_success()
        self._complete(reqs, out, model, kind, fallback=fallback)

    def _complete(self, reqs, out: np.ndarray, model: str, kind: str,
                  fallback: bool = False) -> None:
        now = self._clock()
        pos = 0
        # per-request copies, not views: a view would pin the WHOLE
        # coalesced batch output for as long as any one ticket lives
        split = len(reqs) > 1
        tel = obs.enabled()
        finishes = []
        samples = []
        # one lock hold covers every histogram observe and counter
        # bump: stats() snapshots under the same lock, so a reader can
        # never see a latency sample without its served count (or a
        # half-updated Histogram)
        with self._lock:
            hist = self._hist(model, kind)
            for req in reqs:
                n = req.rows.shape[0]
                res = (out[pos:pos + n].copy() if split
                       else out[pos:pos + n])
                pos += n
                lat = now - req.t_submit
                hist.observe(lat)
                # tenant is a client-supplied string: bound the
                # per-tenant map (same hazard as client-supplied model
                # names — an id rotator would otherwise grow service
                # memory AND the Prometheus exposition without bound);
                # overflow tenants fold into one "~other" bucket
                tkey = req.tenant
                th = self.tenant_latency.get(tkey)
                if th is None:
                    if len(self.tenant_latency) >= self.TENANT_MAX:
                        tkey = "~other"
                    th = self.tenant_latency.get(tkey)
                    if th is None:
                        th = self.tenant_latency[tkey] = Histogram()
                th.observe(lat)
                if tel:
                    samples.append((tkey, lat))
                self.counters["served"] += 1
                if fallback:
                    self.counters["fallback_served"] += 1
                finishes.append((req, res, lat))
        # ticket completion and telemetry run OUTSIDE the lock:
        # _finish wakes waiter threads and observe_span takes the
        # telemetry session lock — neither belongs under self._lock
        for tkey, lat in samples:
            # same sample into the telemetry session so the
            # Prometheus export carries per-tenant p50/p99
            obs.observe_span(f"serve.tenant.{tkey}.{kind}",
                             lat, model=model)
        for req, res, lat in finishes:
            req.ticket._finish("ok", result=res,
                               reason="fallback" if fallback else None,
                               latency=lat)

    # -- async shell -----------------------------------------------------
    def start(self, poll_s: Optional[float] = None) -> None:
        """Run the pump on a daemon worker: wakes on submit, sleeps
        until the next size/deadline flush is due."""
        if self._worker is not None:
            return
        self._running = True
        poll = poll_s if poll_s is not None \
            else max(self.batcher.max_delay / 2.0, 1e-4)

        def loop():
            while self._running:
                try:
                    self.pump()
                except Exception as exc:   # noqa: BLE001 — never die:
                    # a dead pump thread would strand every queued and
                    # future request across all tenants
                    log.warning("serve: pump error: %s", exc)
                with self._cv:
                    if not self._running:
                        break
                    due_at = self.batcher.next_due_at()
                    if due_at is None:
                        self._cv.wait(timeout=0.2)
                    else:
                        wait = due_at - self._clock()
                        if wait > 0:
                            self._cv.wait(timeout=min(wait, poll))

        self._worker = threading.Thread(target=loop, daemon=True,
                                        name="lightgbm-tpu-serve-pump")
        self._worker.start()

    def stop(self, drain: bool = True) -> None:
        self._running = False
        with self._cv:
            self._cv.notify_all()
        if self._worker is not None:
            self._worker.join(timeout=5.0)
            self._worker = None
        if drain:
            self.pump(force=True)

    # -- stats -----------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        # snapshot every service-owned structure under the owning lock
        # (conlint CL001): counters vs shed_rate stay mutually
        # consistent, and a Histogram is never serialized mid-observe.
        # The admission queues and the batcher are service-lock-owned
        # too (see their module docstrings), so their stats ride the
        # same hold.  registry.stats()/_tenant_skew() lock themselves
        # and run OUTSIDE: self._lock -> registry._lock here would add
        # a reader edge to the lock-order graph for no benefit.
        with self._lock:
            counters = dict(self.counters)
            admission = self.admission.stats()
            batcher = self.batcher.stats()
            breakers = {
                m: {"state": br.state, "trips": br.trip_count,
                    "consecutive_failures": br.consecutive_failures}
                for m, br in sorted(self.breakers.items())}
            latency = {k: h.to_json()
                       for k, h in sorted(self.latency.items())}
            tenant_latency = {
                t: {"count": h.count,
                    "p50_s": round(h.quantile(0.5), 6),
                    "p99_s": round(h.quantile(0.99), 6)}
                for t, h in sorted(self.tenant_latency.items())}
        shed_rate = counters["shed"] / max(counters["submitted"], 1)
        return {
            "counters": counters,
            "shed_rate": round(shed_rate, 6),
            "admission": admission,
            "batcher": batcher,
            "breakers": breakers,
            "latency": latency,
            # per-tenant p50/p99 from the admission layer's tenant id
            # (ROADMAP item 1a): readable straight from /stats
            "tenant_latency": tenant_latency,
            # per-tenant distribution skew (PSI vs the training
            # reference profile) from each live model's SkewMonitor,
            # next to the latency percentiles for the same tenant ids
            "tenant_skew": self._tenant_skew(),
            "registry": self.registry.stats(),
        }

    def _tenant_skew(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for name in self.registry.names():
            # peek, not get: a stats scrape must not refresh a model's
            # LRU/eviction priority
            booster = self.registry.peek(name)
            if booster is None:
                continue
            gbdt = getattr(booster, "_gbdt", None)
            serving = getattr(gbdt, "serving", None)
            mon = getattr(serving, "_skew", None)
            if not mon:          # None (never built) or False (can't)
                continue
            scores = mon.tenant_scores()
            if scores:
                out[name] = {
                    t: {"rows": s["rows"],
                        "psi_max": round(float(s["psi_max"]), 6)}
                    for t, s in scores.items()}
        return out
