"""Multi-model registry: N resident forests, versioned hot-swap,
rollback, pack eviction by memory budget — and cohort packs for
multi-forest batched execution.

The registry owns WHICH booster serves a name; the engines own how.
**Cohort packs** (:class:`CohortPack`) stack N resident tenant forests
into one padded (forest, tree, node) tensor family
(``ops/forest_tensor.py stack_forests``) so the service can dispatch a
whole cohort's same-bucket raw requests as ONE compiled program — the
ROADMAP item-1d/6 "one dispatch per tenant cohort" path.  Cohort
compile counts are pinned per (kind, bucket, cohort-signature): the
stacked shapes key the jit cache, so repeated same-cohort waves never
re-trace (``cohort_traces``), and the member-version cache key makes a
stale cohort pack impossible (any member publish/rollback bumps its
model version and the pack rebuilds).

Three older invariants, all inherited from machinery that already
exists:

* **Swap is one reference flip.**  ``publish`` warms the incoming
  booster FIRST (the PR 6 candidate-gate trick: the warm-up predict
  doubles as the pack build, at most ONE compile per (kind, bucket)
  per swap), then installs it with a single dict assignment — a
  concurrent reader holds either the old booster or the new one, never
  a mix, and in-flight traffic on the old booster keeps its own packs
  (zero retraces: engine packs are keyed by each model's own mutation
  counter, so nothing the swap does can invalidate the old program).
* **Rollback is bit-identical.**  The previous version is retained
  after every swap; ``rollback`` flips the reference back to a booster
  whose engine still holds its own packs keyed by its own signature —
  post-rollback predictions are bit-identical to pre-swap ones.
* **Eviction frees packs, not models.**  When the summed pack bytes
  (the same arrays the PR 7 HBM ledger attributes to
  ``serving.packs``) exceed ``pack_budget_bytes``, the least-recently-
  used models' engines are invalidated.  The model stays resident and
  re-warms lazily on its next request — a re-pack (one host gather +
  transfer), ZERO new compiles (the engine's jit cache survives
  invalidation; only the device arrays drop).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..models.serving import K_EPSILON, _pack_memory_arrays, bucket_rows
from ..obs import memory as obs_memory
from ..obs import telemetry as obs
from ..utils import log
from ..utils.log import LightGBMError


def pack_bytes(engine) -> int:
    """Bytes of every pack payload the engine keeps resident (the
    ledger's ``serving.packs`` provider, summed host-side from array
    metadata — never a device sync)."""
    try:
        import jax
        leaves = jax.tree_util.tree_leaves(_pack_memory_arrays(engine))
    except Exception:
        return 0
    return int(sum(getattr(a, "nbytes", 0) or 0 for a in leaves))


class _Entry:
    __slots__ = ("name", "active", "previous", "version", "last_used",
                 "swap_count", "rollback_count")

    def __init__(self, name: str, booster, now: float):
        self.name = name
        self.active = booster
        self.previous = None
        self.version = 1
        self.last_used = now
        self.swap_count = 0
        self.rollback_count = 0


class CohortPack:
    """N tenant forests stacked into one padded (forest, tree, node)
    tensor family, executed as ONE compiled program per
    (bucket, cohort-signature).

    Members flatten per class: a K-class member contributes K forests
    sharing its row block (``model_of_forest`` routes each forest to
    its member's rows inside the program).  Each member's rows are
    binned with its OWN training mappers on the host and zero-padded
    to the widest group count — padded columns are never referenced
    (real nodes' column ids stay inside their forest's true G), and
    padded tree slots are zero-node trees whose leaf 0 carries delta
    0.  The f32 path reuses the layered kernel's oracle-order
    reduction, so each member's cohort scores are bit-identical to its
    own single-model dispatch."""

    def __init__(self, names: List[str], members: List[Any],
                 registry: "ModelRegistry"):
        from ..ops import forest_tensor
        self.names = list(names)
        self._registry = registry
        self._members = []            # (booster, engine, K, G, init)
        host_packs, deltas = [], []
        self.model_of_forest = []
        for mi, bst in enumerate(members):
            g = bst._gbdt
            eng = g.serving
            pack = eng._pack("insession", eng._insession_pack)
            if (pack is None or pack.get("layers_depth") is None
                    or pack["has_cat"]
                    or getattr(g, "average_output", False)):
                raise LightGBMError("cohort-ineligible member")
            G = eng._bin(np.zeros((1, bst.num_feature())),
                         False).shape[1]
            self._members.append((bst, eng, pack["K"], G,
                                  np.asarray(g.init_scores,
                                             np.float64)))
            for pk in pack["per_k"]:
                hp = {k: np.asarray(v)
                      for k, v in pk["layers"].items()}
                hp["max_depth"] = pack["layers_depth"]
                host_packs.append(hp)
                deltas.append(np.asarray(pk["deltas"],
                                         np.float32))
                self.model_of_forest.append(mi)
        stacked = forest_tensor.stack_forests(host_packs, deltas)
        if stacked is None:
            raise LightGBMError("cohort members not stackable")
        self.max_depth = stacked.pop("max_depth")
        self.stacked = stacked
        self.G_max = max(m[3] for m in self._members)
        self._model_idx = np.asarray(self.model_of_forest, np.int32)

    def _jit(self):
        # ONE registry-wide jitted program (its cache keys on the
        # stacked shapes = the cohort signature): a rebuilt same-shape
        # cohort pack, or a second cohort with the same padded shapes,
        # costs ZERO new compiles
        return self._registry._cohort_fn()

    def predict_raw(self, rows_by_member: List[np.ndarray]
                    ) -> List[np.ndarray]:
        """One cohort dispatch: ``rows_by_member[i]`` is member i's
        (n_i, F_i) float matrix; returns each member's raw scores in
        its single-dispatch shape ((n_i,) for K=1, else (n_i, K))."""
        import jax.numpy as jnp
        assert len(rows_by_member) == len(self._members)
        bucket = bucket_rows(max(r.shape[0] for r in rows_by_member))
        binned = []
        for (bst, eng, K, G, init), rows in zip(self._members,
                                                rows_by_member):
            b = eng._bin(np.asarray(rows, np.float64), False)
            if b is None:
                raise LightGBMError("cohort member failed to bin")
            binned.append(b)
        dt = np.result_type(*[b.dtype for b in binned])
        binned_m = np.zeros((len(binned), bucket, self.G_max), dt)
        for i, b in enumerate(binned):
            binned_m[i, :b.shape[0], :b.shape[1]] = b
        self._registry._count_cohort_call(bucket)
        out = np.asarray(self._jit()(
            self.stacked, jnp.asarray(self._model_idx),
            jnp.asarray(binned_m), max_depth=self.max_depth))
        res = []
        off = 0
        for (bst, eng, K, G, init), rows in zip(self._members,
                                                rows_by_member):
            n = rows.shape[0]
            block = out[off:off + K, :n].T.astype(np.float64)  # (n, K)
            off += K
            # boost-from-average rides the first HOST tree only; the
            # device deltas exclude it (same fold-in as raw_insession)
            for k in range(K):
                if abs(init[k]) > K_EPSILON:
                    block[:, k] += init[k]
            res.append(block[:, 0] if K == 1 else block)
        return res


class ModelRegistry:
    """Name -> versioned resident booster, with a pack-memory budget."""

    COHORT_CACHE = 4                   # bounded LRU of cohort packs

    def __init__(self, pack_budget_bytes: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic):
        self._lock = threading.RLock()
        self._entries: Dict[str, _Entry] = {}
        self.pack_budget_bytes = pack_budget_bytes
        self._clock = clock
        self.evictions = 0
        self._version_listeners: List[Callable[[str], None]] = []
        # cohort packs: built outside the registry lock (device work),
        # cached per sorted member-name tuple and keyed by every
        # member's model version so a stale stack is impossible
        self._cohort_lock = threading.Lock()
        self._cohorts: "OrderedDict[Tuple[str, ...], Any]" = \
            OrderedDict()
        self.cohort_traces: Dict[Any, int] = {}
        self.cohort_calls: Dict[Any, int] = {}

    def subscribe_version_change(self,
                                 cb: Callable[[str], None]) -> None:
        """``cb(name)`` fires after every publish/rollback — the
        service uses it to retire the old version's circuit-breaker
        history (a fixed model must serve immediately, not wait out
        the broken version's backoff ladder)."""
        self._version_listeners.append(cb)

    def _notify_version_change(self, name: str) -> None:
        for cb in list(self._version_listeners):
            try:
                cb(name)
            except Exception:   # a listener must never sink a publish
                pass

    def __contains__(self, name: str) -> bool:
        return name in self._entries

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._entries)

    # -- publish / resolve / rollback -----------------------------------
    def _warm(self, booster, gate_rows) -> Dict[Any, int]:
        """Warm the incoming booster's serving packs BEFORE it takes
        traffic; returns the per-(kind, bucket) traces the warm-up
        cost (the swap-under-load drill asserts each is <= 1)."""
        g = booster._gbdt
        g._flush_pending()
        eng = g.serving
        eng.mark_rewarm(("insession", "loaded"))
        snap = eng.trace_snapshot()
        if gate_rows is not None:
            booster.predict(np.asarray(gate_rows), raw_score=True)
        return eng.new_traces_since(snap)

    def publish(self, name: str, booster, gate_rows=None
                ) -> Dict[str, Any]:
        """Install ``booster`` as the serving version of ``name``
        (hot-swap when the name exists).  ``gate_rows`` (optional
        serving-shaped sample) drives the warm-up predict so the first
        real request after the swap is already hot."""
        warm_traces = self._warm(booster, gate_rows)
        with self._lock:
            now = self._clock()
            ent = self._entries.get(name)
            if ent is None:
                ent = self._entries[name] = _Entry(name, booster, now)
            else:
                ent.previous = ent.active
                ent.active = booster       # the atomic step
                ent.version += 1
                ent.swap_count += 1
                ent.last_used = now
            self._enforce_budget(keep=name)
        log.info("registry: published %s v%d (warm traces: %s)",
                 name, ent.version,
                 {f"{k[0]}@{k[1]}": v for k, v in warm_traces.items()})
        self._purge_cohorts(name)
        self._notify_version_change(name)
        return {"name": name, "version": ent.version,
                "warm_traces": warm_traces}

    def get(self, name: str):
        """The serving booster for ``name`` (bumps its LRU clock)."""
        with self._lock:
            ent = self._entries.get(name)
            if ent is None:
                raise LightGBMError(f"no model named {name!r} in the "
                                    "serving registry")
            ent.last_used = self._clock()
            return ent.active

    def peek(self, name: str):
        """The serving booster without touching the LRU clock — for
        cheap pre-admission checks (shed traffic must not refresh a
        model's eviction priority)."""
        with self._lock:
            ent = self._entries.get(name)
            return ent.active if ent is not None else None

    def last_good(self, name: str):
        """The previous version (the breaker's fallback target), or
        None when the name has never been swapped."""
        with self._lock:
            ent = self._entries.get(name)
            return ent.previous if ent is not None else None

    def version(self, name: str) -> int:
        with self._lock:
            ent = self._entries.get(name)
            return ent.version if ent is not None else 0

    def rollback(self, name: str) -> bool:
        """Flip ``name`` back to its previous version (bit-identical:
        the restored booster's engine kept its own packs)."""
        with self._lock:
            ent = self._entries.get(name)
            if ent is None or ent.previous is None:
                return False
            ent.active, ent.previous = ent.previous, None
            ent.version += 1
            ent.rollback_count += 1
            ent.last_used = self._clock()
        log.warning("registry: rolled back %s to the pre-swap version "
                    "(now v%d)", name, ent.version)
        self._purge_cohorts(name)
        self._notify_version_change(name)
        return True

    def remove(self, name: str) -> bool:
        with self._lock:
            removed = self._entries.pop(name, None) is not None
        if removed:
            self._purge_cohorts(name)
        return removed

    # -- cohort packs (multi-forest batched execution) ------------------
    def _purge_cohorts(self, name: str) -> None:
        """Drop every cached cohort pack that stacks ``name``.  Called
        on publish/rollback/remove: the version-keyed rebuild already
        makes a stale stack impossible to SERVE, but without the purge
        a cohort that never re-forms would keep the replaced (or
        removed) booster and its stacked device tensors resident in
        the LRU indefinitely."""
        with self._cohort_lock:
            for key in [k for k in self._cohorts if name in k]:
                del self._cohorts[key]
    def _cohort_fn(self):
        fn = getattr(self, "_cohort_fn_cache", None)
        if fn is None:
            import jax
            import jax.numpy as jnp

            from ..ops import forest_tensor
            reg = self

            def f(stacked, model_idx, binned_m, max_depth):
                reg._count_cohort_trace(int(binned_m.shape[1]))
                # route each forest to its member's row block INSIDE
                # the program: one dispatch covers the whole cohort
                binned_f = jnp.take(binned_m, model_idx, axis=0)
                return forest_tensor.predict_raw_layered_forests(
                    binned_f, stacked, stacked["tree_mask"],
                    max_depth)

            fn = self._cohort_fn_cache = jax.jit(
                f, static_argnames=("max_depth",))
        return fn

    def _count_cohort_trace(self, bucket: int) -> None:
        k = ("cohort_raw", bucket)
        with self._cohort_lock:
            self.cohort_traces[k] = self.cohort_traces.get(k, 0) + 1
        obs.compile_event(f"serving.cohort_raw@{bucket}")

    def _count_cohort_call(self, bucket: int) -> None:
        k = ("cohort_raw", bucket)
        with self._cohort_lock:
            self.cohort_calls[k] = self.cohort_calls.get(k, 0) + 1

    def _cohort_versions(self, names) -> Optional[Tuple]:
        with self._lock:
            out = []
            for n in names:
                ent = self._entries.get(n)
                if ent is None:
                    return None
                out.append((n, ent.version,
                            ent.active._gbdt._model_version,
                            len(ent.active._gbdt.models)))
            return tuple(out)

    def cohort_pack(self, names) -> Optional[CohortPack]:
        """The (cached) stacked multi-forest pack serving ``names``'
        current versions, or None when any member is absent or
        cohort-ineligible (categorical splits, loaded-only, over-deep
        forest).  Built OUTSIDE the registry lock — pack construction
        is host padding + one device transfer — and keyed by every
        member's model version, so publish/rollback can never leave a
        stale stack serving."""
        names = tuple(sorted(names))
        if len(names) < 2:
            return None
        vers = self._cohort_versions(names)
        if vers is None:
            return None
        with self._cohort_lock:
            hit = self._cohorts.get(names)
            if hit is not None and hit[0] == vers:
                self._cohorts.move_to_end(names)
                return hit[1]
        members = [self.peek(n) for n in names]
        try:
            pack = CohortPack(list(names), members, self)
        except Exception:  # noqa: BLE001 — ineligible members raise
            # LightGBMError; a concurrently-removed member surfaces as
            # peek()=None AttributeError.  Either way the caller falls
            # back to per-model dispatch; never propagate from the
            # fast path.
            return None
        with self._cohort_lock:
            self._cohorts[names] = (vers, pack)
            self._cohorts.move_to_end(names)
            while len(self._cohorts) > self.COHORT_CACHE:
                self._cohorts.popitem(last=False)
        return pack

    # -- pack-memory budget ---------------------------------------------
    @staticmethod
    def _entry_bytes(ent: "_Entry") -> int:
        """Resident pack bytes of one entry (active + retained previous
        version — the rollback guarantee is memory the budget must
        see)."""
        n = pack_bytes(ent.active._gbdt.serving)
        if ent.previous is not None:
            n += pack_bytes(ent.previous._gbdt.serving)
        return n

    def pack_usage(self) -> Dict[str, int]:
        """Per-model resident pack bytes (lock-held: ``ent.previous``
        races a concurrent rollback otherwise; the walk reads only
        host-side array metadata, never a device sync)."""
        with self._lock:
            return {ent.name: self._entry_bytes(ent)
                    for ent in self._entries.values()}

    def _enforce_budget(self, keep: Optional[str] = None) -> int:
        """Evict (invalidate packs of) least-recently-used models until
        the summed pack bytes fit the budget; ``keep`` is never
        evicted (it is the model being published/served right now).
        Returns the number of models evicted.  Caller holds the lock."""
        budget = self.pack_budget_bytes
        if not budget or budget <= 0:
            return 0
        usage = {ent.name: self._entry_bytes(ent)
                 for ent in self._entries.values()}
        total = sum(usage.values())
        evicted = 0
        victims = sorted((e for e in self._entries.values()
                          if e.name != keep),
                         key=lambda e: e.last_used)
        for ent in victims:
            if total <= budget:
                break
            if usage.get(ent.name, 0) <= 0:
                continue
            for bst in (ent.active, ent.previous):
                if bst is None:
                    continue
                eng = bst._gbdt.serving
                eng.invalidate()
                # next use re-packs without the cold-row gate (an
                # evicted model was serving small batches; eviction
                # must not silently demote it to the host path)
                eng.mark_rewarm(("insession", "loaded"))
            total -= usage[ent.name]
            evicted += 1
            self.evictions += 1
            log.info("registry: evicted packs of %s (%d bytes) to meet "
                     "the %d-byte budget", ent.name, usage[ent.name],
                     budget)
        return evicted

    def enforce_budget(self, keep: Optional[str] = None) -> int:
        with self._lock:
            return self._enforce_budget(keep=keep)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "models": {
                    e.name: {"version": e.version,
                             "swaps": e.swap_count,
                             "rollbacks": e.rollback_count,
                             "has_previous": e.previous is not None}
                    for e in self._entries.values()},
                "pack_budget_bytes": self.pack_budget_bytes,
                "evictions": self.evictions,
                "cohorts": self._cohort_stats(),
            }

    def _cohort_stats(self) -> Dict[str, Any]:
        # cohort structures are guarded by _cohort_lock, NOT the
        # registry lock: snapshot under the right one so a /stats read
        # can never race a pump thread's pack build/eviction
        with self._cohort_lock:
            return {
                "resident": [list(k) for k in self._cohorts],
                "traces": {f"{k[0]}@{k[1]}": v
                           for k, v in self.cohort_traces.items()},
                "calls": {f"{k[0]}@{k[1]}": v
                          for k, v in self.cohort_calls.items()},
            }


def _registry_arrays(reg: ModelRegistry):
    """Telemetry memory provider: every resident version's packs plus
    the stacked cohort tensors."""
    # snapshot the entry list under the registry lock: a concurrent
    # publish/remove mutates _entries while a span-boundary snapshot
    # walks providers from another thread (conlint CL001)
    with reg._lock:
        entries = list(reg._entries.values())
    out = []
    for ent in entries:
        for bst in (ent.active, ent.previous):
            if bst is not None:
                out.append(_pack_memory_arrays(bst._gbdt.serving))
    with reg._cohort_lock:
        cohorts = [pack.stacked for _, pack in reg._cohorts.values()]
    out.extend(cohorts)
    return out


def register_ledger(reg: ModelRegistry) -> None:
    """Attribute the registry's resident packs in the HBM ledger under
    their own owner name (each engine also self-registers under
    ``serving.packs``; the registry track answers "how much is the
    REGISTRY holding resident" across models)."""
    obs_memory.register("serving.registry", reg, _registry_arrays)
