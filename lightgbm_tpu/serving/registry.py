"""Multi-model registry: N resident forests, versioned hot-swap,
rollback, and pack eviction by memory budget.

The registry owns WHICH booster serves a name; the engines own how.
Three invariants, all inherited from machinery that already exists:

* **Swap is one reference flip.**  ``publish`` warms the incoming
  booster FIRST (the PR 6 candidate-gate trick: the warm-up predict
  doubles as the pack build, at most ONE compile per (kind, bucket)
  per swap), then installs it with a single dict assignment — a
  concurrent reader holds either the old booster or the new one, never
  a mix, and in-flight traffic on the old booster keeps its own packs
  (zero retraces: engine packs are keyed by each model's own mutation
  counter, so nothing the swap does can invalidate the old program).
* **Rollback is bit-identical.**  The previous version is retained
  after every swap; ``rollback`` flips the reference back to a booster
  whose engine still holds its own packs keyed by its own signature —
  post-rollback predictions are bit-identical to pre-swap ones.
* **Eviction frees packs, not models.**  When the summed pack bytes
  (the same arrays the PR 7 HBM ledger attributes to
  ``serving.packs``) exceed ``pack_budget_bytes``, the least-recently-
  used models' engines are invalidated.  The model stays resident and
  re-warms lazily on its next request — a re-pack (one host gather +
  transfer), ZERO new compiles (the engine's jit cache survives
  invalidation; only the device arrays drop).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..models.serving import _pack_memory_arrays
from ..obs import memory as obs_memory
from ..utils import log
from ..utils.log import LightGBMError


def pack_bytes(engine) -> int:
    """Bytes of every pack payload the engine keeps resident (the
    ledger's ``serving.packs`` provider, summed host-side from array
    metadata — never a device sync)."""
    try:
        import jax
        leaves = jax.tree_util.tree_leaves(_pack_memory_arrays(engine))
    except Exception:
        return 0
    return int(sum(getattr(a, "nbytes", 0) or 0 for a in leaves))


class _Entry:
    __slots__ = ("name", "active", "previous", "version", "last_used",
                 "swap_count", "rollback_count")

    def __init__(self, name: str, booster, now: float):
        self.name = name
        self.active = booster
        self.previous = None
        self.version = 1
        self.last_used = now
        self.swap_count = 0
        self.rollback_count = 0


class ModelRegistry:
    """Name -> versioned resident booster, with a pack-memory budget."""

    def __init__(self, pack_budget_bytes: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic):
        self._lock = threading.RLock()
        self._entries: Dict[str, _Entry] = {}
        self.pack_budget_bytes = pack_budget_bytes
        self._clock = clock
        self.evictions = 0
        self._version_listeners: List[Callable[[str], None]] = []

    def subscribe_version_change(self,
                                 cb: Callable[[str], None]) -> None:
        """``cb(name)`` fires after every publish/rollback — the
        service uses it to retire the old version's circuit-breaker
        history (a fixed model must serve immediately, not wait out
        the broken version's backoff ladder)."""
        self._version_listeners.append(cb)

    def _notify_version_change(self, name: str) -> None:
        for cb in list(self._version_listeners):
            try:
                cb(name)
            except Exception:   # a listener must never sink a publish
                pass

    def __contains__(self, name: str) -> bool:
        return name in self._entries

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._entries)

    # -- publish / resolve / rollback -----------------------------------
    def _warm(self, booster, gate_rows) -> Dict[Any, int]:
        """Warm the incoming booster's serving packs BEFORE it takes
        traffic; returns the per-(kind, bucket) traces the warm-up
        cost (the swap-under-load drill asserts each is <= 1)."""
        g = booster._gbdt
        g._flush_pending()
        eng = g.serving
        eng.mark_rewarm(("insession", "loaded"))
        snap = eng.trace_snapshot()
        if gate_rows is not None:
            booster.predict(np.asarray(gate_rows), raw_score=True)
        return eng.new_traces_since(snap)

    def publish(self, name: str, booster, gate_rows=None
                ) -> Dict[str, Any]:
        """Install ``booster`` as the serving version of ``name``
        (hot-swap when the name exists).  ``gate_rows`` (optional
        serving-shaped sample) drives the warm-up predict so the first
        real request after the swap is already hot."""
        warm_traces = self._warm(booster, gate_rows)
        with self._lock:
            now = self._clock()
            ent = self._entries.get(name)
            if ent is None:
                ent = self._entries[name] = _Entry(name, booster, now)
            else:
                ent.previous = ent.active
                ent.active = booster       # the atomic step
                ent.version += 1
                ent.swap_count += 1
                ent.last_used = now
            self._enforce_budget(keep=name)
        log.info("registry: published %s v%d (warm traces: %s)",
                 name, ent.version,
                 {f"{k[0]}@{k[1]}": v for k, v in warm_traces.items()})
        self._notify_version_change(name)
        return {"name": name, "version": ent.version,
                "warm_traces": warm_traces}

    def get(self, name: str):
        """The serving booster for ``name`` (bumps its LRU clock)."""
        with self._lock:
            ent = self._entries.get(name)
            if ent is None:
                raise LightGBMError(f"no model named {name!r} in the "
                                    "serving registry")
            ent.last_used = self._clock()
            return ent.active

    def peek(self, name: str):
        """The serving booster without touching the LRU clock — for
        cheap pre-admission checks (shed traffic must not refresh a
        model's eviction priority)."""
        with self._lock:
            ent = self._entries.get(name)
            return ent.active if ent is not None else None

    def last_good(self, name: str):
        """The previous version (the breaker's fallback target), or
        None when the name has never been swapped."""
        with self._lock:
            ent = self._entries.get(name)
            return ent.previous if ent is not None else None

    def version(self, name: str) -> int:
        with self._lock:
            ent = self._entries.get(name)
            return ent.version if ent is not None else 0

    def rollback(self, name: str) -> bool:
        """Flip ``name`` back to its previous version (bit-identical:
        the restored booster's engine kept its own packs)."""
        with self._lock:
            ent = self._entries.get(name)
            if ent is None or ent.previous is None:
                return False
            ent.active, ent.previous = ent.previous, None
            ent.version += 1
            ent.rollback_count += 1
            ent.last_used = self._clock()
        log.warning("registry: rolled back %s to the pre-swap version "
                    "(now v%d)", name, ent.version)
        self._notify_version_change(name)
        return True

    def remove(self, name: str) -> bool:
        with self._lock:
            return self._entries.pop(name, None) is not None

    # -- pack-memory budget ---------------------------------------------
    @staticmethod
    def _entry_bytes(ent: "_Entry") -> int:
        """Resident pack bytes of one entry (active + retained previous
        version — the rollback guarantee is memory the budget must
        see)."""
        n = pack_bytes(ent.active._gbdt.serving)
        if ent.previous is not None:
            n += pack_bytes(ent.previous._gbdt.serving)
        return n

    def pack_usage(self) -> Dict[str, int]:
        """Per-model resident pack bytes (lock-held: ``ent.previous``
        races a concurrent rollback otherwise; the walk reads only
        host-side array metadata, never a device sync)."""
        with self._lock:
            return {ent.name: self._entry_bytes(ent)
                    for ent in self._entries.values()}

    def _enforce_budget(self, keep: Optional[str] = None) -> int:
        """Evict (invalidate packs of) least-recently-used models until
        the summed pack bytes fit the budget; ``keep`` is never
        evicted (it is the model being published/served right now).
        Returns the number of models evicted.  Caller holds the lock."""
        budget = self.pack_budget_bytes
        if not budget or budget <= 0:
            return 0
        usage = {ent.name: self._entry_bytes(ent)
                 for ent in self._entries.values()}
        total = sum(usage.values())
        evicted = 0
        victims = sorted((e for e in self._entries.values()
                          if e.name != keep),
                         key=lambda e: e.last_used)
        for ent in victims:
            if total <= budget:
                break
            if usage.get(ent.name, 0) <= 0:
                continue
            for bst in (ent.active, ent.previous):
                if bst is None:
                    continue
                eng = bst._gbdt.serving
                eng.invalidate()
                # next use re-packs without the cold-row gate (an
                # evicted model was serving small batches; eviction
                # must not silently demote it to the host path)
                eng.mark_rewarm(("insession", "loaded"))
            total -= usage[ent.name]
            evicted += 1
            self.evictions += 1
            log.info("registry: evicted packs of %s (%d bytes) to meet "
                     "the %d-byte budget", ent.name, usage[ent.name],
                     budget)
        return evicted

    def enforce_budget(self, keep: Optional[str] = None) -> int:
        with self._lock:
            return self._enforce_budget(keep=keep)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "models": {
                    e.name: {"version": e.version,
                             "swaps": e.swap_count,
                             "rollbacks": e.rollback_count,
                             "has_previous": e.previous is not None}
                    for e in self._entries.values()},
                "pack_budget_bytes": self.pack_budget_bytes,
                "evictions": self.evictions,
            }


def _registry_arrays(reg: ModelRegistry):
    """Telemetry memory provider: every resident version's packs."""
    out = []
    for ent in list(reg._entries.values()):
        for bst in (ent.active, ent.previous):
            if bst is not None:
                out.append(_pack_memory_arrays(bst._gbdt.serving))
    return out


def register_ledger(reg: ModelRegistry) -> None:
    """Attribute the registry's resident packs in the HBM ledger under
    their own owner name (each engine also self-registers under
    ``serving.packs``; the registry track answers "how much is the
    REGISTRY holding resident" across models)."""
    obs_memory.register("serving.registry", reg, _registry_arrays)
