"""``lightgbm_tpu serve`` — the stdlib-HTTP front end.

No framework, no dependency: a ``ThreadingHTTPServer`` whose handler
threads submit into the :class:`~lightgbm_tpu.serving.service.
ServingService` and block on their tickets while the service's pump
coalesces across them — which is exactly the concurrency shape the
micro-batcher exists for (N handler threads, one device dispatch per
flushed bucket).

Endpoints::

    POST /v1/predict        {"model": "default", "tenant": "t",
                             "rows": [[...], ...], "kind": "raw",
                             "deadline_ms": 50, "start_iteration": 0,
                             "num_iteration": -1}
    GET  /healthz           liveness + per-model breaker states
    GET  /stats             full service stats (counters, shed rates,
                            latency histograms incl. per-tenant
                            p50/p99, registry, tenants)
    GET  /metrics           Prometheus exposition text (telemetry
                            session; per-tenant span summaries when
                            telemetry is on)
    POST /v1/models/<name>/publish   {"model_file": "path"} hot-swap
    POST /v1/models/<name>/rollback  restore the pre-swap version

Shed responses map to 429 (rate limit / queue full / deadline /
degraded), a tripped breaker with no fallback to 503, an unknown model
to 404 — the client can tell "back off" from "give up".
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..utils import log
from .registry import ModelRegistry, register_ledger
from .service import ServingService

_SHED_STATUS = {"ratelimit": 429, "queue_full": 429, "degraded": 429,
                "deadline": 429}


class _BodyTooLarge(ValueError):
    pass


def build_from_config(cfg) -> Tuple[ModelRegistry, ServingService]:
    """Registry + service wired from the ``serve_*`` config family."""
    budget = int(float(cfg.serve_pack_budget_mb) * 1e6) or None
    registry = ModelRegistry(pack_budget_bytes=budget)
    register_ledger(registry)
    service = ServingService(
        registry,
        flush_rows=int(cfg.serve_flush_rows),
        max_delay=float(cfg.serve_flush_ms) / 1e3,
        queue_depth=int(cfg.serve_queue_depth),
        rate=float(cfg.serve_rate_limit),
        burst=float(cfg.serve_burst),
        breaker_threshold=int(cfg.serve_breaker_threshold),
        breaker_base=float(cfg.serve_breaker_base),
        breaker_jitter=float(cfg.serve_breaker_jitter),
        seed=int(cfg.seed),
        default_deadline=(float(cfg.serve_default_deadline_ms) / 1e3
                          if float(cfg.serve_default_deadline_ms) > 0
                          else None),
        max_request_rows=int(cfg.serve_max_request_rows),
        cohort=bool(cfg.serve_cohort),
        cohort_min=int(cfg.serve_cohort_min))
    return registry, service


def load_models_from_config(registry: ModelRegistry, cfg) -> None:
    """Resident models at startup: ``serve_models=name=path[,...]``,
    else ``input_model=`` as ``default``."""
    from ..basic import Booster
    specs = []
    if cfg.serve_models:
        for item in str(cfg.serve_models).split(","):
            item = item.strip()
            if not item:
                continue
            if "=" not in item:
                log.fatal("serve_models entries must be name=path "
                          "(got %r)", item)
            name, path = item.split("=", 1)
            specs.append((name.strip(), path.strip()))
    elif cfg.input_model:
        specs.append(("default", cfg.input_model))
    if not specs:
        log.fatal("task=serve needs serve_models=name=path[,...] or "
                  "input_model=")
    for name, path in specs:
        bst = Booster(model_file=path)
        nf = bst.num_feature()
        # warm with a serving-shaped zero batch so the first real
        # request is already compiled
        registry.publish(name, bst,
                         gate_rows=np.zeros((1, nf), np.float64))
        log.info("serve: loaded %s from %s (%d features)", name, path,
                 nf)


class _Handler(BaseHTTPRequestHandler):
    service: ServingService = None          # set by make_server
    request_timeout_s: float = 30.0
    admin_token: str = ""
    # body-size ceiling: admission control cannot protect the process
    # from a body it already buffered — an oversized POST answers 413
    # before a byte of it is read
    max_body_bytes: int = 32 << 20

    def _admin_allowed(self) -> bool:
        """Operator endpoints (publish/rollback) load server-side file
        paths and change what every tenant is served: with a
        configured token, the request must present it (constant-time
        compare — the token is a credential); without one, only
        loopback clients qualify."""
        if self.admin_token:
            import hmac
            got = self.headers.get("X-Admin-Token") or ""
            return hmac.compare_digest(got, self.admin_token)
        # the server is AF_INET (IPv4): loopback is exactly 127.0.0.1
        return self.client_address[0] == "127.0.0.1"

    def log_message(self, fmt, *args):       # route through our logger
        log.debug("serve-http: " + fmt, *args)

    def _reply(self, code: int, doc: Dict[str, Any]) -> None:
        body = json.dumps(doc).encode("utf-8")
        try:
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            # the client hung up mid-reply; its deadline already shed
            # the answer — never let one dead socket kill the handler
            pass

    def _body(self) -> Dict[str, Any]:
        n = int(self.headers.get("Content-Length") or 0)
        if n <= 0:
            return {}
        if n > self.max_body_bytes:
            raise _BodyTooLarge(n)
        doc = json.loads(self.rfile.read(n).decode("utf-8"))
        if not isinstance(doc, dict):
            # a bare array/string/number is valid JSON but not a valid
            # request: it must read 400, not crash the handler
            raise ValueError("request body must be a JSON object, "
                             f"got {type(doc).__name__}")
        return doc

    # -- GET -------------------------------------------------------------
    def do_GET(self):                        # noqa: N802 (stdlib name)
        svc = self.service
        if self.path == "/healthz":
            # liveness stays open; the model/breaker inventory is
            # operator detail (same gate as /stats)
            doc: Dict[str, Any] = {"ok": True}
            if self._admin_allowed():
                doc["models"] = svc.registry.names()
                doc["breakers"] = {m: br.state for m, br
                                   in dict(svc.breakers).items()}
            self._reply(200, doc)
        elif self.path == "/stats":
            if not self._admin_allowed():
                # per-tenant queue/shed stats enumerate OTHER tenants'
                # identifiers and traffic — operator surface only
                self._reply(403, {"error": "operator endpoint: set "
                                  "serve_admin_token and send "
                                  "X-Admin-Token, or call from "
                                  "loopback"})
                return
            self._reply(200, svc.stats())
        elif self.path == "/metrics":
            if not self._admin_allowed():
                self._reply(403, {"error": "operator endpoint"})
                return
            # Prometheus exposition text of the process telemetry
            # session — with telemetry on, the per-tenant
            # `serve.tenant.<tenant>.<kind>` span summaries ride it
            from ..obs import telemetry as obs
            from ..obs.exporters import prometheus_text
            body = prometheus_text(obs.get()).encode("utf-8")
            try:
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            except (BrokenPipeError, ConnectionResetError):
                pass
        else:
            self._reply(404, {"error": f"no route {self.path}"})

    # -- POST ------------------------------------------------------------
    def do_POST(self):                       # noqa: N802
        try:
            doc = self._body()
        except _BodyTooLarge as exc:
            self._reply(413, {"error": "request body exceeds "
                              f"{self.max_body_bytes} bytes "
                              f"(got {exc.args[0]})"})
            return
        except (ValueError, OSError) as exc:
            self._reply(400, {"error": f"bad request body: {exc}"})
            return
        if self.path == "/v1/predict":
            self._predict(doc)
            return
        parts = self.path.strip("/").split("/")
        if len(parts) == 4 and parts[:2] == ["v1", "models"]:
            name, action = parts[2], parts[3]
            if action in ("publish", "rollback") \
                    and not self._admin_allowed():
                self._reply(403, {"error": "operator endpoint: set "
                                  "serve_admin_token and send "
                                  "X-Admin-Token, or call from "
                                  "loopback"})
                return
            if action == "publish":
                self._publish(name, doc)
                return
            if action == "rollback":
                ok = self.service.registry.rollback(name)
                self._reply(200 if ok else 409, {
                    "rolled_back": ok,
                    "version": self.service.registry.version(name)})
                return
        self._reply(404, {"error": f"no route {self.path}"})

    def _publish(self, name: str, doc: Dict[str, Any]) -> None:
        from ..basic import Booster
        path = doc.get("model_file")
        if not path:
            self._reply(400, {"error": "publish needs model_file"})
            return
        try:
            bst = Booster(model_file=path)
            gate = np.zeros((1, bst.num_feature()), np.float64)
            rep = self.service.registry.publish(name, bst,
                                                gate_rows=gate)
        except Exception as exc:             # noqa: BLE001
            # the raw error (paths, parse details) belongs in the
            # server log, not the response body
            log.warning("serve: publish of %s from %s failed: %s",
                        name, path, exc)
            self._reply(500, {"error": "publish failed "
                              "(see server log)"})
            return
        self._reply(200, {
            "published": name, "version": rep["version"],
            "warm_traces": {f"{k[0]}@{k[1]}": v
                            for k, v in rep["warm_traces"].items()}})

    def _predict(self, doc: Dict[str, Any]) -> None:
        rows = doc.get("rows")
        if rows is None:
            self._reply(400, {"error": "predict needs rows"})
            return
        try:
            rows = np.asarray(rows, dtype=np.float64)
        except (TypeError, ValueError) as exc:
            self._reply(400, {"error": f"bad rows: {exc}"})
            return
        from ..utils.log import LightGBMError
        try:
            deadline_ms = doc.get("deadline_ms")
            ticket = self.service.submit(
                rows,
                model=str(doc.get("model", "default")),
                tenant=str(doc.get("tenant", "default")),
                kind=str(doc.get("kind", "raw")),
                start_iteration=int(doc.get("start_iteration", 0)),
                num_iteration=int(doc.get("num_iteration", -1)),
                # <= 0 means "no deadline", matching the documented
                # serve_default_deadline_ms convention — a literal 0
                # budget would shed 100% of the client's traffic.
                # Deadline-less HTTP requests get the handler timeout
                # as their budget: once this handler answers 504,
                # nobody reads the result, so the queue must not keep
                # the request alive past that
                deadline_s=(float(deadline_ms) / 1e3
                            if deadline_ms is not None
                            and float(deadline_ms) > 0
                            else self.request_timeout_s))
        except (LightGBMError, TypeError, ValueError) as exc:
            # an unknown kind / non-numeric field is the CLIENT's bug:
            # it must read a 400, not a dropped connection
            self._reply(400, {"error": str(exc)})
            return
        if not ticket.wait(self.request_timeout_s):
            self._reply(504, {"status": "timeout"})
            return
        if ticket.status == "ok":
            self._reply(200, {
                "status": "ok",
                "fallback": ticket.reason == "fallback",
                "latency_ms": round(1e3 * (ticket.latency_s or 0.0), 3),
                "predictions": np.asarray(ticket.result).tolist()})
        elif ticket.status == "shed":
            self._reply(_SHED_STATUS.get(ticket.reason, 429), {
                "status": "shed", "reason": ticket.reason})
        else:
            reason = ticket.reason or "error"
            code = (404 if reason == "unknown_model"
                    else 503 if reason == "breaker_open"
                    # dispatch-time client faults (e.g. a width
                    # mismatch against a just-swapped model) are the
                    # CLIENT's 400, not a retriable server error
                    else 400 if reason.startswith("bad_request")
                    else 500)
            self._reply(code, {"status": "error", "reason": reason})


class _Server(ThreadingHTTPServer):
    # the stdlib default backlog (5) resets connections under exactly
    # the concurrent-client load the micro-batcher exists for
    request_queue_size = 128
    daemon_threads = True


def make_server(service: ServingService, host: str = "127.0.0.1",
                port: int = 8080, request_timeout_s: float = 30.0,
                admin_token: str = "") -> ThreadingHTTPServer:
    """A bound (not yet serving) HTTP server over ``service``; port 0
    binds an ephemeral port (tests read ``server.server_address``)."""
    handler = type("BoundHandler", (_Handler,), {
        "service": service, "request_timeout_s": request_timeout_s,
        "admin_token": str(admin_token or ""),
        # socket read/write timeout (BaseHTTPRequestHandler honors the
        # `timeout` attribute in setup()): a client that withholds its
        # declared body must not pin a handler thread forever
        "timeout": float(request_timeout_s)})
    return _Server((host, int(port)), handler)


def run_serve_task(cfg) -> None:
    """The CLI ``task=serve`` body: build, load, pump, serve forever."""
    if str(cfg.serve_host) not in ("127.0.0.1", "localhost") \
            and not cfg.serve_admin_token:
        # a non-local bind with token-less operator endpoints would
        # also trust loopback SOURCE addresses — which any same-host
        # reverse proxy forges for every remote client
        log.fatal("serve_host=%s is non-loopback: set serve_admin_token "
                  "(operator endpoints must not trust source addresses "
                  "behind a proxy)", cfg.serve_host)
    registry, service = build_from_config(cfg)
    load_models_from_config(registry, cfg)
    service.start()
    server = make_server(service, host=cfg.serve_host,
                         port=int(cfg.serve_port),
                         admin_token=cfg.serve_admin_token)
    host, port = server.server_address[:2]
    log.info("serve: listening on http://%s:%d (models: %s)", host,
             port, ", ".join(registry.names()))
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        log.info("serve: shutting down")
    finally:
        shutdown_server(server, service=service)


def shutdown_server(server: ThreadingHTTPServer,
                    thread: "Optional[threading.Thread]" = None,
                    service: "Optional[ServingService]" = None,
                    deadline_s: float = 5.0) -> bool:
    """Deadline-bounded shutdown of a server (+ optional serve thread
    and service) from :func:`run_serve_task` /
    :func:`serve_in_background`.

    The conlint CL003 contract for the whole teardown path: every join
    carries a timeout and NO lock is held while joining — a wedged
    handler (or a pump stuck in dispatch) costs at most ``deadline_s``,
    never a hang, and can never deadlock against a handler thread that
    is blocked on the service lock.  Returns True when every thread
    exited inside the deadline (the HTTP thread is a daemon either
    way, so a False here is diagnostic, not a leak).
    """
    server.shutdown()               # stop serve_forever's poll loop
    clean = True
    if thread is not None:
        thread.join(deadline_s)     # bounded; lock-free by contract
        clean = not thread.is_alive()
    server.server_close()
    if service is not None:
        # ServingService.stop drains, then joins its pump worker with
        # its own bounded timeout — also without holding service locks
        service.stop()
    return clean


def serve_in_background(service: ServingService, host: str = "127.0.0.1",
                        port: int = 0) -> Tuple[ThreadingHTTPServer,
                                                threading.Thread]:
    """Test/tool helper: worker pump + HTTP server on a daemon thread;
    returns (server, thread) — the caller owns shutdown (pass both,
    plus the service, to :func:`shutdown_server`)."""
    service.start()
    server = make_server(service, host=host, port=port)
    t = threading.Thread(target=server.serve_forever, daemon=True,
                         name="lightgbm-tpu-serve-http")
    t.start()
    return server, t
