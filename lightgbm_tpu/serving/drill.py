"""Deterministic serving-plane fault drills.

Every failure mode the serving plane claims to handle is rehearsed
here with NO real traffic, NO sleeps and NO wall clock: the service,
registry, breakers and batcher all run on one
:class:`lightgbm_tpu.robustness.retry.ManualClock`, faults come from
:mod:`lightgbm_tpu.robustness.faultinject`, and every report field is
a pure function of ``seed`` — two runs with the same seed produce
byte-identical reports (asserted in tier-1), which is what makes a
3 am incident replayable on a laptop.

Scenarios (``run_serve_drill(scenario, seed=0)``):

* ``"breaker"`` — a failing-model injection trips the per-model
  circuit breaker; fail-fast + last-good fallback while open; seeded
  backoff probes; half-open recovery.  Reports the trip tick, every
  per-tick status, and the breaker's event log.
* ``"deadline"`` — a slow-predict injection eats the deadline budget;
  expired requests are shed BEFORE dispatch (never after), surviving
  requests serve with the injected latency.
* ``"flood"`` — a queue-flood injection overruns a bounded tenant
  queue; depth stays bounded and the degradation ladder sheds
  deterministically (pending ``contrib`` evicted for incoming ``raw``,
  oldest first).
* ``"swap"`` — a hot-swap lands under coalesced load: the incoming
  version warms with at most ONE compile per (kind, bucket), the
  outgoing version's compiled programs are untouched (zero retraces
  for in-flight traffic), and post-swap traffic serves the new trees.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import numpy as np

from ..robustness import faultinject
from ..robustness.retry import ManualClock
from .registry import ModelRegistry
from .service import ServingService

DRILL_SCENARIOS = ("breaker", "deadline", "flood", "swap")


def _train_small(seed: int, rows: int = 400, features: int = 5,
                 trees: int = 5):
    from ..basic import Dataset
    from ..engine import train as _train
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(rows, features))
    y = X[:, 0] + 0.5 * np.sin(X[:, 1]) + 0.1 * rng.normal(size=rows)
    bst = _train({"objective": "regression", "num_leaves": 7,
                  "verbosity": -1, "metric": "", "min_data_in_leaf": 5,
                  "seed": seed},
                 Dataset(X, label=y), num_boost_round=trees)
    bst._gbdt._flush_pending()
    return bst, X


def _mk_service(clock: ManualClock, seed: int, **kw) -> ServingService:
    reg = ModelRegistry(clock=clock)
    defaults = dict(flush_rows=128, max_delay=0.002, queue_depth=8,
                    breaker_threshold=3, breaker_attempts=4,
                    breaker_base=0.1, breaker_jitter=0.0, seed=seed,
                    clock=clock)
    defaults.update(kw)
    return ServingService(reg, **defaults)


def _tick_status(t) -> Dict[str, Any]:
    return {"status": t.status, "reason": t.reason,
            "latency": None if t.latency_s is None
            else round(t.latency_s, 9)}


# ---------------------------------------------------------------------------
def _drill_breaker(seed: int) -> Dict[str, Any]:
    clock = ManualClock()
    svc = _mk_service(clock, seed)
    v1, X = _train_small(seed)
    v2, _ = _train_small(seed, trees=7)
    svc.registry.publish("m", v1, gate_rows=X[:4])
    svc.registry.publish("m", v2, gate_rows=X[:4])   # last_good = v1
    threshold = svc._breaker_kw["threshold"]
    # enough failures to trip AND kill the first half-open probe; the
    # second probe (after the next backoff step) finds a healed model
    faultinject.inject(fail_predict_model="m",
                       fail_predict_times=threshold + 1)
    ticks: List[Dict[str, Any]] = []
    trip_tick = recovery_tick = None
    try:
        for tick in range(14):
            clock.sleep(0.05)
            t = svc.submit(X[tick % 4].reshape(1, -1), model="m")
            svc.pump(force=True)
            br = svc.breakers["m"]
            ticks.append(dict(_tick_status(t), tick=tick,
                              breaker=br.state,
                              failures=br.consecutive_failures))
            if trip_tick is None and br.trip_count > 0:
                trip_tick = tick
            if (recovery_tick is None and trip_tick is not None
                    and br.state == "closed"):
                recovery_tick = tick
    finally:
        faultinject.clear()
    br = svc.breakers["m"]
    return {
        "scenario": "breaker", "seed": seed,
        "trip_tick": trip_tick, "recovery_tick": recovery_tick,
        "trip_count": br.trip_count,
        "breaker_events": [dict(e, t=round(e["t"], 9))
                           for e in br.events],
        "ticks": ticks,
        "fallback_served": svc.counters["fallback_served"],
        "errors": svc.counters["errors"],
        "final_state": br.state,
    }


def _drill_deadline(seed: int) -> Dict[str, Any]:
    clock = ManualClock()
    svc = _mk_service(clock, seed, max_delay=0.01)
    bst, X = _train_small(seed)
    svc.registry.publish("m", bst, gate_rows=X[:4])
    # one slow dispatch (0.2 s on the virtual clock) per armed count:
    # requests behind it in later lanes watch their budget die in queue
    faultinject.inject(slow_predict_model="m", slow_predict_seconds=0.2,
                       slow_predict_times=1)
    tickets = []          # (ticket, relative budget or None)
    try:
        # lane A: generous budget, eats the injected stall
        tickets.append((svc.submit(X[0].reshape(1, -1), model="m",
                                   deadline_s=1.0), 1.0))
        # lane B (different range => different lane): tight budgets
        for i in range(4):
            budget = 0.05 if i % 2 == 0 else 0.5
            tickets.append((svc.submit(
                X[i + 1].reshape(1, -1), model="m", num_iteration=3,
                deadline_s=budget), budget))
        svc.pump(force=True)     # dispatches lane A (stalls 0.2s) + B
        # the stall burned 0.2 s before lane B's dispatch check ran
    finally:
        faultinject.clear()
    # the invariant with teeth: nothing that was served outlived its
    # budget — expired work is shed pre-dispatch, never answered late
    dispatched_expired = sum(
        1 for t, budget in tickets
        if t.status == "ok" and budget is not None
        and (t.latency_s or 0.0) > budget)
    return {
        "scenario": "deadline", "seed": seed,
        "tickets": [_tick_status(t) for t, _ in tickets],
        "shed": svc.counters["shed"],
        "shed_reasons": dict(svc.admission.shed),
        "served": svc.counters["served"],
        "dispatched_expired": dispatched_expired,   # must stay 0
        "clock_end": round(clock.now, 9),
    }


def _drill_flood(seed: int) -> Dict[str, Any]:
    clock = ManualClock()
    depth = 6
    svc = _mk_service(clock, seed, queue_depth=depth, flush_rows=1 << 14,
                      max_delay=10.0)
    bst, X = _train_small(seed)
    svc.registry.publish("m", bst, gate_rows=X[:4])
    faultinject.inject(flood_tenant="t0", flood_requests=4 * depth)
    spec = faultinject.take_flood()
    faultinject.clear()
    tenant, count = spec
    rng = np.random.RandomState(seed)
    order = rng.randint(0, 3, size=count)      # seeded kind sequence
    kinds = [("contrib", "raw", "leaf")[i] for i in order]
    tickets = []
    for i, kind in enumerate(kinds):
        tickets.append((i, kind, svc.submit(
            X[i % 8].reshape(1, -1), model="m", tenant=tenant,
            kind=kind)))
    q = svc.admission.queue_for(tenant)
    shed_order = [(i, kind, t.reason) for i, kind, t in tickets
                  if t.status == "shed"]
    svc.pump(force=True)
    return {
        "scenario": "flood", "seed": seed,
        "flood": {"tenant": tenant, "count": count},
        "queue_depth": depth,
        "max_depth_seen": q.max_depth_seen,
        "bounded": q.max_depth_seen <= depth,
        "shed_order": shed_order,
        "shed_total": svc.counters["shed"],
        "served": svc.counters["served"],
        "survivor_kinds": sorted({kind for _, kind, t in tickets
                                  if t.status == "ok"}),
        "final_statuses": [t.status for _, _, t in tickets],
    }


def _drill_swap(seed: int) -> Dict[str, Any]:
    clock = ManualClock()
    svc = _mk_service(clock, seed, flush_rows=64, max_delay=10.0,
                      queue_depth=128)
    v1, X = _train_small(seed)
    v2, _ = _train_small(seed + 1, trees=6)
    svc.registry.publish("m", v1, gate_rows=X[:64])
    eng1 = v1._gbdt.serving
    warm1 = dict(eng1.trace_counts)

    def burst():
        ts = [svc.submit(X[j].reshape(1, -1), model="m")
              for j in range(64)]
        svc.pump(force=True)
        return ts

    pre = burst()                           # coalesced on v1
    snap1 = dict(eng1.trace_counts)
    rep = svc.registry.publish("m", v2, gate_rows=X[:64])  # swap!
    post = burst()                          # coalesced on v2
    eng2 = v2._gbdt.serving
    v1_new_traces = {k: v - snap1.get(k, 0)
                     for k, v in eng1.trace_counts.items()
                     if v - snap1.get(k, 0) > 0}
    out_pre = np.concatenate([t.result.reshape(-1) for t in pre])
    out_post = np.concatenate([t.result.reshape(-1) for t in post])
    want_pre = np.asarray(v1.predict(X[:64], raw_score=True)).reshape(-1)
    want_post = np.asarray(v2.predict(X[:64], raw_score=True)).reshape(-1)
    return {
        "scenario": "swap", "seed": seed,
        "warm_v1": {f"{k[0]}@{k[1]}": v for k, v in warm1.items()},
        "swap_warm_traces": {f"{k[0]}@{k[1]}": v
                             for k, v in rep["warm_traces"].items()},
        "one_trace_per_key_on_swap": all(
            v == 1 for v in rep["warm_traces"].values()),
        "v1_retraces_during_swap": {f"{k[0]}@{k[1]}": v
                                    for k, v in v1_new_traces.items()},
        "v2_total_traces": {f"{k[0]}@{k[1]}": v
                            for k, v in eng2.trace_counts.items()},
        "pre_swap_parity": bool(np.allclose(out_pre, want_pre,
                                            rtol=1e-6, atol=1e-6)),
        "post_swap_parity": bool(np.allclose(out_post, want_post,
                                             rtol=1e-6, atol=1e-6)),
        "versions_differ": bool(not np.allclose(want_pre, want_post)),
        "registry_version": svc.registry.version("m"),
        "served": svc.counters["served"],
    }


_DRILLS: Dict[str, Callable[[int], Dict[str, Any]]] = {
    "breaker": _drill_breaker,
    "deadline": _drill_deadline,
    "flood": _drill_flood,
    "swap": _drill_swap,
}


def run_serve_drill(scenario: str, seed: int = 0) -> Dict[str, Any]:
    """Run one scenario; the report is a pure function of ``seed``
    (tier-1 asserts two runs are identical)."""
    try:
        fn = _DRILLS[scenario]
    except KeyError:
        raise ValueError(f"unknown serve drill {scenario!r} "
                         f"(want one of {DRILL_SCENARIOS})") from None
    return fn(int(seed))
