"""Admission control: who gets to enqueue work, and what gets shed.

Three mechanisms, all deterministic under an injected clock (the same
contract as :mod:`lightgbm_tpu.robustness.retry`: every source of
nondeterminism is threaded explicitly so fault drills replay
bit-for-bit):

* :class:`TokenBucket` — per-tenant rate limiting.  Tokens refill
  continuously from the injected clock; an empty bucket sheds the
  request at submit time with ``ratelimit`` (cheapest possible reject:
  no queue slot, no batch state).
* :class:`TenantQueue` — a bounded per-tenant queue.  A full queue
  backpressures: the DEGRADATION LADDER sheds the lowest class of
  pending work first (``pred_contrib`` before ``leaf`` before ``raw``,
  oldest first within a class — deterministic ordering, pinned by the
  queue-flood drill) to admit higher-class work; an incoming request
  that is itself the lowest class is rejected outright.
* :class:`CircuitBreaker` — per-model fail-fast.  ``threshold``
  consecutive dispatch failures trip it OPEN; while open, requests
  fail fast (or fall back to the last-good model version — the
  registry's side of the ladder).  Recovery follows the seeded
  :func:`lightgbm_tpu.robustness.retry.backoff_schedule`: after each
  scheduled delay ONE probe request passes through (half-open); a
  probe success closes the breaker, a failure re-opens it at the next
  backoff step.  Jitter is seeded, never wall-clock, so a drill's trip
  and recovery ticks replay identically.

Concurrency contract (conlint tier C): none of these classes carries a
lock of its own — they are owned by :class:`ServingService` and only
ever touched under ``service._lock`` (submit/stats paths) or from the
single pump holding ``service._pump_lock`` (dispatch outcomes on the
breaker).  Breaker state reads on the dispatch fast path are
single-attribute GIL-atomic reads by design.
"""

from __future__ import annotations

import time
from collections import OrderedDict, deque
from typing import Any, Callable, Deque, Dict, List, Optional

from ..robustness.retry import backoff_schedule

# degradation ladder: under pressure the expensive explanatory kinds
# are shed before the cheap decision-path kinds — a contrib request
# costs ~100x a raw request through the SHAP kernel and its absence
# degrades a dashboard, not a decision
KIND_PRIORITY = {"raw": 0, "leaf": 1, "contrib": 2}


def kind_priority(kind: str) -> int:
    return KIND_PRIORITY.get(kind, len(KIND_PRIORITY))


class TokenBucket:
    """Continuous-refill token bucket on an injectable clock.

    ``rate`` tokens/second refill up to ``burst``; ``rate <= 0``
    disables limiting (always allows).  Refill is computed from clock
    deltas, not a background thread, so a ManualClock drill replays
    the exact same admit/shed sequence."""

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = max(float(burst), 1.0)
        self._clock = clock
        self._tokens = self.burst
        self._last = clock()

    def allow(self, cost: float = 1.0) -> bool:
        if self.rate <= 0.0:
            return True
        now = self._clock()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._last) * self.rate)
        self._last = now
        if self._tokens >= cost:
            self._tokens -= cost
            return True
        return False

    def is_full(self, now: float) -> bool:
        """True when dropping this bucket loses no rate-limit state (a
        recreated bucket starts at ``burst``, which equals a bucket
        that has refilled completely)."""
        if self.rate <= 0.0:
            return True
        return (self._tokens
                + (now - self._last) * self.rate) >= self.burst


class TenantQueue:
    """Bounded FIFO with ladder-ordered shedding.

    ``depth`` bounds the number of queued requests (never exceeded —
    the queue-flood drill asserts ``max_depth_seen <= depth``).  On
    overflow, :meth:`offer` sheds deterministically: the pending
    request of the LOWEST class (highest ``kind_priority``), oldest
    first, is evicted to admit a higher-class arrival; an arrival that
    is itself lowest-class (or ties the worst pending) is rejected."""

    def __init__(self, depth: int):
        self.depth = max(int(depth), 1)
        self._q: "OrderedDict[int, Any]" = OrderedDict()
        self.max_depth_seen = 0
        self.shed_count = 0

    def __len__(self) -> int:
        return len(self._q)

    def offer(self, req) -> Optional[Any]:
        """Enqueue ``req``.  Returns the request that was SHED to make
        room (the caller fails its ticket), ``req`` itself when the
        arrival is rejected, or None when nothing was shed."""
        shed = None
        if len(self._q) >= self.depth:
            victim = self._worst()
            if victim is not None and (kind_priority(victim.kind)
                                       > kind_priority(req.kind)):
                del self._q[victim.rid]
                shed = victim
            else:
                self.shed_count += 1
                return req
            self.shed_count += 1
        self._q[req.rid] = req
        self.max_depth_seen = max(self.max_depth_seen, len(self._q))
        return shed

    def _worst(self):
        worst = None
        for req in self._q.values():       # insertion (arrival) order
            if worst is None or kind_priority(req.kind) > kind_priority(
                    worst.kind):
                worst = req
        return worst

    def take(self, rid: int) -> Optional[Any]:
        return self._q.pop(rid, None)

    def drain(self) -> List[Any]:
        out = list(self._q.values())
        self._q.clear()
        return out


class CircuitBreaker:
    """Per-model consecutive-failure breaker with seeded backoff probes.

    States: ``closed`` (traffic flows) -> ``open`` (fail fast) ->
    ``half-open`` (one probe per backoff step) -> ``closed`` on probe
    success.  The probe delays are ``backoff_schedule(attempts, base,
    max_delay, jitter, seed)`` — a pure function, so two drills with
    the same seed trip and recover at identical ticks.  Past the last
    scheduled step the final delay repeats (a dead model keeps being
    probed at the capped cadence, never abandoned)."""

    def __init__(self, threshold: int = 5, attempts: int = 6,
                 base_delay: float = 0.05, max_delay: float = 30.0,
                 jitter: float = 0.0, seed: int = 0,
                 deadline: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.threshold = max(int(threshold), 1)
        # ``deadline`` caps the CUMULATIVE scheduled probe delay (the
        # retry.py budget contract); the final surviving delay then
        # repeats, so a capped ladder probes at a steady cadence
        # instead of backing off forever
        self._delays = backoff_schedule(attempts, base_delay, max_delay,
                                        jitter=jitter, seed=seed,
                                        deadline=deadline) \
            or [float(base_delay)]
        self._clock = clock
        self.state = "closed"
        self.consecutive_failures = 0
        self.trip_count = 0
        self._step = 0
        self._probe_at = 0.0
        self._probe_out = False
        # drill/ops-readable history, bounded: a dead model is probed
        # forever at the capped cadence and must not leak memory
        self.events: Deque[Dict[str, Any]] = deque(maxlen=256)

    def _emit(self, what: str) -> None:
        self.events.append({"event": what, "t": self._clock(),
                            "state": self.state,
                            "failures": self.consecutive_failures})

    def allow(self) -> str:
        """``"closed"`` — dispatch normally; ``"probe"`` — dispatch as
        the half-open probe (caller MUST report the outcome);
        ``"open"`` — fail fast / degrade."""
        if self.state == "closed":
            return "closed"
        now = self._clock()
        if not self._probe_out and now >= self._probe_at:
            self._probe_out = True
            self.state = "half-open"
            self._emit("probe")
            return "probe"
        return "open"

    def probe_inconclusive(self) -> None:
        """The in-flight probe carried no evidence about the model
        (e.g. the probe batch itself was malformed): return the token
        so a later dispatch can probe again — without this, the
        breaker would wait forever on an outcome that never arrives."""
        if self._probe_out:
            self._probe_out = False
            self.state = "open"
            self._emit("probe_inconclusive")

    def record_success(self) -> None:
        self.consecutive_failures = 0
        if self.state != "closed":
            self.state = "closed"
            self._step = 0
            self._probe_out = False
            self._emit("recovered")

    def record_failure(self) -> None:
        self.consecutive_failures += 1
        if self.state == "closed":
            if self.consecutive_failures >= self.threshold:
                self._trip()
        else:                               # failed half-open probe
            self._probe_out = False
            self._step = min(self._step + 1, len(self._delays) - 1)
            self.state = "open"
            self._probe_at = self._clock() + self._delays[self._step]
            self._emit("reopened")

    def _trip(self) -> None:
        self.state = "open"
        self.trip_count += 1
        self._step = 0
        self._probe_out = False
        self._probe_at = self._clock() + self._delays[0]
        self._emit("tripped")


class AdmissionController:
    """Submit-time gate: rate limit, queue bound, ladder shedding.

    One :class:`TenantQueue` + :class:`TokenBucket` pair per tenant,
    created lazily with shared policy parameters.  Deadline shedding
    happens later, at dispatch time (:meth:`expired`): a request that
    sat out its budget in the queue is dropped BEFORE it joins a
    batch, never after device work was spent on it."""

    def __init__(self, queue_depth: int = 256, rate: float = 0.0,
                 burst: float = 64.0, max_tenants: int = 1024,
                 clock: Callable[[], float] = time.monotonic):
        self.queue_depth = int(queue_depth)
        self.rate = float(rate)
        self.burst = float(burst)
        # tenant names are CLIENT-supplied: without a cap, rotating
        # names mints a fresh empty queue per burst and total queued
        # memory (and the stats surface) grows without bound
        self.max_tenants = max(int(max_tenants), 1)
        self._clock = clock
        self.queues: Dict[str, TenantQueue] = {}
        self.buckets: Dict[str, TokenBucket] = {}
        self.shed: Dict[str, int] = {}       # reason -> count

    def _shed(self, reason: str) -> None:
        self.shed[reason] = self.shed.get(reason, 0) + 1

    def queue_for(self, tenant: str) -> TenantQueue:
        q = self.queues.get(tenant)
        if q is None:
            q = self.queues[tenant] = TenantQueue(self.queue_depth)
        return q

    def bucket_for(self, tenant: str) -> TokenBucket:
        b = self.buckets.get(tenant)
        if b is None:
            b = self.buckets[tenant] = TokenBucket(self.rate, self.burst,
                                                  self._clock)
        return b

    def _prune_idle_tenants(self) -> None:
        """Drop EMPTY tenant queues so legitimate tenant churn stays
        under ``max_tenants`` while total queued rows remain bounded
        by max_tenants * queue_depth.  A tenant's token bucket only
        goes with it once fully refilled — dropping a part-empty
        bucket would hand the tenant a fresh full burst and defeat the
        rate limit."""
        now = self._clock()
        for t in [t for t, q in self.queues.items() if len(q) == 0]:
            b = self.buckets.get(t)
            if b is not None and not b.is_full(now):
                continue
            del self.queues[t]
            self.buckets.pop(t, None)

    def admit(self, req):
        """Admit ``req`` to its tenant queue.  Returns ``(shed,
        reason)``: ``(None, None)`` on clean admission; ``(req,
        "ratelimit"|"queue_full"|"tenant_limit")`` when the arrival
        itself is rejected; ``(victim, "degraded")`` when the ladder
        evicted a pending lower-class request to make room."""
        if req.tenant not in self.queues \
                and len(self.queues) >= self.max_tenants:
            self._prune_idle_tenants()
            if len(self.queues) >= self.max_tenants:
                self._shed("tenant_limit")
                return req, "tenant_limit"
        if not self.bucket_for(req.tenant).allow(req.cost):
            self._shed("ratelimit")
            return req, "ratelimit"
        victim = self.queue_for(req.tenant).offer(req)
        if victim is None:
            return None, None
        reason = "queue_full" if victim is req else "degraded"
        self._shed(reason)
        return victim, reason

    def expired(self, req, now: float) -> bool:
        if req.deadline is not None and now > req.deadline:
            self._shed("deadline")
            return True
        return False

    def stats(self) -> Dict[str, Any]:
        # dict(...) snapshots: stats readers race with submit threads
        # creating first-seen tenants
        return {
            "tenants": {
                t: {"depth": len(q), "max_depth_seen": q.max_depth_seen,
                    "shed": q.shed_count}
                for t, q in sorted(dict(self.queues).items())},
            "shed": dict(sorted(dict(self.shed).items())),
        }
