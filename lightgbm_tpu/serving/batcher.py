"""The coalescing micro-batcher: many small requests, one dispatch.

Serving-shaped traffic is dominated by single-row and few-row requests;
dispatching each alone would pay one padded-bucket device round trip
per request (a 1-row request costs the full ``MIN_BUCKET`` bucket).
The batcher is the tree-model analog of an LLM serving stack's
continuous batcher: concurrent requests for the same (model, kind,
iteration range) COALESCE into one matrix that the engine pads into
its existing power-of-two buckets (``models/serving.py bucket_rows``)
— so N concurrent clients cost exactly the per-(kind, bucket) compile
counts ``test_predict_engine.py`` already pins, and one dispatch per
flushed bucket.

Flush policy is size-OR-deadline:

* **size** — a lane reaching ``flush_rows`` pending rows flushes
  immediately (``flush_rows`` should be one of the engine's buckets;
  the coalesced matrix then pads to exactly that bucket);
* **deadline** — the lane flushes once its oldest request has waited
  ``max_delay`` seconds, so a lone request is never held hostage for
  a batch that isn't coming; a request whose own deadline budget would
  expire inside the wait flushes the lane early.

The batcher holds NO thread of its own and reads only the injected
clock: the service's pump (or a drill) asks :meth:`due` and drains —
which is what makes flood/deadline drills bit-reproducible.

Concurrency contract (conlint tier C): the batcher has no lock of its
own — every mutation (``add`` on submit, ``due``/drain from the pump)
and every ``stats()`` read happens under the owning
``ServingService._lock``; the service, not the batcher, is the unit of
mutual exclusion.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

# a lane is the unit of coalescing: requests only merge when one
# engine call can serve them all — including the row WIDTH, so two
# clients sending different feature counts can never concatenate into
# one (crashing) batch
LaneKey = Tuple[str, str, int, int, int]  # (model, kind, start, num, F)


def _lane_key(req) -> LaneKey:
    return (req.model, req.kind, req.start_iteration,
            req.num_iteration, int(req.rows.shape[1]))


class _Lane:
    __slots__ = ("reqs", "rows", "oldest_t", "earliest_deadline")

    def __init__(self):
        self.reqs: List[Any] = []
        self.rows = 0
        self.oldest_t: Optional[float] = None
        self.earliest_deadline: Optional[float] = None


class CoalescingBatcher:
    """Accumulate requests per lane; flush by size or deadline."""

    def __init__(self, flush_rows: int = 256, max_delay: float = 0.002,
                 clock: Callable[[], float] = time.monotonic):
        self.flush_rows = max(int(flush_rows), 1)
        self.max_delay = float(max_delay)
        self._clock = clock
        self._lanes: "OrderedDict[LaneKey, _Lane]" = OrderedDict()
        self.coalesced_sizes: Dict[int, int] = {}   # batch rows -> count

    def __len__(self) -> int:
        # list(...) snapshot: stats readers race the pump's del/insert
        return sum(len(lane.reqs) for lane in list(self._lanes.values()))

    def add(self, req) -> bool:
        """Queue ``req`` on its lane; True when the lane is now
        size-due (the caller should pump without waiting)."""
        key = _lane_key(req)
        lane = self._lanes.get(key)
        if lane is None:
            lane = self._lanes[key] = _Lane()
        lane.reqs.append(req)
        lane.rows += req.rows.shape[0]
        if lane.oldest_t is None:
            lane.oldest_t = req.t_submit
        if req.deadline is not None:
            lane.earliest_deadline = (
                req.deadline if lane.earliest_deadline is None
                else min(lane.earliest_deadline, req.deadline))
        return lane.rows >= self.flush_rows

    def _lane_due(self, lane: _Lane, now: float) -> bool:
        if lane.rows >= self.flush_rows:
            return True
        if lane.oldest_t is not None \
                and now - lane.oldest_t >= self.max_delay:
            return True
        # a request that cannot survive the remaining coalescing wait
        # flushes the lane now — holding it for stragglers would turn
        # the batcher itself into the deadline killer
        if lane.earliest_deadline is not None \
                and lane.earliest_deadline <= now + self.max_delay:
            return True
        return False

    def due(self, now: Optional[float] = None,
            force: bool = False) -> List[LaneKey]:
        """Lane keys ready to flush, in lane-creation order (the order
        requests first arrived — deterministic under one clock)."""
        if now is None:
            now = self._clock()
        return [key for key, lane in self._lanes.items()
                if force or self._lane_due(lane, now)]

    def next_due_at(self) -> Optional[float]:
        """Earliest clock time any current lane becomes deadline-due
        (None when empty): the async pump sleeps until then instead of
        polling."""
        out = None
        for lane in self._lanes.values():
            if lane.rows >= self.flush_rows:
                return self._clock()
            cands = []
            if lane.oldest_t is not None:
                cands.append(lane.oldest_t + self.max_delay)
            if lane.earliest_deadline is not None:
                cands.append(lane.earliest_deadline)
            for c in cands:
                out = c if out is None else min(out, c)
        return out

    def drain(self, key: LaneKey,
              max_rows: Optional[int] = None) -> List[Any]:
        """Remove and return the lane's requests (arrival order).
        ``max_rows`` caps the flushed batch at the bucket size: a lane
        that grew past ``flush_rows`` between pumps dispatches in
        bucket-sized slices (one dispatch per flushed bucket — a
        350-row pileup must not pad to the 512 bucket and trace a
        program the serial path never compiles); a single request
        larger than the cap still dispatches alone."""
        lane = self._lanes.get(key)
        if lane is None:
            return []
        if max_rows is None or lane.rows <= max_rows:
            del self._lanes[key]
            out, rows = lane.reqs, lane.rows
        else:
            taken, rows = 0, 0
            while taken < len(lane.reqs) and (
                    taken == 0 or
                    rows + lane.reqs[taken].rows.shape[0] <= max_rows):
                rows += lane.reqs[taken].rows.shape[0]
                taken += 1
            # one slice, not per-request pop(0) shifts — a post-stall
            # pileup must not turn the flush into quadratic host work
            out = lane.reqs[:taken]
            lane.reqs = lane.reqs[taken:]
            lane.rows -= rows
            if not lane.reqs:
                del self._lanes[key]
            else:
                # the remainder keeps waiting: re-derive the aggregates
                # the taken head carried
                lane.oldest_t = lane.reqs[0].t_submit
                dls = [r.deadline for r in lane.reqs
                       if r.deadline is not None]
                lane.earliest_deadline = min(dls) if dls else None
        self.coalesced_sizes[rows] = \
            self.coalesced_sizes.get(rows, 0) + 1
        return out

    def remove(self, req) -> bool:
        """Drop one request (a ladder eviction) from its lane, keeping
        the lane's aggregates consistent."""
        key = _lane_key(req)
        lane = self._lanes.get(key)
        if lane is None or req not in lane.reqs:
            return False
        lane.reqs.remove(req)
        lane.rows -= req.rows.shape[0]
        if not lane.reqs:
            del self._lanes[key]
        else:
            # the victim may have carried the lane's oldest arrival or
            # earliest deadline; a stale aggregate would flush the
            # survivors early in an undersized batch
            lane.oldest_t = min(r.t_submit for r in lane.reqs)
            dls = [r.deadline for r in lane.reqs
                   if r.deadline is not None]
            lane.earliest_deadline = min(dls) if dls else None
        return True

    def stats(self) -> Dict[str, Any]:
        return {"pending": len(self),
                "lanes": len(self._lanes),
                "coalesced_sizes": dict(sorted(
                    dict(self.coalesced_sizes).items()))}
