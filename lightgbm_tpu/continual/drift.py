"""Deterministic drift injection for the continual-training runtime.

In the spirit of ``robustness/faultinject.py``: every failure path of
the online pipeline must be reproducible in tier-1 without real
traffic.  A :class:`DriftStream` emits per-tick mini-batches that are a
PURE function of ``(seed, tick, spec)`` — no shared RNG state between
ticks — so any scenario replays bit-exact, and a :class:`DriftSpec`
arms the four fault classes the runtime must survive:

  * **covariate shift** — feature means jump at a chosen tick (the
    served model extrapolates off its training support and its metric
    regresses);
  * **label flip / concept shift** — the label relation inverts for a
    fraction of rows (binary: Bernoulli flips; regression: sign-flipped
    targets), the classic sudden-concept-drift injection;
  * **NaN burst** — a block of ticks carries NaN features and labels (a
    poisoned upstream join), exercising the refit path's
    ``nonfinite_policy`` guard rails;
  * **kill mid-retrain** — consumed by ``ContinualBooster`` as a
    ``retrain_fault``: the retrain triggered by the drift dies at a
    chosen boosting iteration via ``robustness/faultinject.py``, and
    either resumes from its checkpoint on the next retry or (with
    retries exhausted) degrades to the last-good model.

:func:`run_drift_drill` is the end-to-end rehearsal used by
``tools/profile_continual.py``, ``tools/ab_bench.py --drift`` and the
tier-1 tests.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

import numpy as np


@dataclass
class DriftSpec:
    """Which drifts hit the stream, and when (tick indices, 0-based)."""

    covariate_shift_at: Optional[int] = None
    covariate_shift: float = 2.5          # added to the feature mean(s)
    # None = shift EVERY feature (the classic whole-batch drill);
    # an index = plant the shift on ONE feature, the scenario the
    # health-layer skew attribution must pin (rank it #1)
    covariate_shift_feature: Optional[int] = None
    label_flip_at: Optional[int] = None
    label_flip_fraction: float = 0.4
    nan_burst_at: Optional[int] = None
    nan_burst_ticks: int = 1
    nan_fraction: float = 0.3             # of rows; features AND labels
    # kill-mid-retrain: ContinualBooster(retrain_fault=spec.retrain_fault())
    kill_retrain_at_iteration: Optional[int] = None
    kill_retrain_times: int = 1

    def retrain_fault(self) -> Optional[Dict[str, int]]:
        if self.kill_retrain_at_iteration is None:
            return None
        return {"kill_at_iteration": int(self.kill_retrain_at_iteration),
                "times": int(self.kill_retrain_times)}


class DriftStream:
    """Per-tick mini-batches; ``batch(t)`` is pure in ``(seed, t)``."""

    def __init__(self, num_features: int = 6, rows: int = 256,
                 seed: int = 0, spec: Optional[DriftSpec] = None,
                 binary: bool = False, noise: float = 0.1):
        self.f = int(num_features)
        self.rows = int(rows)
        self.seed = int(seed)
        self.spec = spec or DriftSpec()
        self.binary = bool(binary)
        self.noise = float(noise)
        self.coef = np.random.RandomState(seed).normal(size=self.f)

    def batch(self, t: int):
        """(X, y) for tick ``t`` — replayable in isolation: the RNG is
        re-derived from (seed, t), never carried across ticks."""
        sp = self.spec
        rs = np.random.RandomState((self.seed * 1_000_003 + t)
                                   % (2 ** 31 - 1))
        X = rs.normal(size=(self.rows, self.f))
        if (sp.covariate_shift_at is not None
                and t >= sp.covariate_shift_at):
            if sp.covariate_shift_feature is None:
                X = X + sp.covariate_shift
            else:
                X[:, int(sp.covariate_shift_feature)] += sp.covariate_shift
        raw = X @ self.coef + self.noise * rs.normal(size=self.rows)
        if self.binary:
            y = (raw > np.median(raw)).astype(np.float64)
            if sp.label_flip_at is not None and t >= sp.label_flip_at:
                flip = rs.rand(self.rows) < sp.label_flip_fraction
                y = np.where(flip, 1.0 - y, y)
        else:
            y = raw.astype(np.float64)
            if sp.label_flip_at is not None and t >= sp.label_flip_at:
                flip = rs.rand(self.rows) < sp.label_flip_fraction
                y = np.where(flip, -y, y)
        if (sp.nan_burst_at is not None and
                sp.nan_burst_at <= t < sp.nan_burst_at
                + sp.nan_burst_ticks):
            bad = rs.rand(self.rows) < sp.nan_fraction
            X = X.copy()
            X[bad] = np.nan
            y = y.copy()
            y[bad] = np.nan
        return X, y


# ---------------------------------------------------------------------------
# end-to-end drill scenarios
# ---------------------------------------------------------------------------
_DRILL_PARAMS = {
    "objective": "regression", "num_leaves": 15, "learning_rate": 0.15,
    "min_data_in_leaf": 5, "verbosity": -1, "metric": "",
    "seed": 7, "num_iterations": 20,
    "continual_window": 2, "continual_metric_threshold": 0.5,
    "continual_rollback_window": 3, "continual_buffer_ticks": 4,
    "continual_retrain_attempts": 3, "continual_backoff_base": 0.01,
    "continual_cooldown": 2, "nonfinite_policy": "skip_iteration",
}


def run_drift_drill(scenario: str = "swap", rows: int = 256,
                    features: int = 6, drift_at: int = 4,
                    post_ticks: int = 6, seed: int = 11,
                    checkpoint_dir: Optional[str] = None,
                    params: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Any]:
    """One deterministic scenario end-to-end; returns a report dict.

    * ``swap`` — covariate shift at ``drift_at``; a kill-mid-retrain is
      armed once and resumes from checkpoint (when ``checkpoint_dir``
      is given); expects detection within the window, a completed
      retrain, a guarded swap with at most one compile per
      (kind, bucket), and metric recovery.
    * ``degrade`` — same drift, but every retrain attempt is killed and
      no checkpoints exist: expects retry exhaustion and graceful
      degradation to the last-good model (which keeps serving).
    * ``rollback`` — no drift; a deliberately bad candidate is force-
      swapped in; expects the watchdog to roll back within the rollback
      window and post-rollback predictions bit-identical to pre-swap.
    * ``attribution`` — covariate shift planted on ONE feature (the
      stream's strongest coefficient) with ``health=counters``: the
      regression tick's skew attribution must rank the planted feature
      #1 against the reference profile (the acceptance drill for the
      health layer; asserted by tests and ``ab_bench --drift``).
    """
    import time

    from ..robustness.retry import ManualClock
    from .runtime import ContinualBooster

    p = dict(_DRILL_PARAMS)
    if scenario == "attribution":
        # the drill that must NAME the planted feature: health digests
        # on, cheap retrain (the drill stops at the detection tick)
        p.update({"health": "counters", "continual_retrain_rounds": 2})
    p.update(params or {})
    clk = ManualClock()

    spec = DriftSpec()
    retrain_fault = None
    if scenario == "attribution":
        spec.covariate_shift_at = drift_at
    if scenario in ("swap", "degrade"):
        spec.covariate_shift_at = drift_at
        if scenario == "swap" and checkpoint_dir:
            # die once past the first checkpoint; the retry resumes from
            # it (PR 1 machinery) and completes bit-exact
            # int(): the CLI path forwards key=value overrides as raw
            # strings (Config parses them later; this arithmetic won't)
            interval = max(int(p.get("continual_retrain_rounds")
                               or p["num_iterations"]) // 4, 1)
            spec.kill_retrain_at_iteration = interval + 1
            spec.kill_retrain_times = 1
        elif scenario == "degrade":
            spec.kill_retrain_at_iteration = 1
            spec.kill_retrain_times = 10 ** 6   # every attempt dies
        retrain_fault = spec.retrain_fault()

    planted = None
    if scenario == "attribution":
        # plant on the stream's strongest coefficient so the shift both
        # regresses the metric and has an unambiguous right answer
        planted = int(np.argmax(np.abs(
            np.random.RandomState(seed).normal(size=features))))
        spec.covariate_shift_feature = planted

    stream = DriftStream(num_features=features, rows=rows, seed=seed,
                         spec=spec)
    warm = DriftStream(num_features=features, rows=4 * rows, seed=seed + 1)
    X0, y0 = warm.batch(0)
    cb = ContinualBooster(p, X0, y0, checkpoint_dir=checkpoint_dir,
                          retrain_fault=retrain_fault,
                          sleep=clk.sleep, clock=clk)

    report: Dict[str, Any] = {"scenario": scenario, "rows": rows,
                              "drift_at": drift_at, "ticks": []}
    t0 = time.perf_counter()
    detect_tick = swap_tick = degrade_tick = rollback_tick = None
    n_ticks = drift_at + post_ticks

    if scenario == "rollback":
        # stable stream; swap in a deliberately bad candidate mid-run
        from ..basic import Dataset
        from ..engine import train as _train
        for t in range(drift_at):
            cb.tick(*stream.batch(t))
        Xg, yg = stream.batch(drift_at)
        pre_pred = cb.predict(Xg, raw_score=True)
        Xb = X0[:64]
        bad = _train({**cb._train_params(), "num_iterations": 1,
                      "learning_rate": 1e-6},
                     Dataset(Xb, label=-10.0 * np.ones(len(Xb))),
                     num_boost_round=1)
        cb.force_swap(bad, gate=(Xg, yg))
        swap_tick = drift_at
        for t in range(drift_at, n_ticks):
            r = cb.tick(*stream.batch(t))
            if r.rolled_back and rollback_tick is None:
                rollback_tick = t
                break
        post_pred = cb.predict(Xg, raw_score=True)
        report["rollback_tick"] = rollback_tick
        report["rollback_within"] = (
            rollback_tick is not None and
            rollback_tick - swap_tick <= cb.cfg.continual_rollback_window)
        report["pre_post_identical"] = bool(
            np.array_equal(np.asarray(pre_pred), np.asarray(post_pred)))
        report["swap_tick"] = swap_tick
    elif scenario == "attribution":
        for t in range(n_ticks):
            r = cb.tick(*stream.batch(t))
            report["ticks"].append(r.to_json())
            if r.drift_detected and detect_tick is None:
                detect_tick = t
                break
        report["detect_tick"] = detect_tick
        report["planted_feature"] = planted
        top = (report["ticks"][-1].get("skew_top") or []
               if detect_tick is not None else [])
        report["skew_top"] = top
        report["planted_rank"] = next(
            (i + 1 for i, s in enumerate(top)
             if s["feature"] == planted), None)
        report["planted_ranked_first"] = report["planted_rank"] == 1
        report["detected_within_window"] = (
            detect_tick is not None and
            detect_tick - drift_at <= 2 * cb.cfg.continual_window)
    else:
        for t in range(n_ticks):
            r = cb.tick(*stream.batch(t))
            report["ticks"].append(r.to_json())
            if r.drift_detected and detect_tick is None:
                detect_tick = t
            if r.swapped and swap_tick is None:
                swap_tick = t
                report["swap_new_traces"] = {
                    str(k): v for k, v in r.swap_new_traces.items()}
                report["swap_latency_s"] = r.swap_latency_s
                report["retrain_attempts"] = r.retrain_attempts
            if r.degraded and degrade_tick is None:
                degrade_tick = t
        report["detect_tick"] = detect_tick
        report["swap_tick"] = swap_tick
        report["degrade_tick"] = degrade_tick
        report["detected_within_window"] = (
            detect_tick is not None and
            detect_tick - drift_at <= 2 * cb.cfg.continual_window)
        if swap_tick is not None:
            traces = list(report["swap_new_traces"].values())
            report["one_trace_per_key"] = all(v <= 1 for v in traces)
            post = [r["metric"] for r in report["ticks"][swap_tick + 1:]]
            drifted = [r["metric"] for r in
                       report["ticks"][drift_at:swap_tick + 1]]
            report["metric_recovered"] = bool(
                post and np.mean(post) < np.mean(drifted))
        if scenario == "degrade":
            # the last-good model must still be the one serving
            report["still_serving"] = bool(
                np.isfinite(cb.predict(stream.batch(n_ticks)[0],
                                       raw_score=True)).all())
            report["generation"] = cb.generation
    report["wall_s"] = round(time.perf_counter() - t0, 3)
    report["history"] = [round(float(m), 6) for m in cb.history]
    report["final_generation"] = cb.generation
    return report
