"""Continual-training runtime: drift-aware refit, guarded atomic
hot-swap, rollback watchdog (ROADMAP item 5).

Composes the PR 1 fault-tolerance runtime (checkpoint/resume,
non-finite guards, retry/backoff, fault injection) and the PR 3
serving engine (mutation-counter pack invalidation) into an online
pipeline that *keeps* a model fresh under drift, crashes, and bad
data.  See :mod:`lightgbm_tpu.continual.runtime` for the state
machine and :mod:`lightgbm_tpu.continual.drift` for the deterministic
drift-injection harness.
"""

from .drift import DriftSpec, DriftStream, run_drift_drill
from .runtime import ContinualBooster, TickReport

__all__ = ["ContinualBooster", "TickReport", "DriftSpec", "DriftStream",
           "run_drift_drill"]
