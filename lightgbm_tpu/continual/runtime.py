"""ContinualBooster: an online serving + freshness loop over one model.

LLM serving stacks treat model hot-swap with automatic rollback as
table stakes (cf. the Gemma-on-TPU serving comparison, arXiv:2605.25645);
inference accelerators like Booster (arXiv:2011.02022) assume the
forest being served is *current*.  This module is the loop that makes
that true for a jax_graft forest under drift, crashes, and bad data:

Each :meth:`ContinualBooster.tick` ingests one fresh mini-dataset and

1. **evaluates prequentially** — predict-then-learn: the tick metric
   scores the SERVED model on data it has not seen, the classic online
   evaluation protocol;
2. **refits leaf values on-device** via ``Booster.refit(decay_rate,
   inplace=True)`` — tree structures stay, leaf outputs blend toward
   the fresh gradients; the serving engine takes the leaf-only
   refresh path (one small transfer, zero re-traces), and the
   ``nonfinite_policy`` guard rails protect the refit gradients from
   poisoned batches exactly like full training iterations;
3. **detects regression** over a windowed eval history: mean of the
   last ``continual_window`` tick metrics vs the window before, with a
   configurable relative threshold;
4. on regression, **retrains from the recent-batch buffer** through
   ``robustness/retry.py`` (seeded jitter — replays are
   bit-reproducible) with PR 1 checkpoint/resume inside each retry, so
   a kill mid-retrain resumes bit-exact instead of restarting; retry
   exhaustion degrades gracefully to the last-good model;
5. **hot-swaps atomically with a gate**: the candidate must not be
   worse than the served model on the gate batch; the swap warms the
   candidate's serving pack FIRST (exactly one compile per
   (kind, bucket)), then installs it with a single reference
   assignment — concurrent readers see the old pack or the new one,
   never a mix, and the ServingEngine's mutation-counter keys make a
   stale compiled program impossible by construction;
6. **watches for post-swap regression** for ``continual_rollback_window``
   ticks and rolls back to the pre-swap booster — whose engine still
   holds its own packs keyed by its own model version, so post-rollback
   predictions are bit-identical to the pre-swap pack.

Every failure path is reproducible without real traffic through the
deterministic drift harness (:mod:`lightgbm_tpu.continual.drift`).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..config import Config
from ..obs import memory as obs_memory
from ..obs import telemetry as obs
from ..robustness import faultinject
from ..robustness.retry import retry_with_backoff
from ..utils import log
from ..utils.log import LightGBMError

_EPS = 1e-12
# history/reports retention cap (entries kept: _RETAIN/2 after a trim);
# far above any window/drill size, small enough to serve for months
_RETAIN = 4096


# ---------------------------------------------------------------------------
# tick metrics (lower is better, host numpy — never a device sync)
# ---------------------------------------------------------------------------
def resolve_metric(name: str, objective: str) -> str:
    name = (name or "auto").lower()
    if name != "auto":
        return name
    if objective in ("binary", "cross_entropy", "cross_entropy_lambda"):
        return "binary_logloss"
    if objective in ("multiclass", "multiclassova"):
        return "multi_logloss"
    return "l2"


def tick_metric(name: str, y: np.ndarray, raw: np.ndarray) -> float:
    """Lower-is-better metric of RAW scores against labels, computed on
    the host in float64 (the tick loop must not add device syncs)."""
    y = np.asarray(y, np.float64)
    raw = np.asarray(raw, np.float64)
    if name == "binary_logloss":
        p = 1.0 / (1.0 + np.exp(-raw.reshape(-1)))
        p = np.clip(p, 1e-15, 1.0 - 1e-15)
        return float(-np.mean(y * np.log(p) + (1.0 - y) * np.log(1.0 - p)))
    if name == "multi_logloss":
        z = raw - raw.max(axis=1, keepdims=True)
        p = np.exp(z)
        p /= p.sum(axis=1, keepdims=True)
        rows = np.arange(len(y))
        return float(-np.mean(np.log(
            np.clip(p[rows, y.astype(np.int64)], 1e-15, None))))
    if name in ("l2", "mse"):
        return float(np.mean((raw.reshape(-1) - y) ** 2))
    raise LightGBMError(f"unsupported continual_metric: {name}")


# ---------------------------------------------------------------------------
# per-tick report
# ---------------------------------------------------------------------------
@dataclass
class TickReport:
    tick: int
    n_rows: int = 0
    metric: float = float("nan")
    generation: int = 0
    refit_applied: bool = False
    refit_skipped: bool = False          # guard skipped every iteration
    drift_detected: bool = False
    retrain_attempts: int = 0
    retrain_completed: bool = False
    retrain_failed: bool = False         # retry budget exhausted: degraded
    swapped: bool = False
    swap_rejected: bool = False          # candidate lost the gate
    swap_latency_s: float = 0.0
    swap_new_traces: Dict[Any, int] = field(default_factory=dict)
    rolled_back: bool = False
    degraded: bool = False               # serving last-good after failures
    # drift attribution (obs/health.py, health != off): at a regression
    # tick, the features whose recent-window digest moved furthest from
    # the reference profile, most-skewed first
    skew_top: List[Dict[str, Any]] = field(default_factory=list)
    notes: List[str] = field(default_factory=list)

    def to_json(self) -> Dict[str, Any]:
        d = dict(self.__dict__)
        d["swap_new_traces"] = {str(k): v
                                for k, v in self.swap_new_traces.items()}
        return d


def _buffer_arrays(cb):
    """Telemetry memory provider: the host-side recent-batch buffer."""
    out = []
    for X, y, w in list(cb.buffer):
        out.extend(a for a in (X, y, w) if a is not None)
    return out


# ---------------------------------------------------------------------------
# the runtime
# ---------------------------------------------------------------------------
class ContinualBooster:
    """Serve one forest and keep it fresh (see module docstring).

    ``params`` are ordinary training params plus the ``continual_*``
    family (config.py); ``data``/``label`` train the initial model.

    ``checkpoint_dir`` (optional) roots per-generation retrain
    checkpoints so a killed retrain RESUMES bit-exact on the next retry
    instead of restarting; without it, retries restart from scratch.

    ``retrain_fault`` (drills only) arms a deterministic
    ``kill_at_iteration`` fault for the first ``times`` retrain
    attempts — the kill-mid-retrain scenario of the drift harness.
    Incompatible with ``background=True`` (fault-injection state is
    process-global; kill drills run synchronous).

    ``sleep``/``clock`` thread through to the retry/backoff policy so
    tier-1 drills replay instantly and bit-reproducibly
    (robustness/retry.py ManualClock).
    """

    def __init__(self, params: Dict[str, Any], data, label, weight=None,
                 *, checkpoint_dir: Optional[str] = None,
                 background: bool = False,
                 retrain_fault: Optional[Dict[str, int]] = None,
                 sleep: Optional[Callable[[float], None]] = None,
                 clock: Optional[Callable[[], float]] = None,
                 initial_rounds: Optional[int] = None):
        from ..basic import Dataset
        from ..engine import train as _train
        self.params = dict(params)
        self.cfg = Config(self.params)
        obs.configure_from_config(self.cfg)
        from ..obs import health as _obs_health
        _obs_health.configure_from_config(self.cfg)
        self.metric_name = resolve_metric(self.cfg.continual_metric,
                                          self.cfg.objective)
        self.checkpoint_dir = checkpoint_dir
        self.background = bool(background)
        self._sleep = sleep if sleep is not None else time.sleep
        self._clock = clock if clock is not None else time.monotonic
        if retrain_fault and background:
            # faultinject state is process-GLOBAL: arming it from the
            # background worker would kill concurrent foreground
            # training and its clear() would disarm other injections —
            # drills that need the kill fault run synchronous
            raise LightGBMError(
                "retrain_fault cannot be combined with background=True "
                "(fault injection is process-global, not thread-local)")
        self._retrain_fault = dict(retrain_fault) if retrain_fault else None
        self._fault_remaining = int(
            (retrain_fault or {}).get("times", 1)) if retrain_fault else 0

        rounds = initial_rounds or self.cfg.num_iterations
        self.booster = _train(self._train_params(),
                              Dataset(np.asarray(data), label=label,
                                      weight=weight),
                              num_boost_round=rounds)
        self._warm(self.booster)

        self.tick_no = 0
        self.generation = 0
        self.history: List[float] = []
        self.buffer: deque = deque(maxlen=max(
            int(self.cfg.continual_buffer_ticks), 1))
        self.reports: List[TickReport] = []
        self.last_good: Optional[Any] = None
        self._watch_left = 0
        self._pre_swap_baseline: Optional[float] = None
        self._cooldown = 0
        self._bg: Optional[Dict[str, Any]] = None
        self._gate: Optional[Tuple[np.ndarray, np.ndarray]] = None
        # drift attribution state (obs/health.py): reference profile +
        # binner of the SERVED model, and a rolling window of per-tick
        # digests so a regression tick can name the drifted features
        self._health_ref = None
        self._health_digests: deque = deque(maxlen=1)
        self._refresh_health_ref()
        # telemetry HBM attribution: the recent-batch retrain buffer
        obs_memory.register("continual.buffers", self, _buffer_arrays)

    # -- plumbing -------------------------------------------------------
    def _train_params(self) -> Dict[str, Any]:
        p = dict(self.params)
        # retrain checkpointing is managed per generation below; the
        # caller's checkpoint params must not leak into the initial fit
        for k in ("checkpoint_dir", "checkpoint_interval",
                  "checkpoint_resume"):
            p.pop(k, None)
        return p

    def _warm(self, bst) -> None:
        """Serving-shaped traffic: small tick batches must serve from
        the device pack, so the engine's cold-row gate lifts.  Both
        pack families warm: a kill+resumed retrain restores its head
        trees host-side (no bin-space device arrays), and such a
        candidate serves through the loaded (threshold-index) pack."""
        g = bst._gbdt
        g._flush_pending()
        g.serving.mark_rewarm(("insession", "loaded"))

    def _raw(self, X: np.ndarray) -> np.ndarray:
        return np.asarray(self.booster.predict(np.asarray(X),
                                               raw_score=True))

    # -- drift attribution plumbing (obs/health.py) ---------------------
    def _refresh_health_ref(self) -> None:
        """(Re)bind the attribution reference to the CURRENTLY SERVED
        model's profile and mappers; called at init and after every
        swap/rollback — digests taken against an older model's bin
        space are not comparable, so the window resets with it."""
        from ..obs import health as obs_health
        self._health_ref = None
        self._health_digests = deque(
            maxlen=max(2 * int(self.cfg.continual_window), 4))
        if not obs_health.enabled():
            return
        g = self.booster._gbdt
        prof = getattr(g, "health_profile", None)
        ds = g.train_data
        if prof is not None and ds is not None and ds.groups:
            self._health_ref = (prof, ds)

    def _health_observe(self, X: np.ndarray) -> None:
        ref = self._health_ref
        if ref is None:
            return
        _, ds = ref
        from ..obs import digest as _digest
        try:
            binned = ds.bin_matrix(np.asarray(X, np.float64))
        except Exception:
            return                        # unbinnable batch: no digest
        self._health_digests.append(
            (_digest.bin_counts_host(binned, ds.max_group_bins), len(X)))

    def _health_attribute(self) -> List[Dict[str, Any]]:
        """Top-k drifted features for a regression tick: the recent
        detection window's digests vs the reference profile."""
        ref = self._health_ref
        if ref is None or not self._health_digests:
            return []
        prof, ds = ref
        from ..obs import health as obs_health
        W = int(self.cfg.continual_window)
        recent = list(self._health_digests)[-W:]
        ranked = obs_health.attribute_drift(
            prof, ds, [c for c, _ in recent],
            sum(n for _, n in recent),
            topk=int(getattr(self.cfg, "health_topk", 5) or 5))
        if ranked:
            obs.counter("health.drift.attributed")
            obs.get().instant("health.drift", tick=self.tick_no,
                              feature=ranked[0]["feature"],
                              feature_name=ranked[0]["name"],
                              psi=ranked[0]["psi"])
            log.warning(
                "continual: drift attribution — top skewed features: %s",
                ", ".join(f"{s['name']} (psi={s['psi']:.3f})"
                          for s in ranked[:3]))
        return ranked

    def predict(self, X, **kw):
        """Serve from the current model (atomic against swaps: the
        booster reference flips in one assignment)."""
        return self.booster.predict(np.asarray(X), **kw)

    @property
    def serving_engine(self):
        return self.booster._gbdt.serving

    # -- the tick -------------------------------------------------------
    def tick(self, X, y, weight=None) -> TickReport:
        """Ingest one fresh mini-dataset; returns what happened."""
        X = np.asarray(X, np.float64)
        y = np.asarray(y, np.float64)
        r = TickReport(tick=self.tick_no, n_rows=len(X),
                       generation=self.generation)
        with obs.span("continual.tick", tick=self.tick_no,
                      generation=self.generation):
            self._tick_body(X, y, weight, r)
        # span boundary = the one place the tick is already host-
        # synchronized, so HBM attribution here is race-free; full
        # snapshots only in trace mode (a live_arrays walk per tick is
        # too much for bare counters)
        if obs.get().mode == "trace":
            from .. import obs as obs_pkg
            obs_pkg.memory_snapshot()
        self.tick_no += 1
        return r

    def _tick_body(self, X, y, weight, r: TickReport) -> None:
        # background retrain landed? gate + swap before anything reads
        # the new batch, so this tick already serves the fresher model
        self._poll_background(r)

        # 1. prequential eval of the SERVED model.  A non-finite metric
        # (NaN-burst labels) carries no evidence either way: appending
        # it would poison every window mean — blinding detection for
        # 2*W ticks and permanently disarming a watchdog whose baseline
        # captured the NaN — so it is reported but never enters history
        raw = self._raw(X)
        r.metric = tick_metric(self.metric_name, y, raw)
        if np.isfinite(r.metric):
            self.history.append(r.metric)
            # the swap gate keeps the last batch whose metric was
            # JUDGEABLE: a NaN gate batch would make both gate metrics
            # NaN and the rejection comparison vacuously False —
            # silently installing an ungated candidate
            self._gate = (X, y)
        else:
            r.notes.append("non-finite tick metric excluded from the "
                           "detection history and the swap gate")
        self.buffer.append((X, y, weight))
        self._health_observe(X)

        # 2. rollback watchdog (runs BEFORE drift detection: a bad swap
        # must roll back, not trigger another retrain of the bad model)
        if self._watch_left > 0:
            self._watchdog(r)

        # 3. drift / regression detection -> retrain
        elif self._should_detect() and self._regressed():
            r.drift_detected = True
            # name the offending features BEFORE the retrain consumes
            # the window: the regression tick's report carries the
            # attribution an operator (and the drift drill) reads
            r.skew_top = self._health_attribute()
            log.warning("continual: metric regression detected at tick "
                        "%d (window=%d, threshold=%.3f)", self.tick_no,
                        self.cfg.continual_window,
                        self.cfg.continual_metric_threshold)
            self._start_retrain(r)

        # 4. leaf refit on the fresh batch (after eval: predict-then-
        # learn).  A tick that just rolled back serves the last-good
        # pack VERBATIM — that bit-identity is what makes rollbacks
        # auditable — so refit resumes on the next tick
        if not r.rolled_back:
            self._refit(X, y, weight, r)

        if self._cooldown > 0:
            self._cooldown -= 1
        r.generation = self.generation
        self.reports.append(r)
        # a forever-runtime must not grow without bound: detection only
        # reads the last 2*W history entries and reports are drill/ops
        # telemetry — keep a generous tail, drop the ancient head
        if len(self.history) > _RETAIN:
            del self.history[:-_RETAIN // 2]
        if len(self.reports) > _RETAIN:
            del self.reports[:-_RETAIN // 2]

    # -- refit ----------------------------------------------------------
    def _refit(self, X, y, weight, r: TickReport) -> None:
        try:
            with obs.span("continual.refit", tick=self.tick_no):
                self.booster.refit(
                    X, y, weight=weight,
                    decay_rate=self.cfg.refit_decay_rate, inplace=True)
            r.refit_applied = True
            guard = getattr(self.booster, "_refit_guard", None)
            r.refit_skipped = bool(guard is not None
                                   and guard.skipped_iterations)
        except LightGBMError as exc:
            # nonfinite_policy=raise aborts the refit loudly; the
            # runtime keeps serving the pre-refit model (the refit
            # commits out of place, so nothing was half-applied)
            r.notes.append(f"refit aborted: {exc}")
            log.warning("continual: refit aborted at tick %d: %s",
                        self.tick_no, exc)

    # -- drift detection -------------------------------------------------
    def _should_detect(self) -> bool:
        return (self._cooldown == 0 and self._watch_left == 0
                and self._bg is None
                and len(self.history) >= 2 * self.cfg.continual_window)

    def _regressed(self) -> bool:
        W = self.cfg.continual_window
        recent = float(np.mean(self.history[-W:]))
        base = float(np.mean(self.history[-2 * W:-W]))
        thr = self.cfg.continual_metric_threshold
        return recent > base * (1.0 + thr) + _EPS

    # -- retrain ---------------------------------------------------------
    def _retrain_dataset(self, batches):
        """``batches`` is a snapshot taken on the TICK thread: the live
        deque keeps growing while a background retrain reads, and
        iterating it concurrently would crash — or worse, pair one
        snapshot's features with another's labels."""
        from ..basic import Dataset
        Xs = np.concatenate([b[0] for b in batches], axis=0)
        ys = np.concatenate([np.asarray(b[1]) for b in batches], axis=0)
        ws = None
        if any(b[2] is not None for b in batches):
            ws = np.concatenate(
                [np.asarray(b[2]) if b[2] is not None
                 else np.ones(len(b[0])) for b in batches], axis=0)
        # NaN-burst labels would poison the retrain from the start;
        # drop unlabeled rows (features may keep NaN — trees route them)
        keep = np.isfinite(ys) if ys.ndim == 1 else np.isfinite(
            ys).all(axis=1)
        if not keep.all():
            Xs, ys = Xs[keep], ys[keep]
            ws = ws[keep] if ws is not None else None
        return Dataset(Xs, label=ys, weight=ws)

    def _retrain_once(self, tag: str, attempt_state: Dict[str, int],
                      batches):
        """One retrain attempt: full training over the buffer, with PR 1
        checkpoint/resume riding inside so a kill resumes bit-exact.
        ``tag`` is unique per retrain CYCLE (generation + starting
        tick): attempts within a cycle share the directory (that is
        what resume needs), but a later cycle at the same generation —
        after a degrade — must never resume a stale checkpoint trained
        on an older buffer snapshot (checkpoint.py: one training run
        per checkpoint_dir)."""
        from ..engine import train as _train
        attempt_state["n"] += 1
        p = self._train_params()
        rounds = self.cfg.continual_retrain_rounds or self.cfg.num_iterations
        ckpt = None
        if self.checkpoint_dir:
            import os
            ckpt = os.path.join(self.checkpoint_dir, f"retrain_{tag}")
            p["checkpoint_dir"] = ckpt
            p["checkpoint_interval"] = (self.cfg.checkpoint_interval
                                        or max(rounds // 4, 1))
        resume = attempt_state["n"] > 1 and ckpt is not None
        ds = self._retrain_dataset(batches)
        armed = None
        if self._retrain_fault is not None and self._fault_remaining > 0:
            self._fault_remaining -= 1
            armed = int(self._retrain_fault["kill_at_iteration"])
        try:
            with obs.span("continual.retrain", tag=tag,
                          attempt=attempt_state["n"]):
                if armed is not None:
                    with faultinject.injected(kill_at_iteration=armed):
                        return _train(p, ds, num_boost_round=rounds,
                                      resume=resume)
                return _train(p, ds, num_boost_round=rounds, resume=resume)
        finally:
            del ds

    def _start_retrain(self, r: TickReport) -> None:
        gen = self.generation
        tag = f"g{gen}_t{self.tick_no}"
        attempt_state = {"n": 0}
        batches = list(self.buffer)   # snapshot ON the tick thread

        def cleanup():
            # the cycle is over (candidate built, or retries exhausted):
            # its checkpoints have served their purpose — a later cycle
            # uses its own tag — so a long-running loop must not leak a
            # directory per retrain
            if self.checkpoint_dir:
                import os
                import shutil
                shutil.rmtree(os.path.join(self.checkpoint_dir,
                                           f"retrain_{tag}"),
                              ignore_errors=True)

        def run():
            try:
                return retry_with_backoff(
                    lambda: self._retrain_once(tag, attempt_state,
                                               batches),
                    attempts=self.cfg.continual_retrain_attempts,
                    base_delay=self.cfg.continual_backoff_base,
                    jitter=self.cfg.continual_backoff_jitter,
                    seed=self.cfg.seed + gen,
                    # overall budget: the backoff schedule truncates
                    # where the deadline runs out, so exhaustion (and
                    # the degrade-to-last-good it triggers) lands on
                    # time instead of sleeping past it
                    deadline=(self.cfg.continual_retrain_deadline
                              or None),
                    describe=f"continual retrain (generation {gen})",
                    sleep=self._sleep, clock=self._clock)
            finally:
                cleanup()

        if self.background:
            # attempt_state rides the holder so status() reads the LIVE
            # attempt count while the worker runs, not a post-hoc copy.
            #
            # Lock-free handoff protocol (audited for ISSUE 19 with the
            # tier C schedule explorer; tests/test_conlint.py replays
            # it under permuted interleavings): exactly ONE writer (the
            # worker) and two readers (status(), _poll_background, both
            # on the tick thread).  Each dict write is a single
            # GIL-atomic store, and "done" flips LAST, so a reader that
            # observes done=True is guaranteed to see result/error and
            # attempts; a reader that doesn't stays on the "pending"
            # path, which touches only attempt_state (monotone int,
            # single store).  Inverting the write order is the bug the
            # explorer provokes (a poll sees done without result).
            holder: Dict[str, Any] = {"done": False,
                                      "attempt_state": attempt_state}

            def worker():
                try:
                    holder["result"] = run()
                except BaseException as exc:   # surfaced at the poll
                    holder["error"] = exc
                # "done" flips LAST: the poll reads attempts/result/
                # error only after observing it
                holder["attempts"] = attempt_state["n"]
                holder["done"] = True

            t = threading.Thread(target=worker, daemon=True,
                                 name=f"continual-retrain-g{gen}")
            holder["thread"] = t
            self._bg = holder
            t.start()
            r.notes.append("retrain started in background")
            return

        try:
            cand = run()
            r.retrain_attempts = attempt_state["n"]
            r.retrain_completed = True
            self._gate_and_swap(cand, r)
        except LightGBMError as exc:
            # retry budget exhausted: graceful degradation — the served
            # model stays up (it IS the last-good pack) and detection
            # cools down instead of hammering the failing retrain
            r.retrain_attempts = attempt_state["n"]
            r.retrain_failed = True
            r.degraded = True
            self._cooldown = self.cfg.continual_cooldown
            r.notes.append(f"retrain failed, serving last-good: {exc}")
            log.warning("continual: retrain failed after %d attempt(s); "
                        "degrading to the last-good model: %s",
                        attempt_state["n"], exc)

    def status(self) -> Dict[str, Any]:
        """Retrain-in-flight status, observable BETWEEN ticks (before
        this, a background retrain was only visible once the next tick
        polled it):

        * ``idle`` — no retrain in flight;
        * ``retraining`` — the background worker is still running (the
          live attempt count includes retries in progress);
        * ``awaiting-gate`` — the worker finished and its candidate
          (or failure) is waiting for the next tick's gate + swap.

        Synchronous retrains run inside ``tick`` itself, so between
        ticks they always read ``idle``."""
        bg = self._bg
        if bg is None:
            return {"state": "idle", "attempts": 0,
                    "generation": self.generation}
        attempts = int(bg["attempt_state"]["n"])
        state = "awaiting-gate" if bg.get("done") else "retraining"
        return {"state": state, "attempts": attempts,
                "generation": self.generation}

    def _poll_background(self, r: TickReport) -> None:
        if self._bg is None or not self._bg.get("done"):
            return
        holder, self._bg = self._bg, None
        r.retrain_attempts = int(holder.get("attempts", 0))
        err = holder.get("error")
        if err is not None:
            r.retrain_failed = True
            r.degraded = True
            self._cooldown = self.cfg.continual_cooldown
            r.notes.append(f"background retrain failed: {err}")
            return
        r.retrain_completed = True
        self._gate_and_swap(holder["result"], r)

    # -- guarded atomic swap ---------------------------------------------
    def _gate_and_swap(self, cand, r: TickReport) -> None:
        """Candidate gate: it must not be WORSE than the served model on
        the gate batch (beyond ``continual_swap_margin``) — a retrain
        over a poisoned buffer must not replace a healthy model.  The
        gate prediction doubles as the candidate's pack warm-up, so a
        whole swap costs exactly one compile per (kind, bucket)."""
        t0 = time.perf_counter()
        self._warm(cand)
        snap = cand._gbdt.serving.trace_snapshot()
        if self._gate is not None:
            Xg, yg = self._gate
            cur_m = tick_metric(self.metric_name, yg, self._raw(Xg))
            cand_m = tick_metric(self.metric_name, yg, np.asarray(
                cand.predict(Xg, raw_score=True)))
            margin = self.cfg.continual_swap_margin
            if cand_m > cur_m * (1.0 + margin) + _EPS:
                r.swap_rejected = True
                self._cooldown = self.cfg.continual_cooldown
                r.notes.append(f"swap rejected: candidate {cand_m:.6g} "
                               f"vs served {cur_m:.6g}")
                log.warning("continual: swap rejected (candidate %.6g "
                            "worse than served %.6g on the gate batch)",
                            cand_m, cur_m)
                return
        self._swap(cand, r, snap, t0)

    def _swap(self, cand, r: TickReport,
              snap: Optional[Dict[Any, int]] = None,
              t0: Optional[float] = None) -> None:
        with obs.span("continual.swap", generation=self.generation + 1):
            self._swap_impl(cand, r, snap, t0)

    def _swap_impl(self, cand, r: TickReport,
                   snap: Optional[Dict[Any, int]] = None,
                   t0: Optional[float] = None) -> None:
        if t0 is None:
            t0 = time.perf_counter()
        if snap is None:
            # direct path (force_swap): warm-probe BEFORE the candidate
            # serves — pack build plus at most one compile per (kind,
            # bucket) happens here, off the serving path, so the first
            # post-swap predict is hot.  The gated path already paid
            # exactly this during the gate comparison (snap was taken
            # there); re-running it would double the gate inference and
            # inflate the reported swap latency.
            self._warm(cand)
            snap = cand._gbdt.serving.trace_snapshot()
            if self._gate is not None:
                cand.predict(self._gate[0], raw_score=True)
        r.swap_new_traces = cand._gbdt.serving.new_traces_since(snap)
        W = self.cfg.continual_window
        self._pre_swap_baseline = (float(np.mean(self.history[-W:]))
                                   if self.history else None)
        self.last_good = self.booster
        self.booster = cand          # the atomic step: one reference flip
        r.swapped = True
        r.swap_latency_s = time.perf_counter() - t0
        self.generation += 1
        self._watch_left = self.cfg.continual_rollback_window
        self._cooldown = self.cfg.continual_cooldown
        self._refresh_health_ref()
        log.info("continual: swapped in generation %d (%.1f ms, traces "
                 "%s)", self.generation, 1e3 * r.swap_latency_s,
                 r.swap_new_traces)

    def force_swap(self, cand, gate: Optional[Tuple] = None) -> TickReport:
        """Install an externally built model (drills / operator push),
        skipping the gate but keeping the rollback watchdog armed."""
        r = TickReport(tick=self.tick_no, generation=self.generation)
        if gate is not None:
            self._gate = (np.asarray(gate[0], np.float64),
                          np.asarray(gate[1], np.float64))
        self._swap(cand, r)
        self.reports.append(r)
        return r

    # -- rollback watchdog -----------------------------------------------
    def _watchdog(self, r: TickReport) -> None:
        if not np.isfinite(r.metric):
            return                  # no evidence: the tick doesn't count
        base = self._pre_swap_baseline
        thr = self.cfg.continual_metric_threshold
        if base is not None and r.metric > base * (1.0 + thr) + _EPS:
            self.rollback(r)
        else:
            self._watch_left -= 1
            if self._watch_left == 0:
                # swap confirmed healthy; the pre-swap model stays
                # available for a manual rollback but stops being watched
                self._pre_swap_baseline = None

    def rollback(self, r: Optional[TickReport] = None) -> bool:
        """Restore the pre-swap booster.  Its serving engine still holds
        its own packs keyed by its own (length, mutation-counter)
        signature — the rolled-back model can never serve the swapped
        model's compiled state, and its predictions are bit-identical
        to the pre-swap pack."""
        if self.last_good is None:
            return False
        with obs.span("continual.rollback", generation=self.generation + 1):
            self.booster, self.last_good = self.last_good, None
            self.generation += 1
            self._watch_left = 0
            self._pre_swap_baseline = None
            self._cooldown = self.cfg.continual_cooldown
            self._refresh_health_ref()
            if r is not None:
                r.rolled_back = True
                r.generation = self.generation
        log.warning("continual: rolled back to the pre-swap model "
                    "(generation %d)", self.generation)
        return True
