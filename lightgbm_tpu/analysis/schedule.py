"""Tier C dynamic half: a seeded deterministic schedule explorer.

The static pass (:mod:`.conlint`) proves what it can from the AST; this
module *executes* the threaded planes under adversarially permuted —
but fully deterministic — interleavings.  The trick is cooperative
serialization over real threads: every thread the explorer manages
parks on its own gate Event, the scheduler wakes exactly ONE at a
time, and the woken thread runs until its next *yield point* (a lock
acquire/release, a condition wait, or a source line tier C flagged as
a CL001 hazard, hit via a per-thread ``sys.settrace`` watchlist).
Which thread runs next is drawn from ``random.Random(seed)`` over the
runnable set in spawn order — so the same seed replays the same
schedule byte-for-byte, and a seed sweep is a bounded, replayable
search over interleavings instead of a flaky stress test.

:class:`SchedLock` / :class:`SchedCondition` mirror
``threading.Lock/RLock/Condition`` closely enough to monkeypatch into
a live :class:`~lightgbm_tpu.serving.service.ServingService` +
:class:`~lightgbm_tpu.serving.registry.ModelRegistry`
(:func:`instrument_service`); they need no OS lock at all because only
one managed thread ever runs.  A schedule where nothing can run but
threads still hold/await locks is a DEADLOCK — recorded with the full
wait-for state, which is exactly the dynamic form of conlint's CL002.

Three serving-plane drills ride on top (``run_schedule_drill``):

* ``"publish_pump"``  — a hot publish lands while the pump drains
  coalesced traffic: every ticket must complete with predictions
  bit-equal to the OLD or the NEW version's oracle (a torn registry
  view — CL001 dynamic — fails), warm compiles stay ≤1 per bucket.
* ``"evict_dispatch"`` — a pack-budget eviction races dispatch: the
  engine re-packs on demand, every ticket still matches the oracle,
  counters stay consistent.
* ``"swap_rollback"``  — a retrain-style swap followed by a rollback
  watchdog races traffic: per-ticket results match exactly one
  version's oracle, the registry lands on the rolled-back version,
  breaker state stays consistent.

Like :mod:`..serving.drill`, reports are pure functions of ``seed`` on
a ManualClock — two runs with the same seed are byte-identical, which
tier-1 asserts.
"""

from __future__ import annotations

import json
import random
import sys
import threading
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

__all__ = ["Scheduler", "SchedLock", "SchedCondition",
           "instrument_service", "run_schedule_drill", "report_bytes",
           "SCHEDULE_SCENARIOS"]

SCHEDULE_SCENARIOS = ("publish_pump", "evict_dispatch", "swap_rollback")

_UNMANAGED = "<unmanaged>"


class _TState:
    __slots__ = ("name", "fn", "thread", "gate", "done", "blocked_on",
                 "waiting_cv", "cv_timed", "failure")

    def __init__(self, name: str, fn: Callable[[], None]):
        self.name = name
        self.fn = fn
        self.thread: Optional[threading.Thread] = None
        self.gate = threading.Event()
        self.done = False
        self.blocked_on: Optional["SchedLock"] = None
        self.waiting_cv: Optional["SchedCondition"] = None
        self.cv_timed = False
        self.failure: Optional[BaseException] = None


class SchedLock:
    """Cooperative stand-in for threading.Lock/RLock.  Owner/count
    bookkeeping only — mutual exclusion comes from the scheduler
    running one thread at a time, so there is no OS lock to leak."""

    def __init__(self, sched: "Scheduler", name: str,
                 reentrant: bool = False):
        self._sched = sched
        self.name = name
        self._reentrant = reentrant
        self._owner: Optional[object] = None
        self._count = 0

    # threading.Lock API ---------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        st = self._sched._me()
        if st is None:                  # outside a managed schedule
            if self._owner is None or (self._reentrant
                                       and self._owner == _UNMANAGED):
                self._owner = _UNMANAGED
                self._count += 1
                return True
            raise RuntimeError(
                f"{self.name} still held at unmanaged acquire "
                "(a managed thread deadlocked holding it?)")
        self._sched._yield_point(("acquire", self.name, st.name))
        while not self._try(st):
            if not blocking:
                return False
            st.blocked_on = self
            self._sched._yield_point(("blocked", self.name, st.name))
        self._sched._trace("acq", self.name, st.name)
        return True

    def release(self) -> None:
        st = self._sched._me()
        if self._count <= 0:
            raise RuntimeError(f"release of unheld {self.name}")
        self._count -= 1
        if self._count == 0:
            self._owner = None
            self._sched._wake_blocked(self)
        if st is not None:
            self._sched._trace("rel", self.name, st.name)
            self._sched._yield_point(("release", self.name, st.name))

    def _try(self, st: _TState) -> bool:
        if self._owner is None or (self._reentrant and self._owner is st):
            self._owner = st
            self._count += 1
            return True
        return False

    def locked(self) -> bool:
        return self._owner is not None

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()


class SchedCondition:
    """Cooperative threading.Condition over a :class:`SchedLock`.
    ``wait(timeout=...)`` is DETERMINISTIC: a timed waiter stays
    runnable (the scheduler may resume it = the timeout fired, on the
    manual clock's schedule); an untimed waiter only wakes on
    notify."""

    def __init__(self, lock: SchedLock):
        self._lock = lock
        self._sched = lock._sched
        self._waiters: List[_TState] = []

    def acquire(self, *a, **kw):
        return self._lock.acquire(*a, **kw)

    def release(self) -> None:
        self._lock.release()

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        st = self._sched._me()
        if st is None:
            raise RuntimeError("cv.wait() outside a managed thread")
        if self._lock._owner is not st:
            raise RuntimeError("cv.wait() without holding its lock")
        saved = self._lock._count       # full release, RLock-style
        self._lock._count = 0
        self._lock._owner = None
        self._sched._wake_blocked(self._lock)
        st.waiting_cv = self
        st.cv_timed = timeout is not None
        self._waiters.append(st)
        self._sched._trace("cv_wait", self._lock.name, st.name)
        self._sched._yield_point(("cv_wait", self._lock.name, st.name))
        # resumed: notified (removed from _waiters) or timed out
        notified = st not in self._waiters
        if not notified:
            self._waiters.remove(st)
        st.waiting_cv = None
        st.cv_timed = False
        while not self._lock._try(st):  # reacquire before returning
            st.blocked_on = self._lock
            self._sched._yield_point(("reacquire", self._lock.name,
                                      st.name))
        self._lock._count = saved
        return notified

    def notify_all(self) -> None:
        for st in self._waiters:
            st.waiting_cv = None
            st.cv_timed = False
        self._waiters.clear()

    def notify(self, n: int = 1) -> None:
        for st in self._waiters[:n]:
            st.waiting_cv = None
            st.cv_timed = False
        del self._waiters[:n]


class Scheduler:
    """Seeded cooperative scheduler: spawn threads, then :meth:`run`
    serializes them, picking each next step with ``Random(seed)``."""

    def __init__(self, seed: int = 0, max_steps: int = 20000):
        self.seed = int(seed)
        self._rnd = random.Random(int(seed))
        self.max_steps = int(max_steps)
        self._threads: List[_TState] = []
        self._local = threading.local()
        self._ctl = threading.Event()
        self.schedule: List[str] = []   # thread name per scheduling step
        self.trace: List[Tuple[str, str, str]] = []   # lock events
        self.deadlock: Optional[Dict[str, Any]] = None
        self.stalled = False            # a thread blocked outside us
        self.livelock = False
        self._watch: Dict[str, set] = {}
        self._steps = 0

    # -- construction ------------------------------------------------------
    def lock(self, name: str, reentrant: bool = False) -> SchedLock:
        return SchedLock(self, name, reentrant=reentrant)

    def condition(self, lock: SchedLock) -> SchedCondition:
        return SchedCondition(lock)

    def spawn(self, name: str, fn: Callable[[], None]) -> None:
        self._threads.append(_TState(name, fn))

    def watch_lines(self, filename: str, lines: Iterable[int]) -> None:
        """Add (filename, line) yield points — the bridge from the
        static half: pass each CL001 finding's location so the explorer
        can interleave exactly at the flagged access."""
        self._watch.setdefault(filename, set()).update(int(x) for x in lines)

    def watch_findings(self, findings, filename: str) -> None:
        """Register every CL001 finding from :mod:`.conlint` as a
        yield point in ``filename`` (the runtime co_filename — for an
        exec'd fixture, whatever was passed to compile())."""
        for f in findings:
            if f.rule == "CL001":
                self.watch_lines(filename, [f.line])

    # -- internals ---------------------------------------------------------
    def _me(self) -> Optional[_TState]:
        return getattr(self._local, "st", None)

    def _trace(self, op: str, lock: str, thread: str) -> None:
        self.trace.append((op, lock, thread))

    def _wake_blocked(self, lock: "SchedLock") -> None:
        for st in self._threads:
            if st.blocked_on is lock:
                st.blocked_on = None

    def _yield_point(self, tag) -> None:
        st = self._me()
        if st is None:
            return
        st.gate.clear()
        self._ctl.set()                 # hand control to the scheduler
        st.gate.wait()                  # park until scheduled again

    def _lines_for(self, filename: str) -> Optional[set]:
        got = self._watch.get(filename)
        if got is not None:
            return got
        for k, v in self._watch.items():
            if filename.endswith(k):
                return v
        return None

    def _global_trace(self, frame, event, arg):
        if event == "call" and \
                self._lines_for(frame.f_code.co_filename) is not None:
            return self._line_trace
        return None

    def _line_trace(self, frame, event, arg):
        if event == "line":
            lines = self._lines_for(frame.f_code.co_filename)
            if lines and frame.f_lineno in lines:
                # yield BEFORE the flagged line runs: the scheduler can
                # slot another thread between this access and the next
                self._yield_point(("line", frame.f_code.co_filename,
                                   frame.f_lineno))
        return self._line_trace

    def _body(self, st: _TState) -> None:
        self._local.st = st
        if self._watch:
            sys.settrace(self._global_trace)
        st.gate.wait()                  # first schedule starts us
        try:
            st.fn()
        except BaseException as exc:    # noqa: BLE001 — reported below
            st.failure = exc
        finally:
            sys.settrace(None)
            st.done = True
            st.blocked_on = None
            self._ctl.set()

    def _runnable(self) -> List[_TState]:
        out = []
        for st in self._threads:
            if st.done or st.blocked_on is not None:
                continue
            if st.waiting_cv is not None and not st.cv_timed:
                continue                # untimed cv wait: notify only
            out.append(st)
        return out

    # -- the loop ----------------------------------------------------------
    def run(self, stall_timeout_s: float = 120.0) -> None:
        for st in self._threads:
            t = threading.Thread(target=self._body, args=(st,),
                                 daemon=True, name=f"sched-{st.name}")
            st.thread = t
            t.start()
        while True:
            live = [st for st in self._threads if not st.done]
            if not live:
                break
            runnable = self._runnable()
            if not runnable:
                self.deadlock = {
                    "blocked": {st.name: st.blocked_on.name
                                for st in live
                                if st.blocked_on is not None},
                    "cv_waiting": sorted(st.name for st in live
                                         if st.waiting_cv is not None),
                }
                break
            if self._steps >= self.max_steps:
                self.livelock = True
                break
            self._steps += 1
            pick = runnable[self._rnd.randrange(len(runnable))]
            self.schedule.append(pick.name)
            self._ctl.clear()
            pick.gate.set()
            if not self._ctl.wait(stall_timeout_s):
                # the thread never came back to a yield point: it is
                # blocked on something the scheduler doesn't manage
                self.stalled = True
                break

    @property
    def steps(self) -> int:
        return self._steps

    def failures(self) -> Dict[str, str]:
        return {st.name: repr(st.failure) for st in self._threads
                if st.failure is not None}

    def check(self) -> None:
        """Raise on any outcome that is a drill failure by itself."""
        if self.deadlock is not None:
            raise AssertionError(
                f"seed {self.seed}: deadlock (dynamic CL002): "
                f"{self.deadlock}")
        if self.stalled:
            raise AssertionError(
                f"seed {self.seed}: a managed thread stalled outside "
                "the scheduler")
        if self.livelock:
            raise AssertionError(
                f"seed {self.seed}: exceeded {self.max_steps} steps")
        bad = self.failures()
        if bad:
            raise AssertionError(
                f"seed {self.seed}: thread failures: {bad}")


# ---------------------------------------------------------------------------
# instrumentation of the real serving plane
# ---------------------------------------------------------------------------

def instrument_service(service, sched: Scheduler):
    """Swap the service's and its registry's locks for scheduler-owned
    cooperative ones (post-construction, pre-drill: anything published
    BEFORE this ran under the real locks).  Lock kinds mirror the real
    fields: ``_lock`` is an RLock with a Condition on it, ``_pump_lock``
    and ``_cohort_lock`` are plain Locks."""
    service._lock = sched.lock("service._lock", reentrant=True)
    service._cv = sched.condition(service._lock)
    service._pump_lock = sched.lock("service._pump_lock")
    reg = service.registry
    reg._lock = sched.lock("registry._lock", reentrant=True)
    reg._cohort_lock = sched.lock("registry._cohort_lock")
    return service


# ---------------------------------------------------------------------------
# drills
# ---------------------------------------------------------------------------

_BOOSTERS: Dict[int, Any] = {}


def _boosters(seed: int):
    """Two tiny trained versions + their rows, cached per seed (the
    drills only need *different* forests, and retraining per drill
    call would dominate tier-1 time)."""
    got = _BOOSTERS.get(seed)
    if got is None:
        from ..serving.drill import _train_small
        b1, X = _train_small(seed, rows=160, features=5, trees=3)
        b2, _ = _train_small(seed + 1000, rows=160, features=5, trees=4)
        got = _BOOSTERS[seed] = (b1, b2, X[:16])
    return got


def _mk_plane(seed: int, **reg_kw):
    from ..robustness.retry import ManualClock
    from ..serving.registry import ModelRegistry
    from ..serving.service import ServingService
    clock = ManualClock()
    reg = ModelRegistry(clock=clock, **reg_kw)
    svc = ServingService(reg, flush_rows=8, max_delay=0.0,
                         queue_depth=16, seed=seed, clock=clock)
    return reg, svc


def _oracles(b1, b2, rows):
    import numpy as np
    return (np.asarray(b1.predict(rows, raw_score=True)),
            np.asarray(b2.predict(rows, raw_score=True)))


def _match(res, i, o1, o2) -> str:
    """Which version's oracle does this ticket's result agree with?
    Tolerance-based like drill.py's swap parity check (the compiled
    serving path vs booster.predict differ in float association);
    anything agreeing with NEITHER is a torn registry view."""
    import numpy as np
    r = np.asarray(res).reshape(-1)
    if np.allclose(r, o1[i:i + 1].reshape(-1), rtol=1e-6, atol=1e-6):
        return "v1"
    if np.allclose(r, o2[i:i + 1].reshape(-1), rtol=1e-6, atol=1e-6):
        return "v2"
    return "torn"


def _ticket_rows(tickets) -> List[Dict[str, Any]]:
    return [{"status": t.status, "reason": t.reason} for t in tickets]


def run_schedule_drill(scenario: str, seed: int = 0) -> Dict[str, Any]:
    """Run one scenario under the seed's schedule and return a
    JSON-able report that is a pure function of (scenario, seed)."""
    if scenario not in SCHEDULE_SCENARIOS:
        raise ValueError(f"unknown scenario {scenario!r}; "
                         f"pick from {SCHEDULE_SCENARIOS}")
    b1, b2, rows = _boosters(0)
    o1, o2 = _oracles(b1, b2, rows)
    n = 4
    sched = Scheduler(seed=seed)
    reg, svc = _mk_plane(seed,
                         **({"pack_budget_bytes": 1}
                            if scenario == "evict_dispatch" else {}))
    reg.publish("m", b1, gate_rows=rows)
    if scenario == "swap_rollback":
        reg.publish("m", b2, gate_rows=rows)
    instrument_service(svc, sched)

    tickets: List[Any] = []
    stats_seen: List[Dict[str, Any]] = []

    def t_traffic():
        for i in range(n):
            tickets.append(svc.submit(rows[i:i + 1], model="m"))
        svc.pump(force=True)
        svc.pump(force=True)            # drain anything a racer re-queued

    def t_racer():
        if scenario == "publish_pump":
            reg.publish("m", b2, gate_rows=rows)
        elif scenario == "evict_dispatch":
            reg.enforce_budget()
            stats_seen.append({"evictions": int(reg.evictions)})
        else:                           # swap_rollback: watchdog rolls back
            stats_seen.append({"pre": svc.stats()["counters"].get(
                "served", 0)})
            reg.rollback("m")
            stats_seen.append({"post": svc.stats()["counters"].get(
                "served", 0)})

    sched.spawn("traffic", t_traffic)
    sched.spawn("racer", t_racer)
    sched.run()
    sched.check()

    stats = svc.stats()
    counters = stats["counters"]
    matched = [(_match(t.result, i, o1, o2) if t.status == "ok"
                else t.status)
               for i, t in enumerate(tickets)]

    # invariants --------------------------------------------------------
    if any(m == "torn" for m in matched):
        raise AssertionError(
            f"seed {seed}: torn registry view — a ticket's predictions "
            f"match NEITHER version's oracle: {matched}")
    if any(t.status != "ok" for t in tickets):
        raise AssertionError(
            f"seed {seed}: dropped/failed tickets: "
            f"{_ticket_rows(tickets)}")
    if counters.get("served", 0) != counters.get("submitted", 0):
        raise AssertionError(
            f"seed {seed}: served {counters.get('served')} != "
            f"submitted {counters.get('submitted')} (torn counters)")
    for m, br in stats["breakers"].items():
        if br["state"] != "closed" or br["trips"] != 0:
            raise AssertionError(
                f"seed {seed}: breaker {m} inconsistent: {br}")
    version = reg.version("m")
    if scenario == "publish_pump" and version != 2:
        raise AssertionError(f"seed {seed}: publish lost ({version})")
    if scenario == "swap_rollback":
        if version != 3:
            raise AssertionError(
                f"seed {seed}: rollback mints a NEW version (expected "
                f"3, got {version})")
        rb = reg.stats()["models"]["m"]["rollbacks"]
        if rb != 1:
            raise AssertionError(f"seed {seed}: rollbacks {rb} != 1")

    return {
        "scenario": scenario,
        "seed": seed,
        "steps": sched.steps,
        "schedule": ",".join(s[0] for s in sched.schedule),
        "lock_events": len(sched.trace),
        "tickets": _ticket_rows(tickets),
        "matched": matched,
        "counters": {k: int(v) for k, v in sorted(counters.items())},
        "version": int(version),
        "racer": stats_seen,
        "deadlock": sched.deadlock,
    }


def report_bytes(report: Dict[str, Any]) -> bytes:
    """Canonical serialized form — what tier-1 compares across runs."""
    return json.dumps(report, sort_keys=True, default=str).encode()
