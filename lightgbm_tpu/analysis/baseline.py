"""The jaxlint baseline ratchet (``jaxlint_baseline.json``).

Tier A findings are pinned EXACTLY: a finding key not in the baseline
(or above its pinned count) fails the check, and a pinned count higher
than what the linter now measures is STALE — fixing a violation
requires shrinking the baseline in the same change, so the pinned debt
only ever goes down.

Tier B budgets are CEILINGS: measured values may sit below them (the
HLO counts need headroom for toolchain drift — see
tests/test_hlo_guard.py's ~50% margins), but never above.  Boolean
invariants are encoded as 0/1 metrics with budget 0.

Tier C (concurrency discipline, :mod:`.conlint`) pins exactly like
tier A: same key shape (``RULE:path:qualname``), same new/stale
semantics, its own ``tier_c`` table so the goal state — an EMPTY
table, every surviving site pragma-documented in code — is visible at
a glance.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Any, Dict, List

DEFAULT_BASELINE = "jaxlint_baseline.json"


@dataclass
class Problem:
    kind: str        # "new" | "stale" | "budget"
    key: str         # finding key or "check.metric"
    measured: int
    pinned: int
    message: str

    def render(self) -> str:
        return f"[{self.kind}] {self.key}: {self.message}"

    def to_json(self) -> str:
        return json.dumps({"problem": self.kind, "key": self.key,
                           "measured": self.measured,
                           "pinned": self.pinned,
                           "message": self.message}, sort_keys=True)


def load(path: str) -> Dict[str, Any]:
    if not os.path.exists(path):
        return {"version": 1, "tier_a": {}, "tier_b": {}, "tier_c": {}}
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def save(path: str, data: Dict[str, Any]) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=2, sort_keys=True)
        fh.write("\n")


def make(tier_a_counts: Dict[str, int],
         tier_b: Dict[str, Dict[str, int]],
         headroom: Dict[str, Dict[str, int]] = None,
         tier_c_counts: Dict[str, int] = None) -> Dict[str, Any]:
    """Build a baseline document from measured values.  ``headroom``
    maps check -> {metric: extra budget} for tier B ceilings that need
    drift margin (never applied to invariant metrics pinned at 0)."""
    tb: Dict[str, Dict[str, int]] = {}
    for check, metrics in tier_b.items():
        tb[check] = {}
        for metric, value in metrics.items():
            extra = (headroom or {}).get(check, {}).get(metric, 0)
            tb[check][metric] = value + (extra if value else 0)
    return {"version": 1, "tier_a": dict(sorted(tier_a_counts.items())),
            "tier_b": tb,
            "tier_c": dict(sorted((tier_c_counts or {}).items()))}


def compare_tier_a(measured: Dict[str, int],
                   baseline: Dict[str, Any]) -> List[Problem]:
    return _compare_pins(measured, baseline.get("tier_a", {}))


def compare_tier_c(measured: Dict[str, int],
                   baseline: Dict[str, Any]) -> List[Problem]:
    """Tier C ratchets exactly like tier A — exact pins, new AND stale
    both fail — against the ``tier_c`` table."""
    return _compare_pins(measured, baseline.get("tier_c", {}))


def _compare_pins(measured: Dict[str, int],
                  pinned: Dict[str, int]) -> List[Problem]:
    problems: List[Problem] = []
    for key in sorted(set(measured) | set(pinned)):
        m = measured.get(key, 0)
        p = pinned.get(key, 0)
        if m > p:
            problems.append(Problem(
                "new", key, m, p,
                f"{m - p} new finding(s) over the pinned {p}; fix them "
                "(do not grow the baseline)"))
        elif m < p:
            problems.append(Problem(
                "stale", key, m, p,
                f"pinned {p} but only {m} remain; shrink the baseline "
                "(tools/jaxlint.py --update-baseline) so the ratchet "
                "holds"))
    return problems


def compare_tier_b(measured: Dict[str, Dict[str, int]],
                   baseline: Dict[str, Any]) -> List[Problem]:
    budgets: Dict[str, Dict[str, int]] = baseline.get("tier_b", {})
    problems: List[Problem] = []
    for check, metrics in sorted(measured.items()):
        pinned = budgets.get(check)
        if pinned is None:
            problems.append(Problem(
                "new", check, len(metrics), 0,
                "no budget committed for this check; run "
                "--update-baseline and review the numbers"))
            continue
        for metric, value in sorted(metrics.items()):
            if metric not in pinned:
                problems.append(Problem(
                    "new", f"{check}.{metric}", value, 0,
                    "metric has no committed budget"))
            elif value > pinned[metric]:
                problems.append(Problem(
                    "budget", f"{check}.{metric}", value, pinned[metric],
                    f"measured {value} exceeds the committed budget "
                    f"{pinned[metric]} — a structural regression in "
                    "the compiled artifact"))
    return problems
