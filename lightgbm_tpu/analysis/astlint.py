"""Tier A of jaxlint: AST-level JAX-specific lint over the package.

Five rules, each targeting a structural failure mode that has cost this
repo real measured performance before (PERF.md rounds 2/4/7) and that
the GPU tree-boosting literature names as the difference between
"on the accelerator" and "fast on the accelerator" (Wen et al.,
Mitchell & Frank: keep the hot loop free of host syncs, retraces and
dtype surprises):

JL001  host sync in a hot path — ``.item()``, ``float()``/``int()``/
       ``bool()``/``np.asarray()`` applied to a device-producing
       expression inside the training/serving hot modules, or
       ``jax.device_get``/``.block_until_ready()`` inside a Python
       loop.  Each one is a device round-trip serialized into the
       iteration.
JL002  retrace hazard — ``jax.jit``/``Partial`` constructed inside a
       loop or invoked immediately (``jax.jit(f)(x)`` compiles per
       call), and calls that pass unhashable (list/dict/set) literals
       for a known jitted symbol's static args.
JL003  dtype-promotion leak — explicit float64 dtypes in ``jnp`` calls
       or ``.astype`` on device values outside a lexical
       ``jax.experimental.enable_x64()`` block.  Off-TPU this silently
       doubles bandwidth; on TPU it breaks lowering.
JL004  while-carry growth — ``lax.fori_loop``/``while_loop``/``scan``
       whose carry is built by a comprehension/``[x] * n``/starred
       tuple, so the carry arity depends on a Python value (each extra
       carry element is a body-level fusion per split; see
       ops/histogram.py's single stacked carry).
JL005  rank-divergent collective — a ``lax.p*``/``network.global_*``
       collective lexically under a rank-conditional branch in
       ``parallel/``: ranks disagree on whether they enter the
       collective and the job deadlocks.

Findings are keyed ``RULE:path:qualname`` and counted, so the
committed ``jaxlint_baseline.json`` ratchet is stable under line moves;
intentional single syncs carry a ``# jaxlint: ok=JL001`` pragma with a
justifying comment instead of a baseline entry.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

RULES: Dict[str, str] = {
    "JL001": "host sync in a hot path",
    "JL002": "retrace hazard",
    "JL003": "dtype-promotion leak outside enable_x64",
    "JL004": "while-carry arity depends on a Python value",
    "JL005": "rank-divergent collective",
}

# Per-rule module scopes, matched against the path relative to the
# package root (``lightgbm_tpu/``).  JL001 covers the modules whose
# loops run per split / per iteration / per serving call; JL003 covers
# the modules that stage device programs; JL005 the collective layer.
JL001_SCOPE = ("ops/", "models/learner.py", "models/serving.py",
               "models/boosting.py", "models/metric.py", "continual/",
               "obs/regress.py", "dataset.py")
JL003_SCOPE = ("ops/", "models/learner.py", "models/serving.py",
               "models/shap.py")
JL005_SCOPE = ("parallel/",)

_DEVICE_ROOTS = ("jnp.", "jax.numpy.", "jax.lax.", "jax.random.",
                 "jax.nn.", "lax.")
_F64_NAMES = {"np.float64", "numpy.float64", "jnp.float64",
              "jax.numpy.float64"}
_COLLECTIVE_ATTRS = {"psum", "pmax", "pmin", "pmean", "all_gather",
                     "all_to_all", "ppermute", "pgather",
                     "process_allgather"}
_RANK_TOKENS = {"rank", "machine_rank", "is_master", "is_rank0",
                "process_index", "axis_index"}

_PRAGMA_RE = re.compile(
    r"#\s*jaxlint:\s*(?:ok|disable)(?:\s*=\s*([A-Z0-9,\s]+))?")


@dataclass
class Finding:
    rule: str
    path: str          # repo-relative posix path
    line: int
    col: int
    func: str          # enclosing function qualname or "<module>"
    message: str

    @property
    def key(self) -> str:
        return f"{self.rule}:{self.path}:{self.func}"

    def to_json(self) -> str:
        return json.dumps({
            "tier": "A", "rule": self.rule, "title": RULES[self.rule],
            "path": self.path, "line": self.line, "col": self.col,
            "func": self.func, "message": self.message, "key": self.key,
        }, sort_keys=True)

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"{self.message}  [{self.func}]")


def _dotted(node: ast.AST) -> Optional[str]:
    """``jax.lax.fori_loop``-style dotted name of a Name/Attribute
    chain, or None for anything more dynamic."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _contains_device_call(node: ast.AST) -> bool:
    """True when the expression subtree contains an explicit
    device-producing call (``jnp.*``/``jax.lax.*``/...).  Names bound
    earlier from such calls are deliberately NOT traced — the rule is a
    high-signal subset, not an escape-proof dataflow analysis."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            d = _dotted(sub.func)
            if d and (d.startswith(_DEVICE_ROOTS) or d + "." in
                      _DEVICE_ROOTS):
                return True
    return False


def _is_f64_token(node: ast.AST) -> bool:
    d = _dotted(node)
    if d in _F64_NAMES:
        return True
    return (isinstance(node, ast.Constant)
            and node.value in ("float64", "double"))


def _rank_conditional(test: ast.AST) -> bool:
    for sub in ast.walk(test):
        if isinstance(sub, ast.Name) and sub.id in _RANK_TOKENS:
            return True
        if isinstance(sub, ast.Attribute) and sub.attr in _RANK_TOKENS:
            return True
        if isinstance(sub, ast.Call):
            d = _dotted(sub.func)
            if d and d.split(".")[-1] in _RANK_TOKENS:
                return True
    return False


def _pragmas(source: str) -> Dict[int, Optional[Set[str]]]:
    """{lineno: suppressed-rule-set or None for all} from
    ``# jaxlint: ok[=JL001,JL003]`` comments."""
    out: Dict[int, Optional[Set[str]]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _PRAGMA_RE.search(line)
        if m:
            rules = m.group(1)
            out[i] = (set(r.strip() for r in rules.split(","))
                      if rules else None)
    return out


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, rel: str, source: str):
        self.path = path          # repo-relative, reported
        self.rel = rel            # package-relative, scope-matched
        self.pragmas = _pragmas(source)
        self.findings: List[Finding] = []
        self.func_stack: List[str] = []
        self.loop_depth = 0
        self.x64_depth = 0
        # jitted symbols with static args seen in this module:
        # name -> set of static argnames (JL002 unhashable-static check)
        self.static_args: Dict[str, Set[str]] = {}

    # -- plumbing -------------------------------------------------------
    def _in(self, scope: Sequence[str]) -> bool:
        return self.rel.startswith(tuple(scope))

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 0)
        sup = self.pragmas.get(line)
        if line in self.pragmas and (sup is None or rule in sup):
            return
        self.findings.append(Finding(
            rule=rule, path=self.path, line=line,
            col=getattr(node, "col_offset", 0),
            func=".".join(self.func_stack) or "<module>",
            message=message))

    def visit_FunctionDef(self, node):
        # decorator form of a static-arg jit:
        # @functools.partial(jax.jit, static_argnames=(...))
        for dec in node.decorator_list:
            self._record_static_jit(dec, [ast.Name(id=node.name)])
        self.func_stack.append(node.name)
        saved = self.loop_depth
        self.loop_depth = 0       # a new function body is a new frame
        self.generic_visit(node)
        self.loop_depth = saved
        self.func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        self.func_stack.append(node.name)
        self.generic_visit(node)
        self.func_stack.pop()

    def visit_For(self, node):
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_AsyncFor = visit_For
    visit_While = visit_For

    def visit_With(self, node):
        x64 = any(
            isinstance(item.context_expr, ast.Call)
            and (_dotted(item.context_expr.func) or "").endswith(
                "enable_x64")
            for item in node.items)
        if x64:
            self.x64_depth += 1
        self.generic_visit(node)
        if x64:
            self.x64_depth -= 1

    def visit_Assign(self, node):
        self._record_static_jit(node.value, node.targets)
        self.generic_visit(node)

    def _record_static_jit(self, value: ast.AST, targets) -> None:
        """Track ``name = jax.jit(fn, static_argnames=(...))`` and the
        ``@functools.partial(jax.jit, static_argnames=...)`` decorator
        form so later call sites can be checked for unhashable
        statics."""
        if not isinstance(value, ast.Call):
            return
        d = _dotted(value.func)
        call = value
        if d in ("functools.partial", "partial") and call.args and \
                _dotted(call.args[0]) in ("jax.jit", "jit"):
            pass
        elif d not in ("jax.jit", "jit"):
            return
        names: Set[str] = set()
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                for sub in ast.walk(kw.value):
                    if isinstance(sub, ast.Constant) and \
                            isinstance(sub.value, str):
                        names.add(sub.value)
        if not names:
            return
        for t in targets:
            if isinstance(t, ast.Name):
                self.static_args[t.id] = names

    # -- the rules ------------------------------------------------------
    def visit_Call(self, node):
        d = _dotted(node.func)

        # JL001 — host syncs in hot modules
        if self._in(JL001_SCOPE):
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "item" and not node.args:
                self._emit("JL001", node,
                           ".item() forces a device->host sync")
            elif isinstance(node.func, ast.Name) and \
                    node.func.id in ("float", "int", "bool") and \
                    len(node.args) == 1 and \
                    _contains_device_call(node.args[0]):
                self._emit(
                    "JL001", node,
                    f"{node.func.id}() on a device value blocks on a "
                    "device->host sync; keep it on device or batch the "
                    "sync outside the loop")
            elif d in ("np.asarray", "np.array", "numpy.asarray",
                       "numpy.array") and node.args and \
                    _contains_device_call(node.args[0]):
                self._emit(
                    "JL001", node,
                    f"{d}() on a device value is a blocking transfer")
            elif self.loop_depth > 0 and d == "jax.device_get":
                self._emit("JL001", node,
                           "jax.device_get inside a Python loop: one "
                           "transfer per step; batch it")
            elif self.loop_depth > 0 and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "block_until_ready":
                self._emit("JL001", node,
                           "block_until_ready inside a Python loop "
                           "serializes dispatch")

        # JL002 — retrace hazards (whole package)
        if d in ("jax.jit", "jit") or (
                d in ("functools.partial", "partial") and node.args
                and _dotted(node.args[0]) in ("jax.jit", "jit")):
            if self.loop_depth > 0:
                self._emit("JL002", node,
                           "jax.jit constructed inside a loop compiles "
                           "per iteration; hoist and cache it")
        if isinstance(node.func, ast.Call):
            inner = _dotted(node.func.func)
            if inner in ("jax.jit", "jit"):
                self._emit("JL002", node,
                           "jax.jit(f)(x) traces per call; bind the "
                           "jitted callable once")
        if d and d.split(".")[-1] == "Partial" and self.loop_depth > 0:
            self._emit("JL002", node,
                       "Partial built inside a loop defeats jit "
                       "caching (new hashable identity per step)")
        if d in self.static_args:
            for kw in node.keywords:
                if kw.arg in self.static_args[d] and isinstance(
                        kw.value, (ast.List, ast.Dict, ast.Set)):
                    self._emit(
                        "JL002", node,
                        f"unhashable {type(kw.value).__name__.lower()} "
                        f"literal for static arg '{kw.arg}' of jitted "
                        f"'{d}' retraces every call")

        # JL003 — float64 leaks outside enable_x64
        if self._in(JL003_SCOPE) and self.x64_depth == 0:
            if d and d.startswith(("jnp.", "jax.numpy.")):
                for kw in node.keywords:
                    if kw.arg == "dtype" and _is_f64_token(kw.value):
                        self._emit(
                            "JL003", node,
                            f"explicit float64 dtype in {d} outside an "
                            "enable_x64 context")
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "astype" and node.args and \
                    _is_f64_token(node.args[0]) and \
                    _contains_device_call(node.func.value):
                self._emit("JL003", node,
                           ".astype(float64) on a device value outside "
                           "an enable_x64 context")

        # JL004 — carry arity from a Python value (whole package)
        carry_arg = None
        if d in ("jax.lax.fori_loop", "lax.fori_loop") and \
                len(node.args) >= 4:
            carry_arg = node.args[3]
        elif d in ("jax.lax.while_loop", "lax.while_loop") and \
                len(node.args) >= 3:
            carry_arg = node.args[2]
        elif d in ("jax.lax.scan", "lax.scan"):
            if len(node.args) >= 2:
                carry_arg = node.args[1]
            for kw in node.keywords:
                if kw.arg == "init":
                    carry_arg = kw.value
        if carry_arg is not None and self._carry_is_dynamic(carry_arg):
            self._emit(
                "JL004", node,
                "loop carry built from a Python-sized comprehension/"
                "repetition: carry arity tracks a Python value (one "
                "body-level fusion per extra element; stack into one "
                "array instead)")

        # JL005 — collectives under rank conditionals in parallel/
        if self._in(JL005_SCOPE) and d:
            last = d.split(".")[-1]
            if (last in _COLLECTIVE_ATTRS
                    or last.startswith("global_")) and \
                    self._under_rank_branch(node):
                self._emit(
                    "JL005", node,
                    f"collective '{d}' under a rank-conditional "
                    "branch: ranks disagree on entering it and the "
                    "job deadlocks")

        self.generic_visit(node)

    @staticmethod
    def _carry_is_dynamic(node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, (ast.ListComp, ast.GeneratorExp,
                                ast.SetComp, ast.DictComp, ast.Starred)):
                return True
            if isinstance(sub, ast.BinOp) and \
                    isinstance(sub.op, ast.Mult) and (
                    isinstance(sub.left, (ast.List, ast.Tuple))
                    or isinstance(sub.right, (ast.List, ast.Tuple))):
                return True
        return False

    # rank-branch tracking: a stack of If nodes maintained by visit_If
    _rank_if_depth = 0

    def visit_If(self, node):
        self.visit(node.test)
        divergent = _rank_conditional(node.test)
        if divergent:
            self._rank_if_depth += 1
        # BOTH arms are rank-divergent regions: `else:` is entered by
        # exactly the complementary set of ranks
        for child in node.body:
            self.visit(child)
        for child in node.orelse:
            self.visit(child)
        if divergent:
            self._rank_if_depth -= 1

    def _under_rank_branch(self, node) -> bool:
        return self._rank_if_depth > 0


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------
def lint_source(source: str, path: str,
                package_root: str = "lightgbm_tpu") -> List[Finding]:
    """Lint one module's source.  ``path`` is the repo-relative posix
    path used for scoping and reporting (e.g.
    ``lightgbm_tpu/ops/histogram.py``)."""
    rel = path
    prefix = package_root.rstrip("/") + "/"
    if rel.startswith(prefix):
        rel = rel[len(prefix):]
    tree = ast.parse(source, filename=path)
    linter = _Linter(path, rel, source)
    linter.visit(tree)
    return linter.findings


def iter_package_files(repo_root: str,
                       package: str = "lightgbm_tpu") -> Iterable[str]:
    base = os.path.join(repo_root, package)
    for dirpath, dirnames, filenames in os.walk(base):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def lint_tree(repo_root: str,
              package: str = "lightgbm_tpu") -> List[Finding]:
    findings: List[Finding] = []
    for full in iter_package_files(repo_root, package):
        rel = os.path.relpath(full, repo_root).replace(os.sep, "/")
        with open(full, encoding="utf-8") as fh:
            src = fh.read()
        findings.extend(lint_source(src, rel, package_root=package))
    return findings


def finding_counts(findings: Iterable[Finding]) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for f in findings:
        out[f.key] = out.get(f.key, 0) + 1
    return dict(sorted(out.items()))
