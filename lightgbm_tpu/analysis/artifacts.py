"""Tier B of jaxlint: compile-artifact budget checks.

Tier A reads source; this tier lowers the designated entry points to
optimized HLO / live trace counters and asserts STRUCTURAL invariants
as machine-checked budgets, so the regressions that only a profiler
would otherwise catch fail tier-1 instead:

* ``while_body.default`` / ``while_body.mega`` — op/fusion/copy counts
  of the compiled tree-build while body (generalizing
  tools/hlo_report.py): the default subtraction path carries exactly
  its two known contextual hist-state copies, the mega-kernel body
  carries zero and the (L+1)-slot state buffer must not exist at all.
* ``serving.compiles`` — N same-bucket serving calls (raw / leaf /
  contrib) cost exactly one XLA trace per (kind, bucket); a second
  trace is a retrace regression.
* ``serving.transfers`` — the compiled raw-serving program contains no
  host callbacks and stays under a copy/transfer op budget in its
  entry computation.
* ``predict.layered`` — the layered dense predictor
  (ops/forest_tensor.py) lowers with ZERO while loops (fixed trip
  count, unrolled at trace time), no host callbacks and the pinned
  transfer budget: the dataflow shape cannot silently regress to
  data-dependent traversal.
* ``train.donation`` — the fused train step is jitted with donated
  score/payload buffers (losing donation doubles the resident score
  footprint and adds a copy per iteration).
* ``shap.kernel`` — the device TreeSHAP program keeps its unrolled
  D/q-loop structure (at most the single tree scan ``while``), runs
  f64 under the scoped x64 context, and contains no host callbacks.
* ``linear.gain`` — constant-gain tree builds lower op-for-op
  identically with the piece-wise-linear (leafwise_gain) machinery in
  the codebase: ``linear_tree=True`` in refit mode may not change the
  fused while-body by a single op, and the leafwise body itself keeps
  a pinned op count.
* ``continual.tick`` — steady-state continual-runtime ticks add zero
  serving retraces (the in-place refit rides the leaf-refresh fast
  path) and a hot swap compiles each (kind, bucket) at most once,
  during the candidate warm-up, never on the serving path.
* ``telemetry.off`` — the obs layer stages ZERO device ops: the fused
  train step's lowered while-body is op-for-op identical with
  telemetry off and at full trace mode (spans/counters/compile
  detection are host-side bookkeeping by construction).
* ``health.off`` — same zero-HLO invariant for the model/data-health
  layer (flight recorder, skew digests): the lowered while-body is
  op-for-op identical with health off and at trace mode.
* ``perfwatch.off`` — same zero-HLO invariant for the perf-trajectory
  layer (obs/regress.py): lowering inside an active perfwatch
  recording (injectable clock + BENCH_history append) changes nothing.

Every metric is a ceiling checked against ``jaxlint_baseline.json``
(see :mod:`lightgbm_tpu.analysis.baseline`).  All checks run on the
current backend — CPU in tier-1 — exactly like tests/test_hlo_guard.py.
"""

from __future__ import annotations

import contextlib
import functools
import re
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["collect_tier_b", "CHECKS"]


# ---------------------------------------------------------------------------
# while-body checks (tree build)
# ---------------------------------------------------------------------------
def check_while_body_default() -> Dict[str, int]:
    from .hlo import report
    r = report({})
    return {
        "total_ops": r["total_ops"],
        "fusions": r["fusions"],
        "copies": r["copies"],
        "hist_state_copies": r["hist_state_copies"],
    }


def check_while_body_mega() -> Dict[str, int]:
    from .hlo import report
    r = report({"tpu_megakernel": "xla"})
    return {
        "hist_state_copies": r["hist_state_copies"],
        "hist_state_shape_lines": r["hist_state_shape_lines"],
        "copies": r["copies"],
    }


def check_chunk_adaptive() -> Dict[str, int]:
    """Leaf-size-adaptive chunk-policy budget (ops/chunkpolicy.py).

    The adaptive body must dispatch its per-leaf band variants via
    zero-trip loops, never conditionals: the hist-state copies stay at
    the fixed body's exact count and the total-copy delta versus an
    explicitly fixed lowering stays pinned (lax.switch plumbing would
    add one copy PER ROW BUFFER per split — the round-1 conditional
    pathology, measured again while building this policy).  The traced
    variant registry additionally pins the compiled-variant count per
    pass to the static menu — the training-side analog of the serving
    engine's per-(kind, bucket) compile keys."""
    from ..ops import chunkpolicy
    from .hlo import report
    chunkpolicy.reset_variant_log()
    ra = report({"tpu_chunk_policy": "adaptive"})
    per_pass: Dict[str, set] = {}
    for (pass_name, width) in chunkpolicy.variant_log():
        per_pass.setdefault(pass_name, set()).add(width)
    menu_max = 4
    over = sum(1 for ws in per_pass.values() if len(ws) > menu_max)
    rf = report({"tpu_chunk_policy": "fixed"})
    return {
        "hist_state_copies": ra["hist_state_copies"],
        "hist_state_copies_delta": abs(ra["hist_state_copies"]
                                       - rf["hist_state_copies"]),
        "copies_delta_vs_fixed": max(ra["copies"] - rf["copies"], 0),
        "passes_over_menu": over,
        "variants_missing": 0 if per_pass else 1,
    }


_FRONTIER_K = 4


def check_while_body_frontier() -> Dict[str, int]:
    """Frontier-batched (tpu_frontier_k=4) tree-build while body: the
    per-SPLIT bookkeeping op budget (outer-body ops amortize over up to
    K splits per step) and the structural invariant that the K-row
    parent-hist gather + 2K-row child scatter carry ZERO contextual
    hist-state copies (the subtraction path's two copies per split are
    the round-4 fixed-cost smoking gun; the K=1 budget pins them at
    exactly 2, this budget pins their absence under batching)."""
    from .hlo import report
    r = report({"tpu_frontier_k": _FRONTIER_K})
    return {
        "ops_per_split": -(-r["total_ops"] // _FRONTIER_K),
        "copies": r["copies"],
        "hist_state_copies": r["hist_state_copies"],
    }


# ---------------------------------------------------------------------------
# serving-engine checks
# ---------------------------------------------------------------------------
_TINY = {}


def _tiny_serving_booster():
    """One small trained booster shared by the serving checks (module
    cache: artifact collection may run several checks per process)."""
    if "bst" in _TINY:
        return _TINY["bst"], _TINY["X"]
    import numpy as np

    import lightgbm_tpu as lgb
    rng = np.random.RandomState(3)
    X = rng.normal(size=(4500, 6))
    y = X[:, 0] * 2.0 + np.sin(X[:, 1]) + 0.1 * rng.normal(size=len(X))
    bst = lgb.train({"objective": "regression", "verbosity": -1,
                     "num_leaves": 15, "min_data_in_leaf": 10,
                     "metric": ""},
                    lgb.Dataset(X[:, :], label=y), num_boost_round=5)
    bst._gbdt._flush_pending()
    _TINY["bst"] = bst
    _TINY["X"] = X
    return bst, X


def check_serving_compiles() -> Dict[str, int]:
    """max traces per (kind, bucket) across repeated same-bucket calls
    — the compile-count guard as a budget."""
    bst, X = _tiny_serving_booster()
    eng = bst._gbdt.serving
    eng.trace_counts.clear()
    eng.call_counts.clear()
    bst.predict(X, raw_score=True)            # >= COLD_MIN_ROWS: warms
    for n in (700, 700, 600, 900):            # all pad to bucket 1024
        bst.predict(X[:n], raw_score=True)
        bst.predict(X[:n], pred_leaf=True)
        bst.predict(X[:n], pred_contrib=True)
    max_traces = max(eng.trace_counts.values(), default=0)
    # every (kind, bucket) seen must have exactly one trace
    multi = sum(1 for v in eng.trace_counts.values() if v > 1)
    return {"max_traces_per_bucket": max_traces,
            "buckets_with_retrace": multi}


def _serving_raw_lowered_text() -> str:
    import jax.numpy as jnp
    bst, X = _tiny_serving_booster()
    eng = bst._gbdt.serving
    pack = eng._pack("insession", eng._insession_pack)
    assert pack is not None, "tiny booster must be device-eligible"
    binned = eng._bin(X[:128], pack["has_cat"])
    pk = pack["per_k"][0]
    mask = eng._tree_mask(pack["T_k"], 0, pack["T_k"])
    fn = eng._fn("raw")
    lowered = fn.lower(pk["nodes"], pk["deltas"], mask,
                       jnp.asarray(binned))
    return lowered.compile().as_text()


def check_serving_transfers() -> Dict[str, int]:
    from .hlo import body_counts, entry_name
    txt = _serving_raw_lowered_text()
    entry = entry_name(txt)
    counts = body_counts(txt, body_name=entry) if entry else {
        "copies": 0, "total_ops": 0}
    callbacks = len(re.findall(r"callback", txt))
    transfers = len(re.findall(
        r"\b(?:copy-start|copy-done|send|recv|infeed|outfeed)\(", txt))
    return {"entry_copies": counts["copies"],
            "transfer_ops": transfers,
            "host_callbacks": callbacks}


def check_predict_layered() -> Dict[str, int]:
    """The layered dense predictor (ops/forest_tensor.py) is a
    DATAFLOW program: the lowered raw-serving path must contain ZERO
    while loops (the trip count is a pack-time host constant, unrolled
    at trace time — any ``while`` means the data-dependent traversal
    silently came back), no host callbacks, and the same pinned
    transfer budget as the loop path."""
    import jax.numpy as jnp
    bst, X = _tiny_serving_booster()
    eng = bst._gbdt.serving
    pack = eng._pack("insession", eng._insession_pack)
    assert pack is not None and pack.get("layers_depth") is not None, \
        "tiny booster must be layered-eligible"
    binned = eng._bin(X[:128], pack["has_cat"])
    pk = pack["per_k"][0]
    mask = eng._tree_mask(pack["T_k"], 0, pack["T_k"])
    fn = eng._fn("raw_layered")
    lowered = fn.lower(pk["layers"], pk["deltas"], mask,
                       jnp.asarray(binned),
                       max_depth=pack["layers_depth"])
    txt = lowered.compile().as_text()
    from .hlo import body_counts, entry_name
    entry = entry_name(txt)
    counts = body_counts(txt, body_name=entry) if entry else {
        "copies": 0}
    return {"whiles": len(re.findall(r"\bwhile\(", txt)),
            "host_callbacks": len(re.findall(r"callback", txt)),
            "transfer_ops": len(re.findall(
                r"\b(?:copy-start|copy-done|send|recv|infeed|outfeed)\(",
                txt)),
            "entry_copies": counts["copies"]}


# ---------------------------------------------------------------------------
# donation of the fused train step's score/payload buffers
# ---------------------------------------------------------------------------
@contextlib.contextmanager
def _record_jits(records: List[Tuple[str, Any]]):
    import jax
    orig = jax.jit

    @functools.wraps(orig)
    def spy(fun, *a, **k):
        records.append((getattr(fun, "__qualname__", repr(fun)),
                        k.get("donate_argnums")))
        return orig(fun, *a, **k)

    jax.jit = spy
    try:
        yield
    finally:
        jax.jit = orig


def check_train_donation() -> Dict[str, int]:
    """The fused per-iteration step must be jitted with donated
    buffers; count fused steps constructed WITHOUT donation."""
    import numpy as np

    import lightgbm_tpu as lgb
    records: List[Tuple[str, Any]] = []
    rng = np.random.RandomState(5)
    X = rng.normal(size=(400, 5))
    y = X[:, 0] - X[:, 2] + 0.1 * rng.normal(size=len(X))
    with _record_jits(records):
        lgb.train({"objective": "regression", "verbosity": -1,
                   "num_leaves": 7, "min_data_in_leaf": 5,
                   "metric": ""},
                  lgb.Dataset(X, label=y), num_boost_round=2)
    fused = [(q, d) for q, d in records
             if "_setup_fused" in q and q.endswith(".step")]
    undonated = sum(1 for _, d in fused if not d)
    return {"fused_steps_jitted": len(fused),
            "fused_steps_without_donation": undonated,
            "fused_step_missing": 0 if fused else 1}


def check_train_residency() -> Dict[str, int]:
    """Single-copy binned residency invariants: the fused trainer must
    ADOPT the ingest/learner master buffer (alias, not copy), update it
    in place every iteration, retire every other reference, and the
    ledger must attribute the surviving carrier.  Budgets pin:

      * ``binned_residents`` — live binned-footprint device buffers
        among {physical carrier, learner ``_part0``, ingest buffer}
        after two fused iterations (must be exactly 1);
      * ``adopt_not_aliased`` — the init forwarded a COPY instead of
        aliasing the donated master buffer;
      * ``step_not_inplace`` — the donated step returned the bins in a
        different buffer (XLA refused the aliasing);
      * ``master_not_retired`` — learner/ingest still hold a reference
        the donation is about to invalidate;
      * ``carrier_unattributed`` — the ledger's ``train.state`` owner
        does not account the carrier's bytes."""
    import numpy as np

    import lightgbm_tpu as lgb
    from ..obs import memory as obs_memory
    rng = np.random.RandomState(6)
    X = rng.normal(size=(600, 6))
    y = X[:, 0] - X[:, 3] + 0.1 * rng.normal(size=len(X))
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster({"objective": "regression", "verbosity": -1,
                       "num_leaves": 7, "min_data_in_leaf": 5,
                       "metric": ""}, ds)
    g = bst._gbdt
    lr = g.learner
    p0 = lr._part0
    ptr0 = p0.unsafe_buffer_pointer() if p0 is not None else None
    bst.update()
    if g._phys is None:
        return {"fused_phys_missing": 1, "binned_residents": 0,
                "adopt_not_aliased": 0, "step_not_inplace": 0,
                "master_not_retired": 0, "carrier_unattributed": 0}
    pb = g._phys[0]
    adopt_not_aliased = 0 if (ptr0 is not None
                              and pb.unsafe_buffer_pointer() == ptr0) else 1
    ptr1 = pb.unsafe_buffer_pointer()
    bst.update()
    pb2 = g._phys[0]
    step_not_inplace = 0 if pb2.unsafe_buffer_pointer() == ptr1 else 1
    ing = getattr(lr, "_ingest", None)
    master_not_retired = 0 if (
        lr._part0 is None
        and (ing is None or getattr(ing, "buffer", None) is None)) else 1
    residents = 1                       # the carrier itself
    for cand in (getattr(ing, "buffer", None),
                 getattr(lr, "_part0", None)):
        if cand is not None and not cand.is_deleted():
            residents += 1
    st = obs_memory.snapshot()["owners"].get("train.state", {})
    carrier_unattributed = (
        0 if st.get("device_unique_bytes", 0) >= int(pb2.nbytes) else 1)
    return {"fused_phys_missing": 0, "binned_residents": residents,
            "adopt_not_aliased": adopt_not_aliased,
            "step_not_inplace": step_not_inplace,
            "master_not_retired": master_not_retired,
            "carrier_unattributed": carrier_unattributed}


# ---------------------------------------------------------------------------
# device TreeSHAP program structure
# ---------------------------------------------------------------------------
def check_shap_kernel() -> Dict[str, int]:
    import jax
    import jax.numpy as jnp

    from .hlo import body_counts, entry_name
    from ..ops.shap import tree_shap_stacked
    bst, X = _tiny_serving_booster()
    eng = bst._gbdt.serving
    eng._pack("insession", eng._insession_pack)
    pack = eng._pack("contrib", eng._contrib_pack)
    assert pack is not None, "tiny booster must be SHAP-eligible"
    grp = pack["per_k"][0]["groups"][0]
    binned = eng._bin(X[:128], pack["has_cat"])
    ncols = pack["num_cols"]
    with jax.experimental.enable_x64():
        mask = jnp.asarray((grp["iters"] >= 0).astype("float32"))
        fn = jax.jit(functools.partial(tree_shap_stacked,
                                       num_columns=ncols))
        lowered = fn.lower(jnp.asarray(binned), grp["nodes"],
                           grp["paths"], mask, jnp.asarray(grp["tq"]),
                           jnp.asarray(grp["om"]))
        txt = lowered.compile().as_text()
    entry = entry_name(txt)
    counts = body_counts(txt, body_name=entry) if entry else {}
    whiles = len(re.findall(r"\bwhile\(", txt))
    callbacks = len(re.findall(r"callback", txt))
    f64_absent = 0 if "f64[" in txt else 1
    return {"whiles": whiles, "host_callbacks": callbacks,
            "f64_absent": f64_absent,
            "entry_copies": counts.get("copies", 0)}


# ---------------------------------------------------------------------------
# telemetry zero-HLO invariant
# ---------------------------------------------------------------------------
def check_telemetry_off() -> Dict[str, int]:
    """The obs layer must never stage device ops: the fused train
    step's lowered while-body is OP-FOR-OP identical whether the
    telemetry session is off or at full trace mode.  (The off-mode
    lowering equals the pre-obs program by the same argument — spans
    and the compile detector are host-side bookkeeping — and the
    separate ``while_body.default`` budget pins the absolute counts.)
    Every delta metric is an invariant budgeted at 0."""
    import jax.numpy as jnp
    import numpy as np

    import lightgbm_tpu as lgb
    from ..obs import telemetry as obs
    from .hlo import body_counts

    def lower_step():
        rng = np.random.RandomState(11)
        X = rng.normal(size=(512, 6))
        y = X[:, 0] - 0.5 * X[:, 2] + 0.1 * rng.normal(size=len(X))
        bst = lgb.Booster(params={"objective": "regression",
                                  "verbosity": -1, "num_leaves": 15,
                                  "min_data_in_leaf": 5, "metric": ""},
                          train_set=lgb.Dataset(X, label=y))
        g = bst._gbdt
        assert g._fused_phys is not None, \
            "telemetry.off budget needs the fused physical step"
        pb, ghi = g._init_phys(g.learner._part0, g.scores)
        fmask = jnp.ones((g.learner.F,), dtype=bool)
        feat_used = jnp.zeros((g.learner.F,), dtype=bool)
        lowered = g._fused_phys.lower(pb, ghi, fmask, jnp.int32(1),
                                      feat_used)
        return lowered.compile().as_text()

    sess = obs.get()
    prev = sess.mode
    try:
        sess.set_mode("off")
        off = body_counts(lower_step())
        sess.set_mode("trace")
        on = body_counts(lower_step())
    finally:
        sess.set_mode(prev)
    keys = set(off["ops"]) | set(on["ops"])
    hist_delta = sum(abs(off["ops"].get(k, 0) - on["ops"].get(k, 0))
                     for k in keys)
    return {"body_op_histogram_delta": hist_delta,
            "total_ops_delta": abs(off["total_ops"] - on["total_ops"]),
            "copies_delta": abs(off["copies"] - on["copies"])}


# ---------------------------------------------------------------------------
# health zero-HLO invariant
# ---------------------------------------------------------------------------
def check_health_off() -> Dict[str, int]:
    """The health layer must never stage device ops in the training
    loop: the fused train step's lowered while-body is OP-FOR-OP
    identical with health off and at full trace mode (the flight
    recorder consumes host records the trainer already materializes;
    device digest reductions only run in explicit snapshot calls).
    Mirrors ``telemetry.off``; every delta metric is an invariant
    budgeted at 0."""
    import jax.numpy as jnp
    import numpy as np

    import lightgbm_tpu as lgb
    from ..obs import health as obs_health
    from ..obs import telemetry as obs_tel
    from .hlo import body_counts

    def lower_step(mode):
        rng = np.random.RandomState(13)
        X = rng.normal(size=(512, 6))
        y = X[:, 0] - 0.5 * X[:, 2] + 0.1 * rng.normal(size=len(X))
        bst = lgb.Booster(params={"objective": "regression",
                                  "verbosity": -1, "num_leaves": 15,
                                  "min_data_in_leaf": 5, "metric": "",
                                  "health": mode},
                          train_set=lgb.Dataset(X, label=y))
        g = bst._gbdt
        assert g._fused_phys is not None, \
            "health.off budget needs the fused physical step"
        pb, ghi = g._init_phys(g.learner._part0, g.scores)
        fmask = jnp.ones((g.learner.F,), dtype=bool)
        feat_used = jnp.zeros((g.learner.F,), dtype=bool)
        lowered = g._fused_phys.lower(pb, ghi, fmask, jnp.int32(1),
                                      feat_used)
        return lowered.compile().as_text()

    sess = obs_health.get()
    tel = obs_tel.get()
    prev, tel_prev = sess.mode, tel.mode
    try:
        sess.set_mode("off")
        off = body_counts(lower_step("off"))
        sess.set_mode("trace")
        on = body_counts(lower_step("trace"))
    finally:
        sess.set_mode(prev)
        tel.set_mode(tel_prev)       # health trace upgrades telemetry
    keys = set(off["ops"]) | set(on["ops"])
    hist_delta = sum(abs(off["ops"].get(k, 0) - on["ops"].get(k, 0))
                     for k in keys)
    return {"body_op_histogram_delta": hist_delta,
            "total_ops_delta": abs(off["total_ops"] - on["total_ops"]),
            "copies_delta": abs(off["copies"] - on["copies"])}


# ---------------------------------------------------------------------------
# perfwatch zero-HLO invariant
# ---------------------------------------------------------------------------
def check_perfwatch_off() -> Dict[str, int]:
    """The perf-trajectory layer (obs/regress.py) must never stage
    device ops or syncs: the fused train step's lowered while-body is
    OP-FOR-OP identical whether or not a perfwatch recording (clock +
    BENCH_history append) is in flight around the lowering.  Same
    contract as ``telemetry.off``: spans are host clock reads, the
    store is a host JSONL append — every delta metric is an invariant
    budgeted at 0."""
    import os
    import tempfile

    import jax.numpy as jnp
    import numpy as np

    import lightgbm_tpu as lgb
    from ..obs import regress
    from .hlo import body_counts

    def lower_step():
        rng = np.random.RandomState(17)
        X = rng.normal(size=(512, 6))
        y = X[:, 0] - 0.5 * X[:, 2] + 0.1 * rng.normal(size=len(X))
        bst = lgb.Booster(params={"objective": "regression",
                                  "verbosity": -1, "num_leaves": 15,
                                  "min_data_in_leaf": 5, "metric": ""},
                          train_set=lgb.Dataset(X, label=y))
        g = bst._gbdt
        assert g._fused_phys is not None, \
            "perfwatch.off budget needs the fused physical step"
        pb, ghi = g._init_phys(g.learner._part0, g.scores)
        fmask = jnp.ones((g.learner.F,), dtype=bool)
        feat_used = jnp.zeros((g.learner.F,), dtype=bool)
        lowered = g._fused_phys.lower(pb, ghi, fmask, jnp.int32(1),
                                      feat_used)
        return lowered.compile().as_text()

    off = body_counts(lower_step())
    with tempfile.TemporaryDirectory() as td:
        with regress.recording("jaxlint.perfwatch",
                               path=os.path.join(td, "h.jsonl"),
                               config={}):
            on = body_counts(lower_step())
    keys = set(off["ops"]) | set(on["ops"])
    hist_delta = sum(abs(off["ops"].get(k, 0) - on["ops"].get(k, 0))
                     for k in keys)
    return {"body_op_histogram_delta": hist_delta,
            "total_ops_delta": abs(off["total_ops"] - on["total_ops"]),
            "copies_delta": abs(off["copies"] - on["copies"])}


# ---------------------------------------------------------------------------
# piece-wise-linear gain: constant-mode lowering invariant
# ---------------------------------------------------------------------------
def check_linear_gain() -> Dict[str, int]:
    """The leafwise-gain machinery (models/learner.py NLF_LINEAR rows,
    ops/split.py:find_best_split_linear) must be invisible to constant
    trees: the tree-build while body lowers OP-FOR-OP identically
    between a plain config and ``linear_tree=True`` in the default
    (refit) mode — the refit happens post-hoc on the host, so the
    device program may not change by a single op.  The ``_nlf`` gate
    is a Python-level branch; if it ever leaks into the trace (e.g. an
    unconditional 28-row leafmat), these deltas light up.
    ``leafwise_total_ops`` additionally pins that the leafwise body
    keeps compiling, as a drifting count with headroom.  (The fused
    single-program step is off under linear_tree, so the lowering
    vehicle is the tree-build body itself, same as
    ``while_body.default``.)"""
    from .hlo import report

    plain = report({})
    refit = report({"linear_tree": True})
    leafwise = report(
        {"linear_tree": True, "linear_tree_mode": "leafwise_gain"})
    keys = set(plain["ops"]) | set(refit["ops"])
    hist_delta = sum(abs(plain["ops"].get(k, 0) - refit["ops"].get(k, 0))
                     for k in keys)
    shape_keys = set(plain["copies_by_shape"]) | \
        set(refit["copies_by_shape"])
    shape_delta = sum(abs(plain["copies_by_shape"].get(k, 0)
                          - refit["copies_by_shape"].get(k, 0))
                      for k in shape_keys)
    return {"body_op_histogram_delta": hist_delta,
            "total_ops_delta": abs(plain["total_ops"]
                                   - refit["total_ops"]),
            "copies_delta": abs(plain["copies"] - refit["copies"]),
            "copy_shape_histogram_delta": shape_delta,
            "leafwise_total_ops": leafwise["total_ops"]}


# ---------------------------------------------------------------------------
# continual-runtime tick/swap budgets
# ---------------------------------------------------------------------------
def check_continual_tick() -> Dict[str, int]:
    """Tick-loop artifact budget for the continual runtime: steady-state
    ticks (prequential eval + in-place leaf refit) must add ZERO serving
    retraces — the refit rides the engine's leaf-refresh fast path, so
    only the small delta matrices re-transfer — and a hot swap must cost
    at most ONE compile per (kind, bucket), paid while warming the
    candidate off the serving path."""
    from ..continual.drift import _DRILL_PARAMS, DriftStream
    from ..continual.runtime import ContinualBooster

    p = dict(_DRILL_PARAMS)
    p.update({"num_iterations": 5, "num_leaves": 7})
    stream = DriftStream(num_features=5, rows=128, seed=9)
    X0, y0 = DriftStream(num_features=5, rows=512, seed=10).batch(0)
    cb = ContinualBooster(p, X0, y0)
    # settle: the first tick pays the per-kind compiles once
    cb.tick(*stream.batch(0))
    snap = cb.serving_engine.trace_snapshot()
    for t in range(1, 4):
        cb.tick(*stream.batch(t))
    tick_retraces = sum(
        cb.serving_engine.new_traces_since(snap).values())

    # a forced swap: candidate warm-up may trace each (kind, bucket)
    # once, never twice
    import lightgbm_tpu as lgb
    Xc, yc = DriftStream(num_features=5, rows=512, seed=12).batch(0)
    cand = lgb.train({"objective": "regression", "verbosity": -1,
                      "num_leaves": 7, "metric": ""},
                     lgb.Dataset(Xc, label=yc), num_boost_round=5)
    r = cb.force_swap(cand, gate=stream.batch(4))
    over = sum(1 for v in r.swap_new_traces.values() if v > 1)
    return {"tick_retraces": tick_retraces,
            "swap_retraces_over_one": over,
            "swap_missing_warm": 0 if r.swap_new_traces else 1}


CHECKS = {
    "while_body.default": check_while_body_default,
    "while_body.mega": check_while_body_mega,
    "frontier.body": check_while_body_frontier,
    "chunk.adaptive": check_chunk_adaptive,
    "serving.compiles": check_serving_compiles,
    "serving.transfers": check_serving_transfers,
    "predict.layered": check_predict_layered,
    "train.donation": check_train_donation,
    "train.residency": check_train_residency,
    "shap.kernel": check_shap_kernel,
    "continual.tick": check_continual_tick,
    "linear.gain": check_linear_gain,
    "telemetry.off": check_telemetry_off,
    "health.off": check_health_off,
    "perfwatch.off": check_perfwatch_off,
}


def collect_tier_b(only: Optional[List[str]] = None
                   ) -> Dict[str, Dict[str, int]]:
    out: Dict[str, Dict[str, int]] = {}
    for name, fn in CHECKS.items():
        if only and name not in only:
            continue
        out[name] = fn()
    return out
